"""Querier HTTP API.

Reference analog: server/querier/router/query.go:30 (POST /v1/query/) and
server/querier/profile/router/query.go:33 (POST /v1/profile/ProfileTracing).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import yaml

from deepflow_tpu.query import engine as qengine
from deepflow_tpu.query import qtrace
from deepflow_tpu.query import sql as qsql
from deepflow_tpu.query.flamegraph import profile_flame_tree
from deepflow_tpu.store.db import Database

log = logging.getLogger("df.querier")

# dd-trace agent API paths: accepted on POST and (dd default) PUT
_DD_TRACE_PATHS = ("/v0.3/traces", "/v0.4/traces")


class AuthError(Exception):
    """Missing/invalid API token on a gated endpoint (HTTP 403)."""


class QuerierAPI:
    """Route logic, separated from HTTP plumbing for in-process use."""

    def __init__(self, db: Database, stats_provider=None,
                 controller=None, exporters=None, alerts=None,
                 trace_trees=None, telemetry=None,
                 api_token: str | None = None,
                 membership=None, federation=None,
                 shard_id: int = 0, storage_provider=None,
                 rollup=None) -> None:
        self.db = db
        self.stats_provider = stats_provider or (lambda: {})
        # tiered storage health block (server._storage_stats) + the
        # rollup job whose horizons gate transparent datasource selection
        self.storage_provider = storage_provider
        self.rollup = rollup
        self.controller = controller
        self.exporters = exporters
        self.alerts = alerts
        self.trace_trees = trace_trees  # TraceTreeBuilder (optional)
        self.telemetry = telemetry  # server-side Telemetry (optional)
        # cluster federation (optional): ClusterMembership +
        # FederationCoordinator — when peers are alive, queries scatter
        # over /v1/shard/exec and merge here (see cluster/federation.py)
        self.membership = membership
        self.federation = federation
        self.shard_id = shard_id
        # shared token gating the mutating control-plane surface
        # (/v1/repo upload, the OTA `upgrade` exec). Empty/None = open:
        # the default deployment binds the querier to localhost, and the
        # trust boundary is documented in docs/SECURITY.md.
        import os as _os
        self.api_token = (api_token if api_token is not None
                          else _os.environ.get("DF_API_TOKEN", ""))
        from deepflow_tpu.server.integration import IntegrationAPI
        # combined binary: ingest shares the controller's authoritative
        # SmartEncoding allocator; standalone: process-local allocator
        self.integration = IntegrationAPI(
            db, exporters=exporters,
            prom_encoder=getattr(controller, "prom_encoder", None),
            trace_trees=trace_trees)
        from deepflow_tpu.server.mcp import McpServer
        self.mcp = McpServer(self)
        from deepflow_tpu.query.tracing_adapter import AdapterRegistry
        self.trace_adapters = AdapterRegistry()
        # result + partial-aggregate cache (query/cache.py): serves the
        # local /v1/query path and the shard half of federated scatters
        from deepflow_tpu.query.cache import QueryCache
        self.query_cache = QueryCache(telemetry=telemetry)
        # read-tier wiring (set by server/server.py when storage is
        # disaggregated): the shard-side SegmentPublisher (for the
        # publish-gen exclusion handshake), the querier-side ReadTier,
        # and the cluster-wide partial-aggregate cache
        self.publisher = None
        self.readtier = None
        self.partial_cache = None
        # background integrity scrubber (store/scrub.py), set by
        # server.py: backs /v1/fsck repair and the health scrub block
        self.scrubber = None
        # closed-loop QoS (deepflow_tpu/qos): the facade + the
        # receiver's per-tenant drop attribution, set by server.py on
        # ingest nodes (querier replicas take no agent traffic)
        self.qos = None
        self.drop_attribution = None
        # standing-query registry (query/standing.py), set by server.py:
        # backs /v1/subscribe and the push-evaluated alert path
        self.standing = None
        # zone-map pruning accounting flows into the same hop ledger the
        # rest of the pipeline reports through (query.scan hop)
        from deepflow_tpu.query import engine as _qengine
        _qengine.set_scan_telemetry(telemetry)
        # dogfooded query tracing: every served query writes its span
        # tree into deepflow_system.query_trace through this tracer
        # (query/qtrace.py); the sink is the system table itself, so the
        # Tempo API + flame assembler render the querier's own internals
        from deepflow_tpu.query import qtrace as _qtrace
        self.qtracer = _qtrace.QueryTracer(
            telemetry, service=f"deepflow-querier-{shard_id}",
            shard_id=shard_id, sink=self._qtrace_sink)
        # per-stage observed costs from EXPLAIN ANALYZE runs feed the
        # same EWMA cost-model machinery the kernel/degree choosers use
        from deepflow_tpu.query.costmodel import KernelCostModel
        self.stage_cost = KernelCostModel(
            ("parse", "plan", "execute", "scatter", "merge"))

    def _qtrace_sink(self, spans: list[dict]) -> None:
        from deepflow_tpu.query import qtrace as _qtrace
        self.db.table("deepflow_system.query_trace") \
            .append_rows(_qtrace.rows_from_spans(spans))

    def alerts_api(self, method: str, body: dict) -> dict:
        if self.alerts is None:
            raise qengine.QueryError("alerting not running")
        if method == "list":
            return {"rules": self.alerts.list()}
        if method == "upsert":
            return {"rule": self.alerts.upsert(body).to_dict()}
        if method == "delete":
            return {"deleted": self.alerts.delete(str(body.get("name", "")))}
        raise qengine.QueryError(f"unknown alerts action {method!r}")

    def exporters_api(self, body: dict) -> dict:
        if self.exporters is None:
            raise qengine.QueryError("exporters not running")
        from deepflow_tpu.server.exporters import (
            JsonLinesExporter, KafkaExporter, OtlpJsonExporter,
            RemoteWriteExporter)
        etype = body.get("type", "")
        endpoint = body.get("endpoint", "")
        if not endpoint:
            raise qengine.QueryError("endpoint required")
        if etype == "json-lines":
            exp = JsonLinesExporter(endpoint,
                                    tables=tuple(body.get("tables", [])))
        elif etype == "otlp-json":
            exp = OtlpJsonExporter(endpoint)
        elif etype == "remote-write":
            exp = RemoteWriteExporter(endpoint)
        elif etype == "kafka":
            try:
                exp = KafkaExporter(endpoint,
                                    tables=tuple(body.get("tables", [])))
            except ValueError as e:
                raise qengine.QueryError(str(e))
        else:
            raise qengine.QueryError(
                "type must be json-lines|otlp-json|remote-write|kafka")
        self.exporters.add(exp)  # idempotent on (type, endpoint)
        return {"added": etype, "endpoint": endpoint,
                "exporters": self.exporters.stats()}

    def subscribe_api(self, body: dict) -> dict:
        """POST /v1/subscribe — the standing-query control surface:
        register/unregister queries, create subscribers, long-poll
        drain. The GET side of the same path streams SSE."""
        if self.standing is None:
            raise qengine.QueryError("standing queries not running")
        action = body.get("action", "list")
        if action == "register":
            sql = body.get("sql", "")
            if not sql:
                raise qengine.QueryError("sql required")
            try:
                window_s = float(body.get("window_s", 0) or 0)
            except (TypeError, ValueError):
                raise qengine.QueryError("window_s must be a number")
            return {"registered": self.standing.register(
                sql, name=body.get("name") or None,
                table=body.get("table") or None,
                window_s=window_s, org_id=body.get("org_id"),
                verify=bool(body.get("verify", False)))}
        if action == "unregister":
            return {"unregistered": self.standing.unregister(
                str(body.get("name", "")))}
        if action == "list":
            return {"queries": self.standing.list()}
        if action == "subscribe":
            names = body.get("queries")
            return self.standing.subscribe(
                [str(n) for n in names] if names else None)
        if action == "poll":
            return self.standing.poll(
                str(body.get("subscriber", "")),
                timeout_s=float(body.get("timeout_s", 25.0) or 25.0),
                max_items=int(body.get("max", 64) or 64))
        if action == "unsubscribe":
            return {"unsubscribed": self.standing.unsubscribe(
                str(body.get("subscriber", "")))}
        raise qengine.QueryError(f"unknown subscribe action {action!r}")

    def exporters_delete(self, body: dict) -> dict:
        if self.exporters is None:
            raise qengine.QueryError("exporters not running")
        endpoint = body.get("endpoint", "")
        return {"removed": self.exporters.remove(endpoint)}

    def _resolve_table(self, table_name: str, db_name: str = ""):
        # resolution order: as-given, db-prefixed, then with the default
        # interval suffix (flow_metrics tables are <name>.<interval>)
        candidates = [table_name, f"{table_name}.1s"]
        if db_name:
            candidates = [f"{db_name}.{table_name}",
                          f"{db_name}.{table_name}.1s"] + candidates
        for cand in candidates:
            try:
                return self.db.table(cand)
            except KeyError:
                continue
        raise qengine.QueryError(
            f"no such table {table_name!r}; known: {self.db.tables()}")

    @staticmethod
    def _org_scope(select: qsql.Select, table, org) -> None:
        if "org_id" not in table.columns:
            # silently dropping the filter would hand one tenant
            # another tenant's rows — refuse instead
            raise qengine.QueryError(
                f"table {table.name!r} has no org scoping; "
                "query it without org_id")
        # cooperative VIEW filter, not a security boundary: the
        # caller names the org it wants and nothing verifies it may
        # (see docs/SECURITY.md). ANDed into the parsed AST rather
        # than the SQL text so the filter can't be quoted away.
        cond = qsql.BinOp("=", qsql.Col("org_id"),
                          qsql.Lit(int(org)))
        select.where = (cond if select.where is None
                        else qsql.BinOp("AND", select.where, cond))

    def _fed(self):
        """The FederationCoordinator iff remote peers are alive right
        now — otherwise every query takes the plain local path."""
        if self.federation is not None and self.federation.active():
            return self.federation
        return None

    def query(self, body: dict) -> dict:
        sql_text = body.get("sql", "")
        db_name = body.get("db", "")
        # parse before the trace opens: it decides WHICH trace to open
        # (EXPLAIN runs captured; SHOW is catalog introspection, never
        # traced).  The parse cost is re-attributed as a span below.
        t0, c0 = time.time_ns(), time.thread_time_ns()
        select = qsql.parse_statement(sql_text)
        parse_t1, parse_c1 = time.time_ns(), time.thread_time_ns()
        if isinstance(select, qsql.Show):
            from deepflow_tpu.query import catalog
            try:
                result = catalog.show(select.what, select.table)
            except KeyError as e:
                raise qengine.QueryError(
                    f"no such table {e.args[0]!r} for SHOW") from None
            return {"result": result, "debug": {"show": select.what}}
        if isinstance(select, qsql.Explain):
            return self._explain(body, select, db_name)
        with self.qtracer.start_trace("query", kind="sql",
                                      sql=sql_text[:200]):
            self._parse_span(t0, c0, parse_t1, parse_c1)
            return self._run_select(body, select, sql_text, db_name)

    @staticmethod
    def _parse_span(t0: int, c0: int, t1: int, c1: int) -> None:
        """Re-attribute a parse that happened just before the trace
        opened (statement routing needs the AST first)."""
        sp = qtrace.span("parse")
        if isinstance(sp, qtrace.Span):
            sp.start_ns, sp.cpu_start_ns = t0, c0
            sp.end_ns = t1
            sp.cpu_ns = c1 - c0
            sp._buf.add(sp)

    def _run_select(self, body: dict, select: qsql.Select, sql_text: str,
                    db_name: str) -> dict:
        """Plan + execute one SELECT (the body of ``query()``, shared
        with the EXPLAIN path, running under whatever trace is open)."""
        org = body.get("org_id")
        debug: dict = {}
        with qtrace.span("plan") as pl:
            table = self._resolve_table(select.table, db_name)
            if org is not None:
                self._org_scope(select, table, org)
            fed = self._fed()
            debug["table"] = table.name
            sketch = None
            if fed is None and self.rollup is not None:
                # transparent rollup datasource selection: when the
                # query is an aligned aggregate a coarser tier answers
                # exactly, swap the table (rollup tables share column
                # names — the SQL text itself is reusable verbatim, and
                # the cache keys on the table object)
                from deepflow_tpu.query import datasource as qds
                sketch = qds.sketch_percentile(self.db, table, select,
                                               self.rollup.horizons())
                if sketch is None:
                    picked = qds.select_rollup(self.db, table, select,
                                               self.rollup.horizons())
                    if picked is not None:
                        table, info = picked
                        debug["datasource"] = info
                        debug["table"] = table.name
            pl.annotate(table=debug["table"], federated=fed is not None,
                        **({"datasource": str(debug["datasource"])}
                           if "datasource" in debug else {}))
        if fed is not None:
            with qtrace.span("execute", path="federation") as ex:
                result, info = fed.sql_query(table, select, sql_text,
                                             org_id=org)
                if isinstance(info, dict):
                    ex.annotate(shards=int(info.get("shards", 1)),
                                cache=str(info.get("cache", "")))
            return self._annotate_degraded(
                {"result": result.to_dict(), "debug": debug,
                 "federation": info}, table.name)
        if sketch is not None:
            result, info = sketch
            debug["datasource"] = info
            qtrace.annotate(datasource=str(info))
            return self._annotate_degraded(
                {"result": result.to_dict(), "debug": debug}, table.name)
        # org scoping rewrote the AST, not the text — fold it into the
        # cache key so scoped variants of one SQL string don't collide
        with qtrace.span("execute", path="local") as ex:
            result = self.query_cache.execute(
                table, sql_text, select=select,
                extra_key=None if org is None else ("org", org))
            ex.annotate(rows=len(result.values))
        return self._annotate_degraded(
            {"result": result.to_dict(), "debug": debug}, table.name)

    def _degraded_for(self, table_name: str) -> dict | None:
        """Quarantine marker for a table: rows the integrity scrubber
        pulled from service (corrupt segments) and has not repaired
        yet. None when the table serves its full history."""
        store = getattr(self.db, "tier_store", None)
        if store is None:
            return None
        info = store.quarantine_info(table_name)
        if not info:
            return None
        return {"reason": "segment_quarantine", **info}

    def _annotate_degraded(self, out: dict, table_name: str) -> dict:
        """Attach the degraded marker + a human warning to a query
        response — the same short-answer honesty contract federation's
        missing_shards uses: results during a quarantine gap are
        SERVED, but never silently presented as complete. Remote-shard
        markers gathered by the scatter ride in under federation."""
        deg = self._degraded_for(table_name)
        fed = out.get("federation")
        fed_deg = (fed.get("degraded_shards")
                   if isinstance(fed, dict) else None)
        if deg is not None:
            out["degraded"] = deg
            out.setdefault("warnings", []).append(
                f"results may be incomplete: {deg['rows']} rows in "
                f"{deg['segments']} quarantined segment(s) of "
                f"{table_name} await repair")
        if fed_deg:
            out.setdefault("warnings", []).append(
                f"results may be incomplete: quarantined segments on "
                f"shard(s) {sorted(fed_deg)} await repair")
        return out

    # -- EXPLAIN [ANALYZE] ---------------------------------------------------

    def _explain(self, body: dict, stmt: qsql.Explain,
                 db_name: str) -> dict:
        """EXPLAIN: plan only (table/tier/datasource/federation route).
        EXPLAIN ANALYZE: run the query under a CAPTURED trace and
        annotate the plan with observed wall/CPU per stage; the observed
        stage costs feed the stage cost model (query/costmodel.py)."""
        root = self.qtracer.start_trace(
            "query", kind="explain", sql=stmt.sql[:200], capture=True)
        out = None
        with root:
            if stmt.analyze:
                out = self._run_select(body, stmt.select, stmt.sql,
                                       db_name)
            else:
                with qtrace.span("plan") as pl:
                    table = self._resolve_table(stmt.select.table, db_name)
                    org = body.get("org_id")
                    if org is not None:
                        self._org_scope(stmt.select, table, org)
                    fed = self._fed()
                    pl.annotate(table=table.name,
                                federated=fed is not None)
                    if fed is None and self.rollup is not None:
                        from deepflow_tpu.query import datasource as qds
                        picked = qds.select_rollup(self.db, table,
                                                   stmt.select,
                                                   self.rollup.horizons())
                        if picked is not None:
                            pl.annotate(datasource=str(picked[1]),
                                        table=picked[0].name)
        # E2E = the root span's wall (query work only; excludes the
        # trace's own sink flush) — the number stage walls must sum to
        total_ns = root.duration_ns
        spans = root.trace_spans()
        root_id = root.span_id
        stages = []
        for d in spans:
            if d["parent_span_id"] != root_id:
                continue
            stages.append({"stage": d["name"],
                           "wall_ms": round(d["duration_ns"] / 1e6, 3),
                           "cpu_ms": round(d["cpu_ns"] / 1e6, 3),
                           "status": d["status"],
                           "detail": d["attrs"]})
        stages.sort(key=lambda s: -s["wall_ms"])
        plan: dict = {"analyze": stmt.analyze}
        rows_out = 0
        for d in spans:
            a = d["attrs"]
            nm = d["name"]
            if nm == "plan":
                plan.update({k: a[k] for k in
                             ("table", "federated", "datasource")
                             if k in a})
            elif nm == "execute":
                plan["path"] = a.get("path", "")
                rows_out = int(a.get("rows", 0) or 0)
                if "shards" in a:
                    plan["shards"] = a["shards"]
                if a.get("cache"):
                    plan["scatter_cache"] = a["cache"]
            elif nm.startswith("prune"):
                pr = plan.setdefault("prune", {"candidates": 0,
                                               "zone_pruned": 0,
                                               "bloom_checked": 0,
                                               "bloom_pruned": 0,
                                               "scanned": 0})
                for src, dst in (("candidates", "candidates"),
                                 ("zone_pruned", "zone_pruned"),
                                 ("bloom_checked", "bloom_checked"),
                                 ("bloom_pruned", "bloom_pruned"),
                                 ("scanned", "scanned")):
                    pr[dst] += int(a.get(src, 0) or 0)
            elif nm.startswith("scan"):
                if "mode" in a:
                    plan["scan_mode"] = a["mode"]
                if "degree" in a:
                    plan["morsel_degree"] = a["degree"]
                if "morsels" in a:
                    plan["morsels"] = a["morsels"]
            elif nm == "cache.lookup":
                plan["cache_layer"] = a.get("layer", "")
                if "outcome" in a:
                    plan["cache_outcome"] = a["outcome"]
        if stmt.analyze:
            # observed per-stage costs feed the same EWMA machinery the
            # kernel/degree choosers learn from
            for s in stages:
                self.stage_cost.observe(s["stage"], max(rows_out, 1),
                                        s["wall_ms"] * 1e6)
        result_rows = [[s["stage"], s["wall_ms"], s["cpu_ms"],
                        json.dumps(s["detail"], sort_keys=True,
                                   default=str)]
                       for s in stages]
        explain = {"analyze": stmt.analyze, "trace_id": root.trace_id,
                   "plan": plan, "stages": stages,
                   "total_ms": round(total_ns / 1e6, 3),
                   "spans": len(spans)}
        if stmt.analyze:
            explain["rows_returned"] = rows_out
            if out is not None and "federation" in out:
                explain["federation"] = out["federation"]
        return {"result": {"columns": ["stage", "wall_ms", "cpu_ms",
                                       "detail"],
                           "values": result_rows},
                "explain": explain,
                "debug": {"explain": True, "table": plan.get("table", "")}}

    def profile_tracing(self, body: dict) -> dict:
        table = self.db.table("profile.in_process_profile")
        params = {"time_start": body.get("time_start"),
                  "time_end": body.get("time_end"),
                  "event_type": body.get("event_type"),
                  "app_service": body.get("app_service"),
                  "profiler": body.get("profiler")}
        fed = self._fed()
        if fed is not None:
            from deepflow_tpu.query.flamegraph import build_flame_tree

            def flame_fn(p, db):
                part = self._flame_stacks(p, db)
                return part["stacks"], part["values"]

            (stacks, values), info = fed.flame_stacks(flame_fn, params)
            return {"result": build_flame_tree(stacks, values).to_dict(),
                    "federation": info}
        tree = profile_flame_tree(
            table,
            time_start_ns=params["time_start"],
            time_end_ns=params["time_end"],
            event_type=params["event_type"],
            app_service=params["app_service"],
            profiler=params["profiler"],
        )
        return {"result": tree.to_dict()}

    def _flame_stacks(self, params: dict, db=None) -> dict:
        """Shard-local half of a federated flame graph: aggregate by
        stack in this shard's encoded space, return DECODED stacks."""
        from deepflow_tpu.query.flamegraph import profile_stack_values
        table = (db if db is not None else self.db).table(
            "profile.in_process_profile")
        stacks, values = profile_stack_values(
            table,
            time_start_ns=params.get("time_start"),
            time_end_ns=params.get("time_end"),
            event_type=params.get("event_type"),
            app_service=params.get("app_service"),
            profiler=params.get("profiler"))
        return {"stacks": stacks, "values": values}

    def tpu_flame(self, body: dict) -> dict:
        """Flame view over HLO device spans: module -> op hierarchy.
        Device kinds only by default; pass include_host to include
        host-compile/runtime spans in the same tree."""
        table = self.db.table("profile.tpu_hlo_span")
        where = ["duration_ns > 0"]
        if not body.get("include_host"):
            from deepflow_tpu.store.schema import TPU_SPAN_KINDS
            device_kinds = ", ".join(
                f"'{k}'" for k in TPU_SPAN_KINDS if k.startswith("device-"))
            where.append(f"kind IN ({device_kinds})")
        if body.get("time_start"):
            where.append(f"time >= {int(body['time_start'])}")
        if body.get("time_end"):
            where.append(f"time < {int(body['time_end'])}")
        if body.get("device_id") is not None:
            where.append(f"device_id = {int(body['device_id'])}")
        sql_text = (
            "SELECT hlo_module, hlo_category, hlo_op, Sum(duration_ns) AS d "
            f"FROM t WHERE {' AND '.join(where)} "
            "GROUP BY hlo_module, hlo_category, hlo_op")
        fed = self._fed()
        info = None
        if fed is not None:
            # an exact push-down case: Sum partials merge shard-side ids
            # never travel (group keys are decoded strings)
            res, info = fed.sql_query(table, qsql.parse(sql_text),
                                      sql_text)
        else:
            res = qengine.execute(table, sql_text)
        from deepflow_tpu.query.flamegraph import build_flame_tree
        stacks, values = [], []
        for mod, cat, op, d in res.values:
            stacks.append(";".join(x for x in (mod, cat or "other", op) if x))
            values.append(int(d))
        out = {"result": build_flame_tree(stacks, values).to_dict()}
        if info is not None:
            out["federation"] = info
        return out

    def tpu_memory(self, body: dict) -> dict:
        """HBM observability (BASELINE config 3 '+ HBM'): per-device usage
        timeline, headroom summary, per-HLO memory attribution (top ops by
        bytes_accessed), and OOM forensics — what ran in the window around
        the highest-pressure sample. Reference analog: the EE memory
        profiler (memory_profile.rs) flame view, redesigned around XLA
        allocator statistics + xplane span memory traffic."""
        mem = self.db.table("profile.tpu_memory")
        where = ["bytes_limit > 0"]
        if body.get("time_start"):
            where.append(f"time >= {int(body['time_start'])}")
        if body.get("time_end"):
            where.append(f"time < {int(body['time_end'])}")
        if body.get("device_id") is not None:
            where.append(f"device_id = {int(body['device_id'])}")
        res = qengine.execute(
            mem, "SELECT time, device_id, bytes_in_use, peak_bytes_in_use, "
                 "bytes_limit, largest_free_block FROM t "
                 f"WHERE {' AND '.join(where)} ORDER BY time")
        timeline = [
            {"time": int(t), "device_id": int(d), "bytes_in_use": int(b),
             "peak_bytes_in_use": int(p), "bytes_limit": int(lim),
             "largest_free_block": int(fr)}
            for t, d, b, p, lim, fr in res.values]
        devices: dict[int, dict] = {}
        for s in timeline:  # time-ordered: last write wins = latest
            d = s["device_id"]
            cur = devices.setdefault(d, {"device_id": d, "peak_pct": 0.0})
            cur["bytes_in_use"] = s["bytes_in_use"]
            cur["peak_bytes_in_use"] = s["peak_bytes_in_use"]
            cur["bytes_limit"] = s["bytes_limit"]
            cur["largest_free_block"] = s["largest_free_block"]
            cur["peak_pct"] = round(
                100.0 * s["peak_bytes_in_use"] / s["bytes_limit"], 1)
            cur["headroom_bytes"] = s["bytes_limit"] - s["peak_bytes_in_use"]
        # per-HLO memory attribution: top ops by HBM traffic in the window
        spans = self.db.table("profile.tpu_hlo_span")
        swhere = ["bytes_accessed > 0"]
        if body.get("time_start"):
            swhere.append(f"time >= {int(body['time_start'])}")
        if body.get("time_end"):
            swhere.append(f"time < {int(body['time_end'])}")
        top_n = int(body.get("top", 15))
        sres = qengine.execute(
            spans, "SELECT hlo_op, hlo_module, Sum(bytes_accessed) AS b, "
                   "Sum(duration_ns) AS d, Count() AS n FROM t "
                   f"WHERE {' AND '.join(swhere)} "
                   "GROUP BY hlo_op, hlo_module ORDER BY b DESC "
                   f"LIMIT {top_n}")
        top_ops = [
            {"hlo_op": op, "hlo_module": mod, "bytes_accessed": int(b),
             "duration_ns": int(d), "count": int(n),
             "hbm_gbps": round(b / max(1, d), 2)}  # bytes/ns = GB/s
            for op, mod, b, d, n in sres.values]
        # OOM forensics: the highest-pressure sample and what ran near it
        forensics = None
        if timeline:
            worst = max(timeline,
                        key=lambda s: s["bytes_in_use"] / s["bytes_limit"])
            w = int(body.get("forensics_window_s", 10)) * 1_000_000_000
            t0, t1 = worst["time"] - w, worst["time"] + w
            fres = qengine.execute(
                spans, "SELECT hlo_op, Sum(bytes_accessed) AS b FROM t "
                       f"WHERE bytes_accessed > 0 AND time >= {t0} "
                       f"AND time < {t1} GROUP BY hlo_op "
                       "ORDER BY b DESC LIMIT 10")
            forensics = {
                "pressure_peak": worst,
                "pressure_pct": round(
                    100.0 * worst["bytes_in_use"] / worst["bytes_limit"], 1),
                "ops_near_peak": [
                    {"hlo_op": op, "bytes_accessed": int(b)}
                    for op, b in fres.values],
            }
        return {"result": {
            "devices": sorted(devices.values(),
                              key=lambda d: d["device_id"]),
            "timeline": timeline[-int(body.get("limit", 2000)):],
            "top_ops": top_ops,
            "forensics": forensics,
        }}

    def tpu_collectives(self, body: dict) -> dict:
        """Cross-device stitched collectives (reference: SURVEY §2.9.5 ICI
        observation). Each group = one collective instance across all its
        participant devices, with latency/skew/bandwidth."""
        rows = self._tpu_span_rows(body, collectives_only=True)
        from deepflow_tpu.tpuprobe.collectives import stitch
        return {"result": [g.to_dict() for g in stitch(rows)]}

    def tpu_step_trace(self, body: dict) -> dict:
        """One training step stitched across devices: per-device span
        bounds + collective groups + straggler skew."""
        rows = self._tpu_span_rows(body)
        from deepflow_tpu.tpuprobe.collectives import step_trace
        run_id = body.get("run_id")
        return {"result": step_trace(
            rows, run_id=None if run_id is None else int(run_id))}

    _STEP_COLS = ("time, end_ns, latency_ns, run_id, step, job, "
                  "device_count, device_skew_ns, compute_ns, "
                  "collective_ns, straggler_device, straggler_lag_ns, "
                  "top_hlos, host")

    def _step_rollups(self, body: dict) -> tuple[list[dict], dict | None]:
        """Merged (job, run_id, step) rollups from per-host
        tpu_step_metrics records, plus federation info when the query
        scattered. The shard partial is a plain row SELECT — each host's
        record lands on exactly one shard, so the union of shard rows is
        the exact single-node row set and merge_host_partials on top is
        federation-exact."""
        from deepflow_tpu.server import stephealth
        table = self.db.table("profile.tpu_step_metrics")
        where = []
        if body.get("job"):
            job = str(body["job"]).replace("'", "")
            where.append(f"job = '{job}'")
        if body.get("run_id") is not None:
            where.append(f"run_id = {int(body['run_id'])}")
        if body.get("time_start"):
            where.append(f"time >= {int(body['time_start'])}")
        if body.get("time_end"):
            where.append(f"time < {int(body['time_end'])}")
        sql_text = f"SELECT {self._STEP_COLS} FROM t"
        if where:
            sql_text += f" WHERE {' AND '.join(where)}"
        fed = self._fed()
        info = None
        if fed is not None:
            res, info = fed.sql_query(table, qsql.parse(sql_text),
                                      sql_text)
        else:
            res = qengine.execute(table, sql_text)
        cols = res.columns
        rows = [dict(zip(cols, row)) for row in res.values]
        return stephealth.merge_host_partials(rows), info

    def tpu_steps(self, body: dict) -> dict:
        """Per-step health timeline: merged pod-level rollups annotated by
        the same EWMA+MAD scorer the live StepRegressionDetector runs, so
        what a human reads here agrees with the alerts that fired."""
        from deepflow_tpu.server import stephealth
        rollups, info = self._step_rollups(body)
        scored = stephealth.score_timeline(rollups)
        limit = int(body.get("limit", 500))
        out = {"result": {"steps": scored[-limit:],
                          "total_steps": len(scored)}}
        if info is not None:
            out["federation"] = info
        return out

    def tpu_step_critical_path(self, body: dict) -> dict:
        """Critical-path attribution for ONE step: where its latency went
        (per-device compute vs collective wait vs device skew) relative to
        a rolling baseline of the healthy steps before it, naming the
        straggler device/host and the dominant HLOs by delta."""
        from deepflow_tpu.server import stephealth
        rollups, info = self._step_rollups(body)
        if not rollups:
            raise qengine.QueryError("no step records in window")
        want_run = body.get("run_id")
        want_step = body.get("step")
        idx = len(rollups) - 1
        if want_step is not None:
            idx = next(
                (i for i, r in enumerate(rollups)
                 if r["step"] == int(want_step)
                 and (want_run is None or r["run_id"] == int(want_run))),
                -1)
            if idx < 0:
                raise qengine.QueryError(
                    f"step {want_step} not found in window")
        target = rollups[idx]
        # baseline = the healthy steps BEFORE the target, per the same
        # streaming scorer — the target itself never pollutes it
        sc = stephealth.EwmaMad()
        for r in rollups[:idx]:
            if r["job"] == target["job"]:
                sc.feed(r)
        att = stephealth.attribute(target, sc.baseline())
        out = {"result": {"step": target, "attribution": att}}
        if info is not None:
            out["federation"] = info
        return out

    def _tpu_span_rows(self, body: dict,
                       collectives_only: bool = False) -> list[dict]:
        table = self.db.table("profile.tpu_hlo_span")
        where = ["duration_ns > 0"]
        if collectives_only:
            where.append("collective != ''")
        if body.get("time_start"):
            where.append(f"time >= {int(body['time_start'])}")
        if body.get("time_end"):
            where.append(f"time < {int(body['time_end'])}")
        sql_text = (
            "SELECT time, duration_ns, device_id, core_id, hlo_op, "
            "collective, run_id, bytes_transferred, replica_group_size, "
            "step, host, slice_id, tpu_pod FROM t "
            f"WHERE {' AND '.join(where)}")
        res = qengine.execute(table, sql_text)
        cols = res.columns
        return [dict(zip(cols, row)) for row in res.values]

    def orgs_api(self, body: dict) -> dict:
        """Org/team scoping admin (reference: controller/db org model):
        assign an agent group to an org; list assignments. Scoped reads
        pass org_id on /v1/query and the PromQL endpoints — cooperative
        view filtering only, not tenant isolation (docs/SECURITY.md)."""
        if self.controller is None:
            raise qengine.QueryError("no controller")
        action = body.get("action", "list")
        if action == "assign":
            group = body.get("group", "default")
            try:
                org = int(body.get("org_id", 1))
            except (TypeError, ValueError):
                raise qengine.QueryError("org_id must be an integer")
            if org < 1 or org > 0xFFFF:
                raise qengine.QueryError("org_id out of range (1..65535)")
            self.controller.assign_org(group, org)
        return {"orgs": self.controller.org_assignments(),
                "default_org": 1}

    def qos_api(self, body: dict) -> dict:
        """Multi-tenant QoS admin (deepflow_tpu/qos): list per-tenant
        weights/quotas/pressure, or set a tenant's policy (hot-applied
        to the live admission queues). Backs `dfctl qos`."""
        if self.qos is None or not self.qos.enabled:
            return {"enabled": False, "tenants": {}}
        action = body.get("action", "list")
        if action == "set":
            from deepflow_tpu.qos import TenantQos
            try:
                org = int(body.get("org_id", 0))
            except (TypeError, ValueError):
                raise qengine.QueryError("org_id must be an integer")
            if org < 1 or org > 0xFFFF:
                raise qengine.QueryError("org_id out of range (1..65535)")
            cfg = self.qos.config
            cur = cfg.tenant(org)
            t = TenantQos.from_dict({
                "org_id": org,
                "weight": body.get("weight", cur.weight),
                "rate_fps": body.get("rate_fps", cur.rate_fps),
                "burst": body.get("burst", cur.burst)})
            cfg.set_tenant(t)
            self.qos.reconfigure(cfg)
        out = self.qos.snapshot()
        if self.drop_attribution is not None:
            out["drops"] = self.drop_attribution()
        return out

    def _require_token(self, token: str | None, what: str) -> None:
        """Reject a gated control-plane action unless the caller presented
        the shared token (no-op when no token is configured — localhost
        trust, see docs/SECURITY.md)."""
        if self.api_token and (token or "") != self.api_token:
            raise AuthError(f"{what} requires a valid API token "
                            "(X-DF-Token header or token field)")

    def repo_api(self, body: dict, token: str | None = None) -> dict:
        """Agent package repo (reference: deepflow-ctl repo agent
        upload): upload versioned packages for OTA rollout; list them.
        Rollout = `dfctl exec <agent> upgrade version=vX`."""
        if self.controller is None:
            raise qengine.QueryError("no controller")
        action = body.get("action", "list")
        if action == "upload":
            # uploads feed the OTA path: an unauthenticated upload would
            # be remote code execution on every agent that upgrades
            self._require_token(token, "/v1/repo upload")
            import base64
            try:
                data = base64.b64decode(body.get("data_b64", ""),
                                        validate=True)
            except Exception:
                raise qengine.QueryError("data_b64 is not valid base64")
            try:
                info = self.controller.packages.upload(
                    body.get("name", "agent"),
                    body.get("version", ""), data)
            except ValueError as e:
                raise qengine.QueryError(str(e))
            return {"uploaded": info,
                    "packages": self.controller.packages.list()}
        return {"packages": self.controller.packages.list()}

    def _prom_db(self):
        """The db handed to promql.evaluate: the federated shim when
        peers are alive (raw selectors fan out, the AST still evaluates
        here — exact), else the plain local store."""
        fed = self._fed()
        return fed.prom_db() if fed is not None else self.db

    @staticmethod
    def _prom_annotate(out: dict, db) -> dict:
        missing = sorted(getattr(db, "missing_shards", ()))
        info = dict(getattr(db, "fed_info", None) or {})
        # annotate only when there is something to say — a fully healthy
        # federated answer stays byte-identical to a standalone one
        if missing or info.get("covered_shards"):
            info["missing_shards"] = missing
            out["federation"] = info
        if missing:
            out.setdefault("warnings", []).append(
                f"partial result: shards {missing} did not answer")
        return out

    def prom_query_range(self, params: dict) -> dict:
        """GET /prom/api/v1/query_range (reference: querier/app/prometheus,
        router.go:41)."""
        from deepflow_tpu.query import promql
        q = params.get("query", "")
        try:
            start = int(float(params.get("start", 0)))
            end = int(float(params.get("end", 0)))
            step = max(1, int(float(params.get("step", 15))))
        except ValueError as e:
            raise qengine.QueryError(f"bad time param: {e}")
        db = self._prom_db()
        with self.qtracer.start_trace("query", kind="promql",
                                      promql=q[:200]):
            try:
                ast = promql.parse(q)
                if params.get("org_id") is not None:
                    promql.scope_to_org(ast, int(params["org_id"]))
                with qtrace.span("execute", path="promql_range",
                                 step=step):
                    result = promql.evaluate(db, ast, start, end, step)
            except promql.PromqlError as e:
                return {"status": "error", "errorType": "bad_data",
                        "error": str(e)}
        return self._prom_annotate(
            {"status": "success",
             "data": {"resultType": "matrix", "result": result}}, db)

    def prom_query(self, params: dict) -> dict:
        """GET /prom/api/v1/query — instant queries (reference:
        querier/app/prometheus/router/router.go:40)."""
        import time as _time

        from deepflow_tpu.query import promql
        q = params.get("query", "")
        try:
            t = int(float(params.get("time", _time.time())))
        except ValueError as e:
            raise qengine.QueryError(f"bad time param: {e}")
        db = self._prom_db()
        with self.qtracer.start_trace("query", kind="promql",
                                      promql=q[:200]):
            try:
                ast = promql.parse(q)
                if params.get("org_id") is not None:
                    promql.scope_to_org(ast, int(params["org_id"]))
                with qtrace.span("execute", path="promql_instant"):
                    data = promql.evaluate_instant(db, ast, t)
            except promql.PromqlError as e:
                return {"status": "error", "errorType": "bad_data",
                        "error": str(e)}
        return self._prom_annotate({"status": "success", "data": data}, db)

    def _prom_meta_args(self, params: dict) -> tuple:
        """params is a parse_qs dict (every value a list — match[] can
        repeat). Defaults: the last hour."""
        import time as _time
        matches = params.get("match[]", [])
        now = int(_time.time())
        try:
            start = int(float(params.get("start", [now - 3600])[0]))
            end = int(float(params.get("end", [now])[0]))
        except (ValueError, IndexError) as e:
            raise qengine.QueryError(f"bad time param: {e}")
        return matches, start, end

    def prom_series(self, params: dict) -> dict:
        """GET /prom/api/v1/series (reference: querier/app/prometheus
        series API — Grafana variable queries)."""
        from deepflow_tpu.query import promql
        matches, start, end = self._prom_meta_args(params)
        if not matches:
            return {"status": "error", "errorType": "bad_data",
                    "error": "no match[] parameter"}
        db = self._prom_db()  # series() goes through fetch_raw: federates
        try:
            return self._prom_annotate(
                {"status": "success",
                 "data": promql.series(db, matches, start, end)}, db)
        except promql.PromqlError as e:
            return {"status": "error", "errorType": "bad_data",
                    "error": str(e)}

    def prom_labels(self, params: dict) -> dict:
        from deepflow_tpu.query import promql
        matches, start, end = self._prom_meta_args(params)
        # with match[]: goes through series() -> fetch_raw, so the shim
        # federates it; without matches, metadata stays LOCAL by design
        # (schema is identical cluster-wide — docs/CLUSTER.md)
        db = self._prom_db() if matches else self.db
        try:
            return self._prom_annotate(
                {"status": "success",
                 "data": promql.label_names(db, matches, start, end)}, db)
        except promql.PromqlError as e:
            return {"status": "error", "errorType": "bad_data",
                    "error": str(e)}

    def prom_label_values(self, label: str, params: dict) -> dict:
        from deepflow_tpu.query import promql
        matches, start, end = self._prom_meta_args(params)
        db = self._prom_db() if matches else self.db
        try:
            return self._prom_annotate(
                {"status": "success",
                 "data": promql.label_values(db, label, matches,
                                             start, end)}, db)
        except promql.PromqlError as e:
            return {"status": "error", "errorType": "bad_data",
                    "error": str(e)}

    _TEMPO_DUR = {"ns": 1, "us": 1e3, "µs": 1e3, "ms": 1e6, "s": 1e9,
                  "m": 60e9, "h": 3600e9}

    @classmethod
    def _tempo_duration_ns(cls, s: str) -> int:
        import re as _re
        m = _re.match(r"^([\d.]+)(ns|us|µs|ms|s|m|h)$", s.strip())
        if not m:
            raise qengine.QueryError(f"bad duration {s!r}")
        return int(float(m.group(1)) * cls._TEMPO_DUR[m.group(2)])

    _TEMPO_TAGS = ("service.name", "endpoint", "l7.protocol",
                   "http.status_code")

    def _tempo_scan(self, params: dict, db=None) -> list[dict]:
        """Shard-local Tempo scan: one partial dict per trace seen HERE.
        Tags select per-SPAN, but start/end/duration are per-TRACE and a
        trace's spans may live on several shards — so duration filters
        and the limit must NOT apply here; only at the merge/finalize.
        db: an optional claim-filtered view (replication) to scan
        instead of the raw local store."""
        import re as _re
        import time as _time
        tags = {}
        for k, v_quoted, v_plain in _re.findall(
                r'([\w.]+)=(?:"([^"]*)"|(\S+))', params.get("tags", "")):
            tags[k] = v_quoted or v_plain
        for k in tags:
            if k not in self._TEMPO_TAGS:
                raise qengine.QueryError(
                    f"unsupported search tag {k!r}; known: "
                    f"{sorted(self._TEMPO_TAGS)}")
        where = ["trace_id != ''"]
        # a search must ALWAYS have a lower bound (a bare or end-only
        # request must not scan all history): default start is one hour
        # before end (or before now)
        if params.get("start"):
            start_ts = int(float(params["start"]))
        else:
            ref = (int(float(params["end"])) if params.get("end")
                   else int(_time.time()))
            start_ts = ref - 3600
        where.append(f"time >= {start_ts * 1_000_000_000}")
        if params.get("end"):
            where.append(
                f"time < {int(float(params['end'])) * 1_000_000_000}")
        table = (db if db is not None else self.db).table(
            "flow_log.l7_flow_log")
        res = qengine.execute(
            table,
            "SELECT time, trace_id, app_service, request_type, endpoint, "
            "response_duration, l7_protocol, response_code FROM t "
            "WHERE " + " AND ".join(where))
        traces: dict[str, dict] = {}
        for t, tid, svc, rtype, ep, dur, proto, code in res.values:
            t, dur = int(t), int(dur)
            span_tags = {"service.name": svc or "", "endpoint": ep or "",
                         "l7.protocol": str(proto),
                         "http.status_code": str(int(code))}
            matched = all(span_tags.get(k) == v for k, v in tags.items())
            tr = traces.get(tid)
            if tr is None:
                tr = traces[tid] = {
                    "traceID": tid, "_start_ns": t, "_end_ns": t + dur,
                    "spanCount": 1,
                    "rootServiceName": svc or "",
                    "rootTraceName": f"{rtype} {ep}".strip() or tid,
                    "_root_t": t, "_matched": matched}
            else:
                tr["_start_ns"] = min(tr["_start_ns"], t)
                tr["_end_ns"] = max(tr["_end_ns"], t + dur)
                tr["spanCount"] += 1
                tr["_matched"] = tr["_matched"] or matched
                if t < tr["_root_t"]:
                    tr["_root_t"] = t
                    tr["rootServiceName"] = svc or ""
                    tr["rootTraceName"] = f"{rtype} {ep}".strip() or tid
        # dogfood: the querier's own query traces (self-monitoring
        # store) surface through the SAME search API as workload traces
        self.qtracer.flush()
        qt = self.db.table("deepflow_system.query_trace")
        if len(qt):
            qres = qengine.execute(
                qt, "SELECT time, trace_id, parent_span_id, name, "
                    "service, duration_ns, status FROM t "
                    "WHERE " + " AND ".join(where))
            for t, tid, psid, name, svc, dur, status in qres.values:
                t, dur = int(t), int(dur)
                span_tags = {"service.name": svc or "", "endpoint": "",
                             "l7.protocol": "query",
                             "http.status_code": str(status)}
                matched = all(span_tags.get(k) == v
                              for k, v in tags.items())
                tr = traces.get(tid)
                if tr is None:
                    tr = traces[tid] = {
                        "traceID": tid, "_start_ns": t,
                        "_end_ns": t + dur, "spanCount": 1,
                        "rootServiceName": svc or "",
                        "rootTraceName": name or tid,
                        "_root_t": t, "_matched": matched}
                else:
                    tr["_start_ns"] = min(tr["_start_ns"], t)
                    tr["_end_ns"] = max(tr["_end_ns"], t + dur)
                    tr["spanCount"] += 1
                    tr["_matched"] = tr["_matched"] or matched
                if psid == "":
                    # the coordinator root names the trace regardless
                    # of span arrival order
                    tr["_root_t"] = t
                    tr["rootServiceName"] = svc or ""
                    tr["rootTraceName"] = name or tid
        return list(traces.values())

    def tempo_search(self, params: dict) -> dict:
        """GET /api/search — Tempo search API (reference: querier/tempo):
        logfmt tags filter, min/maxDuration, time range, limit.

        Tempo semantics: tags select traces (any single span matching ALL
        tags qualifies the trace), but root/start/duration report the
        WHOLE trace — so the scan keeps every span of the window and
        filters at the trace level (cluster: after the cross-shard
        merge)."""
        limit = max(1, min(int(params.get("limit", 20)), 500))
        min_ns = (self._tempo_duration_ns(params["minDuration"])
                  if params.get("minDuration") else 0)
        max_ns = (self._tempo_duration_ns(params["maxDuration"])
                  if params.get("maxDuration") else 0)
        fed = self._fed()
        info = None
        with self.qtracer.start_trace("query", kind="tempo",
                                      tags=params.get("tags", "")):
            if fed is not None:
                with qtrace.span("execute", path="federation"):
                    traces, info = fed.tempo_search(
                        self._tempo_scan, params)
            else:
                with qtrace.span("execute", path="local"):
                    traces = self._tempo_scan(params)
        out = []
        for tr in traces:
            if not tr["_matched"]:
                continue
            dur_ns = tr["_end_ns"] - tr["_start_ns"]
            if min_ns and dur_ns < min_ns:
                continue
            if max_ns and dur_ns > max_ns:
                continue
            out.append({"traceID": tr["traceID"],
                        "rootServiceName": tr["rootServiceName"],
                        "rootTraceName": tr["rootTraceName"],
                        "startTimeUnixNano": str(tr["_start_ns"]),
                        "durationMs": dur_ns // 1_000_000})
        out.sort(key=lambda tr: -int(tr["startTimeUnixNano"]))
        resp = {"traces": out[:limit], "metrics": {
            "inspectedTraces": len(traces)}}
        if info is not None:
            resp["federation"] = info
        return resp

    def tempo_search_tags(self) -> dict:
        return {"tagNames": list(self._TEMPO_TAGS)}

    def tempo_search_tag_values(self, name: str) -> dict:
        """Values come from live rows (chunk scan), not dictionary
        snapshots: retention-trimmed services must not keep appearing."""
        from deepflow_tpu.query.promql import _codes_in_range
        table = self.db.table("flow_log.l7_flow_log")
        lo, hi = 0, 1 << 62
        if name in ("service.name", "endpoint"):
            col = "app_service" if name == "service.name" else "endpoint"
            d = table.dicts[col]
            vals = []
            for c in _codes_in_range(table, col, lo, hi):
                try:
                    s = d.decode(c)
                except IndexError:
                    continue
                if s:
                    vals.append(s)
        elif name == "l7.protocol":
            enum = table.columns["l7_protocol"].enum_values
            vals = [enum[c] for c in _codes_in_range(
                table, "l7_protocol", lo, hi)
                if 0 <= c < len(enum) and enum[c]]
        elif name == "http.status_code":
            vals = [str(c) for c in sorted(_codes_in_range(
                table, "response_code", lo, hi)) if c]
        else:
            vals = []
        return {"tagValues": sorted(vals)}

    def tempo_trace(self, trace_id: str) -> dict:
        """GET /api/traces/{id} — Grafana Tempo-compatible shape
        (reference: querier/tempo)."""
        tree = self._assemble_trace(trace_id)
        spans = []

        def walk(node, parent_id=""):
            spans.append({
                "traceID": trace_id,
                "spanID": node["span_id"],
                "parentSpanID": parent_id,
                "operationName": node["name"],
                "serviceName": node["service"],
                "startTimeUnixNano": str(node["start_ns"]),
                "durationNano": str(node["duration_ns"]),
                "tags": [{"key": "l7_protocol",
                          "value": node["l7_protocol"]},
                         {"key": "status", "value": node["status"]},
                         {"key": "kind", "value": node["kind"]}],
            })
            for c in node["children"]:
                walk(c, node["span_id"])

        for root in tree["spans"]:
            walk(root)
        return {"batches": [{"spans": spans}]}

    def trace(self, body: dict) -> dict:
        """Distributed trace tree by trace_id (reference: tracemap), or by
        syscall chain id for uprobe-sourced flows without W3C headers."""
        trace_id = body.get("trace_id", "")
        syscall_id = body.get("syscall_trace_id")
        if syscall_id is not None:
            try:
                syscall_id = int(syscall_id)
            except (TypeError, ValueError):
                raise qengine.QueryError(
                    f"bad syscall_trace_id {syscall_id!r}") from None
            from deepflow_tpu.query.tracing import build_syscall_trace
            return {"result": build_syscall_trace(
                self.db.table("flow_log.l7_flow_log"), syscall_id)}
        if not trace_id:
            raise qengine.QueryError("trace_id or syscall_trace_id required")
        tree = self._assemble_trace(trace_id)
        # tracing adapter: splice spans from configured EXTERNAL backends
        tree = self.trace_adapters.merge_into(tree, trace_id)
        return {"result": tree}

    def collect_trace_spans(self, trace_id: str, db=None) -> list[dict]:
        """This shard's span dicts for one trace. Prefers the ingest-time
        precompute (flow_log.trace_tree rows + TraceTreeBuilder pending
        spans): touches only this trace's data. Falls back to the l7 scan
        for data ingested before the builder existed (e.g. loaded from an
        old data_dir). db: optional claim-filtered view (replication) —
        either way replica span copies also dedup at assembly by
        (span_id, start_ns, flow_id)."""
        import json as _json

        import numpy as np

        from deepflow_tpu.query.tracing import scan_trace_spans
        if db is None:
            db = self.db
        spans: list[dict] = []
        tree_table = db.table("flow_log.trace_tree")
        code = tree_table.dicts["trace_id"].lookup(trace_id)
        if code is not None:
            for ch in tree_table.snapshot():
                if not ch:
                    continue
                for i in np.flatnonzero(ch["trace_id"] == code).tolist():
                    spans.extend(_json.loads(
                        tree_table.dicts["tree"].decode(int(ch["tree"][i]))))
        if self.trace_trees is not None:
            spans.extend(self.trace_trees.pending_spans(trace_id))
        if not spans:
            spans = scan_trace_spans(
                db.table("flow_log.l7_flow_log"), trace_id)
        # dogfooded query traces live in the self-monitoring store, NOT
        # the flow store — union them so /api/traces and /v1/trace
        # render the querier's own spans like any workload's
        spans.extend(self._query_trace_spans(trace_id))
        return spans

    def _query_trace_spans(self, trace_id: str) -> list[dict]:
        """This node's deepflow_system.query_trace span dicts for one
        trace (+ the tracer's unflushed pending rows: read-your-writes
        for a trace completed microseconds ago)."""
        import numpy as np
        qt = self.db.table("deepflow_system.query_trace")
        code = qt.dicts["trace_id"].lookup(trace_id)
        rows: list[dict] = []
        if code is not None:
            for ch in qt.snapshot():
                if not ch:
                    continue
                for i in np.flatnonzero(ch["trace_id"] == code).tolist():
                    row = {}
                    for name, arr in ch.items():
                        spec = qt.columns[name]
                        v = arr[i]
                        if spec.kind == "str":
                            row[name] = qt.dicts[name].decode(int(v))
                        elif spec.kind == "enum":
                            row[name] = spec.enum_values[int(v)]
                        else:
                            row[name] = int(v)
                    rows.append(row)
        spans = qtrace.spans_from_rows(rows)
        spans.extend(self.qtracer.pending_spans(trace_id))
        return spans

    def _assemble_trace(self, trace_id: str, max_spans: int = 1000) -> dict:
        """One trace's tree: this shard's spans, plus — when peers are
        alive — every other shard's (one trace's spans may be ingested
        anywhere; build_trace_from_spans dedups on the merged set)."""
        from deepflow_tpu.query.tracing import build_trace_from_spans
        fed = self._fed()
        info = None
        if fed is not None:
            spans, info = fed.trace_spans(self.collect_trace_spans,
                                          trace_id)
        else:
            spans = self.collect_trace_spans(trace_id)
        tree = build_trace_from_spans(
            trace_id, spans,
            tpu_table=self.db.table("profile.tpu_hlo_span"),
            max_spans=max_spans)
        if info is not None:
            tree["federation"] = info
        return tree

    def log_search(self, body: dict) -> dict:
        """Search over the dedicated application_log.log store (reference:
        server/ingester/app_log + querier log queries). Filters:
        app_service, trace_id, min_severity, body substring (pushed down
        onto the body dictionary, not the rows), time range, limit.
        Newest rows first."""
        import numpy as np
        body = body or {}
        t = self.db.table("application_log.log")
        limit = max(1, min(10_000, int(body.get("limit", 100) or 100)))
        svc = body.get("app_service")
        needle = body.get("query") or body.get("body_contains")
        trace_id = body.get("trace_id")
        min_sev = int(body.get("min_severity", 0) or 0)
        t_from = int(body.get("from_ns", 0) or 0)
        t_to = int(body.get("to_ns", 0) or 0)
        empty = {"result": {"logs": [], "count": 0}}
        body_ids = None
        if needle:
            needle_l = str(needle).lower()
            body_ids = t.dicts["body"].match_ids(
                lambda s: needle_l in s.lower())
            if not len(body_ids):
                return empty
        svc_id = t.dicts["app_service"].lookup(str(svc)) if svc else None
        if svc and svc_id is None:
            return empty
        tid_id = (t.dicts["trace_id"].lookup(str(trace_id))
                  if trace_id else None)
        if trace_id and tid_id is None:
            return empty
        names = ("time", "app_service", "app_instance", "severity_number",
                 "severity_text", "body", "trace_id", "span_id", "attrs")
        # chunks are NOT globally time-ordered: concurrent HTTP handler
        # threads write through per-thread stripes, so newest-first needs
        # an explicit sort over the matches, not reversed chunk order
        chunks = t.snapshot()
        cand: list[tuple[int, int, int]] = []
        for ci, ch in enumerate(chunks):
            if not ch:
                continue
            mask = np.ones(len(ch["time"]), dtype=bool)
            if t_from:
                mask &= ch["time"] >= t_from
            if t_to:
                mask &= ch["time"] < t_to
            if svc_id is not None:
                mask &= ch["app_service"] == svc_id
            if tid_id is not None:
                mask &= ch["trace_id"] == tid_id
            if min_sev:
                mask &= ch["severity_number"] >= min_sev
            if body_ids is not None:
                mask &= np.isin(ch["body"], body_ids)
            times = ch["time"]
            for i in np.flatnonzero(mask).tolist():
                cand.append((int(times[i]), ci, i))
        cand.sort(key=lambda c: (-c[0], -c[1], -c[2]))
        out: list[dict] = []
        for _tm, ci, i in cand[:limit]:
            ch = chunks[ci]
            row = {}
            for n in names:
                v = ch[n][i]
                row[n] = (t.dicts[n].decode(int(v)) if n in t.dicts
                          else int(v))
            out.append(row)
        return {"result": {"logs": out, "count": len(out)}}

    def trace_search(self, body: dict) -> dict:
        """Service-path search over precomputed trace trees (reference:
        trace_tree service-path queries). Body: {service_path: [..],
        root_service, from_ns, to_ns, min_duration_ns, limit}."""
        from deepflow_tpu.server import tracetree as tt
        body = body or {}
        path = body.get("service_path") or []
        if isinstance(path, str):
            path = [p for p in path.split(">") if p]
        pending = (self.trace_trees.pending_summaries()
                   if self.trace_trees is not None else None)
        hits = tt.search(
            self.db.table("flow_log.trace_tree"),
            service_path_query=[str(p) for p in path],
            root_service=body.get("root_service"),
            time_from_ns=int(body.get("from_ns", 0) or 0),
            time_to_ns=int(body.get("to_ns", 0) or 0),
            min_duration_ns=int(body.get("min_duration_ns", 0) or 0),
            limit=int(body.get("limit", 50) or 50),
            pending=pending)
        return {"result": {"traces": hits, "count": len(hits)}}

    def tracing_adapters_api(self, body: dict | None = None) -> dict:
        if body and body.get("remove"):
            return {"removed": self.trace_adapters.remove(
                str(body["remove"])),
                "adapters": self.trace_adapters.list()}
        if body and body.get("kind"):
            try:
                self.trace_adapters.add(str(body["kind"]),
                                        str(body.get("base_url", "")))
            except ValueError as e:
                raise qengine.QueryError(str(e)) from None
        return {"adapters": self.trace_adapters.list()}

    def pcaps(self, body: dict | None = None) -> dict:
        store = getattr(self.db, "pcap_store", None)
        entries = list(store["entries"]) if store else []
        if body and body.get("name"):
            import base64
            import os
            for e in entries:
                if e["name"] == body["name"]:
                    data = e.get("data")
                    if data is None and e.get("path") and \
                            os.path.exists(e["path"]):
                        with open(e["path"], "rb") as f:
                            data = f.read()
                    if data is None:
                        raise qengine.QueryError("capture data gone")
                    return {"name": e["name"],
                            "pcap_gz_b64":
                                base64.b64encode(data).decode()}
            raise qengine.QueryError(f"no capture {body['name']!r}")
        return {"pcaps": [{k: v for k, v in e.items()
                           if k not in ("data",)} for e in entries]}

    def analyzers_api(self, body: dict | None = None) -> dict:
        if self.controller is None:
            raise qengine.QueryError("no controller")
        if body and "addrs" in body:
            addrs = [str(a) for a in body["addrs"]]
            try:
                self.controller.set_analyzers(addrs)
            except ValueError as e:
                raise qengine.QueryError(f"bad analyzer address: {e}") \
                    from None
        return {"analyzers": self.controller.analyzers()}

    def agent_exec(self, body: dict, token: str | None = None) -> dict:
        """Queue a registry command for an agent; poll with result_id."""
        if self.controller is None:
            raise qengine.QueryError("no controller")
        if "result_id" in body:
            r = self.controller.commands.result(int(body["result_id"]))
            if r is None:
                raise qengine.QueryError("unknown result_id")
            return {"result": r}
        agent_id = int(body.get("agent_id", 0))
        cmd = str(body.get("cmd", ""))
        if not agent_id or not cmd:
            raise qengine.QueryError("agent_id and cmd required")
        if cmd == "upgrade":
            # `upgrade` makes the agent re-exec a repo package: it is the
            # other half of the OTA code-execution path — same gate
            self._require_token(token, "the `upgrade` exec command")
        cid = self.controller.commands.submit(
            agent_id, cmd, [str(a) for a in body.get("args", [])])
        return {"result_id": cid}

    def agents(self) -> dict:
        """Agent fleet listing with health (reference: deepflow-ctl agent
        list / cli/ctl/agent.go:49 — staleness, exception bitmap, degraded
        state are the primary ops signals)."""
        if self.controller is None:
            return {"agents": []}
        import time as _time
        now = _time.time_ns()
        out = []
        for a in self.controller.registry.list():
            staleness_s = (now - a.get("last_seen_ns", now)) / 1e9
            out.append({
                "agent_id": a["agent_id"],
                "hostname": a["hostname"],
                "ctrl_ip": a["ctrl_ip"],
                "last_seen_ns": a.get("last_seen_ns", 0),
                "staleness_s": round(staleness_s, 1),
                "stale": staleness_s > 60.0,
                "state": a.get("state", 0),
                "exception_bitmap": a.get("exception_bitmap", 0),
                "degraded": a.get("degraded", False),
                "version": a.get("version", ""),
                "cpu_usage": a.get("cpu_usage", 0.0),
                "mem_bytes": a.get("mem_bytes", 0),
                "agent_group": a.get("agent_group", "default"),
                "config_version": a.get("config_version", 0),
                "syncs": a.get("syncs", 0),
            })
        return {"agents": out}

    def update_agent_config(self, body: dict) -> dict:
        if self.controller is None:
            raise qengine.QueryError("controller not running")
        group = body.get("group", "default")
        yaml_text = body.get("yaml", "")
        version = self.controller.configs.update(group, yaml_text.encode())
        return {"group": group, "version": version}

    # -- cluster (scatter-gather) endpoints ---------------------------------

    def shard_exec(self, body: dict, token: str | None = None) -> dict:
        """POST /v1/shard/exec — the shard-local half of every federated
        query. Execution here is STRICTLY local (never re-fans-out, even
        with peers alive): the coordinator is the only merge point, so a
        cycle of shards can't amplify one query."""
        self._require_token(token, "/v1/shard/exec")
        op = body.get("op", "")
        # a replication-aware coordinator ships a ring snapshot + alive
        # set in the body; answer from the claim-filtered view so each
        # replicated row is reported by exactly one alive owner. A
        # pre-replication coordinator sends no ring: raw local answer.
        from deepflow_tpu.cluster.hashring import claim_db_from_body
        db = claim_db_from_body(body, self.db, self.shard_id)
        # a traced coordinator ships its trace context in the body: this
        # shard's spans join the SAME trace, parented under the
        # coordinator's scatter span, and land in the shard-local
        # query_trace table — read-time trace assembly unions them
        from deepflow_tpu.cluster import wire as _wire
        with self.qtracer.adopt(_wire.extract_ctx(body), "shard.exec",
                                op=op, shard=self.shard_id):
            return self._shard_exec_op(body, db, op)

    def _shard_exec_op(self, body: dict, db, op: str) -> dict:
        if op == "sql_partial":
            table = (db.table(body["table"]) if body.get("table")
                     else self._resolve_table("", ""))
            select = qsql.parse_statement(body.get("sql", ""))
            if not isinstance(select, qsql.Select):
                raise qengine.QueryError("sql_partial needs a SELECT")
            org = body.get("org_id")
            if org is not None:
                # the coordinator's org filter lives in its AST, not the
                # SQL text — re-inject it here from the op body
                self._org_scope(select, table, org)
            if not body.get("enc"):
                # pre-encoding coordinator: decoded partial, old wire form
                out = qengine.execute_partial(table, select)
            else:
                out = self._sql_partial_enc(body, table, select, org)
            # shard-side degraded marker: computed fresh per call (an
            # unchanged-token short-circuit reply still reports a NEW
            # quarantine), merged by the coordinator into
            # federation.degraded_shards
            deg = self._degraded_for(str(body.get("table") or ""))
            if deg is not None:
                out["degraded"] = deg
            return out
        if op == "promql_raw":
            from deepflow_tpu.query import promql
            vs = promql.VectorSelector(
                metric=str(body.get("metric", "")),
                matchers=[tuple(m) for m in body.get("matchers", [])])
            try:
                series = promql.fetch_raw(db, vs,
                                          float(body.get("lo_s", 0)),
                                          float(body.get("hi_s", 0)))
            except promql.UnknownMetricError:
                return {"unknown": True}
            return {"series": [
                {"labels": s.labels, "t": s.t.tolist(), "v": s.v.tolist(),
                 "counter": bool(s.counter)} for s in series]}
        if op == "tempo_scan":
            return {"traces": self._tempo_scan(body.get("params") or {},
                                               db)}
        if op == "trace_spans":
            return {"spans": self.collect_trace_spans(
                str(body.get("trace_id", "")), db)}
        if op == "profile_flame":
            return self._flame_stacks(body.get("params") or {}, db)
        if op == "table_counts":
            return {name: len(self.db.table(name))
                    for name in self.db.tables()}
        raise qengine.QueryError(f"unknown shard op {op!r}")

    def _sql_partial_enc(self, body: dict, table, select: qsql.Select,
                         org) -> dict:
        """Encoded half of a v2 sql_partial: change-token short-circuit,
        bucket-cached encoded partial, and the dictionary delta the
        coordinator needs to remap our ids (cluster/dictsync.py)."""
        # claim filtering answers for different rows under a different
        # ring/alive set even when the table itself is unchanged — fold
        # the ring context into both the change token and the cache key
        ring = body.get("ring") or {}
        ring_ctx = None if not ring else [
            ring.get("epoch"), ring.get("token"),
            sorted(int(s) for s in body.get("alive") or [])]
        # publish-gen handshake: a read-tier coordinator names the
        # pointer generation it adopted from us. On a gen match, answer
        # WITHOUT the published segments — the coordinator serves those
        # from the object store — so each sealed row is counted exactly
        # once. On a mismatch (it adopted an older pointer, or none)
        # answer in full; the coordinator drops our adopted segments
        # from its own scan instead.
        rt_req = (body.get("readtier") or {}).get(str(self.shard_id))
        rt_ack = None
        if rt_req is not None and self.publisher is not None:
            gen, fn_sets = self.publisher.current
            if int(rt_req) == gen:
                fns = fn_sets.get(table.name)
                if fns:
                    from deepflow_tpu.store.segcache import \
                        PublishedExcludeView
                    view = PublishedExcludeView(table, fns)
                    # a compaction/eviction may have retired published
                    # fns before the next publish tick moves `current`;
                    # excluding then would leave the replacement run
                    # (same rows) in our answer while the coordinator
                    # also serves the published blobs. Ack only while
                    # every published fn is still live — otherwise
                    # answer in full and let the coordinator drop our
                    # adopted segments (same path as a gen mismatch).
                    if view.complete:
                        table = view
                        rt_ack = gen
                else:
                    rt_ack = gen
        from deepflow_tpu.query.cache import change_token
        # read BEFORE computing; the exclusion context joins the token —
        # the same table state answers for different rows at a
        # different publish gen
        tok = [change_token(table), ring_ctx] + \
            ([["pub", rt_ack]] if rt_ack is not None else [])
        rt_reply = (None if rt_req is None else
                    {"gen": (self.publisher.current[0]
                             if self.publisher is not None else 0),
                     "excluded": rt_ack is not None})
        if_state = (body.get("if_state") or {}).get(str(self.shard_id))
        if if_state is not None and if_state == tok:
            out = {"kind": "unchanged", "state": tok}
            if rt_reply is not None:
                out["rt"] = rt_reply
            return out
        extra = ("fed", org, repr(ring_ctx)) + \
            ((("pub", rt_ack),) if rt_ack is not None else ())
        part = dict(self.query_cache.partial(
            table, body.get("sql", ""), select=select, extra_key=extra))
        dicts = part.get("dicts")
        if dicts:
            from deepflow_tpu.cluster.dictsync import build_sync
            known = (body.get("dict_known") or {}).get(
                str(self.shard_id)) or {}
            sync = build_sync(table, dicts, known)
            if sync is None:
                # a dictionary gen flipped between the partial build and
                # now — ids in the partial are unremappable; re-run in
                # the decoded wire form instead of shipping garbage
                # (against the SAME exclusion view, and still carrying
                # the rt ack so the coordinator's accounting holds)
                part = dict(qengine.execute_partial(table, select))
                if rt_reply is not None:
                    part["rt"] = rt_reply
                return part
            part["dict_sync"] = sync
        part["state"] = tok
        if rt_reply is not None:
            part["rt"] = rt_reply
        return part

    def cache_partial(self, body: dict, token: str | None = None) -> dict:
        """POST /v1/cache/partial — the serve side of the cluster-wide
        partial-aggregate cache (cluster/partialcache.py): hand a peer
        replica whatever warm, currently-valid bucket slices we hold
        for its (table, sql, org, pub_token) claim."""
        self._require_token(token, "/v1/cache/partial")
        if self.partial_cache is None:
            return {"buckets": {}}
        return self.partial_cache.serve(body)

    def cluster_join(self, body: dict) -> dict:
        if self.membership is None:
            raise qengine.QueryError("clustering not enabled")
        return self.membership.handle_join(body)

    def cluster_peers(self) -> dict:
        if self.membership is None:
            return {"version": 0, "peers": []}
        self.membership.refresh_self()
        return self.membership.directory.snapshot()

    def cluster_status(self) -> dict:
        """The dfctl `cluster` view: peer table with per-shard row
        counts and probe latency."""
        if self.federation is None:
            return {"shard_id": self.shard_id, "version": 0, "peers": [],
                    "fanout": {}}
        return self.federation.cluster_status()

    def segments(self, table: str | None = None,
                 v1_only: bool = False) -> dict:
        """Per-segment inspector (the `dfctl segments` backend): format
        version, rows, per-column codecs, zone/skip-index presence and
        sorted-run membership for every on-disk segment of a table (or
        all tables). ``v1_only`` filters to segments still awaiting
        format migration."""
        store = getattr(self.db, "tier_store", None)
        if store is None:
            return {"tables": {}, "storage": False}
        self.db._ensure_loaded()
        names = [table] if table else sorted(store.tables())
        tables: dict[str, list] = {}
        for name in names:
            tt = store.tier(name)
            rows = []
            for seg in tt.segments():
                if v1_only and seg.fmt >= 2:
                    continue
                codecs = seg.codecs()
                rows.append({
                    "file": os.path.basename(seg.path),
                    "format": seg.fmt,
                    "rows": seg.rows,
                    "bytes": seg.nbytes,
                    "tmin": seg.tmin, "tmax": seg.tmax,
                    "run": seg.run,
                    "sorted_by": seg.sorted_by,
                    "codecs": codecs,
                    "zoned_cols": len(seg.zones),
                    "indexed_cols": sorted(
                        c for c in codecs if seg.has_index(c)),
                })
            if rows or not v1_only:
                tables[name] = rows
        return {"tables": tables, "storage": True,
                "compact_gen": store.compact_gen}

    def fsck(self, table: str | None = None,
             repair: bool = True) -> dict:
        """On-demand integrity check (the `dfctl fsck` backend): verify
        every block checksum of every sealed local segment NOW, without
        waiting for the background scrubber's paced walk. Corrupt
        segments go through the same quarantine + repair path the
        scrubber uses (repair=False reports only). Pre-checksum (v1/
        early-v2) segments count as unverifiable, never as corrupt."""
        store = getattr(self.db, "tier_store", None)
        if store is None:
            return {"storage": False, "tables": {}}
        self.db._ensure_loaded()
        scrub = self.scrubber
        if scrub is None and repair:
            from deepflow_tpu.store.scrub import Scrubber
            scrub = Scrubber(self.db, shard_id=self.shard_id,
                             telemetry=self.telemetry)
        names = [table] if table else sorted(store.tables())
        tables: dict[str, dict] = {}
        for name in names:
            tt = store.tier(name)
            res = {"segments": 0, "blocks_checked": 0, "bytes": 0,
                   "clean": 0, "unverifiable": 0, "corrupt": [],
                   "repaired": [], "repair_failed": []}
            for seg in tt.segments():
                v = seg.verify()
                res["segments"] += 1
                res["blocks_checked"] += v["checked"]
                res["bytes"] += v["bytes"]
                if v["corrupt"]:
                    fn = os.path.basename(seg.path)
                    res["corrupt"].append({"file": fn,
                                           "blocks": v["corrupt"]})
                    if scrub is not None:
                        ok = scrub.quarantine_and_repair(
                            name, seg, f"fsck:{','.join(v['corrupt'])}")
                        res["repaired" if ok
                            else "repair_failed"].append(fn)
                elif v["verifiable"]:
                    res["clean"] += 1
                else:
                    res["unverifiable"] += 1
            q = store.quarantined().get(name)
            if q:
                res["quarantined"] = q
            tables[name] = res
        return {"storage": True, "tables": tables,
                "ok": not any(t["corrupt"] or t.get("quarantined")
                              for t in tables.values())}

    def health(self) -> dict:
        """Liveness + the self-telemetry spine: per-stage heartbeat
        status, the per-hop frame ledger (with imbalance), and wedge
        verdicts — the server's from its live Telemetry, the agents'
        mined back out of deepflow_system.deepflow_system (they run in
        other processes; the DFSTATS path is their only voice here)."""
        out = {
            "status": "ok",
            "tables": {name: len(self.db.table(name))
                       for name in self.db.tables()},
            "stats": self.stats_provider(),
        }
        out["query_cache"] = self.query_cache.snapshot()
        from deepflow_tpu.query import engine as _qengine
        from deepflow_tpu.query import pool as _qpool
        pool_stats = _qpool.stats()
        out["query"] = {
            **_qengine.scan_stats(),  # scanned/pruned segment counters
            "pool_busy": pool_stats["busy"],
            "pool_threads": pool_stats["threads"],
            "pool_dispatched": pool_stats["dispatched"],
            "degree": _qengine._DEGREE.snapshot(),
        }
        if self.storage_provider is not None:
            storage = self.storage_provider()
            if storage is not None:
                out["storage"] = storage
        if self.qos is not None:
            # overload-control state: admission queues, per-tenant
            # pressure levels, adaptive-sampling rates + the receiver's
            # per-(org, agent) drop attribution
            qos = self.qos.snapshot()
            if self.drop_attribution is not None:
                qos["drops"] = self.drop_attribution()
            out["qos"] = qos
        if self.membership is not None:
            out["cluster"] = {
                "shard_id": self.shard_id,
                "version": self.membership.directory.version,
                "peers_alive": len(self.membership.peers()),
            }
        if self.federation is not None:
            out["dict_sync"] = self.federation.dict_sync.snapshot()
            out["federation_cache"] = dict(
                self.federation.sql_cache_counters)
        if self.readtier is not None:
            # adopted publish state + the segment cache's fetch/hit/
            # miss/evict ledger (the readtier-check conservation input)
            out["readtier"] = self.readtier.snapshot()
        if self.partial_cache is not None:
            out["partial_cache"] = self.partial_cache.snapshot()
        if self.standing is not None:
            # standing queries: per-query generations/fold counters +
            # the conserved query.standing push ledger
            out["standing"] = self.standing.snapshot()
        if self.exporters is not None:
            ex = self.exporters.stats()
            if ex:
                # per-exporter counters now carry the conserved
                # exporter.<kind> hop ledger (satellite: spool evictions
                # and ship failures are accounted, never silent)
                out["exporters"] = ex
        if self.alerts is not None:
            out["alerting"] = self.alerts.snapshot()
        # dogfooded query tracing: span counters + the query.trace hop
        # ledger (emitted == delivered + dropped + pending holds, same
        # conservation law as every frame hop)
        out["query_trace"] = self.qtracer.snapshot()
        if self.publisher is not None:
            out["publish"] = dict(self.publisher.stats)
            out["publish"]["publish_gen"] = self.publisher.publish_gen
        wedged_stages: list[str] = []
        if self.telemetry is not None:
            selfmon = self.telemetry.snapshot()
            out["selfmon"] = selfmon
            out["stages"] = selfmon["stages"]
            out["pipeline"] = selfmon["pipeline"]
            out["ledger_imbalance"] = selfmon["ledger_imbalance"]
            out["wedges"] = selfmon["wedges"]
            wedged_stages += [w["stage"] for w in selfmon["wedges"]]
        from deepflow_tpu.telemetry import collect_agent_selfmon
        agents = collect_agent_selfmon(self.db)
        if (agents["pipeline"] or agents["heartbeats"]
                or agents["wedges"]):
            out["agents_selfmon"] = agents
        # an agent wedge only degrades health while it is CURRENT
        # (latest heartbeat row still says wedged=1): recovered stages
        # stop counting even though their verdict rows persist
        live = {s["stage"] for s in agents["heartbeats"].values()
                if s.get("wedged")}
        for w in agents["wedges"]:
            if w["stage"] in live or not agents["heartbeats"]:
                wedged_stages.append("agent:" + w["stage"])
        if wedged_stages:
            out["status"] = "degraded"
            out["wedged_stages"] = sorted(set(wedged_stages))
        return out


class QuerierHTTP:
    def __init__(self, api: QuerierAPI, host: str = "127.0.0.1",
                 port: int = 20416) -> None:
        self.api = api
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None

    def start(self) -> "QuerierHTTP":
        api = self.api

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug(fmt, *args)

            def _send(self, code: int, obj: dict) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _raw(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                # Telegraf's HTTP output gzips by default; dropping such a
                # body as "0 accepted" with a 200 would silence all metrics
                if raw and self.headers.get("Content-Encoding",
                                            "").lower() == "gzip":
                    import gzip
                    import zlib
                    try:
                        raw = gzip.decompress(raw)
                    except (OSError, EOFError, zlib.error) as e:
                        # client-side input error -> 400, not a 500
                        raise ValueError(f"bad gzip body: {e}") from None
                return raw

            def _body(self) -> dict:
                return json.loads(self._raw() or b"{}")

            def _token(self, body: dict | None = None) -> str | None:
                """Shared API token: X-DF-Token header, Bearer auth, or a
                `token` body field (dfctl sends the header)."""
                tok = self.headers.get("X-DF-Token")
                if tok:
                    return tok
                auth = self.headers.get("Authorization", "")
                if auth.startswith("Bearer "):
                    return auth[len("Bearer "):]
                if body is not None:
                    return body.get("token")
                return None

            def _sse(self, params: dict) -> None:
                """GET /v1/subscribe?subscriber=ID — SSE stream of
                standing-query updates (long-poll POST action=poll is
                the fallback). One `data:` line per update; comment
                keepalives every idle poll round."""
                sid = params.get("subscriber", "")
                if api.standing is None or not sid:
                    self._send(400, {"error": "subscriber required "
                                     "(POST action=subscribe first)"})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    while True:
                        out = api.standing.poll(sid, timeout_s=10.0,
                                                max_items=64)
                        for u in out["updates"]:
                            self.wfile.write(
                                b"data: " + json.dumps(u).encode()
                                + b"\n\n")
                        if not out["updates"]:
                            self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        if out["closed"]:
                            return
                except (BrokenPipeError, ConnectionResetError, OSError):
                    return  # client went away: the idle reaper cleans up

            def do_GET(self) -> None:
                from urllib.parse import parse_qsl, urlparse
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/")
                params = dict(parse_qsl(parsed.query))
                try:
                    if path == "/v1/subscribe":
                        self._sse(params)
                    elif path in ("/v1/health", "/health"):
                        self._send(200, api.health())
                    elif path == "/v1/cluster/peers":
                        self._send(200, api.cluster_peers())
                    elif path == "/v1/cluster/status":
                        self._send(200, api.cluster_status())
                    elif path == "/v1/agents":
                        self._send(200, api.agents())
                    elif path == "/v1/segments":
                        self._send(200, api.segments(
                            table=params.get("table") or None,
                            v1_only=params.get("v1") in ("1", "true")))
                    elif path == "/v1/fsck":
                        self._send(200, api.fsck(
                            table=params.get("table") or None,
                            repair=params.get("repair")
                            not in ("0", "false")))
                    elif path == "/v1/alerts":
                        self._send(200, api.alerts_api("list", {}))
                    elif path == "/v1/exporters":
                        self._send(200, {"exporters":
                                         api.exporters.stats()
                                         if api.exporters else {}})
                    elif path in ("/prom/api/v1/query_range",
                                  "/api/v1/query_range"):
                        self._send(200, api.prom_query_range(params))
                    elif path in ("/prom/api/v1/query", "/api/v1/query"):
                        self._send(200, api.prom_query(params))
                    elif path in ("/prom/api/v1/series", "/api/v1/series"):
                        from urllib.parse import parse_qs
                        self._send(200, api.prom_series(
                            parse_qs(parsed.query)))
                    elif path in ("/prom/api/v1/labels", "/api/v1/labels"):
                        from urllib.parse import parse_qs
                        self._send(200, api.prom_labels(
                            parse_qs(parsed.query)))
                    elif (path.startswith(("/prom/api/v1/label/",
                                           "/api/v1/label/"))
                          and path.endswith("/values")):
                        from urllib.parse import parse_qs
                        label = path.rsplit("/label/", 1)[1][:-len("/values")]
                        self._send(200, api.prom_label_values(
                            label, parse_qs(parsed.query)))
                    elif path.startswith("/api/traces/"):
                        self._send(200, api.tempo_trace(
                            path.rsplit("/", 1)[-1]))
                    elif path == "/api/echo":  # Tempo datasource health
                        self._send(200, {"status": "echo"})
                    elif path == "/api/search":
                        self._send(200, api.tempo_search(params))
                    elif path == "/api/search/tags":
                        self._send(200, api.tempo_search_tags())
                    elif (path.startswith("/api/search/tag/")
                          and path.endswith("/values")):
                        name = path[len("/api/search/tag/"):-len("/values")]
                        self._send(200, api.tempo_search_tag_values(name))
                    else:
                        self._send(404, {"error": f"no route {self.path}"})
                except (qengine.QueryError, ValueError) as e:
                    self._send(400, {"error": str(e)})

            def do_POST(self) -> None:
                from urllib.parse import parse_qsl, urlparse
                try:
                    parsed = urlparse(self.path)
                    if parsed.path.rstrip("/") == "/api/v1/profile/ingest":
                        self._send(200, api.integration.ingest_profile(
                            dict(parse_qsl(parsed.query)), self._raw()))
                        return
                    if parsed.path.rstrip("/") == "/api/v1/write":
                        self._send(200, api.integration.ingest_prometheus(
                            self._raw()))
                        return
                    if parsed.path.rstrip("/") == "/api/v1/telegraf":
                        self._send(200, api.integration.ingest_telegraf(
                            self._raw()))
                        return
                    if parsed.path.rstrip("/") in _DD_TRACE_PATHS:
                        self._send(200, api.integration.ingest_datadog(
                            self._raw(),
                            self.headers.get("Content-Type", "")))
                        return
                    body = self._body()
                    path = parsed.path.rstrip("/")
                    if path == "/v1/shard/exec":
                        # binary columnar response (codec SHARD_RESULT
                        # frame), not JSON: numeric result columns ride
                        # as raw little-endian arrays
                        from deepflow_tpu.cluster import wire
                        obj = api.shard_exec(body, token=self._token(body))
                        payload = wire.encode_result(
                            obj, shard_id=api.shard_id)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/octet-stream")
                        self.send_header("Content-Length",
                                         str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                        return
                    if path == "/v1/cache/partial":
                        # binary CACHE_PARTIAL frame: bucket slices
                        # carry ndarray id columns, jsonb keeps them raw
                        from deepflow_tpu.cluster import wire
                        obj = api.cache_partial(body,
                                                token=self._token(body))
                        payload = wire.encode_cache_partial(
                            obj, shard_id=api.shard_id)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/octet-stream")
                        self.send_header("Content-Length",
                                         str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                        return
                    if path == "/v1/cluster/join":
                        self._send(200, api.cluster_join(body))
                    elif path == "/v1/query":
                        self._send(200, api.query(body))
                    elif path == "/v1/profile/ProfileTracing":
                        self._send(200, api.profile_tracing(body))
                    elif path == "/v1/profile/TpuFlame":
                        self._send(200, api.tpu_flame(body))
                    elif path == "/v1/profile/TpuCollectives":
                        self._send(200, api.tpu_collectives(body))
                    elif path == "/v1/profile/TpuStepTrace":
                        self._send(200, api.tpu_step_trace(body))
                    elif path == "/v1/tpu/steps":
                        self._send(200, api.tpu_steps(body))
                    elif path == "/v1/tpu/steps/critical_path":
                        self._send(200, api.tpu_step_critical_path(body))
                    elif path == "/v1/profile/TpuMemory":
                        self._send(200, api.tpu_memory(body))
                    elif path == "/v1/tracing-adapters":
                        self._send(200, api.tracing_adapters_api(body))
                    elif path == "/v1/pcaps":
                        self._send(200, api.pcaps(body))
                    elif path == "/v1/analyzers":
                        self._send(200, api.analyzers_api(body))
                    elif path == "/v1/orgs":
                        self._send(200, api.orgs_api(body))
                    elif path == "/v1/qos":
                        self._send(200, api.qos_api(body))
                    elif path == "/v1/repo":
                        self._send(200, api.repo_api(
                            body, token=self._token(body)))
                    elif path == "/v1/agents/exec":
                        self._send(200, api.agent_exec(
                            body, token=self._token(body)))
                    elif path == "/v1/agent-group-config":
                        self._send(200, api.update_agent_config(body))
                    elif path == "/v1/trace/Tracing":
                        self._send(200, api.trace(body))
                    elif path == "/v1/trace/Search":
                        self._send(200, api.trace_search(body))
                    elif path == "/api/v1/otlp/traces":
                        self._send(200,
                                   api.integration.ingest_otlp_traces(body))
                    elif path == "/api/v1/log":
                        self._send(200, api.integration.ingest_app_log(body))
                    elif path == "/api/v1/otlp/logs":
                        self._send(200,
                                   api.integration.ingest_otlp_logs(body))
                    elif path == "/v1/log/search":
                        self._send(200, api.log_search(body))
                    elif path == "/v3/segments":
                        self._send(200,
                                   api.integration.ingest_skywalking(body))
                    elif path == "/v1/alerts":
                        self._send(200, api.alerts_api("upsert", body))
                    elif path == "/v1/alerts/delete":
                        self._send(200, api.alerts_api("delete", body))
                    elif path == "/v1/subscribe":
                        self._send(200, api.subscribe_api(body))
                    elif path == "/v1/exporters":
                        self._send(200, api.exporters_api(body))
                    elif path == "/v1/exporters/delete":
                        self._send(200, api.exporters_delete(body))
                    elif path == "/mcp":
                        resp = api.mcp.handle(body)
                        self._send(200 if resp else 202,
                                   resp or {"accepted": True})
                    else:
                        self._send(404, {"error": f"no route {self.path}"})
                except AuthError as e:
                    self._send(403, {"error": str(e)})
                except (qengine.QueryError, qsql.SqlError, KeyError,
                        json.JSONDecodeError, ValueError,
                        yaml.YAMLError) as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:  # pragma: no cover
                    log.exception("querier 500")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def do_PUT(self) -> None:
                # only dd-trace PUTs are method-aliased; the rest of the
                # POST router must not gain mutation-via-PUT
                from urllib.parse import urlparse
                if urlparse(self.path).path.rstrip("/") in _DD_TRACE_PATHS:
                    return self.do_POST()
                self._send(405, {"error": "method not allowed"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="df-querier-http", daemon=True)
        t.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
