"""Retention janitor: periodic TTL enforcement over the embedded store.

Reference analog: the ingester's ClickHouse TTLs (per-table retention set
at DDL time) plus the flow_metrics datasource retention config. Embedded
redesign: one thread walks the tables on an interval and drops whole
sealed chunks older than each table's TTL (trim_before — CK partition
drops, not row deletes). Trim counts surface in dfstats so drops are
visible, never silent.
"""

from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("df.janitor")

# seconds; tables absent here are never trimmed (dictionaries, rollups
# carry their own watermarks)
DEFAULT_TTL_S = {
    "flow_log.l4_flow_log": 3 * 86400,
    "flow_log.l7_flow_log": 3 * 86400,
    "profile.in_process_profile": 3 * 86400,
    "profile.tpu_hlo_span": 3 * 86400,
    "flow_metrics.network.1s": 1 * 86400,
    "flow_metrics.application.1s": 1 * 86400,
    "flow_metrics.network.1m": 7 * 86400,
    "flow_metrics.application.1m": 7 * 86400,
    "flow_metrics.network.1h": 30 * 86400,
    "flow_metrics.application.1h": 30 * 86400,
    "prometheus.samples": 7 * 86400,
    "deepflow_system.deepflow_system": 7 * 86400,
    "event.event": 7 * 86400,
    "application_log.log": 7 * 86400,
}


class Janitor:
    def __init__(self, db, ttl_s: dict | None = None,
                 interval_s: float = 300.0, telemetry=None,
                 tier_max_bytes: int = 0) -> None:
        self.db = db
        self.ttl_s = dict(DEFAULT_TTL_S)
        if ttl_s:
            self.ttl_s.update(ttl_s)
        self.interval_s = interval_s
        # on-disk tier size budget for the whole node (0 = TTL only);
        # past it the globally-oldest segments go first
        self.tier_max_bytes = max(0, int(tier_max_bytes))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"sweeps": 0, "rows_trimmed": 0,
                      "tier_rows_evicted": 0, "tier_segments_evicted": 0,
                      "tier_bytes_evicted": 0}
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("server", enabled=False)
        self._telemetry = telemetry

    def start(self) -> "Janitor":
        if self.running():
            return self
        self._stop.clear()  # restartable (HA leader churn)
        self._thread = threading.Thread(
            target=self._run, name="df-janitor", daemon=True)
        self._thread.start()
        return self

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
            if not self._thread.is_alive():
                self._thread = None
            # else: keep the reference — running() stays True and start()
            # won't spawn a second loop over a cleared stop event

    def sweep(self, now_s: float | None = None) -> int:
        """One pass; returns rows trimmed."""
        now = now_s if now_s is not None else time.time()
        trimmed = 0
        for name, ttl in self.ttl_s.items():
            try:
                table = self.db.table(name)
            except KeyError:
                # a typo'd TTL entry must be visible, not silently skipped
                log.warning("janitor: no such table %r in TTL config", name)
                continue
            if "time" not in table.columns:
                continue
            # schema convention: u64 time = ns, u32 = epoch seconds
            if table.columns["time"].kind == "u64":
                cutoff = int((now - ttl) * 1e9)
            else:
                cutoff = int(now - ttl)
            n = table.trim_before("time", cutoff)
            if n:
                log.info("janitor: trimmed %d rows from %s", n, name)
                # dictionaries are append-only; without compaction after a
                # trim, high-cardinality columns (log bodies, trace ids,
                # stacks) grow without bound
                compacted = table.compact_dictionaries()
                if compacted:
                    log.info("janitor: compacted dictionaries on %s: %s",
                             name, compacted)
                    self.stats["dicts_compacted"] = \
                        self.stats.get("dicts_compacted", 0) + len(compacted)
            trimmed += n
        trimmed += self.sweep_tier(now)
        self.stats["sweeps"] += 1
        self.stats["rows_trimmed"] += trimmed
        return trimmed

    def _tier_drop(self, name: str, dropped: dict) -> int:
        """Fold one tier eviction into table bookkeeping + the ledger.
        Drops are never silent: every evicted row is accounted under
        ``segment_evict`` so the pipeline ledger stays conserved."""
        if not dropped["rows"] and not dropped["segments"]:
            return 0
        try:
            self.db.table(name).note_tier_evict(
                dropped["rows"], dropped["tmin"], dropped["tmax"])
        except KeyError:
            pass  # segments for a table this build no longer has
        self._telemetry.hop("storage").account(
            emitted=dropped["rows"], dropped=dropped["rows"],
            reason="segment_evict")
        self.stats["tier_rows_evicted"] += dropped["rows"]
        self.stats["tier_segments_evicted"] += dropped["segments"]
        self.stats["tier_bytes_evicted"] += dropped["bytes"]
        log.info("janitor: evicted %d segments (%d rows, %d bytes) "
                 "from tier %s", dropped["segments"], dropped["rows"],
                 dropped["bytes"], name)
        return dropped["rows"]

    def sweep_tier(self, now: float) -> int:
        """On-disk tier retention: per-table TTL (whole-segment drops —
        the CK partition-drop analog; segments are immutable so rows are
        never deleted in place), then the node-wide size budget taking
        globally-oldest segments first."""
        ts = getattr(self.db, "tier_store", None)
        if ts is None:
            return 0
        evicted = 0
        for name, ttl in self.ttl_s.items():
            try:
                table = self.db.table(name)
            except KeyError:
                continue
            if table.tier is None or "time" not in table.columns:
                continue
            # same native-unit convention as the RAM trim above
            if table.columns["time"].kind == "u64":
                cutoff = int((now - ttl) * 1e9)
            else:
                cutoff = int(now - ttl)
            evicted += self._tier_drop(name, ts.evict(name, cutoff=cutoff))
        if self.tier_max_bytes:
            # node budget: repeatedly drop the oldest segment of the
            # table holding the globally-oldest data until we fit
            while True:
                tables = ts.snapshot()["tables"]
                total = sum(v["bytes"] for v in tables.values())
                if total <= self.tier_max_bytes:
                    break
                cand = [(v["tmin"] is None, v["tmin"], n, v["bytes"])
                        for n, v in tables.items() if v["segments"]]
                if not cand:
                    break
                cand.sort()
                _, _, name, nbytes = cand[0]
                # max_bytes just under the current size forces exactly
                # the oldest segment(s) out of THIS table
                dropped = ts.evict(name, max_bytes=max(0, nbytes - 1))
                if not dropped["segments"]:
                    break
                evicted += self._tier_drop(name, dropped)
        return evicted

    def _run(self) -> None:
        # interval_hint: the janitor legitimately sleeps interval_s
        # between beats; the deadman widens its window accordingly
        hb = self._telemetry.heartbeat("janitor",
                                       interval_hint_s=self.interval_s)
        hb.beat()
        while not self._stop.wait(self.interval_s):
            hb.beat(progress=self.stats["sweeps"])
            try:
                self.sweep()
            except Exception:
                log.exception("janitor sweep failed")
