"""MCP server: expose observability data to LLM agents.

Reference analog: server/mcp (Model-Context-Protocol endpoint exposing
tracing data, server/mcp/mcp.go). JSON-RPC 2.0 over the querier HTTP port
(POST /mcp) implementing initialize / tools/list / tools/call.
"""

from __future__ import annotations

import json
import logging

log = logging.getLogger("df.mcp")

PROTOCOL_VERSION = "2024-11-05"

TOOLS = [
    {
        "name": "query",
        "description": ("Run a DF-SQL query over the observability store. "
                        "Tables: profile.in_process_profile, "
                        "profile.tpu_hlo_span, flow_log.l4_flow_log, "
                        "flow_log.l7_flow_log, flow_metrics.network.1s/1m/1h, "
                        "flow_metrics.application.1s/1m/1h, event.event, "
                        "prometheus.samples. Dialect: SELECT/WHERE/GROUP BY/"
                        "ORDER BY/LIMIT with Sum/Avg/Min/Max/Count/Percentile"
                        "/time(time, interval)."),
        "inputSchema": {
            "type": "object",
            "properties": {
                "db": {"type": "string", "description": "database prefix"},
                "sql": {"type": "string"},
            },
            "required": ["sql"],
        },
    },
    {
        "name": "profile_flame",
        "description": ("Flame graph (self/total values per frame) from "
                        "continuous profiling. event_type: on-cpu | off-cpu "
                        "| tpu-device | tpu-host."),
        "inputSchema": {
            "type": "object",
            "properties": {
                "app_service": {"type": "string"},
                "event_type": {"type": "string"},
            },
        },
    },
    {
        "name": "tpu_flame",
        "description": ("TPU device-time flame graph: HLO module -> category "
                        "-> op with summed device nanoseconds."),
        "inputSchema": {
            "type": "object",
            "properties": {
                "device_id": {"type": "integer"},
                "include_host": {
                    "type": "boolean",
                    "description": "include host compile/runtime spans"},
            },
        },
    },
    {
        "name": "trace",
        "description": "Distributed trace tree for a trace_id "
                       "(network spans + TPU device span overlay).",
        "inputSchema": {
            "type": "object",
            "properties": {"trace_id": {"type": "string"}},
            "required": ["trace_id"],
        },
    },
    {
        "name": "promql",
        "description": ("Evaluate a PromQL expression (full engine: "
                        "rate/histogram_quantile/aggregations/binary ops/"
                        "subqueries). Instant query at `time`, or a range "
                        "query when start+end are given. Metrics: "
                        "flow_metrics_network_*, flow_metrics_application_*, "
                        "deepflow_system_*, plus any remote-write name."),
        "inputSchema": {
            "type": "object",
            "properties": {
                "query": {"type": "string"},
                "time": {"type": "integer",
                         "description": "instant eval time (epoch s)"},
                "start": {"type": "integer"},
                "end": {"type": "integer"},
                "step": {"type": "integer"},
            },
            "required": ["query"],
        },
    },
    {
        "name": "search_traces",
        "description": ("Search distributed traces: tags is logfmt (keys: "
                        "service.name, endpoint, l7.protocol, "
                        "http.status_code), plus minDuration/maxDuration "
                        "(e.g. 100ms) and start/end epoch seconds. Returns "
                        "trace IDs with root span and duration; follow up "
                        "with the `trace` tool."),
        "inputSchema": {
            "type": "object",
            "properties": {
                "tags": {"type": "string"},
                "minDuration": {"type": "string"},
                "maxDuration": {"type": "string"},
                "start": {"type": "integer"},
                "end": {"type": "integer"},
                "limit": {"type": "integer"},
            },
        },
    },
    {
        "name": "list_metrics",
        "description": "Every queryable PromQL metric name.",
        "inputSchema": {"type": "object", "properties": {}},
    },
    {
        "name": "list_agents",
        "description": "List registered deepflow-tpu agents.",
        "inputSchema": {"type": "object", "properties": {}},
    },
    {
        "name": "health",
        "description": "Server health: per-table row counts and pipeline "
                       "statistics.",
        "inputSchema": {"type": "object", "properties": {}},
    },
]


class McpServer:
    def __init__(self, api) -> None:
        self.api = api  # QuerierAPI

    def handle(self, body) -> dict | None:
        """One JSON-RPC request -> response dict (None for notifications)."""
        if not isinstance(body, dict):
            # batch arrays / scalars: not supported -> Invalid Request
            return _rpc_error(None, -32600, "request must be an object")
        rpc_id = body.get("id")
        method = body.get("method", "")
        params = body.get("params") or {}
        try:
            if method == "initialize":
                result = {
                    "protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {"tools": {}},
                    "serverInfo": {"name": "deepflow-tpu",
                                   "version": "0.1.0"},
                }
            elif method == "notifications/initialized":
                return None
            elif method == "tools/list":
                result = {"tools": TOOLS}
            elif method == "tools/call":
                result = self._call_tool(
                    params.get("name", ""), params.get("arguments") or {})
            elif method == "ping":
                result = {}
            else:
                return _rpc_error(rpc_id, -32601,
                                  f"method not found: {method}")
            return {"jsonrpc": "2.0", "id": rpc_id, "result": result}
        except Exception as e:
            log.debug("mcp error: %s", e)
            return _rpc_error(rpc_id, -32000, f"{type(e).__name__}: {e}")

    def _call_tool(self, name: str, args: dict) -> dict:
        api = self.api
        if name == "query":
            out = api.query({"db": args.get("db", ""),
                             "sql": args.get("sql", "")})["result"]
        elif name == "profile_flame":
            out = api.profile_tracing(args)["result"]
        elif name == "tpu_flame":
            out = api.tpu_flame(args)["result"]
        elif name == "trace":
            out = api.trace(args)["result"]
        elif name == "promql":
            if (args.get("start") is None) != (args.get("end") is None):
                raise ValueError(
                    "promql: start and end must be given together")
            if args.get("start") is not None and args.get("end") is not None:
                out = api.prom_query_range({
                    "query": args.get("query", ""),
                    "start": args["start"], "end": args["end"],
                    "step": args.get("step", 15)})
            else:
                p = {"query": args.get("query", "")}
                if args.get("time") is not None:
                    p["time"] = args["time"]
                out = api.prom_query(p)
        elif name == "search_traces":
            out = api.tempo_search(
                {k: str(v) for k, v in args.items() if v is not None})
        elif name == "list_metrics":
            from deepflow_tpu.query import promql as _promql
            out = {"metrics": _promql.metric_names(api.db)}
        elif name == "list_agents":
            out = api.agents()
        elif name == "health":
            out = api.health()
        else:
            raise ValueError(f"unknown tool {name!r}")
        return {"content": [{"type": "text",
                             "text": json.dumps(out, default=str)}]}


def _rpc_error(rpc_id, code: int, message: str) -> dict:
    return {"jsonrpc": "2.0", "id": rpc_id,
            "error": {"code": code, "message": message}}
