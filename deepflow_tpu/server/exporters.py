"""Exporters: re-export ingested telemetry to downstream systems.

Reference analog: server/ingester/exporters (kafka / OTLP / prometheus
remote-write re-export with tag translation). Decoders feed rows after
ingest; each exporter filters by table, converts, batches, and ships over
HTTP in a background thread (failures never block ingest).
"""

from __future__ import annotations

import gzip
import json
import logging
import queue
import threading
import time
import urllib.request

log = logging.getLogger("df.exporters")


class BaseExporter:
    """Background batch shipper; subclasses convert rows to a payload."""

    TABLES: tuple = ()

    SPOOL_MAX_FILES = 256     # ~bounded disk: oldest dropped beyond this

    def __init__(self, endpoint: str, batch_size: int = 256,
                 flush_interval_s: float = 2.0,
                 queue_size: int = 8192, max_retries: int = 2,
                 spool_dir: str | None = None) -> None:
        self.endpoint = endpoint
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self.max_retries = max_retries
        # durability: exhausted retries land in a disk spool and replay
        # when the destination recovers (reference exporters buffer to
        # kafka; embedded design spools locally). None = legacy drop.
        self.spool_dir = spool_dir
        self._spool_seq = 0
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"exported": 0, "batches": 0, "dropped": 0,
                      "errors": 0, "spooled": 0, "replayed": 0,
                      "spool_dropped": 0}
        # conserved hop ledger (emitted == delivered + dropped + in_flight;
        # in_flight = queue + spool). Files spooled by a PREVIOUS process
        # were never emitted in this ledger: they account emitted at
        # adoption (first successful load), tracked via _spooled_rows.
        self._hop = None
        self._spooled_rows: dict[str, int] = {}  # fn -> rows, this ledger

    @property
    def kind(self) -> str:
        return type(self).__name__.removesuffix("Exporter").lower()

    def attach_telemetry(self, telemetry) -> "BaseExporter":
        self._hop = telemetry.hop(f"exporter.{self.kind}")
        return self

    def _acct(self, **kw) -> None:
        if self._hop is not None:
            self._hop.account(**kw)

    def accepts(self, table: str) -> bool:
        return not self.TABLES or table in self.TABLES

    def feed(self, table: str, rows: list[dict]) -> None:
        if not self.accepts(table):
            return
        full = 0
        for row in rows:
            try:
                self._q.put_nowait((table, row))
            except queue.Full:
                full += 1
        if full:
            self.stats["dropped"] += full
        # every accepted row enters the ledger; queue-full rows enter and
        # immediately drop so the books still balance
        self._acct(emitted=len(rows), dropped=full, reason="queue_full")

    def start(self) -> "BaseExporter":
        self._thread = threading.Thread(
            target=self._run, name=f"df-exporter-{type(self).__name__}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3.0)

    def _run(self) -> None:
        batch: list = []
        while not self._stop.is_set() or not self._q.empty():
            try:
                batch.append(self._q.get(timeout=self.flush_interval_s))
            except queue.Empty:
                pass
            if batch and (len(batch) >= self.batch_size or self._q.empty()):
                shipped = False
                for attempt in range(1 + self.max_retries):
                    try:
                        self._ship(batch)
                        shipped = True
                        self.stats["exported"] += len(batch)
                        self.stats["batches"] += 1
                        self._acct(delivered=len(batch))
                        break
                    except Exception as e:
                        self.stats["errors"] += 1
                        log.debug("export failed (try %d): %s", attempt, e)
                        if self._stop.is_set():
                            break  # shutdown mid-retry: still a drop
                        time.sleep(min(0.5 * (attempt + 1), 2.0))
                if not shipped:
                    if self._spool(batch):
                        self.stats["spooled"] += len(batch)
                        # spooled rows stay in_flight until replayed
                    else:
                        self.stats["dropped"] += len(batch)
                        self._acct(dropped=len(batch),
                                   reason="ship_failed")
                batch = []
            # disk-driven replay: runs whether the spool predates this
            # process or filled this run, throttled between attempts
            self._maybe_replay_spool()

    def _spool(self, batch: list) -> bool:
        if not self.spool_dir:
            return False
        import os
        import pickle
        try:
            os.makedirs(self.spool_dir, exist_ok=True)
            files = sorted(f for f in os.listdir(self.spool_dir)
                           if f.endswith(".spool"))
            while len(files) >= self.SPOOL_MAX_FILES:
                victim = files.pop(0)  # oldest out; drops stay VISIBLE
                n = 0
                try:
                    import pickle as _p
                    with open(os.path.join(self.spool_dir, victim),
                              "rb") as f:
                        n = len(_p.load(f))
                    self.stats["spool_dropped"] += n
                except Exception:
                    pass
                if victim in self._spooled_rows:
                    # rows this ledger already emitted: close them out
                    self._acct(dropped=self._spooled_rows.pop(victim),
                               reason="spool_evict")
                elif n:
                    # foreign file (previous process): adopt-then-drop so
                    # the eviction is visible without going negative
                    self._acct(emitted=n, dropped=n, reason="spool_evict")
                os.unlink(os.path.join(self.spool_dir, victim))
            self._spool_seq += 1
            path = os.path.join(
                self.spool_dir,
                f"{time.time_ns():020d}_{self._spool_seq:06d}.spool")
            with open(path + ".tmp", "wb") as f:
                pickle.dump(batch, f)
            os.replace(path + ".tmp", path)
            self._spooled_rows[os.path.basename(path)] = len(batch)
            return True
        except OSError as e:
            log.warning("spool write failed: %s", e)
            return False

    def _maybe_replay_spool(self) -> None:
        if not self.spool_dir:
            return
        now = time.monotonic()
        if now < getattr(self, "_next_replay", 0):
            return
        self._next_replay = now + 5.0
        self._replay_spool()

    def _replay_spool(self, max_files: int = 8) -> None:
        """Drain spooled batches oldest-first (including batches spooled
        by a PREVIOUS process run)."""
        import os
        import pickle
        try:
            files = sorted(f for f in os.listdir(self.spool_dir)
                           if f.endswith(".spool"))
        except OSError:
            return
        attempts = getattr(self, "_replay_attempts", None)
        if attempts is None:
            attempts = self._replay_attempts = {}
        for fn in files[:max_files]:
            path = os.path.join(self.spool_dir, fn)
            try:
                with open(path, "rb") as f:
                    batch = pickle.load(f)
                self._ship(batch)
                os.unlink(path)
                attempts.pop(fn, None)
                self.stats["replayed"] += len(batch)
                self.stats["exported"] += len(batch)
                if fn in self._spooled_rows:
                    self._spooled_rows.pop(fn)
                    self._acct(delivered=len(batch))
                else:
                    # foreign spool file: adopted into this ledger only
                    # once it actually ships
                    self._acct(emitted=len(batch), delivered=len(batch))
            except Exception as e:
                # a file the destination deterministically rejects must not
                # block everything behind it forever: quarantine after 5
                # tries (visible in spool_dropped + the .bad file on disk)
                attempts[fn] = attempts.get(fn, 0) + 1
                if attempts.get(fn, 0) >= 5:
                    try:
                        n = 0
                        try:
                            with open(path, "rb") as f:
                                n = len(pickle.load(f))
                        except Exception:
                            pass
                        os.replace(path, path + ".bad")
                        self.stats["spool_dropped"] += n
                        attempts.pop(fn, None)
                        if fn in self._spooled_rows:
                            self._acct(
                                dropped=self._spooled_rows.pop(fn),
                                reason="spool_poison")
                        elif n:
                            self._acct(emitted=n, dropped=n,
                                       reason="spool_poison")
                        log.warning("quarantined poison spool file %s", fn)
                        continue
                    except OSError:
                        pass
                log.debug("spool replay stopped at %s: %s", fn, e)
                return  # destination flapped again; keep the file

    def _ship(self, batch: list) -> None:
        raise NotImplementedError

    def _post(self, data: bytes, content_type: str,
              headers: dict | None = None) -> None:
        req = urllib.request.Request(
            self.endpoint, data=data,
            headers={"Content-Type": content_type, **(headers or {})})
        with urllib.request.urlopen(req, timeout=10):
            pass


class JsonLinesExporter(BaseExporter):
    """NDJSON over HTTP (the kafka-topic analog for environments without
    kafka: any collector that takes line-delimited JSON)."""

    def __init__(self, endpoint: str, tables: tuple = (), **kw) -> None:
        super().__init__(endpoint, **kw)
        self.TABLES = tables

    def _ship(self, batch: list) -> None:
        lines = b"\n".join(
            json.dumps({"table": t, **row}, default=str).encode()
            for t, row in batch)
        self._post(gzip.compress(lines), "application/x-ndjson",
                   {"Content-Encoding": "gzip"})


class OtlpJsonExporter(BaseExporter):
    """l7_flow_log rows -> OTLP/HTTP JSON traces; tpu_step_metrics rows
    ride along as one span per (host, step) so a training-step waterfall
    shows up next to the request traces in any OTLP backend."""

    TABLES = ("flow_log.l7_flow_log", "profile.tpu_step_metrics")

    @staticmethod
    def _step_span(row: dict) -> dict:
        start = int(row.get("time", 0))
        end = int(row.get("end_ns", 0)) or start
        rid = int(row.get("run_id", 0))
        step = int(row.get("step", 0))
        return {
            "traceId": f"steprun-{rid}",
            "spanId": f"step-{rid}-{step}-{row.get('host', '')}",
            "parentSpanId": "",
            "name": f"{row.get('job', '') or 'step'}/{step}",
            "kind": 1,  # INTERNAL
            "startTimeUnixNano": str(start),
            "endTimeUnixNano": str(end),
            "attributes": [
                {"key": "tpu.run_id", "value": {"intValue": rid}},
                {"key": "tpu.step", "value": {"intValue": step}},
                {"key": "tpu.device_count",
                 "value": {"intValue": int(row.get("device_count", 0))}},
                {"key": "tpu.device_skew_ns",
                 "value": {"intValue": int(row.get("device_skew_ns", 0))}},
                {"key": "tpu.collective_ns",
                 "value": {"intValue": int(row.get("collective_ns", 0))}},
                {"key": "tpu.straggler_device",
                 "value": {"intValue": int(row.get("straggler_device", 0))}},
                {"key": "host.name",
                 "value": {"stringValue": str(row.get("host", ""))}},
            ],
            "status": {"code": 1},
        }

    def _ship(self, batch: list) -> None:
        spans = []
        for table, row in batch:
            if table == "profile.tpu_step_metrics":
                spans.append(self._step_span(row))
                continue
            start = int(row.get("time", 0))
            dur = int(row.get("response_duration", 0))
            spans.append({
                "traceId": row.get("trace_id", ""),
                "spanId": row.get("span_id", "") or f"flow-{row.get('flow_id', 0)}",
                "parentSpanId": row.get("parent_span_id", ""),
                "name": (f"{row.get('request_type', '')} "
                         f"{row.get('endpoint', '')}").strip() or "span",
                "kind": 2,
                "startTimeUnixNano": str(start),
                "endTimeUnixNano": str(start + dur),
                "attributes": [
                    {"key": "l7.protocol",
                     "value": {"stringValue": str(row.get("l7_protocol", ""))}},
                    {"key": "http.status_code",
                     "value": {"intValue": int(row.get("response_code", 0))}},
                    {"key": "net.peer.ip",
                     "value": {"stringValue": row.get("ip_dst", "")}},
                ],
                "status": {"code": 1 if row.get("response_status") in (1, "ok")
                           else 2},
            })
        payload = {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "deepflow-tpu"}}]},
            "scopeSpans": [{"spans": spans}]}]}
        self._post(json.dumps(payload).encode(), "application/json")


class RemoteWriteExporter(BaseExporter):
    """flow_metrics rows -> prometheus remote-write (snappy WriteRequest)."""

    TABLES = ("flow_metrics.network.1s", "flow_metrics.application.1s")

    _METERS = {
        "flow_metrics.network.1s": (
            "flow_metrics_network_", ("byte_tx", "byte_rx", "packet_tx",
                                      "packet_rx", "retrans")),
        "flow_metrics.application.1s": (
            "flow_metrics_application_", ("request", "response",
                                          "error_client", "error_server")),
    }
    _LABELS = ("ip_src", "ip_dst", "server_port", "host", "app_service")

    def _ship(self, batch: list) -> None:
        from deepflow_tpu.utils import promwire, snappy
        series = []
        for table, row in batch:
            prefix, meters = self._METERS.get(table, ("", ()))
            labels = {lbl: str(row[lbl]) for lbl in self._LABELS
                      if row.get(lbl) not in (None, "", 0)}
            ts_ms = int(row.get("time", 0)) * 1000
            for meter in meters:
                # zeros export too: downstream series must return to 0
                # after a burst, not go stale inside the staleness window
                series.append((prefix + meter, labels,
                               [(ts_ms, float(row.get(meter, 0)))]))
        if series:
            self._post(snappy.compress(promwire.write_request(series)),
                       "application/x-protobuf",
                       {"Content-Encoding": "snappy",
                        "X-Prometheus-Remote-Write-Version": "0.1.0"})


class KafkaExporter(BaseExporter):
    """Rows -> Kafka topic as JSON messages over the raw wire protocol
    (reference: ingester/exporters/kafka_exporter; no client library in
    this image, so deepflow_tpu.utils.kafkawire speaks the protocol).

    Endpoint form: kafka://host:port/topic. Partition-leader discovery via
    Metadata v0, messages partitioned round-robin, acks=1; broker errors
    raise so the Base retry/spool machinery engages."""

    def __init__(self, endpoint: str, tables: tuple = (), **kw) -> None:
        super().__init__(endpoint, **kw)
        self.TABLES = tables
        from urllib.parse import urlparse
        u = urlparse(endpoint)
        if u.scheme != "kafka" or not u.hostname or not u.path.strip("/"):
            raise ValueError(
                f"kafka endpoint must be kafka://host:port/topic, "
                f"got {endpoint!r}")
        self.bootstrap = (u.hostname, u.port or 9092)
        self.topic = u.path.strip("/")
        self._corr = 0
        self._rr = 0
        self._conns: dict = {}       # (host, port) -> socket
        self._leaders: dict = {}     # partition -> (host, port)

    def stop(self) -> None:
        super().stop()
        for sock in self._conns.values():
            try:
                sock.close()
            except OSError:
                pass
        self._conns.clear()

    def _next_corr(self) -> int:
        self._corr += 1
        return self._corr

    def _connect(self, addr: tuple):
        import socket
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr, timeout=10)
        sock.settimeout(10)
        self._conns[addr] = sock
        return sock

    def _drop_conn(self, addr: tuple) -> None:
        sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _refresh_metadata(self) -> None:
        from deepflow_tpu.utils import kafkawire as kw
        corr = self._next_corr()
        sock = self._connect(self.bootstrap)
        try:
            sock.sendall(kw.metadata_request([self.topic], corr))
            got_corr, body = kw.read_response(sock)
        except OSError:
            self._drop_conn(self.bootstrap)
            raise
        if got_corr != corr:
            self._drop_conn(self.bootstrap)
            raise kw.KafkaWireError(
                f"correlation mismatch {got_corr} != {corr}")
        md = kw.parse_metadata_response(body, self.topic)
        if md.topic_error not in (0, 5):  # 5: leader election in progress
            raise kw.KafkaWireError(
                f"topic {self.topic!r}: {kw.error_name(md.topic_error)}")
        self._leaders = {
            pid: md.brokers[leader]
            for pid, leader in md.partition_leaders.items()
            if leader in md.brokers}
        if not self._leaders:
            raise kw.KafkaWireError(
                f"no leaders for topic {self.topic!r}")

    def _ship(self, batch: list) -> None:
        import time as _time

        from deepflow_tpu.utils import kafkawire as kw
        if not self._leaders:
            self._refresh_metadata()
        parts = sorted(self._leaders)
        partition = parts[self._rr % len(parts)]
        self._rr += 1
        now_ms = int(_time.time() * 1000)
        msgs = [(None, json.dumps({"table": t, **row},
                                  default=str).encode(), now_ms)
                for t, row in batch]
        corr = self._next_corr()
        req = kw.produce_request(self.topic, partition,
                                 kw.message_set(msgs), corr)
        addr = self._leaders[partition]
        try:
            sock = self._connect(addr)
            sock.sendall(req)
            got_corr, body = kw.read_response(sock)
        except OSError:
            # connect failures too: a dead leader must invalidate the
            # cached topology or failover never recovers
            self._drop_conn(addr)
            self._leaders = {}
            raise
        if got_corr != corr:
            self._drop_conn(addr)
            raise kw.KafkaWireError(
                f"correlation mismatch {got_corr} != {corr}")
        res = kw.parse_produce_response(body)
        if res.error_code != 0:
            if res.error_code in kw.RETRIABLE_ERRORS:
                self._leaders = {}  # re-discover on next attempt
            raise kw.KafkaWireError(
                f"produce to {self.topic}[{partition}]: "
                f"{kw.error_name(res.error_code)}")


class ExporterManager:
    def __init__(self, telemetry=None) -> None:
        self.exporters: list[BaseExporter] = []
        self.telemetry = telemetry

    def add(self, exporter: BaseExporter) -> BaseExporter:
        """Idempotent on (type, endpoint): re-adding returns the existing
        exporter instead of leaking threads and double-shipping."""
        for e in self.exporters:
            if (type(e) is type(exporter)
                    and e.endpoint == exporter.endpoint):
                return e
        if self.telemetry is not None:
            exporter.attach_telemetry(self.telemetry)
        self.exporters.append(exporter.start())
        return exporter

    def remove(self, endpoint: str) -> int:
        removed = [e for e in self.exporters if e.endpoint == endpoint]
        self.exporters = [e for e in self.exporters
                          if e.endpoint != endpoint]
        for e in removed:
            e.stop()
        return len(removed)

    def feed(self, table: str, rows: list[dict]) -> None:
        for e in self.exporters:
            e.feed(table, rows)

    def wants(self, table: str) -> bool:
        """Does any registered exporter accept this table? Lets hot-path
        writers skip materializing row dicts when nobody is listening."""
        return any(e.accepts(table) for e in self.exporters)

    def stop(self) -> None:
        for e in self.exporters:
            e.stop()

    def stats(self) -> dict:
        out = {}
        for e in self.exporters:
            st = dict(e.stats)
            if e._hop is not None:
                st["ledger"] = e._hop.snapshot()
            out[f"{type(e).__name__}:{e.endpoint}"] = st
        return out
