"""Self-telemetry spine: frame ledger, stage heartbeats, deadman detection.

Reference analog: server/ingester/ingesterctl (per-queue counters),
server/libs/stats (self-metrics -> deepflow_system) and ckmonitor. The
port's version is deliberately small: three primitives shared by agent
and server —

* ``HopLedger`` — per pipeline hop, every frame/batch is accounted as
  ``emitted = delivered + dropped(reason) + in_flight`` with an
  enqueue->dequeue latency histogram, so loss anywhere in
  dispatcher -> flow_map -> collector -> sender -> receiver -> decoder
  -> table_write is attributable to one hop and one reason.
* ``Heartbeat`` — every long-running thread beats with a monotonic
  progress counter.  A beat is ~2 attribute stores; stages that wake
  rarely declare ``interval_hint_s`` so the detector scales its window.
* ``DeadmanDetector`` — flags stages whose heartbeat stalls past a
  configurable window and snapshots the wedged thread's stack via
  ``sys._current_frames()``.  This is the component that turns the
  "tpuprobe relay wedges silently, bench returns null" failure mode
  (VERDICT r05) into a named, stack-attributed verdict.

Everything ships through the existing DFSTATS path into
``deepflow_system.deepflow_system`` (agent side) or is written into the
table directly (server side), so PromQL queries like
``deepflow_system_agent_pipeline_emitted`` work with no extra wiring.

Disable knob: ``DF_NO_SELFMON=1`` (or ``Telemetry(enabled=False)``)
swaps in no-op hops/heartbeats; the bench overhead gate (<2%) runs the
ingest benchmark both ways.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback

import numpy as np

log = logging.getLogger("df.telemetry")

# one knob, same spirit as DF_NO_NATIVE: kill-switch for incident debugging
SELFMON_DISABLED = os.environ.get("DF_NO_SELFMON", "") not in ("", "0")

# max bytes of formatted stack shipped per wedge verdict (tag_json cell)
_STACK_LIMIT = 4096


def _now_ns() -> int:
    return time.monotonic_ns()


class LatencyHistogram:
    """Fixed-bound latency histogram (ns).  Cheap: one list index per
    observe, percentiles estimated from bucket upper bounds."""

    # 0.1ms 1ms 10ms 100ms 1s 10s +inf — queue waits, not packet times
    BOUNDS_NS = (100_000, 1_000_000, 10_000_000, 100_000_000,
                 1_000_000_000, 10_000_000_000)

    __slots__ = ("counts", "count", "sum_ns")

    def __init__(self) -> None:
        self.counts = [0] * (len(self.BOUNDS_NS) + 1)
        self.count = 0
        self.sum_ns = 0

    def observe(self, wait_ns: int, n: int = 1) -> None:
        i = 0
        for bound in self.BOUNDS_NS:
            if wait_ns <= bound:
                break
            i += 1
        self.counts[i] += n
        self.count += n
        self.sum_ns += wait_ns * n

    def quantile(self, q: float) -> float:
        """Upper-bound estimate in ms (conservative: reports the bucket
        ceiling the q-th observation fell into)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                if i < len(self.BOUNDS_NS):
                    return self.BOUNDS_NS[i] / 1e6
                # +inf bucket: fall back to the mean (better than lying
                # with an arbitrary ceiling)
                return self.sum_ns / self.count / 1e6
        return self.BOUNDS_NS[-1] / 1e6

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "sum_ms": round(self.sum_ns / 1e6, 3),
            "p50_ms": round(self.quantile(0.50), 3),
            "p99_ms": round(self.quantile(0.99), 3),
        }


class HopLedger:
    """One pipeline hop's frame accounting.

    Invariant (after quiescence): ``emitted == delivered + dropped``.
    While traffic is moving the difference is ``in_flight`` (items
    sitting in the hop's queue/buffer).  ``account()`` is called per
    BATCH on hot paths, so the lock is cold."""

    __slots__ = ("name", "_lock", "emitted", "delivered", "dropped", "wait")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self.emitted = 0
        self.delivered = 0
        self.dropped: dict[str, int] = {}
        self.wait = LatencyHistogram()

    def account(self, emitted: int = 0, delivered: int = 0,
                dropped: int = 0, reason: str = "",
                wait_ns: int | None = None) -> None:
        with self._lock:
            self.emitted += emitted
            self.delivered += delivered
            if dropped:
                key = reason or "unknown"
                self.dropped[key] = self.dropped.get(key, 0) + dropped
            if wait_ns is not None:
                self.wait.observe(wait_ns, max(1, delivered or emitted))

    def observe_wait(self, wait_ns: int, weight: int = 1) -> None:
        """Record queue wait without moving the frame counters — for
        callers that batch emitted/delivered accounting separately (the
        query tracer defers its ledger off the query hot path)."""
        with self._lock:
            self.wait.observe(wait_ns, max(1, weight))

    def snapshot(self) -> dict:
        with self._lock:
            dropped_total = sum(self.dropped.values())
            return {
                "hop": self.name,
                "emitted": self.emitted,
                "delivered": self.delivered,
                "dropped": dict(self.dropped),
                "dropped_total": dropped_total,
                "in_flight": self.emitted - self.delivered - dropped_total,
                "wait": self.wait.snapshot(),
            }


class Heartbeat:
    """One long-running thread's liveness record.  ``beat()`` must be
    called from the owning thread (it records the thread ident used for
    the deadman stack snapshot)."""

    __slots__ = ("stage", "interval_hint_s", "beats", "progress",
                 "last_beat_mono", "thread_ident", "started_mono")

    def __init__(self, stage: str, interval_hint_s: float = 0.0) -> None:
        self.stage = stage
        # stages that legitimately sleep a long time (janitor: 300s)
        # declare it so the detector widens their window instead of
        # crying wolf
        self.interval_hint_s = interval_hint_s
        self.beats = 0
        self.progress = 0
        self.started_mono = time.monotonic()
        self.last_beat_mono = self.started_mono  # armed at registration
        self.thread_ident: int | None = None

    def beat(self, progress: int | None = None) -> None:
        if self.thread_ident is None:
            self.thread_ident = threading.get_ident()
        self.beats += 1
        if progress is not None:
            self.progress = progress
        self.last_beat_mono = time.monotonic()

    def snapshot(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        return {
            "stage": self.stage,
            "beats": self.beats,
            "progress": self.progress,
            "age_s": round(now - self.last_beat_mono, 3),
            "interval_hint_s": self.interval_hint_s,
        }


class _NullHop:
    """API-compatible no-op hop for DF_NO_SELFMON / bench baseline."""

    __slots__ = ("name",)

    def __init__(self, name: str) -> None:
        self.name = name

    def account(self, emitted: int = 0, delivered: int = 0,
                dropped: int = 0, reason: str = "",
                wait_ns: int | None = None) -> None:
        pass

    def observe_wait(self, wait_ns: int, weight: int = 1) -> None:
        pass

    def snapshot(self) -> dict:
        return {"hop": self.name, "emitted": 0, "delivered": 0,
                "dropped": {}, "dropped_total": 0, "in_flight": 0,
                "wait": {"count": 0, "sum_ms": 0.0,
                         "p50_ms": 0.0, "p99_ms": 0.0}}


class _NullHeartbeat:
    __slots__ = ("stage",)

    def __init__(self, stage: str) -> None:
        self.stage = stage

    def beat(self, progress: int | None = None) -> None:
        pass

    def snapshot(self, now: float | None = None) -> dict:
        return {"stage": self.stage, "beats": 0, "progress": 0,
                "age_s": 0.0, "interval_hint_s": 0.0}


class Telemetry:
    """Registry of hops + heartbeats for ONE component (one per Agent,
    one per Server — NOT process-global, because tests run both in a
    single process)."""

    def __init__(self, component: str = "agent",
                 enabled: bool | None = None) -> None:
        self.component = component
        self.enabled = (not SELFMON_DISABLED) if enabled is None else enabled
        self._lock = threading.Lock()
        self._hops: dict[str, HopLedger] = {}   # insertion order = pipeline
        self._beats: dict[str, Heartbeat] = {}
        # stage -> wedge verdict dict; maintained by the DeadmanDetector
        self.wedges: dict[str, dict] = {}
        self._wedges_total = 0

    # -- registration --------------------------------------------------------

    def hop(self, name: str) -> HopLedger:
        if not self.enabled:
            return _NullHop(name)
        with self._lock:
            h = self._hops.get(name)
            if h is None:
                h = self._hops[name] = HopLedger(name)
            return h

    def heartbeat(self, stage: str,
                  interval_hint_s: float = 0.0) -> Heartbeat:
        """Register (or re-register after a restart) a stage heartbeat."""
        if not self.enabled:
            return _NullHeartbeat(stage)
        with self._lock:
            hb = Heartbeat(stage, interval_hint_s=interval_hint_s)
            self._beats[stage] = hb
            return hb

    def unregister(self, stage: str) -> None:
        with self._lock:
            self._beats.pop(stage, None)
            self.wedges.pop(stage, None)

    # -- snapshots -----------------------------------------------------------

    def pipeline_snapshot(self) -> list[dict]:
        with self._lock:
            hops = list(self._hops.values())
        return [h.snapshot() for h in hops]

    def stages_snapshot(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            beats = list(self._beats.values())
            wedged = set(self.wedges)
        out = []
        for hb in beats:
            s = hb.snapshot(now)
            s["wedged"] = hb.stage in wedged
            out.append(s)
        return out

    def snapshot(self) -> dict:
        """Everything /v1/health needs, JSON-ready."""
        pipeline = self.pipeline_snapshot()
        imbalance = sum(abs(h["in_flight"]) for h in pipeline)
        return {
            "component": self.component,
            "enabled": self.enabled,
            "pipeline": pipeline,
            "ledger_imbalance": imbalance,
            "stages": self.stages_snapshot(),
            "wedges": sorted(self.wedges.values(),
                             key=lambda w: w["stage"]),
            "wedges_total": self._wedges_total,
        }

    # -- DFSTATS shipping ----------------------------------------------------

    def stats_metrics(self):
        """Yield ``(metric_name, tags, values)`` triples in the shape the
        agent's ``_emit_stats``/StatsBatch expects.  Metric names are
        chosen so PromQL resolution through the ``deepflow_system_``
        narrow-table prefix yields e.g.
        ``deepflow_system_agent_pipeline_emitted{hop="sender"}``."""
        c = self.component
        for h in self.pipeline_snapshot():
            vals = {"emitted": float(h["emitted"]),
                    "delivered": float(h["delivered"]),
                    "dropped": float(h["dropped_total"]),
                    "in_flight": float(h["in_flight"]),
                    "wait_p99_ms": h["wait"]["p99_ms"]}
            yield f"{c}.pipeline", {"hop": h["hop"]}, vals
            for reason, n in h["dropped"].items():
                yield (f"{c}.pipeline.drop", {"hop": h["hop"],
                                              "reason": reason},
                       {"dropped": float(n)})
        for s in self.stages_snapshot():
            yield (f"{c}.heartbeat", {"stage": s["stage"]},
                   {"beats": float(s["beats"]),
                    "progress": float(s["progress"]),
                    "age_s": s["age_s"],
                    "wedged": 1.0 if s["wedged"] else 0.0})
        for w in sorted(self.wedges.values(), key=lambda w: w["stage"]):
            yield (f"{c}.deadman", {"stage": w["stage"],
                                    "stack": w["stack"]},
                   {"wedged": 1.0, "stalled_s": w["stalled_s"],
                    "progress": float(w["progress"])})


class DeadmanDetector:
    """Scans a Telemetry's heartbeats; flags stalls; snapshots stacks.

    A stage is wedged when its last beat is older than
    ``max(window_s, 2.5 * interval_hint_s)``.  The verdict carries the
    wedged thread's current stack (``sys._current_frames()``), which is
    exactly the datum four rounds of null TPU benches were missing:
    WHERE the relay is stuck, not just that rows stopped."""

    def __init__(self, telemetry: Telemetry, window_s: float = 15.0,
                 check_interval_s: float | None = None,
                 on_wedge=None) -> None:
        self.telemetry = telemetry
        self.window_s = window_s
        self.check_interval_s = (check_interval_s if check_interval_s
                                 else max(0.1, window_s / 4.0))
        self.on_wedge = on_wedge  # callback(verdict_dict), e.g. log/ship
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "DeadmanDetector":
        if not self.telemetry.enabled:
            return self
        self._thread = threading.Thread(
            target=self._run,
            name=f"df-deadman-{self.telemetry.component}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
            self._thread = None

    def check_once(self) -> list[dict]:
        """One scan; returns NEW wedge verdicts (also records them)."""
        t = self.telemetry
        now = time.monotonic()
        new = []
        with t._lock:
            beats = list(t._beats.values())
        frames = None  # lazy: only taken when something looks stuck
        for hb in beats:
            window = max(self.window_s, 2.5 * hb.interval_hint_s)
            age = now - hb.last_beat_mono
            if age <= window:
                if t.wedges.pop(hb.stage, None) is not None:
                    log.info("deadman: stage %r recovered", hb.stage)
                continue
            if hb.stage in t.wedges:  # already flagged; refresh stall age
                t.wedges[hb.stage]["stalled_s"] = round(age, 3)
                continue
            if frames is None:
                frames = sys._current_frames()
            stack = ""
            fr = frames.get(hb.thread_ident) if hb.thread_ident else None
            if fr is not None:
                stack = "".join(traceback.format_stack(fr))[-_STACK_LIMIT:]
            verdict = {
                "stage": hb.stage,
                "stalled_s": round(age, 3),
                "beats": hb.beats,
                "progress": hb.progress,
                "window_s": window,
                "stack": stack,
            }
            t.wedges[hb.stage] = verdict
            t._wedges_total += 1
            new.append(verdict)
            log.error("deadman: stage %r wedged (no beat for %.1fs, "
                      "progress=%d)\n%s", hb.stage, age, hb.progress,
                      stack or "<no stack: thread gone>")
            if self.on_wedge is not None:
                try:
                    self.on_wedge(verdict)
                except Exception:
                    log.exception("on_wedge callback failed")
        return new

    def _run(self) -> None:
        hb = self.telemetry.heartbeat(
            "deadman", interval_hint_s=self.check_interval_s)
        hb.beat()
        while not self._stop.wait(self.check_interval_s):
            hb.beat(progress=self.telemetry._wedges_total)
            try:
                self.check_once()
            except Exception:
                log.exception("deadman scan failed")


# -- deepflow_system readback (server-side health aggregation) --------------

def collect_agent_selfmon(db, window_ns: int = 600_000_000_000) -> dict:
    """Reconstitute the AGENTS' latest self-telemetry from the rows they
    shipped into ``deepflow_system.deepflow_system``.

    Agent wedges happen in a different process than the server, so
    /v1/health can't read them from a live Telemetry object — it mines
    the table the same way an operator would with PromQL.  Counters are
    cumulative; latest row per (metric, tags, value_name) wins."""
    try:
        t = db.table("deepflow_system.deepflow_system")
    except KeyError:
        return {"pipeline": {}, "heartbeats": {}, "wedges": []}
    # the metric-name set is closed, so resolve dictionary ids ONCE and
    # mask with numpy instead of decoding every row
    name_dict = t.dicts["metric_name"]
    wanted: dict[int, str] = {}
    for nm in ("agent.pipeline", "agent.pipeline.drop",
               "agent.heartbeat", "agent.deadman"):
        sid = name_dict.lookup(nm)
        if sid is not None:
            wanted[sid] = nm
    if not wanted:
        return {"pipeline": {}, "heartbeats": {}, "wedges": []}
    latest: dict[tuple, tuple[int, float, str]] = {}
    cutoff = time.time_ns() - window_ns
    tag_dict = t.dicts["tag_json"]
    vname_dict = t.dicts["value_name"]
    for chunk in t.snapshot():
        name_ids = chunk["metric_name"]
        times = chunk["time"]
        mask = np.isin(name_ids, list(wanted))
        mask &= times.astype("int64") >= cutoff
        if not mask.any():
            continue
        idx = np.nonzero(mask)[0]
        tag_ids = chunk["tag_json"]
        vname_ids = chunk["value_name"]
        values = chunk["value"]
        for i in idx:
            name = wanted[int(name_ids[i])]
            ts = int(times[i])
            tag_json = tag_dict.decode(int(tag_ids[i]))
            vname = vname_dict.decode(int(vname_ids[i]))
            key = (name, tag_json, vname)
            prev = latest.get(key)
            if prev is None or ts >= prev[0]:
                latest[key] = (ts, float(values[i]), tag_json)
    pipeline: dict[str, dict] = {}
    heartbeats: dict[str, dict] = {}
    wedges: dict[str, dict] = {}
    for (name, tag_json, value_name), (ts, value, _) in latest.items():
        try:
            tags_d = json.loads(tag_json) if tag_json else {}
        except ValueError:
            tags_d = {}
        if name == "agent.pipeline":
            hop = tags_d.get("hop", "?")
            pipeline.setdefault(hop, {"hop": hop})[value_name] = value
        elif name == "agent.pipeline.drop":
            hop = tags_d.get("hop", "?")
            d = pipeline.setdefault(hop, {"hop": hop})
            d.setdefault("dropped_by_reason", {})[
                tags_d.get("reason", "unknown")] = value
        elif name == "agent.heartbeat":
            stage = tags_d.get("stage", "?")
            heartbeats.setdefault(stage, {"stage": stage})[value_name] = value
        elif name == "agent.deadman":
            stage = tags_d.get("stage", "?")
            w = wedges.setdefault(
                stage, {"stage": stage, "stack": tags_d.get("stack", ""),
                        "time_ns": ts})
            w[value_name] = value
            if ts > w["time_ns"]:
                w["time_ns"] = ts
    return {"pipeline": pipeline, "heartbeats": heartbeats,
            "wedges": sorted(wedges.values(), key=lambda w: w["stage"])}
