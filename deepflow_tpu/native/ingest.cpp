// Native columnar decode for the non-flow ingest hot path: DocumentBatch
// (metrics) and TpuSpanBatch (device spans + HBM samples) protobuf wire
// -> struct-of-arrays, no Python objects until the store append.
//
// Companion to pbcols.cpp (FlowLogBatch): same caller-owned packed-struct
// ABI, same shared string arena with (offset,len) cells, same -1-on-any-
// trouble contract so Python can always fall back to the protobuf path.
// Layouts must match the ctypes bindings in native/__init__.py; bump
// DF_ABI_VERSION in dfnative.cpp on ANY change here.
//
// Wire schema parsed here must match deepflow_tpu/proto/messages.proto:
//   DocumentBatch{ repeated Document docs = 1; }
//   Document{ timestamp_s=1, MetricTag tag=2, FlowMeter flow_meter=3,
//             AppMeter app_meter=4, interval_s=5 }
//   TpuSpanBatch{ repeated TpuSpan spans = 1;
//                 repeated TpuMemorySample memory = 2; }
// Unknown fields are skipped by wire type so proto ADDITIONS stay
// compatible.

#include <cstdint>
#include <cstring>

namespace {

struct Reader {
    const uint8_t* p;
    const uint8_t* end;
    bool ok = true;

    uint64_t varint() {
        uint64_t v = 0;
        int shift = 0;
        while (p < end && shift < 64) {
            uint8_t b = *p++;
            v |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) return v;
            shift += 7;
        }
        ok = false;
        return 0;
    }

    bool skip(uint32_t wire) {
        switch (wire) {
            case 0: varint(); return ok;
            case 1: if (end - p < 8) return ok = false; p += 8; return true;
            case 2: {
                uint64_t n = varint();
                if (!ok || (uint64_t)(end - p) < n) return ok = false;
                p += n;
                return true;
            }
            case 5: if (end - p < 4) return ok = false; p += 4; return true;
            default: return ok = false;
        }
    }
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// DocumentBatch (METRICS frames) -> DfDocCols
// ---------------------------------------------------------------------------

// ip_flags bits: decoders.py's _ip_decode maps empty bytes to "" (NOT
// "0.0.0.0"), and v6/odd-length addresses take the python formatting
// path — the flags let Python reproduce that exactly or bail out.
enum {
    DF_IP_SRC_EMPTY = 1,  // tag.ip_src was absent/empty -> ""
    DF_IP_DST_EMPTY = 2,  // tag.ip_dst was absent/empty -> ""
    DF_IP_FALLBACK = 4,   // length not in {0,4}: batch needs the pb path
};

#pragma pack(push, 1)
struct DfDocCols {
    uint64_t* timestamp_s;
    // FlowMeter (column names match flow_metrics.network.1s)
    uint64_t* packet_tx;
    uint64_t* packet_rx;
    uint64_t* byte_tx;
    uint64_t* byte_rx;
    uint64_t* flow_count;
    uint64_t* new_flow;
    uint64_t* closed_flow;
    uint64_t* rtt_sum;
    uint64_t* rtt_count;
    uint64_t* retrans;
    uint64_t* syn_count;
    uint64_t* synack_count;
    // AppMeter (column names match flow_metrics.application.1s)
    uint64_t* request;
    uint64_t* response;
    uint64_t* rrt_sum;
    uint64_t* rrt_count;
    uint64_t* rrt_max;
    uint64_t* error_client;
    uint64_t* error_server;
    uint64_t* timeout;
    // MetricTag
    uint32_t* ip4_src;         // host byte order; see ip_flags
    uint32_t* ip4_dst;
    uint32_t* proto;
    uint32_t* l7_protocol;
    uint32_t* app_svc_off;     // tag.app_service in the arena
    uint32_t* app_svc_len;
    uint16_t* port;
    uint8_t*  direction;
    uint8_t*  has_flow;        // wire presence == pb HasField
    uint8_t*  has_app;
    uint8_t*  ip_flags;        // DF_IP_* bits
    // shared string arena
    uint8_t*  arena;
    uint32_t  arena_cap;
    uint32_t  arena_used;
    uint32_t  cap;
};
#pragma pack(pop)

static bool doc_arena_put(uint8_t* arena, uint32_t cap, uint32_t* used,
                          const uint8_t* s, uint64_t n, uint32_t* off_out,
                          uint32_t* len_out) {
    if (*used + n > cap) return false;
    memcpy(arena + *used, s, n);
    *off_out = *used;
    *len_out = (uint32_t)n;
    *used += (uint32_t)n;
    return true;
}

// Parse FlowMeter / AppMeter submessages: all fields are varints, so one
// loop with a field->slot table per meter keeps them branch-cheap.
static bool parse_flow_meter(const uint8_t* sub, uint64_t n, DfDocCols* c,
                             uint32_t r) {
    Reader rd{sub, sub + n};
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire != 0) {
            if (!rd.skip(wire)) return false;
            continue;
        }
        uint64_t v = rd.varint();
        if (!rd.ok) return false;
        switch (field) {
            case 1: c->packet_tx[r] = v; break;
            case 2: c->packet_rx[r] = v; break;
            case 3: c->byte_tx[r] = v; break;
            case 4: c->byte_rx[r] = v; break;
            case 5: c->flow_count[r] = v; break;
            case 6: c->new_flow[r] = v; break;
            case 7: c->closed_flow[r] = v; break;
            case 8: c->rtt_sum[r] = v; break;
            case 9: c->rtt_count[r] = v; break;
            case 10: c->retrans[r] = v; break;
            case 11: c->syn_count[r] = v; break;
            case 12: c->synack_count[r] = v; break;
            default: break;
        }
    }
    return rd.ok;
}

static bool parse_app_meter(const uint8_t* sub, uint64_t n, DfDocCols* c,
                            uint32_t r) {
    Reader rd{sub, sub + n};
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire != 0) {
            if (!rd.skip(wire)) return false;
            continue;
        }
        uint64_t v = rd.varint();
        if (!rd.ok) return false;
        switch (field) {
            case 1: c->request[r] = v; break;
            case 2: c->response[r] = v; break;
            case 3: c->rrt_sum[r] = v; break;
            case 4: c->rrt_count[r] = v; break;
            case 5: c->rrt_max[r] = v; break;
            case 6: c->error_client[r] = v; break;
            case 7: c->error_server[r] = v; break;
            case 8: c->timeout[r] = v; break;
            default: break;
        }
    }
    return rd.ok;
}

static bool parse_metric_tag(const uint8_t* sub, uint64_t n, DfDocCols* c,
                             uint32_t r) {
    Reader rd{sub, sub + n};
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire == 0) {
            uint64_t v = rd.varint();
            if (!rd.ok) return false;
            switch (field) {
                case 3: c->port[r] = (uint16_t)v; break;
                case 4: c->proto[r] = (uint32_t)v; break;
                case 5: c->l7_protocol[r] = (uint32_t)v; break;
                case 10: c->direction[r] = (uint8_t)v; break;
                default: break;  // 6 agent_id, 8/9 gpids unused by rows
            }
            continue;
        }
        if (wire == 2) {
            uint64_t kn = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < kn) return false;
            const uint8_t* ks = rd.p;
            rd.p += kn;
            if (field == 1 || field == 2) {
                if (kn == 4) {
                    uint32_t ip = (uint32_t)ks[0] << 24 |
                                  (uint32_t)ks[1] << 16 |
                                  (uint32_t)ks[2] << 8 | ks[3];
                    (field == 1 ? c->ip4_src : c->ip4_dst)[r] = ip;
                    // field may repeat on the wire: last value wins, so
                    // clear a previously set empty/fallback bit
                    c->ip_flags[r] &= (uint8_t)~(
                        field == 1 ? DF_IP_SRC_EMPTY : DF_IP_DST_EMPTY);
                } else if (kn == 0) {
                    c->ip_flags[r] |= (uint8_t)(
                        field == 1 ? DF_IP_SRC_EMPTY : DF_IP_DST_EMPTY);
                } else {
                    c->ip_flags[r] |= DF_IP_FALLBACK;  // v6 / malformed
                }
            } else if (field == 7 && kn) {  // app_service
                if (!doc_arena_put(c->arena, c->arena_cap, &c->arena_used,
                                   ks, kn, &c->app_svc_off[r],
                                   &c->app_svc_len[r]))
                    return false;
            }
            continue;
        }
        if (!rd.skip(wire)) return false;
    }
    return rd.ok;
}

static bool parse_doc(const uint8_t* sub, uint64_t n, DfDocCols* c,
                      uint32_t r) {
    // zero the row (batches reuse arrays)
    c->timestamp_s[r] = 0;
    c->packet_tx[r] = c->packet_rx[r] = c->byte_tx[r] = c->byte_rx[r] = 0;
    c->flow_count[r] = c->new_flow[r] = c->closed_flow[r] = 0;
    c->rtt_sum[r] = c->rtt_count[r] = c->retrans[r] = 0;
    c->syn_count[r] = c->synack_count[r] = 0;
    c->request[r] = c->response[r] = 0;
    c->rrt_sum[r] = c->rrt_count[r] = c->rrt_max[r] = 0;
    c->error_client[r] = c->error_server[r] = c->timeout[r] = 0;
    c->ip4_src[r] = c->ip4_dst[r] = 0;
    c->proto[r] = c->l7_protocol[r] = 0;
    c->app_svc_off[r] = c->app_svc_len[r] = 0;
    c->port[r] = 0;
    c->direction[r] = 0;
    c->has_flow[r] = c->has_app[r] = 0;
    // absent bytes fields decode as empty in pb, so start from "empty"
    c->ip_flags[r] = DF_IP_SRC_EMPTY | DF_IP_DST_EMPTY;

    Reader rd{sub, sub + n};
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire == 0) {
            uint64_t v = rd.varint();
            if (!rd.ok) return false;
            if (field == 1) c->timestamp_s[r] = v;
            // 5 interval_s: unused by the row build
            continue;
        }
        if (wire == 2) {
            uint64_t sn = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < sn) return false;
            const uint8_t* sp = rd.p;
            rd.p += sn;
            switch (field) {
                case 2:
                    if (!parse_metric_tag(sp, sn, c, r)) return false;
                    break;
                case 3:
                    // wire presence == pb HasField (an explicitly set but
                    // default-valued submessage still serializes its tag)
                    c->has_flow[r] = 1;
                    if (!parse_flow_meter(sp, sn, c, r)) return false;
                    break;
                case 4:
                    c->has_app[r] = 1;
                    if (!parse_app_meter(sp, sn, c, r)) return false;
                    break;
                default:
                    break;
            }
            continue;
        }
        if (!rd.skip(wire)) return false;
    }
    return rd.ok;
}

// Decode a DocumentBatch columnar. Returns the number of docs decoded,
// or -1 on malformed input / capacity overflow (caller falls back to the
// Python pb path).
int64_t df_decode_doc_cols(const uint8_t* data, uint64_t len,
                           DfDocCols* cols) {
    Reader rd{data, data + len};
    uint32_t n = 0;
    cols->arena_used = 0;
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return -1;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (field == 1 && wire == 2) {
            uint64_t sublen = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < sublen) return -1;
            if (n >= cols->cap) return -1;
            const uint8_t* sub = rd.p;
            rd.p += sublen;
            if (!parse_doc(sub, sublen, cols, n)) return -1;
            n++;
        } else if (!rd.skip(wire)) {
            return -1;
        }
    }
    if (!rd.ok) return -1;
    return n;
}

// ---------------------------------------------------------------------------
// TpuSpanBatch (TPU_SPAN frames) -> DfSpanCols
// ---------------------------------------------------------------------------

// Span string slots (order matches SpanColumnDecoder.STRS in
// native/__init__.py): 0 hlo_module(7) 1 hlo_op(8) 2 hlo_category(9)
// 3 collective(15) 4 process_name(20)
#define DF_SPAN_NSTR 5

#pragma pack(push, 1)
struct DfSpanCols {
    // spans
    uint64_t* start_ns;
    uint64_t* duration_ns;
    uint64_t* flops;
    uint64_t* bytes_accessed;
    uint64_t* bytes_transferred;
    uint64_t* step;
    uint32_t* device_id;
    uint32_t* chip_id;
    uint32_t* core_id;
    uint32_t* slice_id;
    uint32_t* kind;
    uint32_t* program_id;
    uint32_t* run_id;
    uint32_t* replica_group_size;
    uint32_t* pid;
    uint32_t* str_off[DF_SPAN_NSTR];
    uint32_t* str_len[DF_SPAN_NSTR];
    // memory samples
    uint64_t* m_timestamp_ns;
    uint64_t* m_bytes_in_use;
    uint64_t* m_peak_bytes_in_use;
    uint64_t* m_bytes_limit;
    uint64_t* m_largest_free_block;
    uint32_t* m_device_id;
    uint32_t* m_num_allocs;
    uint32_t* m_pid;
    uint32_t* m_pname_off;
    uint32_t* m_pname_len;
    // shared string arena
    uint8_t*  arena;
    uint32_t  arena_cap;
    uint32_t  arena_used;
    uint32_t  cap;       // span rows
    uint32_t  mem_cap;   // memory rows
    uint32_t  n_mem;     // OUT: memory rows decoded
};
#pragma pack(pop)

static int span_str_slot(uint32_t field) {
    switch (field) {
        case 7: return 0; case 8: return 1; case 9: return 2;
        case 15: return 3; case 20: return 4;
        default: return -1;
    }
}

static bool parse_span(const uint8_t* sub, uint64_t n, DfSpanCols* c,
                       uint32_t r) {
    c->start_ns[r] = c->duration_ns[r] = c->flops[r] = 0;
    c->bytes_accessed[r] = c->bytes_transferred[r] = c->step[r] = 0;
    c->device_id[r] = c->chip_id[r] = c->core_id[r] = 0;
    c->slice_id[r] = c->kind[r] = c->program_id[r] = 0;
    c->run_id[r] = c->replica_group_size[r] = c->pid[r] = 0;
    for (int i = 0; i < DF_SPAN_NSTR; i++)
        c->str_off[i][r] = c->str_len[i][r] = 0;

    Reader rd{sub, sub + n};
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire == 0) {
            uint64_t v = rd.varint();
            if (!rd.ok) return false;
            switch (field) {
                case 1: c->start_ns[r] = v; break;
                case 2: c->duration_ns[r] = v; break;
                case 3: c->device_id[r] = (uint32_t)v; break;
                case 4: c->chip_id[r] = (uint32_t)v; break;
                case 5: c->core_id[r] = (uint32_t)v; break;
                case 6: c->slice_id[r] = (uint32_t)v; break;
                case 10: c->kind[r] = (uint32_t)v; break;
                case 11: c->flops[r] = v; break;
                case 12: c->bytes_accessed[r] = v; break;
                case 13: c->program_id[r] = (uint32_t)v; break;
                case 14: c->run_id[r] = (uint32_t)v; break;
                case 16: c->bytes_transferred[r] = v; break;
                case 17: c->replica_group_size[r] = (uint32_t)v; break;
                case 18: c->step[r] = v; break;
                case 19: c->pid[r] = (uint32_t)v; break;
                default: break;
            }
            continue;
        }
        if (wire == 2) {
            uint64_t kn = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < kn) return false;
            const uint8_t* ks = rd.p;
            rd.p += kn;
            int slot = span_str_slot(field);
            if (slot >= 0 && kn) {
                if (!doc_arena_put(c->arena, c->arena_cap, &c->arena_used,
                                   ks, kn, &c->str_off[slot][r],
                                   &c->str_len[slot][r]))
                    return false;
            }
            continue;
        }
        if (!rd.skip(wire)) return false;
    }
    return rd.ok;
}

static bool parse_mem_sample(const uint8_t* sub, uint64_t n, DfSpanCols* c,
                             uint32_t r) {
    c->m_timestamp_ns[r] = c->m_bytes_in_use[r] = 0;
    c->m_peak_bytes_in_use[r] = c->m_bytes_limit[r] = 0;
    c->m_largest_free_block[r] = 0;
    c->m_device_id[r] = c->m_num_allocs[r] = c->m_pid[r] = 0;
    c->m_pname_off[r] = c->m_pname_len[r] = 0;

    Reader rd{sub, sub + n};
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire == 0) {
            uint64_t v = rd.varint();
            if (!rd.ok) return false;
            switch (field) {
                case 1: c->m_timestamp_ns[r] = v; break;
                case 2: c->m_device_id[r] = (uint32_t)v; break;
                case 3: c->m_bytes_in_use[r] = v; break;
                case 4: c->m_peak_bytes_in_use[r] = v; break;
                case 5: c->m_bytes_limit[r] = v; break;
                case 6: c->m_largest_free_block[r] = v; break;
                case 7: c->m_num_allocs[r] = (uint32_t)v; break;
                case 8: c->m_pid[r] = (uint32_t)v; break;
                default: break;
            }
            continue;
        }
        if (wire == 2) {
            uint64_t kn = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < kn) return false;
            const uint8_t* ks = rd.p;
            rd.p += kn;
            if (field == 9 && kn) {
                if (!doc_arena_put(c->arena, c->arena_cap, &c->arena_used,
                                   ks, kn, &c->m_pname_off[r],
                                   &c->m_pname_len[r]))
                    return false;
            }
            continue;
        }
        if (!rd.skip(wire)) return false;
    }
    return rd.ok;
}

// Decode a TpuSpanBatch columnar. Returns the number of SPAN rows (memory
// rows are counted in cols->n_mem), or -1 on malformed input / capacity
// overflow (caller falls back to the Python pb path).
int64_t df_decode_span_cols(const uint8_t* data, uint64_t len,
                            DfSpanCols* cols) {
    Reader rd{data, data + len};
    uint32_t n = 0, nm = 0;
    cols->arena_used = 0;
    cols->n_mem = 0;
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return -1;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (field == 1 && wire == 2) {
            uint64_t sublen = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < sublen) return -1;
            if (n >= cols->cap) return -1;
            const uint8_t* sub = rd.p;
            rd.p += sublen;
            if (!parse_span(sub, sublen, cols, n)) return -1;
            n++;
        } else if (field == 2 && wire == 2) {
            uint64_t sublen = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < sublen) return -1;
            if (nm >= cols->mem_cap) return -1;
            const uint8_t* sub = rd.p;
            rd.p += sublen;
            if (!parse_mem_sample(sub, sublen, cols, nm)) return -1;
            nm++;
        } else if (!rd.skip(wire)) {
            return -1;
        }
    }
    if (!rd.ok) return -1;
    cols->n_mem = nm;
    return n;
}

}  // extern "C"
