"""ctypes bindings for libdfnative.so (C++ hot paths) with pure-Python
fallback. Build: `make -C deepflow_tpu/native` (auto-attempted on first
import; failures leave the Python paths in charge)."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

log = logging.getLogger("df.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdfnative.so")
_lib = None
_ABI_VERSION = 8  # must match df_abi_version() in dfnative.cpp


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _DIR], capture_output=True,
                       timeout=120, check=True)
        return True
    except Exception as e:
        log.debug("dfnative build failed: %s", e)
        return False


def load():
    """Load (building first — make is mtime-based so a fresh dfnative.cpp
    always rebuilds). Returns the ctypes lib or None. DF_NO_NATIVE=1 is
    the operator/test kill-switch: every native fast path then reports
    unavailable and the pure-Python fallbacks take over."""
    global _lib
    if os.environ.get("DF_NO_NATIVE"):
        return None
    if _lib is not None:
        return _lib
    if not _build() and not os.path.exists(_SO):
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        log.debug("dfnative load failed: %s", e)
        return None
    # stale-.so guard: a previously built lib with older signatures/struct
    # layouts would be called with mismatched dtypes (silent corruption,
    # not a clean error) — check the ABI version and refuse the whole lib.
    # _ABI_VERSION must match df_abi_version() in dfnative.cpp; bump both
    # on any exported-signature or packed-struct change.
    try:
        lib.df_abi_version.restype = ctypes.c_int32
        got = lib.df_abi_version()
    except AttributeError:
        got = -1
    if got != _ABI_VERSION:
        log.warning("libdfnative.so ABI %d != expected %d; "
                    "rebuild failed? falling back to pure Python", got,
                    _ABI_VERSION)
        return None
    lib.df_dict_new.restype = ctypes.c_void_p
    lib.df_dict_free.argtypes = [ctypes.c_void_p]
    lib.df_dict_len.argtypes = [ctypes.c_void_p]
    lib.df_dict_len.restype = ctypes.c_uint64
    lib.df_dict_encode_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.uint32), ctypes.c_uint32,
        np.ctypeslib.ndpointer(np.uint32)]
    lib.df_dict_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint32]
    lib.df_dict_lookup.restype = ctypes.c_uint32
    lib.df_dict_get.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                ctypes.c_char_p, ctypes.c_uint32]
    lib.df_dict_get.restype = ctypes.c_int32
    lib.df_dict_load.argtypes = lib.df_dict_encode_batch.argtypes[:4]
    lib.df_dict_encode_arena.restype = ctypes.c_uint64
    lib.df_dict_encode_arena.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,            # handle, arena ptr
        np.ctypeslib.ndpointer(np.uint32),           # offs
        np.ctypeslib.ndpointer(np.uint32),           # lens
        ctypes.c_uint32, np.ctypeslib.ndpointer(np.uint32)]  # n, out
    lib.df_decode_eth.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                  ctypes.c_void_p]
    lib.df_decode_eth.restype = ctypes.c_int32
    lib.df_decode_eth_batch.argtypes = [
        ctypes.c_char_p, np.ctypeslib.ndpointer(np.uint32), ctypes.c_uint32,
        ctypes.c_void_p, np.ctypeslib.ndpointer(np.uint8)]
    # -- native flow map ----------------------------------------------------
    lib.df_fm_new.restype = ctypes.c_void_p
    lib.df_fm_new.argtypes = [ctypes.c_uint32]
    lib.df_fm_free.argtypes = [ctypes.c_void_p]
    lib.df_fm_inject_batch.restype = ctypes.c_uint64
    lib.df_fm_inject_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.uint32),           # offsets
        np.ctypeslib.ndpointer(np.uint64),           # ts_ns
        ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_uint32,            # l7 buf
        ctypes.c_void_p, ctypes.c_uint32,            # l7 events
        ctypes.POINTER(ctypes.c_uint32),             # n_l7
        np.ctypeslib.ndpointer(np.uint32),           # slow_idx
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32)]  # n_slow
    lib.df_fm_set_l7.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint16, ctypes.c_uint16, ctypes.c_uint8,
        ctypes.c_uint8, ctypes.c_uint32, ctypes.c_int32]
    lib.df_fm_tick.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.df_fm_poll_closed.restype = ctypes.c_uint32
    lib.df_fm_poll_closed.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_uint32]
    lib.df_fm_export_active.restype = ctypes.c_uint32
    lib.df_fm_export_active.argtypes = lib.df_fm_poll_closed.argtypes
    lib.df_fm_flush_all.argtypes = [ctypes.c_void_p]
    lib.df_fm_active_count.restype = ctypes.c_uint32
    lib.df_fm_active_count.argtypes = [ctypes.c_void_p]
    lib.df_fm_closed_count.restype = ctypes.c_uint32
    lib.df_fm_closed_count.argtypes = [ctypes.c_void_p]
    lib.df_fm_stats.argtypes = [ctypes.c_void_p,
                                np.ctypeslib.ndpointer(np.uint64)]
    lib.df_fm_exclude_port.argtypes = [ctypes.c_void_p, ctypes.c_uint16,
                                       ctypes.c_int32]
    # -- TPACKET_V3 ring ----------------------------------------------------
    lib.df_ring_open.restype = ctypes.c_void_p
    lib.df_ring_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                 ctypes.c_uint32,
                                 ctypes.POINTER(ctypes.c_int32)]
    lib.df_ring_close.argtypes = [ctypes.c_void_p]
    lib.df_ring_rx_batch.restype = ctypes.c_int64
    lib.df_ring_rx_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32)]
    lib.df_ring_drops.restype = ctypes.c_uint64
    lib.df_ring_drops.argtypes = [ctypes.c_void_p]
    lib.df_ring_promisc.restype = ctypes.c_int32
    lib.df_ring_promisc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int32]
    # -- columnar protobuf decode (ingest hot path) -------------------------
    # data pointers are c_void_p (not c_char_p) so the zero-copy receiver
    # hand-off can pass raw addresses of read-only memoryviews over the
    # socket recv buffer — see _payload_buf()
    lib.df_decode_l4_cols.restype = ctypes.c_int64
    lib.df_decode_l4_cols.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.uint32),           # l7_off
        np.ctypeslib.ndpointer(np.uint32),           # l7_len
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32)]  # n_l7
    lib.df_decode_l7_cols.restype = ctypes.c_int64
    lib.df_decode_l7_cols.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
    lib.df_decode_doc_cols.restype = ctypes.c_int64
    lib.df_decode_doc_cols.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
    lib.df_decode_span_cols.restype = ctypes.c_int64
    lib.df_decode_span_cols.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
    # -- encoded query execution (qexec.cpp) --------------------------------
    lib.df_qx_group.restype = ctypes.c_int64
    lib.df_qx_group.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_uint32, ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.uint64),           # order_out
        np.ctypeslib.ndpointer(np.uint64)]           # bounds_out
    lib.df_qx_isin_u32.argtypes = [
        np.ctypeslib.ndpointer(np.uint32), ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.uint32), ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.uint8)]
    lib.df_qx_agg_f64.argtypes = [
        np.ctypeslib.ndpointer(np.float64),
        np.ctypeslib.ndpointer(np.uint64),           # order
        np.ctypeslib.ndpointer(np.uint64),           # bounds (n_groups+1)
        ctypes.c_uint64, ctypes.c_int32,
        np.ctypeslib.ndpointer(np.float64)]          # out
    lib.df_qx_sel_cmp.restype = ctypes.c_int64
    lib.df_qx_sel_cmp.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.uint64)]           # out_idx
    lib.df_qx_sel_isin_u32.restype = ctypes.c_int64
    lib.df_qx_sel_isin_u32.argtypes = [
        np.ctypeslib.ndpointer(np.uint32), ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.uint32), ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.uint64)]           # out_idx
    lib.df_qx_gather.restype = ctypes.c_int32
    lib.df_qx_gather.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32,
        np.ctypeslib.ndpointer(np.uint64), ctypes.c_uint64,
        ctypes.c_void_p]
    _lib = lib
    return lib


# must match #pragma pack(1) struct FlowRecord in flowmap.cpp
FLOW_RECORD_DTYPE = np.dtype([
    ("flow_id", np.uint64),
    ("ip_src", np.uint32), ("ip_dst", np.uint32),
    ("port_src", np.uint16), ("port_dst", np.uint16),
    ("protocol", np.uint8), ("state", np.uint8),
    ("close_type", np.uint8), ("closed", np.uint8),
    ("start_ns", np.uint64), ("end_ns", np.uint64),
    ("tx_packets", np.uint64), ("rx_packets", np.uint64),
    ("tx_bytes", np.uint64), ("rx_bytes", np.uint64),
    ("tx_retrans", np.uint32), ("rx_retrans", np.uint32),
    ("tx_zero_window", np.uint32), ("rx_zero_window", np.uint32),
    ("tx_flags_bits", np.uint8), ("rx_flags_bits", np.uint8),
    ("syn_count", np.uint16), ("synack_count", np.uint16),
    ("rtt_us", np.uint32),
    ("tunnel_type", np.uint8), ("tunnel_id", np.uint32)])

# must match #pragma pack(1) struct SlowEvent in flowmap.cpp
SLOW_EVENT_DTYPE = np.dtype([
    ("ts_ns", np.uint64), ("off", np.uint32), ("len", np.uint32)])

# must match #pragma pack(1) struct L7Event in flowmap.cpp
L7_EVENT_DTYPE = np.dtype([
    ("flow_id", np.uint64), ("ts_ns", np.uint64),
    ("payload_off", np.uint32), ("payload_len", np.uint32),
    ("is_tx", np.uint8), ("protocol", np.uint8),
    ("ip_src", np.uint32), ("ip_dst", np.uint32),
    ("port_src", np.uint16), ("port_dst", np.uint16),
    ("tunnel_type", np.uint8), ("tunnel_id", np.uint32)])


# packet record layout must match struct DfPacketOut in dfpacket.h
PACKET_DTYPE = np.dtype([
    ("ip_src", np.uint32), ("ip_dst", np.uint32),
    ("port_src", np.uint16), ("port_dst", np.uint16),
    ("protocol", np.uint8), ("tcp_flags", np.uint8),
    ("window", np.uint16), ("seq", np.uint32), ("ack", np.uint32),
    ("payload_off", np.uint32), ("payload_len", np.uint32),
    ("tunnel_type", np.uint8), ("_pad", np.uint8, (3,)),
    ("tunnel_id", np.uint32)], align=True)


def decode_eth_batch(frames: list[bytes]):
    """Decode a batch of ethernet frames natively.

    Returns (records: structured array PACKET_DTYPE, ok: bool array) or
    None when the native lib is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    n = len(frames)
    offsets = np.zeros(n + 1, dtype=np.uint32)
    total = 0
    for i, f in enumerate(frames):
        total += len(f)
        offsets[i + 1] = total
    data = b"".join(frames)
    outs = np.zeros(n, dtype=PACKET_DTYPE)
    ok = np.zeros(n, dtype=np.uint8)
    lib.df_decode_eth_batch(data, offsets, n,
                            outs.ctypes.data_as(ctypes.c_void_p), ok)
    return outs, ok.astype(bool)


class NativeDict:
    """C++-backed string dictionary (standalone handle). For PYTHON-string
    inputs CPython's dict wins through ctypes marshalling (see dfnative.cpp
    header) — the store's hot path instead drives the same C++ table
    through Dictionary.encode_arena (store/dictionary.py), where inputs
    are (arena, off, len) cells that never become Python strings."""

    def __init__(self) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("dfnative unavailable")
        self._lib = lib
        self._h = lib.df_dict_new()

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.df_dict_free(self._h)
                self._h = None
        except Exception:
            pass

    def __len__(self) -> int:
        return self._lib.df_dict_len(self._h)

    def encode_many(self, values: list[str]) -> np.ndarray:
        n = len(values)
        enc = [v.encode("utf-8", "replace") for v in values]
        offsets = np.zeros(n + 1, dtype=np.uint32)
        total = 0
        for i, b in enumerate(enc):
            total += len(b)
            offsets[i + 1] = total
        data = b"".join(enc)
        out = np.empty(n, dtype=np.uint32)
        self._lib.df_dict_encode_batch(self._h, data, offsets, n, out)
        return out

    def lookup(self, s: str):
        b = s.encode("utf-8", "replace")
        r = self._lib.df_dict_lookup(self._h, b, len(b))
        return None if r == 0xFFFFFFFF else int(r)

    def decode(self, sid: int) -> str:
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.df_dict_get(self._h, sid, buf, 4096)
        if n < 0:
            raise IndexError(sid)
        if n > 4096:
            buf = ctypes.create_string_buffer(n)
            self._lib.df_dict_get(self._h, sid, buf, n)
        return buf.raw[:n].decode("utf-8", "replace")

    def load_entries(self, values: list[str]) -> None:
        enc = [v.encode("utf-8", "replace") for v in values]
        offsets = np.zeros(len(enc) + 1, dtype=np.uint32)
        total = 0
        for i, b in enumerate(enc):
            total += len(b)
            offsets[i + 1] = total
        self._lib.df_dict_load(self._h, b"".join(enc), offsets, len(enc))


def available() -> bool:
    return load() is not None


def _payload_buf(payload):
    """(address, nbytes, keepalive) for a bytes-like payload. Accepts the
    read-only memoryviews the zero-copy receiver hand-off produces as
    well as plain bytes — np.frombuffer shares memory in both cases, so
    nothing is copied here. The keepalive array must stay referenced for
    the duration of the native call."""
    a = np.frombuffer(payload, dtype=np.uint8)
    return a.ctypes.data, a.nbytes, a


class ArenaStrings:
    """A string column that has not been materialized: (arena, off, len)
    triples straight out of a native columnar decoder. The store's
    dictionary encoder consumes this form natively (one batched
    intern under one lock, Dictionary.encode_arena) so hot-path string
    cells never become Python objects; every other consumer (exporters,
    trace trees, the pb fallback) gets lazy decode via tolist()/[i].

    The constructor COPIES the three arrays — decoder buffers are reused
    per batch, while a column handed to the store must stay stable."""

    __slots__ = ("arena", "off", "lens", "_list")

    def __init__(self, arena: np.ndarray, off: np.ndarray,
                 lens: np.ndarray) -> None:
        self.arena = np.ascontiguousarray(arena, dtype=np.uint8).copy()
        self.off = np.ascontiguousarray(off, dtype=np.uint32).copy()
        self.lens = np.ascontiguousarray(lens, dtype=np.uint32).copy()
        self._list: list[str] | None = None

    def __len__(self) -> int:
        return len(self.off)

    def __getitem__(self, i):
        if self._list is not None:
            return self._list[i]
        o, ln = int(self.off[i]), int(self.lens[i])
        if not ln:
            return ""
        return bytes(self.arena[o:o + ln]).decode("utf-8", "replace")

    def tolist(self) -> list[str]:
        """Materialize (memoized; decodes each DISTINCT value once —
        real traffic repeats a bounded string set per batch)."""
        if self._list is None:
            ab = self.arena.tobytes()
            memo: dict[bytes, str] = {}
            get = memo.get
            out = []
            for o, ln in zip(self.off.tolist(), self.lens.tolist()):
                if not ln:
                    out.append("")
                    continue
                b = ab[o:o + ln]
                s = get(b)
                if s is None:
                    s = memo[b] = b.decode("utf-8", "replace")
                out.append(s)
            self._list = out
        return self._list

    def __iter__(self):
        return iter(self.tolist())


# -- columnar L4 protobuf decode (must mirror DfL4Cols in pbcols.cpp) -------

class _DfL4Cols(ctypes.Structure):
    _pack_ = 1
    _fields_ = (
        [(n, ctypes.c_void_p) for n in (
            "flow_id", "start_time_ns", "end_time_ns", "packet_tx",
            "packet_rx", "byte_tx", "byte_rx", "l7_request", "l7_response",
            "rtt_us", "art_us", "retrans_tx", "retrans_rx", "zero_win_tx",
            "zero_win_rx", "close_type", "syn_count", "synack_count",
            "gpid_0", "gpid_1", "ip4_src", "ip4_dst", "is_v6",
            "ip6_src_off", "ip6_dst_off", "port_src", "port_dst", "proto",
            "tap_port", "tunnel_type", "tunnel_id", "pod0_off", "pod0_len",
            "pod1_off", "pod1_len", "arena")]
        + [("arena_cap", ctypes.c_uint32),
           ("arena_used", ctypes.c_uint32),
           ("cap", ctypes.c_uint32)])


class L4ColumnDecoder:
    """Reusable buffers for df_decode_l4_cols: FlowLogBatch bytes ->
    numpy column views with zero Python-object rows. decode() returns
    (n_l4, cols dict, l7_segments, arena bytes-view) or None when the
    native path can't take the batch (overflow/malformed) — caller falls
    back to the protobuf Python path."""

    U64 = ("flow_id", "start_time_ns", "end_time_ns", "packet_tx",
           "packet_rx", "byte_tx", "byte_rx", "l7_request", "l7_response")
    U32 = ("rtt_us", "art_us", "retrans_tx", "retrans_rx", "zero_win_tx",
           "zero_win_rx", "syn_count", "synack_count", "gpid_0", "gpid_1",
           "ip4_src", "ip4_dst", "ip6_src_off", "ip6_dst_off", "tap_port",
           "tunnel_id", "pod0_off", "pod0_len", "pod1_off", "pod1_len")
    U16 = ("port_src", "port_dst")
    U8 = ("close_type", "is_v6", "proto", "tunnel_type")

    def __init__(self, cap: int = 65536, arena_cap: int = 1 << 20,
                 l7_cap: int = 65536) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._lib = lib
        self.cap = cap
        self.arrays: dict[str, np.ndarray] = {}
        for names, dt in ((self.U64, np.uint64), (self.U32, np.uint32),
                          (self.U16, np.uint16), (self.U8, np.uint8)):
            for n in names:
                self.arrays[n] = np.zeros(cap, dtype=dt)
        self.arena = np.zeros(arena_cap, dtype=np.uint8)
        self._l7_off = np.zeros(l7_cap, dtype=np.uint32)
        self._l7_len = np.zeros(l7_cap, dtype=np.uint32)
        self._l7_cap = l7_cap
        self._n_l7 = ctypes.c_uint32(0)
        self._cols = _DfL4Cols()
        for n, a in self.arrays.items():
            setattr(self._cols, n, a.ctypes.data)
        self._cols.arena = self.arena.ctypes.data
        self._cols.arena_cap = arena_cap
        self._cols.cap = cap

    def decode(self, payload):
        ptr, nbytes, _keep = _payload_buf(payload)
        n = self._lib.df_decode_l4_cols(
            ptr, nbytes, ctypes.byref(self._cols),
            self._l7_off, self._l7_len, self._l7_cap,
            ctypes.byref(self._n_l7))
        if n < 0:
            return None
        n = int(n)
        n_l7 = int(self._n_l7.value)
        l7_segs = [(int(self._l7_off[i]), int(self._l7_len[i]))
                   for i in range(n_l7)]
        cols = {k: a[:n] for k, a in self.arrays.items()}
        return n, cols, l7_segs, self.arena[:self._cols.arena_used]


# -- columnar L7 protobuf decode (must mirror DfL7Cols in pbcols.cpp) -------

# string-column slot order; must match l7_str_slot() in pbcols.cpp
L7_STRS = ("version", "request_type", "request_domain", "request_resource",
           "endpoint", "response_exception", "response_result", "trace_id",
           "span_id", "parent_span_id", "x_request_id", "process_kname_0",
           "process_kname_1", "attrs_json", "pod_0", "pod_1")


class _DfL7Cols(ctypes.Structure):
    _pack_ = 1
    _fields_ = (
        [(n, ctypes.c_void_p) for n in (
            "flow_id", "start_time_ns", "end_time_ns",
            "syscall_trace_id_request", "syscall_trace_id_response",
            "captured_request_byte", "captured_response_byte",
            "l7_protocol", "request_id", "response_status",
            "response_code", "syscall_thread_0", "syscall_thread_1",
            "gpid_0", "gpid_1", "ip4_src", "ip4_dst", "is_v6",
            "ip6_src_off", "ip6_dst_off", "port_src", "port_dst", "proto",
            "tunnel_type", "tunnel_id")]
        + [("str_off", ctypes.c_void_p * 16),
           ("str_len", ctypes.c_void_p * 16),
           ("arena", ctypes.c_void_p),
           ("arena_cap", ctypes.c_uint32),
           ("arena_used", ctypes.c_uint32),
           ("cap", ctypes.c_uint32)])


class L7ColumnDecoder:
    """Reusable buffers for df_decode_l7_cols: FlowLogBatch bytes ->
    numpy column views for every L7FlowLog field the row build consumes
    (varints + 16 string columns in a shared arena). decode() returns
    (n_l7, cols dict, arena bytes-view) or None when the native path
    can't take the batch (overflow/malformed) — caller falls back to the
    protobuf Python path."""

    U64 = ("flow_id", "start_time_ns", "end_time_ns",
           "syscall_trace_id_request", "syscall_trace_id_response",
           "captured_request_byte", "captured_response_byte")
    U32 = ("l7_protocol", "request_id", "response_status",
           "syscall_thread_0", "syscall_thread_1", "gpid_0", "gpid_1",
           "ip4_src", "ip4_dst", "ip6_src_off", "ip6_dst_off", "tunnel_id")
    I32 = ("response_code",)
    U16 = ("port_src", "port_dst")
    U8 = ("is_v6", "proto", "tunnel_type")

    def __init__(self, cap: int = 65536, arena_cap: int = 1 << 22) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._lib = lib
        self.cap = cap
        self.arrays: dict[str, np.ndarray] = {}
        for names, dt in ((self.U64, np.uint64), (self.U32, np.uint32),
                          (self.I32, np.int32), (self.U16, np.uint16),
                          (self.U8, np.uint8)):
            for n in names:
                self.arrays[n] = np.zeros(cap, dtype=dt)
        for s in L7_STRS:
            self.arrays[f"{s}_off"] = np.zeros(cap, dtype=np.uint32)
            self.arrays[f"{s}_len"] = np.zeros(cap, dtype=np.uint32)
        self.arena = np.zeros(arena_cap, dtype=np.uint8)
        self._cols = _DfL7Cols()
        for names in (self.U64, self.U32, self.I32, self.U16, self.U8):
            for n in names:
                setattr(self._cols, n, self.arrays[n].ctypes.data)
        for i, s in enumerate(L7_STRS):
            self._cols.str_off[i] = self.arrays[f"{s}_off"].ctypes.data
            self._cols.str_len[i] = self.arrays[f"{s}_len"].ctypes.data
        self._cols.arena = self.arena.ctypes.data
        self._cols.arena_cap = arena_cap
        self._cols.cap = cap

    def decode(self, payload):
        ptr, nbytes, _keep = _payload_buf(payload)
        n = self._lib.df_decode_l7_cols(ptr, nbytes,
                                        ctypes.byref(self._cols))
        if n < 0:
            return None
        n = int(n)
        cols = {k: a[:n] for k, a in self.arrays.items()}
        return n, cols, self.arena[:self._cols.arena_used]


# -- columnar DocumentBatch decode (must mirror DfDocCols in ingest.cpp) ----

# ip_flags bits (must match the enum in ingest.cpp)
IP_SRC_EMPTY = 1
IP_DST_EMPTY = 2
IP_FALLBACK = 4


class _DfDocCols(ctypes.Structure):
    _pack_ = 1
    _fields_ = (
        [(n, ctypes.c_void_p) for n in (
            "timestamp_s",
            "packet_tx", "packet_rx", "byte_tx", "byte_rx", "flow_count",
            "new_flow", "closed_flow", "rtt_sum", "rtt_count", "retrans",
            "syn_count", "synack_count",
            "request", "response", "rrt_sum", "rrt_count", "rrt_max",
            "error_client", "error_server", "timeout",
            "ip4_src", "ip4_dst", "proto", "l7_protocol",
            "app_service_off", "app_service_len",
            "port", "direction", "has_flow", "has_app", "ip_flags",
            "arena")]
        + [("arena_cap", ctypes.c_uint32),
           ("arena_used", ctypes.c_uint32),
           ("cap", ctypes.c_uint32)])


class DocColumnDecoder:
    """Reusable buffers for df_decode_doc_cols: DocumentBatch bytes ->
    numpy column views for everything MetricsDecoder consumes (FlowMeter
    and AppMeter fields already under their flow_metrics column names).
    decode() returns (n, cols dict, arena bytes-view) or None when the
    native path can't take the batch — caller falls back to pb."""

    U64 = ("timestamp_s",
           "packet_tx", "packet_rx", "byte_tx", "byte_rx", "flow_count",
           "new_flow", "closed_flow", "rtt_sum", "rtt_count", "retrans",
           "syn_count", "synack_count",
           "request", "response", "rrt_sum", "rrt_count", "rrt_max",
           "error_client", "error_server", "timeout")
    U32 = ("ip4_src", "ip4_dst", "proto", "l7_protocol",
           "app_service_off", "app_service_len")
    U16 = ("port",)
    U8 = ("direction", "has_flow", "has_app", "ip_flags")

    def __init__(self, cap: int = 65536, arena_cap: int = 1 << 20) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._lib = lib
        self.cap = cap
        self.arrays: dict[str, np.ndarray] = {}
        for names, dt in ((self.U64, np.uint64), (self.U32, np.uint32),
                          (self.U16, np.uint16), (self.U8, np.uint8)):
            for n in names:
                self.arrays[n] = np.zeros(cap, dtype=dt)
        self.arena = np.zeros(arena_cap, dtype=np.uint8)
        self._cols = _DfDocCols()
        for n, a in self.arrays.items():
            setattr(self._cols, n, a.ctypes.data)
        self._cols.arena = self.arena.ctypes.data
        self._cols.arena_cap = arena_cap
        self._cols.cap = cap

    def decode(self, payload):
        ptr, nbytes, _keep = _payload_buf(payload)
        n = self._lib.df_decode_doc_cols(ptr, nbytes,
                                         ctypes.byref(self._cols))
        if n < 0:
            return None
        n = int(n)
        cols = {k: a[:n] for k, a in self.arrays.items()}
        return n, cols, self.arena[:self._cols.arena_used]


# -- columnar TpuSpanBatch decode (must mirror DfSpanCols in ingest.cpp) ----

# span string-column slot order; must match span_str_slot() in ingest.cpp
SPAN_STRS = ("hlo_module", "hlo_op", "hlo_category", "collective",
             "process_name")


class _DfSpanCols(ctypes.Structure):
    _pack_ = 1
    _fields_ = (
        [(n, ctypes.c_void_p) for n in (
            "start_ns", "duration_ns", "flops", "bytes_accessed",
            "bytes_transferred", "step",
            "device_id", "chip_id", "core_id", "slice_id", "kind",
            "program_id", "run_id", "replica_group_size", "pid")]
        + [("str_off", ctypes.c_void_p * len(SPAN_STRS)),
           ("str_len", ctypes.c_void_p * len(SPAN_STRS))]
        + [(n, ctypes.c_void_p) for n in (
            "m_timestamp_ns", "m_bytes_in_use", "m_peak_bytes_in_use",
            "m_bytes_limit", "m_largest_free_block",
            "m_device_id", "m_num_allocs", "m_pid",
            "m_pname_off", "m_pname_len", "arena")]
        + [("arena_cap", ctypes.c_uint32),
           ("arena_used", ctypes.c_uint32),
           ("cap", ctypes.c_uint32),
           ("mem_cap", ctypes.c_uint32),
           ("n_mem", ctypes.c_uint32)])


class SpanColumnDecoder:
    """Reusable buffers for df_decode_span_cols: TpuSpanBatch bytes ->
    numpy column views for spans AND memory samples (m_* columns).
    decode() returns (n_spans, cols dict, n_mem, arena bytes-view) or
    None when the native path can't take the batch — caller falls back
    to pb."""

    U64 = ("start_ns", "duration_ns", "flops", "bytes_accessed",
           "bytes_transferred", "step")
    U32 = ("device_id", "chip_id", "core_id", "slice_id", "kind",
           "program_id", "run_id", "replica_group_size", "pid")
    M_U64 = ("m_timestamp_ns", "m_bytes_in_use", "m_peak_bytes_in_use",
             "m_bytes_limit", "m_largest_free_block")
    M_U32 = ("m_device_id", "m_num_allocs", "m_pid",
             "m_pname_off", "m_pname_len")

    def __init__(self, cap: int = 65536, mem_cap: int = 16384,
                 arena_cap: int = 1 << 22) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._lib = lib
        self.cap = cap
        self.mem_cap = mem_cap
        self.arrays: dict[str, np.ndarray] = {}
        for names, dt in ((self.U64, np.uint64), (self.U32, np.uint32)):
            for n in names:
                self.arrays[n] = np.zeros(cap, dtype=dt)
        for s in SPAN_STRS:
            self.arrays[f"{s}_off"] = np.zeros(cap, dtype=np.uint32)
            self.arrays[f"{s}_len"] = np.zeros(cap, dtype=np.uint32)
        for names, dt in ((self.M_U64, np.uint64), (self.M_U32, np.uint32)):
            for n in names:
                self.arrays[n] = np.zeros(mem_cap, dtype=dt)
        self.arena = np.zeros(arena_cap, dtype=np.uint8)
        self._cols = _DfSpanCols()
        for names in (self.U64, self.U32, self.M_U64, self.M_U32):
            for n in names:
                setattr(self._cols, n, self.arrays[n].ctypes.data)
        for i, s in enumerate(SPAN_STRS):
            self._cols.str_off[i] = self.arrays[f"{s}_off"].ctypes.data
            self._cols.str_len[i] = self.arrays[f"{s}_len"].ctypes.data
        self._cols.arena = self.arena.ctypes.data
        self._cols.arena_cap = arena_cap
        self._cols.cap = cap
        self._cols.mem_cap = mem_cap

    def decode(self, payload):
        ptr, nbytes, _keep = _payload_buf(payload)
        n = self._lib.df_decode_span_cols(ptr, nbytes,
                                          ctypes.byref(self._cols))
        if n < 0:
            return None
        n = int(n)
        n_mem = int(self._cols.n_mem)
        cols = {}
        for k, a in self.arrays.items():
            cols[k] = a[:n_mem] if k.startswith("m_") else a[:n]
        return n, cols, n_mem, self.arena[:self._cols.arena_used]


# -- encoded query execution kernels (qexec.cpp) ----------------------------

def qx_group(key_cols: list[np.ndarray]):
    """Hash-group rows over encoded key columns in one O(n) native pass.

    Returns (order, bounds, n_groups) — rows `order[bounds[g]:bounds[g+1]]`
    form group g, groups in FIRST-OCCURRENCE order, rows within a group in
    original order — or None when the native lib is unavailable (caller
    uses the numpy lexsort fallback in query/engine.py). Keys are cast to
    int64 (dict ids, enum ids and ns timestamps all fit)."""
    lib = load()
    if lib is None or not key_cols:
        return None
    n = len(key_cols[0])
    order = np.empty(n, dtype=np.uint64)
    bounds = np.empty(n + 1, dtype=np.uint64)
    cast = [np.ascontiguousarray(k, dtype=np.int64) for k in key_cols]
    ptrs = (ctypes.c_void_p * len(cast))(
        *[k.ctypes.data_as(ctypes.c_void_p).value for k in cast])
    ng = lib.df_qx_group(ptrs, len(cast), n, order, bounds)
    if ng < 0:
        return None
    return order.astype(np.int64), bounds[:ng + 1].astype(np.int64), int(ng)


def qx_agg_f64(vals: np.ndarray, order: np.ndarray, bounds: np.ndarray,
               op: int):
    """Fused gather + segmented reduce: out[g] = op(vals[order[i]]) over
    [bounds[g], bounds[g+1]). op: 0=sum, 1=min, 2=max. Accumulates
    sequentially per group — bit-identical to ufunc.reduceat over the
    gathered array — and releases the GIL, so the morsel scan pool gets
    real concurrency out of it. Returns None when unavailable (caller
    falls back to numpy)."""
    lib = load()
    if lib is None:
        return None
    n_groups = len(bounds) - 1
    if n_groups < 0:
        return None
    order64 = (order.view(np.uint64)
               if order.dtype == np.int64 and order.flags.c_contiguous
               else np.ascontiguousarray(order, dtype=np.uint64))
    bounds64 = (bounds.view(np.uint64)
                if bounds.dtype == np.int64 and bounds.flags.c_contiguous
                else np.ascontiguousarray(bounds, dtype=np.uint64))
    out = np.empty(n_groups, dtype=np.float64)
    lib.df_qx_agg_f64(vals, order64, bounds64, n_groups, op, out)
    return out


def qx_sel_range(col: np.ndarray, lo, hi):
    """Ascending index list of rows where lo <= col[i] <= hi (both
    inclusive; lo/hi must already be representable in col's dtype). The
    selective-filter fast path over encoded segment columns: survivors
    come back as positions, never as a full bool mask. Returns a uint64
    index array or None when the native lib is unavailable."""
    lib = load()
    if lib is None:
        return None
    if col.dtype.kind not in "iu" or col.itemsize not in (1, 2, 4, 8):
        return None
    col = np.ascontiguousarray(col)
    n = len(col)
    udt = np.dtype(f"u{col.itemsize}")
    lo_bits = int(np.asarray(lo, dtype=col.dtype).view(udt))
    hi_bits = int(np.asarray(hi, dtype=col.dtype).view(udt))
    out = np.empty(n, dtype=np.uint64)
    m = lib.df_qx_sel_cmp(col.ctypes.data_as(ctypes.c_void_p),
                          col.itemsize, 1 if col.dtype.kind == "i" else 0,
                          n, lo_bits, hi_bits, out)
    if m < 0:
        return None
    return out[:m]


def qx_sel_isin(col: np.ndarray, ids: np.ndarray):
    """Ascending index list of rows where col[i] is in ids (native hash
    set) — the dictionary-id IN / LIKE filter as positions instead of a
    mask. Returns a uint64 index array or None when unavailable."""
    lib = load()
    if lib is None:
        return None
    col = np.ascontiguousarray(col, dtype=np.uint32)
    ids = np.ascontiguousarray(ids, dtype=np.uint32)
    out = np.empty(len(col), dtype=np.uint64)
    m = lib.df_qx_sel_isin_u32(col, len(col), ids, len(ids), out)
    if m < 0:
        return None
    return out[:m]


def qx_gather(src: np.ndarray, idx: np.ndarray):
    """out[j] = src[idx[j]] natively (idx uint64, any 1/2/4/8-byte
    dtype). Returns the gathered array or None when unavailable."""
    lib = load()
    if lib is None:
        return None
    if src.itemsize not in (1, 2, 4, 8) or src.dtype.hasobject:
        return None
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.uint64)
    out = np.empty(len(idx), dtype=src.dtype)
    rc = lib.df_qx_gather(src.ctypes.data_as(ctypes.c_void_p),
                          src.itemsize, idx, len(idx),
                          out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        return None
    return out


def qx_isin_u32(col: np.ndarray, ids: np.ndarray):
    """mask[i] = col[i] in ids via a native hash set (O(n), vs np.isin's
    sort-based O(n log m)) — the encoded-predicate filter for dictionary-id
    IN sets and LIKE pushdown. Returns a bool array or None."""
    lib = load()
    if lib is None:
        return None
    col = np.ascontiguousarray(col, dtype=np.uint32)
    ids = np.ascontiguousarray(ids, dtype=np.uint32)
    mask = np.empty(len(col), dtype=np.uint8)
    lib.df_qx_isin_u32(col, len(col), ids, len(ids), mask)
    return mask.astype(bool)
