// Out-of-process OnCPU sampler: perf_event_open + mmap rings.
//
// Reference analog: agent/src/ebpf/kernel/perf_profiler.bpf.c:688 (99Hz
// perf_event sampling) + user/profile/profile_common.c (aggregation, A/B
// swap) + kernel/perf_profiler.bpf.c:1015 PROGPE(dwarf_unwind). Redesign:
// no BPF — per-CPU inherited perf events on the target pid, frame-pointer
// callchains from PERF_SAMPLE_CALLCHAIN, and a DWARF unwinder over
// PERF_SAMPLE_REGS_USER + PERF_SAMPLE_STACK_USER walking .eh_frame tables
// (built by agent/ehframe.py, registered via df_prof_add_table — the
// trace-utils/src/unwind/dwarf.rs split). Address-level aggregation here;
// symbolization in Python (cold path, /proc/pid/maps + ELF symtab there).
// Per sample the longer of the two chains wins, so FP-omitted binaries
// get full stacks wherever a table covers the IP.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <vector>

#include <dirent.h>

#include <linux/perf_event.h>
#include <poll.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

extern "C" {

namespace {

constexpr uint32_t kRingPages = 64;  // data pages per cpu (256KB)
constexpr uint64_t kContextMask = 0xFFFFFFFFFFFFF000ULL;  // PERF_CONTEXT_*

struct CpuRing {
    int fd = -1;
    uint8_t* map = nullptr;
    size_t map_len = 0;
    std::vector<int> extra_fds;  // per-tid events redirected into this ring
};

// Existing tids of a process (inherit=1 only follows FUTURE children, so
// threads alive at attach time each need their own event, perf-record
// style).
std::vector<int> list_tids(int pid) {
    std::vector<int> tids;
    char path[64];
    snprintf(path, sizeof(path), "/proc/%d/task", pid);
    DIR* d = opendir(path);
    if (!d) {
        tids.push_back(pid);
        return tids;
    }
    while (dirent* e = readdir(d)) {
        int tid = atoi(e->d_name);
        if (tid > 0) tids.push_back(tid);
    }
    closedir(d);
    if (tids.empty()) tids.push_back(pid);
    return tids;
}

}  // namespace

// One binary's unwind rows (file vaddrs; bias maps to runtime addrs).
// Row encoding must match agent/ehframe.py: cfa_reg 0=rsp 1=rbp 2=invalid,
// INT32_MIN offsets = no rule.
struct UnwindModule {
    uint64_t start, end;  // runtime [start, end) this table covers
    uint64_t bias;        // runtime addr - file vaddr
    std::vector<uint64_t> pc;
    std::vector<uint8_t> cfa_reg;
    std::vector<int32_t> cfa_off, rbp_off, ra_off;
};

constexpr int32_t kNoRule = INT32_MIN;

struct DfProf {
    std::vector<CpuRing> rings;
    // aggregation: callchain (leaf..root addresses + tid tail) -> count
    std::map<std::vector<uint64_t>, uint64_t> agg;
    uint64_t n_samples = 0, n_lost = 0, n_export_dropped = 0;
    uint64_t n_dwarf = 0, n_fp = 0;  // which unwinder won, per sample
    uint32_t max_stack;
    int target_pid;
    bool dwarf = false;
    uint32_t stack_dump = 0;
    uint32_t ring_pages = kRingPages;
    std::vector<UnwindModule> modules;  // sorted by start
};

static long pe_open(perf_event_attr* attr, pid_t pid, int cpu) {
    return syscall(SYS_perf_event_open, attr, pid, cpu, -1,
                   PERF_FLAG_FD_CLOEXEC);
}

namespace {

// Shared attach: one ring-owning event per CPU for the first existing tid,
// per-tid events redirected into it (SET_OUTPUT, perf-record style);
// inherit picks up threads spawned later. Returns empty + *err on failure.
std::vector<CpuRing> open_rings(perf_event_attr* attr, int pid,
                                uint32_t ring_pages, int32_t* err) {
    std::vector<CpuRing> rings;
    auto cleanup = [&]() {
        for (auto& q : rings) {
            for (int efd : q.extra_fds) close(efd);
            if (q.map) munmap(q.map, q.map_len);
            if (q.fd >= 0) close(q.fd);
        }
        rings.clear();
    };
    std::vector<int> tids = list_tids(pid);
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    for (int cpu = 0; cpu < ncpu; cpu++) {
        CpuRing r;
        r.fd = (int)pe_open(attr, tids[0], cpu);
        if (r.fd < 0) {
            if (errno == ENODEV) continue;  // offline cpu
            *err = errno;
            cleanup();
            return rings;
        }
        r.map_len = (ring_pages + 1) * (size_t)getpagesize();
        r.map = (uint8_t*)mmap(nullptr, r.map_len, PROT_READ | PROT_WRITE,
                               MAP_SHARED, r.fd, 0);
        if (r.map == MAP_FAILED) {
            *err = errno;
            close(r.fd);
            cleanup();
            return rings;
        }
        ioctl(r.fd, PERF_EVENT_IOC_ENABLE, 0);
        for (size_t t = 1; t < tids.size(); t++) {
            int efd = (int)pe_open(attr, tids[t], cpu);
            if (efd < 0) continue;  // tid exited since listing: fine
            if (ioctl(efd, PERF_EVENT_IOC_SET_OUTPUT, r.fd) < 0) {
                close(efd);
                continue;
            }
            ioctl(efd, PERF_EVENT_IOC_ENABLE, 0);
            r.extra_fds.push_back(efd);
        }
        rings.push_back(r);
    }
    if (rings.empty()) *err = ENODEV;
    return rings;
}

void close_rings(std::vector<CpuRing>& rings) {
    for (auto& r : rings) {
        for (int efd : r.extra_fds) {
            ioctl(efd, PERF_EVENT_IOC_DISABLE, 0);
            close(efd);
        }
        if (r.fd >= 0) ioctl(r.fd, PERF_EVENT_IOC_DISABLE, 0);
        if (r.map) munmap(r.map, r.map_len);
        if (r.fd >= 0) close(r.fd);
    }
}

}  // namespace

// Attach to `pid` (all threads via inherit) at `freq` Hz across all CPUs.
// dwarf != 0 additionally samples user regs (bp/sp/ip) + a stack dump of
// stack_dump bytes for the .eh_frame unwinder. Returns nullptr with
// errno-like code in *err on failure.
DfProf* df_prof_open_ex(int32_t pid, uint32_t freq, uint32_t max_stack,
                        int32_t dwarf, uint32_t stack_dump, int32_t* err) {
    *err = 0;
    perf_event_attr attr;
    memset(&attr, 0, sizeof(attr));
    attr.size = sizeof(attr);
    attr.type = PERF_TYPE_SOFTWARE;
    attr.config = PERF_COUNT_SW_CPU_CLOCK;
    attr.sample_freq = freq ? freq : 99;
    attr.freq = 1;
    attr.sample_type = PERF_SAMPLE_IP | PERF_SAMPLE_TID |
                       PERF_SAMPLE_CALLCHAIN;
    if (dwarf) {
        attr.sample_type |= PERF_SAMPLE_REGS_USER | PERF_SAMPLE_STACK_USER;
        // x86-64 perf reg indices: BP=6, SP=7, IP=8
        attr.sample_regs_user = (1ULL << 6) | (1ULL << 7) | (1ULL << 8);
        if (stack_dump == 0) stack_dump = 8192;
        attr.sample_stack_user = stack_dump & ~7u;  // must be 8-aligned
    }
    attr.exclude_kernel = 1;
    attr.exclude_hv = 1;
    attr.inherit = 1;          // follow the target's threads
    attr.disabled = 1;
    attr.wakeup_events = 128;  // don't wake the poller per sample; the
                               // window timeout drains the tail

    auto* p = new DfProf();
    p->max_stack = max_stack ? max_stack : 64;
    p->target_pid = pid;
    p->dwarf = dwarf != 0;
    p->stack_dump = attr.sample_stack_user;
    // stack dumps inflate records ~8KB each: give dwarf mode 1MB rings
    // (power of two pages) so a 200ms poll interval can't overflow them
    p->ring_pages = dwarf ? 256 : kRingPages;
    p->rings = open_rings(&attr, pid, p->ring_pages, err);
    if (p->rings.empty()) {
        delete p;
        return nullptr;
    }
    return p;
}

// Back-compat entry point: FP-only sampling.
DfProf* df_prof_open(int32_t pid, uint32_t freq, uint32_t max_stack,
                     int32_t* err) {
    return df_prof_open_ex(pid, freq, max_stack, 0, 0, err);
}

// Register one binary's unwind table (from agent/ehframe.py) covering the
// runtime range [start, end) with file-vaddr rows biased by `bias`.
// NOT thread-safe against df_prof_poll: call before the poll loop starts
// or from the same thread that polls.
void df_prof_add_table(DfProf* p, uint64_t start, uint64_t end,
                       uint64_t bias, const uint64_t* pc,
                       const uint8_t* cfa_reg, const int32_t* cfa_off,
                       const int32_t* rbp_off, const int32_t* ra_off,
                       uint32_t n) {
    if (!p || !n) return;
    UnwindModule m;
    m.start = start;
    m.end = end;
    m.bias = bias;
    m.pc.assign(pc, pc + n);
    m.cfa_reg.assign(cfa_reg, cfa_reg + n);
    m.cfa_off.assign(cfa_off, cfa_off + n);
    m.rbp_off.assign(rbp_off, rbp_off + n);
    m.ra_off.assign(ra_off, ra_off + n);
    auto it = std::lower_bound(
        p->modules.begin(), p->modules.end(), m,
        [](const UnwindModule& a, const UnwindModule& b) {
            return a.start < b.start;
        });
    p->modules.insert(it, std::move(m));
}

void df_prof_clear_tables(DfProf* p) {
    if (p) p->modules.clear();
}

namespace {

const UnwindModule* find_module(const DfProf* p, uint64_t ip) {
    // modules sorted by start; find last start <= ip
    int lo = 0, hi = (int)p->modules.size() - 1, best = -1;
    while (lo <= hi) {
        int mid = (lo + hi) / 2;
        if (p->modules[mid].start <= ip) {
            best = mid;
            lo = mid + 1;
        } else {
            hi = mid - 1;
        }
    }
    if (best < 0 || ip >= p->modules[best].end) return nullptr;
    return &p->modules[best];
}

// Walk the .eh_frame rows: ip/sp/bp from sampled user regs, memory reads
// answered from the stack dump [sp_base, sp_base + stack_len).
void dwarf_walk(const DfProf* p, uint64_t ip, uint64_t sp, uint64_t bp,
                const uint8_t* stack, uint64_t sp_base, uint64_t stack_len,
                std::vector<uint64_t>& out) {
    out.clear();
    auto read_u64 = [&](uint64_t addr, uint64_t* v) -> bool {
        // overflow-safe: addr can be wild (rbp is scratch in FP-omitted
        // code), and `addr + 8` may wrap past 2^64
        if (addr < sp_base) return false;
        uint64_t off = addr - sp_base;
        if (off > stack_len || stack_len - off < 8) return false;
        memcpy(v, stack + off, 8);
        return true;
    };
    uint64_t cur = ip;
    while (out.size() < p->max_stack) {
        out.push_back(cur);
        // after the first frame `cur` is a return address: look up the
        // call site (ra - 1) so a call ending a function resolves right
        uint64_t lookup = out.size() == 1 ? cur : cur - 1;
        const UnwindModule* m = find_module(p, lookup);
        if (!m) return;
        uint64_t fpc = lookup - m->bias;
        // last row with pc <= fpc
        const auto& pcs = m->pc;
        size_t idx = std::upper_bound(pcs.begin(), pcs.end(), fpc) -
                     pcs.begin();
        if (idx == 0) return;
        idx--;
        uint8_t creg = m->cfa_reg[idx];
        int32_t ra_off = m->ra_off[idx];
        if (creg > 1 || ra_off == kNoRule) return;
        uint64_t cfa = (creg == 0 ? sp : bp) + (int64_t)m->cfa_off[idx];
        uint64_t ra = 0;
        if (!read_u64(cfa + (int64_t)ra_off, &ra)) return;
        if (m->rbp_off[idx] != kNoRule) {
            uint64_t nbp;
            if (read_u64(cfa + (int64_t)m->rbp_off[idx], &nbp)) bp = nbp;
        }
        if (ra == 0 || cfa <= sp) return;  // no progress: corrupt frame
        sp = cfa;
        cur = ra;
    }
}

}  // namespace

void df_prof_close(DfProf* p) {
    if (!p) return;
    close_rings(p->rings);
    delete p;
}

static void drain_ring(DfProf* p, CpuRing& r) {
    auto* meta = (perf_event_mmap_page*)r.map;
    uint64_t head = __atomic_load_n(&meta->data_head, __ATOMIC_ACQUIRE);
    uint64_t tail = meta->data_tail;
    size_t data_size = p->ring_pages * (size_t)getpagesize();
    uint8_t* data = r.map + getpagesize();
    std::vector<uint8_t> rec;
    std::vector<uint64_t> chain, dchain;
    while (tail < head) {
        auto* hdr = (perf_event_header*)(data + (tail % data_size));
        uint16_t size = hdr->size;
        if (size == 0) break;  // corrupt; bail
        // record may wrap the ring edge: copy out
        rec.resize(size);
        size_t off = tail % data_size;
        size_t first = data_size - off < size ? data_size - off : size;
        memcpy(rec.data(), data + off, first);
        if (first < size) memcpy(rec.data() + first, data, size - first);
        auto* h = (perf_event_header*)rec.data();
        if (h->type == PERF_RECORD_SAMPLE) {
            // layout per sample_type order: ip u64, pid u32, tid u32,
            // nr u64 + ips[nr], then (dwarf mode) regs_user: abi u64 +
            // bp/sp/ip u64, stack_user: size u64 + data + dyn_size u64
            const uint8_t* q = rec.data() + sizeof(perf_event_header);
            const uint8_t* end = rec.data() + size;
            uint64_t ip;
            memcpy(&ip, q, 8);
            q += 8;
            uint32_t spid, tid;
            memcpy(&spid, q, 4);
            memcpy(&tid, q + 4, 4);
            q += 8;
            uint64_t nr;
            memcpy(&nr, q, 8);
            q += 8;
            chain.clear();
            for (uint64_t i = 0; i < nr && q + 8 <= end; i++, q += 8) {
                uint64_t a;
                memcpy(&a, q, 8);
                if (a >= kContextMask) continue;  // context marker
                if (chain.size() < p->max_stack) chain.push_back(a);
            }
            if (p->dwarf && q + 8 <= end) {
                uint64_t abi;
                memcpy(&abi, q, 8);
                q += 8;
                uint64_t bp = 0, sp = 0, uip = 0;
                if (abi != 0 && q + 24 <= end) {
                    memcpy(&bp, q, 8);       // ascending bit order:
                    memcpy(&sp, q + 8, 8);   // BP(6), SP(7), IP(8)
                    memcpy(&uip, q + 16, 8);
                    q += 24;
                }
                if (q + 8 <= end) {
                    uint64_t ssize;
                    memcpy(&ssize, q, 8);
                    q += 8;
                    const uint8_t* sdata = q;
                    uint64_t dyn = 0;
                    if (ssize && q + ssize + 8 <= end) {
                        memcpy(&dyn, q + ssize, 8);
                        if (dyn > ssize) dyn = ssize;
                    }
                    if (abi != 0 && dyn >= 16 && sp &&
                        !p->modules.empty()) {
                        dwarf_walk(p, uip ? uip : ip, sp, bp, sdata, sp,
                                   dyn, dchain);
                        // the longer unwind wins (FP chains are truncated
                        // exactly where tables help, and vice versa)
                        if (dchain.size() > chain.size()) {
                            chain = dchain;
                            p->n_dwarf++;
                        } else if (!chain.empty()) {
                            p->n_fp++;
                        }
                    } else if (!chain.empty()) {
                        p->n_fp++;
                    }
                }
            }
            if (chain.empty() && ip < kContextMask) chain.push_back(ip);
            if (!chain.empty()) {
                chain.push_back((uint64_t)tid);  // tid tail distinguishes
                p->agg[chain]++;
                p->n_samples++;
            }
        } else if (h->type == PERF_RECORD_LOST) {
            uint64_t lost;
            memcpy(&lost, rec.data() + sizeof(perf_event_header) + 8, 8);
            p->n_lost += lost;
        }
        tail += size;
    }
    __atomic_store_n(&meta->data_tail, tail, __ATOMIC_RELEASE);
}

// Drain all rings (non-blocking unless timeout_ms > 0 and nothing ready).
// Returns samples aggregated so far in this window.
uint64_t df_prof_poll(DfProf* p, int32_t timeout_ms) {
    if (timeout_ms > 0) {
        std::vector<pollfd> fds;
        for (auto& r : p->rings) fds.push_back({r.fd, POLLIN, 0});
        poll(fds.data(), fds.size(), timeout_ms);
    }
    for (auto& r : p->rings) drain_ring(p, r);
    return p->n_samples;
}

// Export the window's unique chains and RESET (A/B swap).
// addrs: concatenated chains (leaf..root, NO tid); lens[i] = chain length;
// tids[i], counts[i] per chain. Returns number of chains written.
uint32_t df_prof_export(DfProf* p, uint64_t* addrs, uint32_t addr_cap,
                        uint16_t* lens, uint32_t* tids, uint32_t* counts,
                        uint32_t stack_cap) {
    uint32_t n = 0, used = 0;
    for (auto& kv : p->agg) {
        if (n >= stack_cap || used + (kv.first.size() - 1) > addr_cap) {
            p->n_export_dropped++;  // overflow is counted, never silent
            continue;
        }
        const auto& chain = kv.first;
        uint32_t clen = (uint32_t)chain.size() - 1;  // drop tid tail
        memcpy(addrs + used, chain.data(), (size_t)clen * 8);
        lens[n] = (uint16_t)clen;
        tids[n] = (uint32_t)chain.back();
        counts[n] = (uint32_t)kv.second;
        used += clen;
        n++;
    }
    p->agg.clear();
    return n;
}

// stats: [samples_total, lost_total, rings, export_dropped_chains]
void df_prof_stats(DfProf* p, uint64_t* out4) {
    out4[0] = p->n_samples;
    out4[1] = p->n_lost;
    out4[2] = p->rings.size();
    out4[3] = p->n_export_dropped;
}

// extended stats: adds [4] dwarf-unwound samples, [5] fp-fallback samples,
// [6] registered unwind tables
void df_prof_stats2(DfProf* p, uint64_t* out7) {
    df_prof_stats(p, out7);
    out7[4] = p->n_dwarf;
    out7[5] = p->n_fp;
    out7[6] = p->modules.size();
}

// ---------------------------------------------------------------------------
// OffCPU profiler: context-switch events with callchains.
//
// Reference analog: the OffCPU profiler of user/extended/extended.h:26-80
// (EE) over perf_profiler.bpf.c's machinery. Redesign without BPF: a
// software CONTEXT_SWITCHES event (period=1) samples a callchain at every
// switch-OUT of the target's threads, and attr.context_switch=1 delivers
// PERF_RECORD_SWITCH markers whose sample_id trailer (sample_id_all)
// carries tid+time for the switch-IN — blocked duration = in.time -
// out.time, aggregated per callchain in nanoseconds. FP chains only: an
// 8KB stack dump per switch (10k+/s under IO load) would swamp the rings,
// so DWARF stays an OnCPU-only feature.
// ---------------------------------------------------------------------------

// one drained record, time-sortable ACROSS rings: a thread migrating
// between CPUs lands its switch and resume records in different rings,
// and processing them in ring order would pair a resume against a stale
// departure — counting run time as blocked time
struct OffCpuRec {
    uint64_t t;
    uint32_t tid;
    uint8_t kind;  // 0 = switch marker (departure candidate), 1 = sample
    std::vector<uint64_t> chain;  // samples only
};

struct DfOffCpu {
    std::vector<CpuRing> rings;
    // chain (leaf..root + tid tail) -> [total_ns, count]
    std::map<std::vector<uint64_t>, std::pair<uint64_t, uint64_t>> agg;
    // tid -> time the task left the CPU (block start)
    std::map<uint32_t, uint64_t> block_start;
    std::vector<OffCpuRec> scratch;  // per-poll, sorted by time
    uint64_t n_switches = 0, n_lost = 0, n_export_dropped = 0;
    uint64_t n_switch_in = 0, n_paired = 0, n_other = 0;
    uint64_t min_block_ns = 1000;
    uint32_t max_stack = 64;
    uint32_t ring_pages = 256;  // switches burst far harder than 99Hz
    int target_pid;
};

DfOffCpu* df_offcpu_open(int32_t pid, uint32_t max_stack,
                         uint64_t min_block_ns, int32_t* err) {
    *err = 0;
    perf_event_attr attr;
    memset(&attr, 0, sizeof(attr));
    attr.size = sizeof(attr);
    attr.type = PERF_TYPE_SOFTWARE;
    attr.config = PERF_COUNT_SW_CONTEXT_SWITCHES;
    attr.sample_period = 1;          // every switch-out
    attr.sample_type = PERF_SAMPLE_IP | PERF_SAMPLE_TID |
                       PERF_SAMPLE_TIME | PERF_SAMPLE_CALLCHAIN;
    // the switch event FIRES in kernel context (schedule()), so
    // exclude_kernel would drop every sample — instead keep the event and
    // trim kernel frames from the chain (needs perf_event_paranoid <= 1
    // or CAP_PERFMON; open fails cleanly otherwise)
    attr.exclude_kernel = 0;
    attr.exclude_callchain_kernel = 1;
    attr.exclude_hv = 1;
    attr.inherit = 1;
    attr.disabled = 1;
    attr.context_switch = 1;         // PERF_RECORD_SWITCH in/out markers
    attr.sample_id_all = 1;          // tid+time trailer on SWITCH records
    attr.wakeup_events = 256;

    auto* p = new DfOffCpu();
    if (max_stack) p->max_stack = max_stack;
    if (min_block_ns) p->min_block_ns = min_block_ns;
    p->target_pid = pid;
    p->rings = open_rings(&attr, pid, p->ring_pages, err);
    if (p->rings.empty()) {
        delete p;
        return nullptr;
    }
    return p;
}

void df_offcpu_close(DfOffCpu* p) {
    if (!p) return;
    close_rings(p->rings);
    delete p;
}

#ifndef PERF_RECORD_MISC_SWITCH_OUT
#define PERF_RECORD_MISC_SWITCH_OUT (1 << 13)
#endif
#ifndef PERF_RECORD_SWITCH_TYPE
enum { PERF_RECORD_SWITCH_LOCAL = 14 };  // PERF_RECORD_SWITCH
#define PERF_RECORD_SWITCH_TYPE PERF_RECORD_SWITCH_LOCAL
#endif

static void offcpu_drain_ring(DfOffCpu* p, CpuRing& r) {
    auto* meta = (perf_event_mmap_page*)r.map;
    uint64_t head = __atomic_load_n(&meta->data_head, __ATOMIC_ACQUIRE);
    uint64_t tail = meta->data_tail;
    size_t data_size = p->ring_pages * (size_t)getpagesize();
    uint8_t* data = r.map + getpagesize();
    std::vector<uint8_t> rec;
    std::vector<uint64_t> chain;
    while (tail < head) {
        auto* hdr = (perf_event_header*)(data + (tail % data_size));
        uint16_t size = hdr->size;
        if (size == 0) break;
        rec.resize(size);
        size_t off = tail % data_size;
        size_t first = data_size - off < size ? data_size - off : size;
        memcpy(rec.data(), data + off, first);
        if (first < size) memcpy(rec.data() + first, data, size - first);
        auto* h = (perf_event_header*)rec.data();
        if (h->type == PERF_RECORD_SAMPLE) {
            // ip u64, pid/tid u32s, time u64, nr u64 + ips — the sample
            // fires at switch-OUT with the blocking callchain
            const uint8_t* q = rec.data() + sizeof(perf_event_header);
            const uint8_t* end = rec.data() + size;
            uint64_t ip;
            memcpy(&ip, q, 8);
            q += 8;
            uint32_t spid, tid;
            memcpy(&spid, q, 4);
            memcpy(&tid, q + 4, 4);
            q += 8;
            uint64_t t;
            memcpy(&t, q, 8);
            q += 8;
            uint64_t nr;
            memcpy(&nr, q, 8);
            q += 8;
            chain.clear();
            for (uint64_t i = 0; i < nr && q + 8 <= end; i++, q += 8) {
                uint64_t a;
                memcpy(&a, q, 8);
                if (a >= kContextMask) continue;
                if (chain.size() < p->max_stack) chain.push_back(a);
            }
            if (chain.empty() && ip < kContextMask) chain.push_back(ip);
            p->n_switches++;
            if (!chain.empty())
                p->scratch.push_back(OffCpuRec{t, tid, 1, chain});
        } else if (h->type == PERF_RECORD_SWITCH_TYPE) {
            bool out_bit = (h->misc & PERF_RECORD_MISC_SWITCH_OUT) != 0;
            if (!out_bit) p->n_switch_in++;
            // Only switch-OUT marks a departure. A switch-IN lands just
            // before the resume sample; treating it as a departure
            // candidate would overwrite block_start with the resume time
            // and collapse every real blocked span to ~0.
            if (out_bit && size >= sizeof(perf_event_header) + 16) {
                // sample_id trailer = pid u32, tid u32, time u64
                const uint8_t* q = rec.data() + sizeof(perf_event_header);
                uint32_t spid, tid;
                memcpy(&spid, q, 4);
                memcpy(&tid, q + 4, 4);
                uint64_t t;
                memcpy(&t, q + 8, 8);
                p->scratch.push_back(OffCpuRec{t, tid, 0, {}});
            }
        } else if (h->type == PERF_RECORD_LOST) {
            uint64_t lost;
            memcpy(&lost, rec.data() + sizeof(perf_event_header) + 8, 8);
            p->n_lost += lost;
        } else {
            p->n_other++;
        }
        tail += size;
    }
    __atomic_store_n(&meta->data_tail, tail, __ATOMIC_RELEASE);
}

uint64_t df_offcpu_poll(DfOffCpu* p, int32_t timeout_ms) {
    if (timeout_ms > 0) {
        std::vector<pollfd> fds;
        for (auto& r : p->rings) fds.push_back({r.fd, POLLIN, 0});
        poll(fds.data(), fds.size(), timeout_ms);
    }
    p->scratch.clear();
    for (auto& r : p->rings) offcpu_drain_ring(p, r);
    // merge records ACROSS rings in time order before running the state
    // machine (migrating threads interleave rings)
    std::stable_sort(p->scratch.begin(), p->scratch.end(),
                     [](const OffCpuRec& a, const OffCpuRec& b) {
                         return a.t < b.t;
                     });
    for (auto& rec : p->scratch) {
        if (rec.kind == 0) {
            // departure candidate; the LATEST one before the resume
            // sample bounds the true block (delayed-dequeue kernels emit
            // an extra quick out/in pair right after blocking, which the
            // overwrite absorbs)
            p->block_start[rec.tid] = rec.t;
            continue;
        }
        // Observed semantics (verified on 6.x EEVDF kernels, see the
        // timeline test): the CONTEXT_SWITCHES sample fires when the task
        // RESUMES, and its callchain IS the blocking stack (the user
        // stack is untouched while the task is off-CPU).
        auto it = p->block_start.find(rec.tid);
        if (it == p->block_start.end()) continue;
        uint64_t t0 = it->second;
        if (rec.t > t0 && rec.t - t0 >= p->min_block_ns) {
            rec.chain.push_back((uint64_t)rec.tid);  // tid tail
            auto& acc = p->agg[rec.chain];
            acc.first += rec.t - t0;
            acc.second += 1;
            p->n_paired++;
        }
        p->block_start.erase(it);
    }
    p->scratch.clear();
    return p->n_switches;
}

// Export unique blocked-chains and RESET. values[i] = total blocked ns.
uint32_t df_offcpu_export(DfOffCpu* p, uint64_t* addrs, uint32_t addr_cap,
                          uint16_t* lens, uint32_t* tids, uint64_t* values,
                          uint32_t* counts, uint32_t stack_cap) {
    uint32_t n = 0, used = 0;
    for (auto& kv : p->agg) {
        if (n >= stack_cap || used + (kv.first.size() - 1) > addr_cap) {
            p->n_export_dropped++;
            continue;
        }
        const auto& chain = kv.first;
        uint32_t clen = (uint32_t)chain.size() - 1;
        memcpy(addrs + used, chain.data(), (size_t)clen * 8);
        lens[n] = (uint16_t)clen;
        tids[n] = (uint32_t)chain.back();
        values[n] = kv.second.first;
        counts[n] = (uint32_t)kv.second.second;
        used += clen;
        n++;
    }
    p->agg.clear();
    return n;
}

// stats: [switches, lost, rings, export_dropped, switch_in, paired, other]
void df_offcpu_stats(DfOffCpu* p, uint64_t* out7) {
    out7[0] = p->n_switches;
    out7[1] = p->n_lost;
    out7[2] = p->rings.size();
    out7[3] = p->n_export_dropped;
    out7[4] = p->n_switch_in;
    out7[5] = p->n_paired;
    out7[6] = p->n_other;
}

}  // extern "C"
