// Out-of-process OnCPU sampler: perf_event_open + mmap rings.
//
// Reference analog: agent/src/ebpf/kernel/perf_profiler.bpf.c:688 (99Hz
// perf_event sampling) + user/profile/profile_common.c (aggregation, A/B
// swap). Redesign: no BPF — per-CPU inherited perf events on the target
// pid, frame-pointer callchains from PERF_SAMPLE_CALLCHAIN, address-level
// aggregation here, symbolization in Python (cold path, /proc/pid/maps +
// ELF symtab there).
//
// The DWARF unwinder gap is acknowledged: FP-omitted binaries yield
// shallow chains (leaf IP still samples correctly).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <vector>

#include <dirent.h>

#include <linux/perf_event.h>
#include <poll.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

extern "C" {

namespace {

constexpr uint32_t kRingPages = 64;  // data pages per cpu (256KB)
constexpr uint64_t kContextMask = 0xFFFFFFFFFFFFF000ULL;  // PERF_CONTEXT_*

struct CpuRing {
    int fd = -1;
    uint8_t* map = nullptr;
    size_t map_len = 0;
    std::vector<int> extra_fds;  // per-tid events redirected into this ring
};

// Existing tids of a process (inherit=1 only follows FUTURE children, so
// threads alive at attach time each need their own event, perf-record
// style).
std::vector<int> list_tids(int pid) {
    std::vector<int> tids;
    char path[64];
    snprintf(path, sizeof(path), "/proc/%d/task", pid);
    DIR* d = opendir(path);
    if (!d) {
        tids.push_back(pid);
        return tids;
    }
    while (dirent* e = readdir(d)) {
        int tid = atoi(e->d_name);
        if (tid > 0) tids.push_back(tid);
    }
    closedir(d);
    if (tids.empty()) tids.push_back(pid);
    return tids;
}

}  // namespace

struct DfProf {
    std::vector<CpuRing> rings;
    // aggregation: callchain (leaf..root addresses + tid tail) -> count
    std::map<std::vector<uint64_t>, uint64_t> agg;
    uint64_t n_samples = 0, n_lost = 0, n_export_dropped = 0;
    uint32_t max_stack;
    int target_pid;
};

static long pe_open(perf_event_attr* attr, pid_t pid, int cpu) {
    return syscall(SYS_perf_event_open, attr, pid, cpu, -1,
                   PERF_FLAG_FD_CLOEXEC);
}

// Attach to `pid` (all threads via inherit) at `freq` Hz across all CPUs.
// Returns nullptr with errno-like code in *err on failure.
DfProf* df_prof_open(int32_t pid, uint32_t freq, uint32_t max_stack,
                     int32_t* err) {
    *err = 0;
    perf_event_attr attr;
    memset(&attr, 0, sizeof(attr));
    attr.size = sizeof(attr);
    attr.type = PERF_TYPE_SOFTWARE;
    attr.config = PERF_COUNT_SW_CPU_CLOCK;
    attr.sample_freq = freq ? freq : 99;
    attr.freq = 1;
    attr.sample_type = PERF_SAMPLE_IP | PERF_SAMPLE_TID |
                       PERF_SAMPLE_CALLCHAIN;
    attr.exclude_kernel = 1;
    attr.exclude_hv = 1;
    attr.inherit = 1;          // follow the target's threads
    attr.disabled = 1;
    attr.wakeup_events = 128;  // don't wake the poller per sample; the
                               // window timeout drains the tail

    auto* p = new DfProf();
    p->max_stack = max_stack ? max_stack : 64;
    p->target_pid = pid;
    auto cleanup = [&]() {
        for (auto& q : p->rings) {
            for (int efd : q.extra_fds) close(efd);
            if (q.map) munmap(q.map, q.map_len);
            if (q.fd >= 0) close(q.fd);
        }
        delete p;
    };
    // one event per (existing tid, cpu): the leader's event owns the cpu's
    // ring; the other tids' events redirect into it (SET_OUTPUT), and
    // inherit picks up any threads spawned later
    std::vector<int> tids = list_tids(pid);
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    for (int cpu = 0; cpu < ncpu; cpu++) {
        CpuRing r;
        r.fd = (int)pe_open(&attr, tids[0], cpu);
        if (r.fd < 0) {
            if (errno == ENODEV) continue;  // offline cpu
            *err = errno;
            cleanup();
            return nullptr;
        }
        r.map_len = (kRingPages + 1) * (size_t)getpagesize();
        r.map = (uint8_t*)mmap(nullptr, r.map_len, PROT_READ | PROT_WRITE,
                               MAP_SHARED, r.fd, 0);
        if (r.map == MAP_FAILED) {
            *err = errno;
            close(r.fd);
            cleanup();
            return nullptr;
        }
        ioctl(r.fd, PERF_EVENT_IOC_ENABLE, 0);
        for (size_t t = 1; t < tids.size(); t++) {
            int efd = (int)pe_open(&attr, tids[t], cpu);
            if (efd < 0) continue;  // tid exited since listing: fine
            if (ioctl(efd, PERF_EVENT_IOC_SET_OUTPUT, r.fd) < 0) {
                close(efd);
                continue;
            }
            ioctl(efd, PERF_EVENT_IOC_ENABLE, 0);
            r.extra_fds.push_back(efd);
        }
        p->rings.push_back(r);
    }
    if (p->rings.empty()) {
        *err = ENODEV;
        delete p;
        return nullptr;
    }
    return p;
}

void df_prof_close(DfProf* p) {
    if (!p) return;
    for (auto& r : p->rings) {
        for (int efd : r.extra_fds) {
            ioctl(efd, PERF_EVENT_IOC_DISABLE, 0);
            close(efd);
        }
        if (r.fd >= 0) ioctl(r.fd, PERF_EVENT_IOC_DISABLE, 0);
        if (r.map) munmap(r.map, r.map_len);
        if (r.fd >= 0) close(r.fd);
    }
    delete p;
}

static void drain_ring(DfProf* p, CpuRing& r) {
    auto* meta = (perf_event_mmap_page*)r.map;
    uint64_t head = __atomic_load_n(&meta->data_head, __ATOMIC_ACQUIRE);
    uint64_t tail = meta->data_tail;
    size_t data_size = kRingPages * (size_t)getpagesize();
    uint8_t* data = r.map + getpagesize();
    std::vector<uint8_t> rec;
    std::vector<uint64_t> chain;
    while (tail < head) {
        auto* hdr = (perf_event_header*)(data + (tail % data_size));
        uint16_t size = hdr->size;
        if (size == 0) break;  // corrupt; bail
        // record may wrap the ring edge: copy out
        rec.resize(size);
        size_t off = tail % data_size;
        size_t first = data_size - off < size ? data_size - off : size;
        memcpy(rec.data(), data + off, first);
        if (first < size) memcpy(rec.data() + first, data, size - first);
        auto* h = (perf_event_header*)rec.data();
        if (h->type == PERF_RECORD_SAMPLE) {
            // layout per sample_type: ip u64, pid u32, tid u32,
            // nr u64, ips[nr] u64
            const uint8_t* q = rec.data() + sizeof(perf_event_header);
            uint64_t ip;
            memcpy(&ip, q, 8);
            q += 8;
            uint32_t spid, tid;
            memcpy(&spid, q, 4);
            memcpy(&tid, q + 4, 4);
            q += 8;
            uint64_t nr;
            memcpy(&nr, q, 8);
            q += 8;
            const uint8_t* end = rec.data() + size;
            chain.clear();
            for (uint64_t i = 0; i < nr && q + 8 <= end; i++, q += 8) {
                uint64_t a;
                memcpy(&a, q, 8);
                if (a >= kContextMask) continue;  // context marker
                chain.push_back(a);
                if (chain.size() >= p->max_stack) break;
            }
            if (chain.empty() && ip < kContextMask) chain.push_back(ip);
            if (!chain.empty()) {
                chain.push_back((uint64_t)tid);  // tid tail distinguishes
                p->agg[chain]++;
                p->n_samples++;
            }
        } else if (h->type == PERF_RECORD_LOST) {
            uint64_t lost;
            memcpy(&lost, rec.data() + sizeof(perf_event_header) + 8, 8);
            p->n_lost += lost;
        }
        tail += size;
    }
    __atomic_store_n(&meta->data_tail, tail, __ATOMIC_RELEASE);
}

// Drain all rings (non-blocking unless timeout_ms > 0 and nothing ready).
// Returns samples aggregated so far in this window.
uint64_t df_prof_poll(DfProf* p, int32_t timeout_ms) {
    if (timeout_ms > 0) {
        std::vector<pollfd> fds;
        for (auto& r : p->rings) fds.push_back({r.fd, POLLIN, 0});
        poll(fds.data(), fds.size(), timeout_ms);
    }
    for (auto& r : p->rings) drain_ring(p, r);
    return p->n_samples;
}

// Export the window's unique chains and RESET (A/B swap).
// addrs: concatenated chains (leaf..root, NO tid); lens[i] = chain length;
// tids[i], counts[i] per chain. Returns number of chains written.
uint32_t df_prof_export(DfProf* p, uint64_t* addrs, uint32_t addr_cap,
                        uint16_t* lens, uint32_t* tids, uint32_t* counts,
                        uint32_t stack_cap) {
    uint32_t n = 0, used = 0;
    for (auto& kv : p->agg) {
        if (n >= stack_cap || used + (kv.first.size() - 1) > addr_cap) {
            p->n_export_dropped++;  // overflow is counted, never silent
            continue;
        }
        const auto& chain = kv.first;
        uint32_t clen = (uint32_t)chain.size() - 1;  // drop tid tail
        memcpy(addrs + used, chain.data(), (size_t)clen * 8);
        lens[n] = (uint16_t)clen;
        tids[n] = (uint32_t)chain.back();
        counts[n] = (uint32_t)kv.second;
        used += clen;
        n++;
    }
    p->agg.clear();
    return n;
}

// stats: [samples_total, lost_total, rings, export_dropped_chains]
void df_prof_stats(DfProf* p, uint64_t* out4) {
    out4[0] = p->n_samples;
    out4[1] = p->n_lost;
    out4[2] = p->rings.size();
    out4[3] = p->n_export_dropped;
}

}  // extern "C"
