// qexec: native kernels for dictionary-encoded DF-SQL execution.
//
// Reference analog: ClickHouse executes GROUP BY over LowCardinality
// columns with a hash table keyed on the small ints, never the strings
// (SmartEncoding end-to-end). The Python engine's composite-radix
// np.unique grouping is O(n log n) per key column; these kernels do one
// O(n) open-addressing pass over all key columns at once.
//
// All entry points take pre-cast int64 key columns (dictionary ids,
// enum ids and integer timestamps all fit; the ctypes wrapper casts).
// Consumed via ctypes — see qx_group / qx_isin_u32 in native/__init__.py,
// numpy fallbacks live there behind the same DF_NO_NATIVE kill-switch.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline uint64_t mix64(uint64_t x) {
    // splitmix64 finalizer — good avalanche for sequential dict ids
    x += 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    return x ^ (x >> 31);
}

inline uint64_t next_pow2(uint64_t v) {
    uint64_t p = 16;
    while (p < v) p <<= 1;
    return p;
}

}  // namespace

extern "C" {

// Hash-group n_rows over n_keys int64 key columns.
//   order_out:  n_rows indices, grouped (all rows of group 0, then 1, ...)
//               in FIRST-OCCURRENCE group order
//   bounds_out: n_groups+1 offsets into order_out (caller sizes n_rows+1)
// Returns n_groups (>= 0), or -1 on bad args. Row order within a group is
// the original row order (counting sort is stable), which the engine's
// reduceat/LAST semantics rely on.
int64_t df_qx_group(const int64_t* const* keys, uint32_t n_keys,
                    uint64_t n_rows, uint64_t* order_out,
                    uint64_t* bounds_out) {
    if (n_keys == 0 || keys == nullptr) return -1;
    if (n_rows == 0) {
        bounds_out[0] = 0;
        return 0;
    }
    const uint64_t cap = next_pow2(n_rows * 2);
    const uint64_t mask = cap - 1;
    // open-addressing table: slot -> representative row (+1; 0 == empty)
    std::vector<uint64_t> slot_row(cap, 0);
    std::vector<uint32_t> slot_gid(cap, 0);
    std::vector<uint32_t> gids(n_rows);
    std::vector<uint64_t> counts;
    counts.reserve(1024);
    uint32_t n_groups = 0;
    for (uint64_t i = 0; i < n_rows; i++) {
        uint64_t h = 0x243f6a8885a308d3ULL;
        for (uint32_t k = 0; k < n_keys; k++)
            h = mix64(h ^ (uint64_t)keys[k][i]);
        uint64_t s = h & mask;
        for (;;) {
            const uint64_t rep = slot_row[s];
            if (rep == 0) {  // new group
                slot_row[s] = i + 1;
                slot_gid[s] = n_groups;
                gids[i] = n_groups;
                counts.push_back(1);
                n_groups++;
                break;
            }
            const uint64_t r = rep - 1;
            bool eq = true;
            for (uint32_t k = 0; k < n_keys; k++) {
                if (keys[k][r] != keys[k][i]) { eq = false; break; }
            }
            if (eq) {
                const uint32_t g = slot_gid[s];
                gids[i] = g;
                counts[g]++;
                break;
            }
            s = (s + 1) & mask;
        }
    }
    // counting sort rows into group-contiguous order
    bounds_out[0] = 0;
    for (uint32_t g = 0; g < n_groups; g++)
        bounds_out[g + 1] = bounds_out[g] + counts[g];
    std::vector<uint64_t> cursor(bounds_out, bounds_out + n_groups);
    for (uint64_t i = 0; i < n_rows; i++)
        order_out[cursor[gids[i]]++] = i;
    return (int64_t)n_groups;
}

// Fused gather + segmented reduce: out[g] = op over vals[order[i]] for
// i in [bounds[g], bounds[g+1]). op: 0=sum, 1=min, 2=max. Replaces the
// engine's gather-copy + ufunc.reduceat; accumulation is sequential in
// group order, so results are bit-identical to the numpy path (sum is
// left-to-right, min/max propagate NaN exactly like np.minimum/maximum).
// Releases the GIL via ctypes — the morsel pool's scan workers run this
// concurrently.
void df_qx_agg_f64(const double* vals, const uint64_t* order,
                   const uint64_t* bounds, uint64_t n_groups,
                   int32_t op, double* out) {
    for (uint64_t g = 0; g < n_groups; g++) {
        const uint64_t s = bounds[g], e = bounds[g + 1];
        if (s >= e) { out[g] = 0.0; continue; }
        double acc = vals[order[s]];
        if (op == 0) {
            for (uint64_t i = s + 1; i < e; i++) acc += vals[order[i]];
        } else if (op == 1) {
            for (uint64_t i = s + 1; i < e; i++) {
                const double v = vals[order[i]];
                // mirror np.minimum: NaN in either operand propagates
                if (v < acc || v != v) acc = v;
            }
        } else {
            for (uint64_t i = s + 1; i < e; i++) {
                const double v = vals[order[i]];
                if (v > acc || v != v) acc = v;
            }
        }
        out[g] = acc;
    }
}

// mask[i] = 1 iff col[i] is in `set` (hash set, O(n + n_set)) — the
// dictionary-id IN / LIKE-pushdown filter. np.isin is sort-based
// O(n log n_set); this is the encoded-predicate fast path.
void df_qx_isin_u32(const uint32_t* col, uint64_t n, const uint32_t* set,
                    uint64_t n_set, uint8_t* mask_out) {
    if (n_set == 0) {
        std::memset(mask_out, 0, n);
        return;
    }
    const uint64_t cap = next_pow2(n_set * 2);
    const uint64_t hmask = cap - 1;
    // slot -> value+1 (0 == empty)
    std::vector<uint64_t> tbl(cap, 0);
    for (uint64_t j = 0; j < n_set; j++) {
        uint64_t s = mix64(set[j]) & hmask;
        while (tbl[s] != 0 && tbl[s] != (uint64_t)set[j] + 1)
            s = (s + 1) & hmask;
        tbl[s] = (uint64_t)set[j] + 1;
    }
    for (uint64_t i = 0; i < n; i++) {
        const uint64_t v = (uint64_t)col[i] + 1;
        uint64_t s = mix64(col[i]) & hmask;
        uint8_t hit = 0;
        for (;;) {
            const uint64_t t = tbl[s];
            if (t == 0) break;
            if (t == v) { hit = 1; break; }
            s = (s + 1) & hmask;
        }
        mask_out[i] = hit;
    }
}

// -- selective filter + gather (segment format v2 fast path) ----------------
//
// Selective predicates over encoded columns produce INDEX LISTS instead of
// full boolean masks: out_idx holds the ascending row positions that pass,
// so downstream gathers touch only survivors. All three release the GIL via
// ctypes; the morsel pool runs them concurrently across scan units.

// out_idx[j] = ascending positions i where lo <= col[i] <= hi (inclusive
// both ends; caller encodes one-sided ranges with dtype min/max). Bounds
// arrive as raw 64-bit patterns (lo_bits/hi_bits) reinterpreted per
// esize/is_signed — the ctypes wrapper packs them from the column dtype.
// Returns the match count, or -1 on unsupported esize.
int64_t df_qx_sel_cmp(const void* vals, uint32_t esize, uint32_t is_signed,
                      uint64_t n, uint64_t lo_bits, uint64_t hi_bits,
                      uint64_t* out_idx) {
    uint64_t m = 0;
    switch ((esize << 1) | (is_signed & 1)) {
#define DF_SEL_CASE(sz, sgn, T)                                         \
    case ((sz << 1) | sgn): {                                           \
        const T* v = (const T*)vals;                                    \
        const T lo = (T)lo_bits, hi = (T)hi_bits;                       \
        for (uint64_t i = 0; i < n; i++)                                \
            if (v[i] >= lo && v[i] <= hi) out_idx[m++] = i;             \
        break;                                                          \
    }
        DF_SEL_CASE(1, 0, uint8_t)
        DF_SEL_CASE(1, 1, int8_t)
        DF_SEL_CASE(2, 0, uint16_t)
        DF_SEL_CASE(2, 1, int16_t)
        DF_SEL_CASE(4, 0, uint32_t)
        DF_SEL_CASE(4, 1, int32_t)
        DF_SEL_CASE(8, 0, uint64_t)
        DF_SEL_CASE(8, 1, int64_t)
#undef DF_SEL_CASE
        default:
            return -1;
    }
    return (int64_t)m;
}

// Index-list sibling of df_qx_isin_u32: out_idx[j] = ascending positions
// where col[i] is in `set` (hash set, O(n + n_set)). Returns match count.
int64_t df_qx_sel_isin_u32(const uint32_t* col, uint64_t n,
                           const uint32_t* set, uint64_t n_set,
                           uint64_t* out_idx) {
    if (n_set == 0) return 0;
    const uint64_t cap = next_pow2(n_set * 2);
    const uint64_t hmask = cap - 1;
    std::vector<uint64_t> tbl(cap, 0);  // slot -> value+1 (0 == empty)
    for (uint64_t j = 0; j < n_set; j++) {
        uint64_t s = mix64(set[j]) & hmask;
        while (tbl[s] != 0 && tbl[s] != (uint64_t)set[j] + 1)
            s = (s + 1) & hmask;
        tbl[s] = (uint64_t)set[j] + 1;
    }
    uint64_t m = 0;
    for (uint64_t i = 0; i < n; i++) {
        const uint64_t v = (uint64_t)col[i] + 1;
        uint64_t s = mix64(col[i]) & hmask;
        for (;;) {
            const uint64_t t = tbl[s];
            if (t == 0) break;
            if (t == v) { out_idx[m++] = i; break; }
            s = (s + 1) & hmask;
        }
    }
    return (int64_t)m;
}

// out[j] = src[idx[j]] for any element size — the survivor gather that
// replaces numpy fancy indexing (which allocates an intermediate bool
// mask first on the python path). Returns 0, or -1 on unsupported esize.
int32_t df_qx_gather(const void* src, uint32_t esize, const uint64_t* idx,
                     uint64_t n_idx, void* out) {
    switch (esize) {
#define DF_GATHER_CASE(sz, T)                                           \
    case sz: {                                                          \
        const T* s = (const T*)src;                                     \
        T* o = (T*)out;                                                 \
        for (uint64_t j = 0; j < n_idx; j++) o[j] = s[idx[j]];          \
        break;                                                          \
    }
        DF_GATHER_CASE(1, uint8_t)
        DF_GATHER_CASE(2, uint16_t)
        DF_GATHER_CASE(4, uint32_t)
        DF_GATHER_CASE(8, uint64_t)
#undef DF_GATHER_CASE
        default:
            return -1;
    }
    return 0;
}

}  // extern "C"
