// Native flow pipeline: packets stay C structs from capture to the L7
// boundary; only payload segments that need protocol parsing and closed-flow
// records ever surface to Python.
//
// Reference analog: agent/src/flow_generator/flow_map.rs:716
// (inject_meta_packet), agent/src/dispatcher/recv_engine/mod.rs:40 (the
// TPACKET ring recv engine), perf/tcp.rs (seq-window retrans logic).
// Redesigned, not translated: one single-threaded map per dispatcher shard,
// batch ABI for ctypes (per-call overhead amortized over thousands of
// packets), and an L7 sink that copies payload bytes out of the ring so
// blocks can be released immediately.

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <cerrno>
#include <net/if.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>

#include <linux/if_ether.h>
#include <linux/if_packet.h>

#include "dfpacket.h"
extern "C" int32_t df_decode_eth(const uint8_t* data, uint32_t len,
                                 DfPacketOut* out);

namespace {

// ---------------------------------------------------------------------------
// flow key / hash
// ---------------------------------------------------------------------------

struct FlowKey {
    uint64_t a;  // ip_src << 32 | ip_dst
    uint64_t b;  // port_src << 32 | port_dst << 16 | proto
    uint64_t c;  // tunnel_type << 32 | tunnel_id — overlapping tenant IP
                 // space across VNIs must NOT merge into one flow
    bool operator==(const FlowKey& o) const {
        return a == o.a && b == o.b && c == o.c;
    }
};

static inline FlowKey make_key(const DfPacketOut& p) {
    return FlowKey{(uint64_t)p.ip_src << 32 | p.ip_dst,
                   (uint64_t)p.port_src << 32 |
                       (uint64_t)p.port_dst << 16 | p.protocol,
                   (uint64_t)p.tunnel_type << 32 | p.tunnel_id};
}

static inline FlowKey reverse_key(const FlowKey& k) {
    return FlowKey{(k.a << 32) | (k.a >> 32),
                   ((k.b >> 32) & 0xFFFF) << 16 |
                       ((k.b >> 16) & 0xFFFF) << 32 | (k.b & 0xFF),
                   k.c};
}

struct KeyHash {
    size_t operator()(const FlowKey& k) const {
        uint64_t x = k.a * 0x9E3779B97F4A7C15ULL;
        x ^= (k.b + 0xBF58476D1CE4E5B9ULL) * 0x94D049BB133111EBULL;
        x ^= (k.c + 0xD6E8FEB86659FD93ULL) * 0xFF51AFD7ED558CCDULL;
        x ^= x >> 31;
        return (size_t)x;
    }
};

// TCP FSM states (mirror of the Python FlowState enum)
enum : uint8_t {
    ST_INIT = 0, ST_SYN_SENT, ST_SYN_ACK, ST_ESTABLISHED,
    ST_FIN_1, ST_CLOSED, ST_RST
};
enum : uint8_t { CT_UNKNOWN = 0, CT_FIN, CT_RST, CT_TIMEOUT, CT_FORCED };
enum : uint8_t {
    TCP_FIN = 0x01, TCP_SYN = 0x02, TCP_RST = 0x04,
    TCP_PSH = 0x08, TCP_ACK = 0x10
};

struct DirStats {
    uint64_t packets = 0, bytes = 0;
    uint32_t retrans = 0, zero_window = 0;
    uint32_t max_payload_seq = 0;
    uint8_t tcp_flags_bits = 0;
    bool has_payload_seq = false;
};

struct Flow {
    uint64_t flow_id;
    FlowKey key;  // canonical: client (initiator) side first
    uint64_t start_ns, end_ns;
    uint64_t syn_ns = 0, synack_ns = 0;
    DirStats tx, rx;
    uint32_t rtt_us = 0;
    uint16_t syn_count = 0, synack_count = 0;
    uint8_t state = ST_INIT;
    uint8_t close_type = CT_UNKNOWN;
    int32_t l7_mode = 0;  // 0 = infer (surface payloads), >0 = known proto
                          // (keep surfacing), -1 = muted (stop surfacing)
    uint32_t payload_pkts = 0;
};

}  // namespace

extern "C" {

// Must match FLOW_RECORD_DTYPE in native/__init__.py (packed, no padding).
#pragma pack(push, 1)
struct FlowRecord {
    uint64_t flow_id;
    uint32_t ip_src, ip_dst;
    uint16_t port_src, port_dst;
    uint8_t protocol;
    uint8_t state;
    uint8_t close_type;
    uint8_t closed;
    uint64_t start_ns, end_ns;
    uint64_t tx_packets, rx_packets, tx_bytes, rx_bytes;
    uint32_t tx_retrans, rx_retrans, tx_zero_window, rx_zero_window;
    uint8_t tx_flags_bits, rx_flags_bits;
    uint16_t syn_count, synack_count;
    uint32_t rtt_us;
    uint8_t tunnel_type;
    uint32_t tunnel_id;
};

// Must match SLOW_EVENT_DTYPE in native/__init__.py: a frame the v4 fast
// path can't decode (v6/vlan-exotic), copied out of the ring for the Python
// slow path.
struct SlowEvent {
    uint64_t ts_ns;
    uint32_t off;
    uint32_t len;
};

// Must match L7_EVENT_DTYPE in native/__init__.py. payload_off indexes into
// the caller-provided l7 payload buffer (bytes are COPIED there, so ring
// blocks / batch buffers can be released before Python parses).
struct L7Event {
    uint64_t flow_id;
    uint64_t ts_ns;
    uint32_t payload_off;
    uint32_t payload_len;
    uint8_t is_tx;
    uint8_t protocol;
    uint32_t ip_src, ip_dst;
    uint16_t port_src, port_dst;
    uint8_t tunnel_type;
    uint32_t tunnel_id;
};
#pragma pack(pop)

struct L7Sink {
    uint8_t* buf;
    uint32_t buf_cap, buf_used;
    L7Event* evs;
    uint32_t ev_cap, n;
    uint64_t dropped;
};

struct DfFlowMap {
    std::unordered_map<FlowKey, Flow, KeyHash> flows;
    // lazy-deletion min-heap: (end_ns, tiebreak, key)
    struct HeapEnt {
        uint64_t end_ns, seq;
        FlowKey key;
        bool operator>(const HeapEnt& o) const {
            return end_ns != o.end_ns ? end_ns > o.end_ns : seq > o.seq;
        }
    };
    std::priority_queue<HeapEnt, std::vector<HeapEnt>, std::greater<HeapEnt>>
        evict_heap;
    std::vector<FlowRecord> closed;   // drained via df_fm_poll_closed
    uint64_t next_flow_id = 1, heap_seq = 0;
    uint32_t max_flows;
    // stats
    uint64_t n_packets = 0, n_created = 0, n_closed = 0, n_evicted = 0,
             n_l7_events = 0, n_l7_dropped = 0, n_slow = 0, n_excluded = 0;
    bool server_port[65536] = {};
    bool exclude_port[65536] = {};  // agent's own telemetry ports
};

static const uint16_t kKnownPorts[] = {
    22, 25, 53, 80, 88, 110, 143, 389, 443, 465, 587, 993, 995, 1433, 1521,
    2379, 3000, 3306, 4222, 5000, 5432, 5672, 6379, 8000, 8080, 8443, 8888,
    9000, 9090, 9092, 9200, 11211, 27017, 50051};

DfFlowMap* df_fm_new(uint32_t max_flows) {
    auto* fm = new DfFlowMap();
    fm->max_flows = max_flows ? max_flows : (1u << 16);
    fm->flows.reserve(fm->max_flows * 2);
    for (int i = 0; i < 1024; i++) fm->server_port[i] = true;
    for (uint16_t p : kKnownPorts) fm->server_port[p] = true;
    return fm;
}

void df_fm_free(DfFlowMap* fm) { delete fm; }

static void fill_record(const Flow& f, uint8_t closed_flag, FlowRecord* r) {
    r->flow_id = f.flow_id;
    r->ip_src = (uint32_t)(f.key.a >> 32);
    r->ip_dst = (uint32_t)f.key.a;
    r->port_src = (uint16_t)(f.key.b >> 32);
    r->port_dst = (uint16_t)(f.key.b >> 16);
    r->protocol = (uint8_t)f.key.b;
    r->state = f.state;
    r->close_type = f.close_type;
    r->closed = closed_flag;
    r->start_ns = f.start_ns;
    r->end_ns = f.end_ns;
    r->tx_packets = f.tx.packets;
    r->rx_packets = f.rx.packets;
    r->tx_bytes = f.tx.bytes;
    r->rx_bytes = f.rx.bytes;
    r->tx_retrans = f.tx.retrans;
    r->rx_retrans = f.rx.retrans;
    r->tx_zero_window = f.tx.zero_window;
    r->rx_zero_window = f.rx.zero_window;
    r->tx_flags_bits = f.tx.tcp_flags_bits;
    r->rx_flags_bits = f.rx.tcp_flags_bits;
    r->syn_count = f.syn_count;
    r->synack_count = f.synack_count;
    r->rtt_us = f.rtt_us;
    r->tunnel_type = (uint8_t)(f.key.c >> 32);
    r->tunnel_id = (uint32_t)f.key.c;
}

static void close_flow(DfFlowMap* fm, Flow& f) {
    fm->n_closed++;
    FlowRecord r;
    fill_record(f, 1, &r);
    fm->closed.push_back(r);
}

static void evict_oldest(DfFlowMap* fm) {
    while (!fm->evict_heap.empty()) {
        auto ent = fm->evict_heap.top();
        fm->evict_heap.pop();
        auto it = fm->flows.find(ent.key);
        if (it == fm->flows.end()) continue;  // stale
        if (it->second.end_ns > ent.end_ns) {  // refreshed: re-file
            fm->evict_heap.push({it->second.end_ns, ++fm->heap_seq, ent.key});
            continue;
        }
        it->second.close_type = CT_FORCED;
        close_flow(fm, it->second);
        fm->flows.erase(it);
        fm->n_evicted++;
        return;
    }
}

static void tcp_update(Flow& f, const DfPacketOut& p, DirStats& d,
                       uint64_t ts_ns) {
    uint8_t flags = p.tcp_flags;
    d.tcp_flags_bits |= flags;
    if (p.window == 0 && !(flags & TCP_RST)) d.zero_window++;
    if (p.payload_len) {
        uint32_t end_seq = p.seq + p.payload_len;  // u32 wraps naturally
        if (d.has_payload_seq) {
            uint32_t behind = d.max_payload_seq - p.seq;
            if (behind > 0 && behind < 0x80000000u) {
                d.retrans++;
            } else {
                d.max_payload_seq = end_seq;
            }
        } else {
            d.max_payload_seq = end_seq;
            d.has_payload_seq = true;
        }
    }
    if (flags & TCP_RST) {
        f.state = ST_RST;
        f.close_type = CT_RST;
        return;
    }
    bool syn = flags & TCP_SYN, ack = flags & TCP_ACK, fin = flags & TCP_FIN;
    if (syn && !ack) {
        f.syn_count++;
        if (f.state == ST_INIT) {
            f.state = ST_SYN_SENT;
            f.syn_ns = ts_ns;
        }
    } else if (syn && ack) {
        f.synack_count++;
        if (f.state == ST_SYN_SENT) {
            f.state = ST_SYN_ACK;
            f.synack_ns = ts_ns;
        }
    } else if (fin) {
        if (f.state == ST_ESTABLISHED || f.state == ST_SYN_ACK ||
            f.state == ST_INIT) {
            f.state = ST_FIN_1;
        } else if (f.state == ST_FIN_1) {
            f.state = ST_CLOSED;
            f.close_type = CT_FIN;
        }
    } else if (ack) {
        if (f.state == ST_SYN_ACK) {
            f.state = ST_ESTABLISHED;
            if (f.syn_ns && f.synack_ns && ts_ns > f.syn_ns)
                f.rtt_us = (uint32_t)((ts_ns - f.syn_ns) / 1000);
        } else if (f.state == ST_INIT) {
            f.state = ST_ESTABLISHED;  // mid-stream pickup
        }
    }
}

// Inject one decoded packet. Returns the flow (creating it if needed).
static void inject_decoded(DfFlowMap* fm, const DfPacketOut& p,
                           const uint8_t* frame, uint64_t ts_ns,
                           L7Sink* sink) {
    if (fm->exclude_port[p.port_src] || fm->exclude_port[p.port_dst]) {
        fm->n_excluded++;  // agent's own telemetry: feedback-loop guard
        return;
    }
    fm->n_packets++;
    FlowKey k = make_key(p);
    bool is_tx = true;
    auto it = fm->flows.find(k);
    if (it == fm->flows.end()) {
        FlowKey rk = reverse_key(k);
        it = fm->flows.find(rk);
        if (it != fm->flows.end()) {
            is_tx = false;
        } else {
            if (fm->flows.size() >= fm->max_flows) evict_oldest(fm);
            // direction heuristic on mid-stream pickup: a well-known source
            // port marks the SERVER side
            FlowKey canon = k;
            if (p.protocol == 1 && !(p.tcp_flags & TCP_SYN)) {
                bool src_srv = fm->server_port[p.port_src] &&
                               !fm->server_port[p.port_dst];
                if (src_srv) {
                    canon = rk;
                    is_tx = false;
                }
            }
            Flow f;
            f.flow_id = fm->next_flow_id++;
            f.key = canon;
            f.start_ns = ts_ns;
            f.end_ns = ts_ns;
            fm->n_created++;
            it = fm->flows.emplace(canon, f).first;
            fm->evict_heap.push({ts_ns, ++fm->heap_seq, canon});
        }
    }
    Flow& f = it->second;
    f.end_ns = ts_ns;
    DirStats& d = is_tx ? f.tx : f.rx;
    d.packets++;
    // bytes = wire length approximation: ip total via payload_off+len covers
    // the decoded portion; use the frame view (payload_off+payload_len)
    d.bytes += p.payload_off + p.payload_len;
    if (p.protocol == 1) tcp_update(f, p, d, ts_ns);
    if (p.payload_len && f.l7_mode >= 0 && sink != nullptr) {
        f.payload_pkts++;
        if (sink->n < sink->ev_cap &&
            sink->buf_used + p.payload_len <= sink->buf_cap) {
            memcpy(sink->buf + sink->buf_used, frame + p.payload_off,
                   p.payload_len);
            L7Event& e = sink->evs[sink->n++];
            e.flow_id = f.flow_id;
            e.ts_ns = ts_ns;
            e.payload_off = sink->buf_used;
            e.payload_len = p.payload_len;
            e.is_tx = is_tx ? 1 : 0;
            e.protocol = p.protocol;
            e.ip_src = (uint32_t)(f.key.a >> 32);
            e.ip_dst = (uint32_t)f.key.a;
            e.port_src = (uint16_t)(f.key.b >> 32);
            e.port_dst = (uint16_t)(f.key.b >> 16);
            e.tunnel_type = (uint8_t)(f.key.c >> 32);
            e.tunnel_id = (uint32_t)f.key.c;
            sink->buf_used += p.payload_len;
            fm->n_l7_events++;
        } else {
            sink->dropped++;
            fm->n_l7_dropped++;
        }
    }
    // CLOSED/RST flows are reaped at the next tick (not immediately), so
    // trailing ACKs land on the existing flow instead of spawning a stray
    // one-packet flow (mirrors the Python FlowMap)
}

// Batch inject from packed frames. slow_idx receives indices of frames the
// v4 fast path can't decode (v6/short) for the Python slow path.
// Returns number of packets handled natively.
uint64_t df_fm_inject_batch(DfFlowMap* fm, const uint8_t* data,
                            const uint32_t* offsets, const uint64_t* ts_ns,
                            uint32_t n, uint8_t* l7_buf, uint32_t l7_buf_cap,
                            L7Event* l7_out, uint32_t l7_cap,
                            uint32_t* n_l7, uint32_t* slow_idx,
                            uint32_t slow_cap, uint32_t* n_slow) {
    L7Sink sink{l7_buf, l7_buf_cap, 0, l7_out, l7_cap, 0, 0};
    uint32_t slow = 0;
    uint64_t handled = 0;
    DfPacketOut p;
    for (uint32_t i = 0; i < n; i++) {
        const uint8_t* frame = data + offsets[i];
        uint32_t len = offsets[i + 1] - offsets[i];
        if (df_decode_eth(frame, len, &p)) {
            inject_decoded(fm, p, frame, ts_ns[i], &sink);
            handled++;
        } else {
            fm->n_slow++;
            if (slow < slow_cap) slow_idx[slow++] = i;
        }
    }
    *n_l7 = sink.n;
    *n_slow = slow;
    return handled;
}

void df_fm_set_l7(DfFlowMap* fm, uint32_t ip_src, uint32_t ip_dst,
                  uint16_t port_src, uint16_t port_dst, uint8_t proto,
                  uint8_t tunnel_type, uint32_t tunnel_id, int32_t mode) {
    FlowKey k{(uint64_t)ip_src << 32 | ip_dst,
              (uint64_t)port_src << 32 | (uint64_t)port_dst << 16 | proto,
              (uint64_t)tunnel_type << 32 | tunnel_id};
    auto it = fm->flows.find(k);
    if (it == fm->flows.end()) {
        it = fm->flows.find(reverse_key(k));
        if (it == fm->flows.end()) return;
    }
    it->second.l7_mode = mode;
}

// Expire idle/closed flows. Timeouts mirror FlowMap.FLOW_TIMEOUT_NS.
void df_fm_tick(DfFlowMap* fm, uint64_t now_ns) {
    static const uint64_t kTimeout[7] = {
        5'000'000'000ULL,    // INIT
        5'000'000'000ULL,    // SYN_SENT
        5'000'000'000ULL,    // SYN_ACK
        300'000'000'000ULL,  // ESTABLISHED
        30'000'000'000ULL,   // FIN_1
        0, 0};               // CLOSED/RST close immediately on packet
    for (auto it = fm->flows.begin(); it != fm->flows.end();) {
        Flow& f = it->second;
        uint64_t timeout =
            f.state < 5 ? kTimeout[f.state] : 60'000'000'000ULL;
        if (f.state == ST_CLOSED || f.state == ST_RST ||
            (now_ns > f.end_ns && now_ns - f.end_ns > timeout)) {
            if (f.close_type == CT_UNKNOWN) f.close_type = CT_TIMEOUT;
            close_flow(fm, f);
            it = fm->flows.erase(it);
        } else {
            ++it;
        }
    }
}

// Drain closed-flow records. Returns number written.
uint32_t df_fm_poll_closed(DfFlowMap* fm, FlowRecord* out, uint32_t cap) {
    uint32_t n = (uint32_t)fm->closed.size();
    if (n > cap) n = cap;
    memcpy(out, fm->closed.data(), (size_t)n * sizeof(FlowRecord));
    fm->closed.erase(fm->closed.begin(), fm->closed.begin() + n);
    return n;
}

// Snapshot all active flows (metering). Returns number written.
uint32_t df_fm_export_active(DfFlowMap* fm, FlowRecord* out, uint32_t cap) {
    uint32_t n = 0;
    for (auto& kv : fm->flows) {
        if (n >= cap) break;
        fill_record(kv.second, 0, &out[n++]);
    }
    return n;
}

// Force-close everything (shutdown).
void df_fm_flush_all(DfFlowMap* fm) {
    for (auto& kv : fm->flows) {
        if (kv.second.close_type == CT_UNKNOWN)
            kv.second.close_type = CT_FORCED;
        close_flow(fm, kv.second);
    }
    fm->flows.clear();
}

uint32_t df_fm_active_count(DfFlowMap* fm) {
    return (uint32_t)fm->flows.size();
}

uint32_t df_fm_closed_count(DfFlowMap* fm) {
    return (uint32_t)fm->closed.size();
}

// stats: [packets, created, closed, evicted, l7_events, l7_dropped, slow,
//         excluded]
void df_fm_stats(DfFlowMap* fm, uint64_t* out8) {
    out8[0] = fm->n_packets;
    out8[1] = fm->n_created;
    out8[2] = fm->n_closed;
    out8[3] = fm->n_evicted;
    out8[4] = fm->n_l7_events;
    out8[5] = fm->n_l7_dropped;
    out8[6] = fm->n_slow;
    out8[7] = fm->n_excluded;
}

void df_fm_exclude_port(DfFlowMap* fm, uint16_t port, int32_t on) {
    fm->exclude_port[port] = on != 0;
}

// ---------------------------------------------------------------------------
// TPACKET_V3 mmap RX ring (reference: dispatcher/recv_engine af_packet)
// ---------------------------------------------------------------------------

struct DfRing {
    int fd = -1;
    uint8_t* map = nullptr;
    size_t map_len = 0;
    uint32_t block_size = 0, block_nr = 0;
    uint32_t cur_block = 0;
};

// Returns nullptr on failure with errno-style code in *err.
DfRing* df_ring_open(const char* ifname, uint32_t block_size,
                     uint32_t block_nr, int32_t* err) {
    *err = 0;
    int fd = socket(AF_PACKET, SOCK_RAW, htons(ETH_P_ALL));
    if (fd < 0) {
        *err = errno;
        return nullptr;
    }
    int ver = TPACKET_V3;
    if (setsockopt(fd, SOL_PACKET, PACKET_VERSION, &ver, sizeof(ver)) < 0) {
        *err = errno;
        close(fd);
        return nullptr;
    }
    tpacket_req3 req{};
    req.tp_block_size = block_size;
    req.tp_block_nr = block_nr;
    req.tp_frame_size = 2048;
    req.tp_frame_nr = (block_size / 2048) * block_nr;
    req.tp_retire_blk_tov = 60;  // ms: deliver partial blocks promptly
    req.tp_feature_req_word = 0;
    if (setsockopt(fd, SOL_PACKET, PACKET_RX_RING, &req, sizeof(req)) < 0) {
        *err = errno;
        close(fd);
        return nullptr;
    }
    size_t map_len = (size_t)block_size * block_nr;
    void* map = mmap(nullptr, map_len, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_LOCKED, fd, 0);
    if (map == MAP_FAILED) {
        map = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);  // retry without MAP_LOCKED (ulimit)
        if (map == MAP_FAILED) {
            *err = errno;
            close(fd);
            return nullptr;
        }
    }
    sockaddr_ll sll{};
    sll.sll_family = AF_PACKET;
    sll.sll_protocol = htons(ETH_P_ALL);
    sll.sll_ifindex = ifname && ifname[0] ? (int)if_nametoindex(ifname) : 0;
    if (ifname && ifname[0] && sll.sll_ifindex == 0) {
        *err = ENODEV;
        munmap(map, map_len);
        close(fd);
        return nullptr;
    }
    if (bind(fd, (sockaddr*)&sll, sizeof(sll)) < 0) {
        *err = errno;
        munmap(map, map_len);
        close(fd);
        return nullptr;
    }
    auto* r = new DfRing();
    r->fd = fd;
    r->map = (uint8_t*)map;
    r->map_len = map_len;
    r->block_size = block_size;
    r->block_nr = block_nr;
    return r;
}

void df_ring_close(DfRing* r) {
    if (!r) return;
    if (r->map) munmap(r->map, r->map_len);
    if (r->fd >= 0) close(r->fd);
    delete r;
}

// Promiscuous mode for mirror/SPAN capture: the NIC must accept frames
// addressed to the mirrored hosts, not just to us. Returns 0 on success.
int32_t df_ring_promisc(DfRing* r, const char* ifname, int32_t on) {
    if (!r || !ifname || !ifname[0]) return -1;
    unsigned idx = if_nametoindex(ifname);
    if (!idx) return -1;
    packet_mreq mr{};
    mr.mr_ifindex = (int)idx;
    mr.mr_type = PACKET_MR_PROMISC;
    int opt = on ? PACKET_ADD_MEMBERSHIP : PACKET_DROP_MEMBERSHIP;
    return setsockopt(r->fd, SOL_PACKET, opt, &mr, sizeof(mr)) < 0
        ? -1 : 0;
}

// Poll for ready blocks and inject frames straight into the flow map.
// Payload segments needing L7 parsing are COPIED into l7_buf (events in
// l7_out) so blocks can be released before Python sees them. Returns the
// number of packets consumed this call; 0 on timeout; -1 on error.
int64_t df_ring_rx_batch(DfRing* r, DfFlowMap* fm, int32_t timeout_ms,
                         uint8_t* l7_buf, uint32_t l7_buf_cap,
                         L7Event* l7_out, uint32_t l7_cap, uint32_t* n_l7,
                         uint32_t max_blocks, int32_t skip_outgoing,
                         uint8_t* slow_buf, uint32_t slow_buf_cap,
                         SlowEvent* slow_out, uint32_t slow_cap,
                         uint32_t* n_slow) {
    L7Sink sink{l7_buf, l7_buf_cap, 0, l7_out, l7_cap, 0, 0};
    *n_l7 = 0;
    *n_slow = 0;
    uint32_t slow_used = 0, slow_n = 0;
    int64_t consumed = 0;
    uint32_t blocks_done = 0;
    if (max_blocks == 0) max_blocks = r->block_nr;
    while (blocks_done < max_blocks) {
        auto* desc = (tpacket_block_desc*)(r->map +
                                           (size_t)r->cur_block *
                                               r->block_size);
        auto& h1 = desc->hdr.bh1;
        if (!(h1.block_status & TP_STATUS_USER)) {
            if (consumed > 0 || timeout_ms == 0) break;
            pollfd pfd{r->fd, POLLIN | POLLERR, 0};
            int pr = poll(&pfd, 1, timeout_ms);
            if (pr < 0) return errno == EINTR ? consumed : -1;
            if (pr == 0) break;  // timeout
            continue;
        }
        uint32_t num = h1.num_pkts;
        auto* ppd = (tpacket3_hdr*)((uint8_t*)desc + h1.offset_to_first_pkt);
        DfPacketOut p;
        for (uint32_t i = 0; i < num; i++) {
            const uint8_t* frame = (uint8_t*)ppd + ppd->tp_mac;
            uint32_t len = ppd->tp_snaplen;
            uint64_t ts = (uint64_t)ppd->tp_sec * 1'000'000'000ULL +
                          ppd->tp_nsec;
            // loopback duplicates every frame as in+out: drop one copy
            auto* sll = (sockaddr_ll*)((uint8_t*)ppd +
                                       TPACKET_ALIGN(sizeof(tpacket3_hdr)));
            if (skip_outgoing && sll->sll_pkttype == PACKET_OUTGOING) {
                consumed++;
                ppd = (tpacket3_hdr*)((uint8_t*)ppd + ppd->tp_next_offset);
                continue;
            }
            if (df_decode_eth(frame, len, &p)) {
                inject_decoded(fm, p, frame, ts, &sink);
            } else {
                // v6/vlan-exotic: copy out for the Python slow path (the
                // block is released before Python runs)
                fm->n_slow++;
                if (slow_out != nullptr && slow_n < slow_cap &&
                    slow_used + len <= slow_buf_cap) {
                    memcpy(slow_buf + slow_used, frame, len);
                    slow_out[slow_n].ts_ns = ts;
                    slow_out[slow_n].off = slow_used;
                    slow_out[slow_n].len = len;
                    slow_used += len;
                    slow_n++;
                }
            }
            consumed++;
            ppd = (tpacket3_hdr*)((uint8_t*)ppd + ppd->tp_next_offset);
        }
        h1.block_status = TP_STATUS_KERNEL;  // release to kernel
        __sync_synchronize();
        r->cur_block = (r->cur_block + 1) % r->block_nr;
        blocks_done++;
    }
    *n_l7 = sink.n;
    *n_slow = slow_n;
    return consumed;
}

// Kernel drop counter (tpacket_stats_v3); returns drops since last call.
uint64_t df_ring_drops(DfRing* r) {
    tpacket_stats_v3 st{};
    socklen_t len = sizeof(st);
    if (getsockopt(r->fd, SOL_PACKET, PACKET_STATISTICS, &st, &len) < 0)
        return 0;
    return st.tp_drops;
}

}  // extern "C"
