// Shared decoded-packet record for the native fast path.
// Layout must match PACKET_DTYPE in deepflow_tpu/native/__init__.py.
#pragma once

#include <cstdint>

struct DfPacketOut {
    uint32_t ip_src;     // v4 only on the fast path; v6 falls back to Python
    uint32_t ip_dst;
    uint16_t port_src;
    uint16_t port_dst;
    uint8_t  protocol;   // 1 tcp, 2 udp, 3 icmp, 0 = not decodable here
    uint8_t  tcp_flags;
    uint16_t window;
    uint32_t seq;
    uint32_t ack;
    uint32_t payload_off;
    uint32_t payload_len;
    // tunnel decapsulation (reference: agent/src/common/decapsulate.rs):
    // when a VXLAN/GENEVE/GRE/ERSPAN outer was stripped, the fields above
    // describe the INNER packet and these record the tunnel
    uint8_t  tunnel_type;  // 0 none, 1 vxlan, 2 geneve, 3 erspan, 4 gre-teb
    uint8_t  _pad[3];
    uint32_t tunnel_id;    // VNI / session id / GRE key
};
