// Native columnar decode of FlowLogBatch L4 rows: protobuf wire format ->
// struct-of-arrays, no Python objects on the hot path.
//
// Reference analog: the Go ingester's per-type unmarshallers
// (server/ingester/flow_metrics/flow_metrics.go:55 fan decode across
// cores). Redesign: instead of sharding Python decode across processes,
// the columnar parse itself is native and releases the GIL — N decoder
// threads then scale across cores while Python only broadcasts tags and
// appends numpy arrays.
//
// Wire schema parsed here must match deepflow_tpu/proto/messages.proto:
//   FlowLogBatch{ repeated L4FlowLog l4 = 1; repeated L7FlowLog l7 = 2; }
//   L4FlowLog fields 1..26 (see proto); FlowKey fields 1..8.
// Unknown fields are skipped by wire type, so proto ADDITIONS stay
// compatible; if a parsed field changes meaning, bump DF_ABI_VERSION.

#include <cstdint>
#include <cstring>

namespace {

struct Reader {
    const uint8_t* p;
    const uint8_t* end;
    bool ok = true;

    uint64_t varint() {
        uint64_t v = 0;
        int shift = 0;
        while (p < end && shift < 64) {
            uint8_t b = *p++;
            v |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) return v;
            shift += 7;
        }
        ok = false;
        return 0;
    }

    bool skip(uint32_t wire) {
        switch (wire) {
            case 0: varint(); return ok;
            case 1: if (end - p < 8) return ok = false; p += 8; return true;
            case 2: {
                uint64_t n = varint();
                if (!ok || (uint64_t)(end - p) < n) return ok = false;
                p += n;
                return true;
            }
            case 5: if (end - p < 4) return ok = false; p += 4; return true;
            default: return ok = false;
        }
    }
};

}  // namespace

extern "C" {

// Packed column output for one batch of L4 rows. Arrays are caller-owned
// with capacity `cap`. Strings (close_type as enum; pod_0/pod_1) land in
// a shared arena as (offset,len) pairs. Layout must match the ctypes
// binding in native/__init__.py; bump DF_ABI_VERSION on change.
#pragma pack(push, 1)
struct DfL4Cols {
    uint64_t* flow_id;
    uint64_t* start_time_ns;
    uint64_t* end_time_ns;
    uint64_t* packet_tx;
    uint64_t* packet_rx;
    uint64_t* byte_tx;
    uint64_t* byte_rx;
    uint64_t* l7_request;
    uint64_t* l7_response;
    uint32_t* rtt_us;
    uint32_t* art_us;
    uint32_t* retrans_tx;
    uint32_t* retrans_rx;
    uint32_t* zero_win_tx;
    uint32_t* zero_win_rx;
    uint8_t*  close_type;      // enum idx: 0 unknown,1 fin,2 rst,3 timeout,4 forced
    uint32_t* syn_count;
    uint32_t* synack_count;
    uint32_t* gpid_0;
    uint32_t* gpid_1;
    // key
    uint32_t* ip4_src;         // host byte order; 0 when v6 (see is_v6)
    uint32_t* ip4_dst;
    uint8_t*  is_v6;           // 1 -> ips live in the arena
    uint32_t* ip6_src_off;     // arena offsets (16 bytes each) when v6
    uint32_t* ip6_dst_off;
    uint16_t* port_src;
    uint16_t* port_dst;
    uint8_t*  proto;
    uint32_t* tap_port;
    uint8_t*  tunnel_type;
    uint32_t* tunnel_id;
    // pod strings: arena (off,len); len 0 = empty
    uint32_t* pod0_off;
    uint32_t* pod0_len;
    uint32_t* pod1_off;
    uint32_t* pod1_len;
    // shared string arena
    uint8_t*  arena;
    uint32_t  arena_cap;
    uint32_t  arena_used;
    uint32_t  cap;
};
#pragma pack(pop)

static uint8_t close_type_idx(const uint8_t* s, uint64_t n) {
    // matches store/schema.py CLOSE_TYPES order
    if (n == 3 && !memcmp(s, "fin", 3)) return 1;
    if (n == 3 && !memcmp(s, "rst", 3)) return 2;
    if (n == 7 && !memcmp(s, "timeout", 7)) return 3;
    if (n == 6 && !memcmp(s, "forced", 6)) return 4;
    return 0;
}

static bool arena_put(DfL4Cols* c, const uint8_t* s, uint64_t n,
                      uint32_t* off_out, uint32_t* len_out) {
    if (c->arena_used + n > c->arena_cap) return false;
    memcpy(c->arena + c->arena_used, s, n);
    *off_out = c->arena_used;
    if (len_out) *len_out = (uint32_t)n;
    c->arena_used += (uint32_t)n;
    return true;
}

// Parse one L4FlowLog submessage into row r. Returns false on malformed
// input or arena overflow.
static bool parse_l4(Reader& rd, const uint8_t* end, DfL4Cols* c,
                     uint32_t r) {
    // zero the row (batches reuse arrays)
    c->flow_id[r] = c->start_time_ns[r] = c->end_time_ns[r] = 0;
    c->packet_tx[r] = c->packet_rx[r] = c->byte_tx[r] = c->byte_rx[r] = 0;
    c->l7_request[r] = c->l7_response[r] = 0;
    c->rtt_us[r] = c->art_us[r] = 0;
    c->retrans_tx[r] = c->retrans_rx[r] = 0;
    c->zero_win_tx[r] = c->zero_win_rx[r] = 0;
    c->close_type[r] = 0;
    c->syn_count[r] = c->synack_count[r] = 0;
    c->gpid_0[r] = c->gpid_1[r] = 0;
    c->ip4_src[r] = c->ip4_dst[r] = 0;
    c->is_v6[r] = 0;
    c->ip6_src_off[r] = c->ip6_dst_off[r] = 0;
    c->port_src[r] = c->port_dst[r] = 0;
    c->proto[r] = 0;
    c->tap_port[r] = 0;
    c->tunnel_type[r] = 0;
    c->tunnel_id[r] = 0;
    c->pod0_len[r] = c->pod1_len[r] = 0;
    c->pod0_off[r] = c->pod1_off[r] = 0;

    while (rd.ok && rd.p < end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire == 0) {
            uint64_t v = rd.varint();
            if (!rd.ok) return false;
            switch (field) {
                case 1: c->flow_id[r] = v; break;
                case 3: c->start_time_ns[r] = v; break;
                case 4: c->end_time_ns[r] = v; break;
                case 5: c->packet_tx[r] = v; break;
                case 6: c->packet_rx[r] = v; break;
                case 7: c->byte_tx[r] = v; break;
                case 8: c->byte_rx[r] = v; break;
                case 9: c->l7_request[r] = v; break;
                case 10: c->l7_response[r] = v; break;
                case 11: c->rtt_us[r] = (uint32_t)v; break;
                case 12: c->art_us[r] = (uint32_t)v; break;
                case 13: c->retrans_tx[r] = (uint32_t)v; break;
                case 14: c->retrans_rx[r] = (uint32_t)v; break;
                case 15: c->zero_win_tx[r] = (uint32_t)v; break;
                case 16: c->zero_win_rx[r] = (uint32_t)v; break;
                case 20: c->syn_count[r] = (uint32_t)v; break;
                case 21: c->synack_count[r] = (uint32_t)v; break;
                case 23: c->gpid_0[r] = (uint32_t)v; break;
                case 24: c->gpid_1[r] = (uint32_t)v; break;
                default: break;  // 18,19,22 unused by the row build
            }
            continue;
        }
        if (wire == 2) {
            uint64_t n = rd.varint();
            if (!rd.ok || (uint64_t)(end - rd.p) < n) return false;
            const uint8_t* sub = rd.p;
            rd.p += n;
            switch (field) {
                case 2: {  // FlowKey
                    Reader kr{sub, sub + n};
                    while (kr.ok && kr.p < kr.end) {
                        uint64_t ktag = kr.varint();
                        if (!kr.ok) return false;
                        uint32_t kf = (uint32_t)(ktag >> 3),
                                 kw = (uint32_t)(ktag & 7);
                        if (kw == 0) {
                            uint64_t kv = kr.varint();
                            if (!kr.ok) return false;
                            switch (kf) {
                                case 3: c->port_src[r] = (uint16_t)kv; break;
                                case 4: c->port_dst[r] = (uint16_t)kv; break;
                                case 5: c->proto[r] = (uint8_t)kv; break;
                                case 6: c->tap_port[r] = (uint32_t)kv; break;
                                case 7: c->tunnel_type[r] = (uint8_t)kv; break;
                                case 8: c->tunnel_id[r] = (uint32_t)kv; break;
                                default: break;
                            }
                        } else if (kw == 2) {
                            uint64_t kn = kr.varint();
                            if (!kr.ok ||
                                (uint64_t)(kr.end - kr.p) < kn)
                                return false;
                            const uint8_t* ks = kr.p;
                            kr.p += kn;
                            if (kf == 1 || kf == 2) {
                                if (kn == 4) {
                                    uint32_t ip =
                                        (uint32_t)ks[0] << 24 |
                                        (uint32_t)ks[1] << 16 |
                                        (uint32_t)ks[2] << 8 | ks[3];
                                    (kf == 1 ? c->ip4_src
                                             : c->ip4_dst)[r] = ip;
                                } else if (kn == 16) {
                                    c->is_v6[r] = 1;
                                    uint32_t off;
                                    if (!arena_put(c, ks, kn, &off,
                                                   nullptr))
                                        return false;
                                    (kf == 1 ? c->ip6_src_off
                                             : c->ip6_dst_off)[r] = off;
                                }
                            }
                        } else if (!kr.skip(kw)) {
                            return false;
                        }
                    }
                    if (!kr.ok) return false;
                    break;
                }
                case 17:
                    c->close_type[r] = close_type_idx(sub, n);
                    break;
                case 25:
                    if (n && !arena_put(c, sub, n, &c->pod0_off[r],
                                        &c->pod0_len[r]))
                        return false;
                    break;
                case 26:
                    if (n && !arena_put(c, sub, n, &c->pod1_off[r],
                                        &c->pod1_len[r]))
                        return false;
                    break;
                default:
                    break;
            }
            continue;
        }
        if (!rd.skip(wire)) return false;
    }
    return rd.ok;
}

// Packed column output for one batch of L7 rows. Same ownership model as
// DfL4Cols: caller-owned arrays with capacity `cap`, strings land in the
// shared arena as (offset,len) pairs. Layout must match _DfL7Cols in
// native/__init__.py; bump DF_ABI_VERSION on change.
#pragma pack(push, 1)
struct DfL7Cols {
    uint64_t* flow_id;
    uint64_t* start_time_ns;
    uint64_t* end_time_ns;
    uint64_t* syscall_trace_id_request;
    uint64_t* syscall_trace_id_response;
    uint64_t* captured_request_byte;
    uint64_t* captured_response_byte;
    uint32_t* l7_protocol;
    uint32_t* request_id;
    uint32_t* response_status;
    int32_t*  response_code;
    uint32_t* syscall_thread_0;
    uint32_t* syscall_thread_1;
    uint32_t* gpid_0;
    uint32_t* gpid_1;
    // key
    uint32_t* ip4_src;         // host byte order; 0 when v6 (see is_v6)
    uint32_t* ip4_dst;
    uint8_t*  is_v6;           // 1 -> ips live in the arena
    uint32_t* ip6_src_off;     // arena offsets (16 bytes each) when v6
    uint32_t* ip6_dst_off;
    uint16_t* port_src;
    uint16_t* port_dst;
    uint8_t*  proto;
    uint8_t*  tunnel_type;
    uint32_t* tunnel_id;
    // string columns: arena (off,len); len 0 = empty. Order here matches
    // L7ColumnDecoder.STRS in native/__init__.py.
    uint32_t* str_off[16];
    uint32_t* str_len[16];
    // shared string arena
    uint8_t*  arena;
    uint32_t  arena_cap;
    uint32_t  arena_used;
    uint32_t  cap;
};
#pragma pack(pop)

// proto field number -> index into str_off/str_len (STRS order):
//   0 version(4) 1 request_type(5) 2 request_domain(6)
//   3 request_resource(7) 4 endpoint(8) 5 response_exception(12)
//   6 response_result(13) 7 trace_id(16) 8 span_id(17)
//   9 parent_span_id(18) 10 x_request_id(19) 11 process_kname_0(29)
//   12 process_kname_1(30) 13 attrs_json(31) 14 pod_0(32) 15 pod_1(33)
static int l7_str_slot(uint32_t field) {
    switch (field) {
        case 4: return 0; case 5: return 1; case 6: return 2;
        case 7: return 3; case 8: return 4; case 12: return 5;
        case 13: return 6; case 16: return 7; case 17: return 8;
        case 18: return 9; case 19: return 10; case 29: return 11;
        case 30: return 12; case 31: return 13; case 32: return 14;
        case 33: return 15;
        default: return -1;
    }
}

static bool arena_put7(DfL7Cols* c, const uint8_t* s, uint64_t n,
                       uint32_t* off_out, uint32_t* len_out) {
    if (c->arena_used + n > c->arena_cap) return false;
    memcpy(c->arena + c->arena_used, s, n);
    *off_out = c->arena_used;
    if (len_out) *len_out = (uint32_t)n;
    c->arena_used += (uint32_t)n;
    return true;
}

// Parse one L7FlowLog submessage into row r. Returns false on malformed
// input or arena overflow.
static bool parse_l7(Reader& rd, const uint8_t* end, DfL7Cols* c,
                     uint32_t r) {
    // zero the row (batches reuse arrays)
    c->flow_id[r] = c->start_time_ns[r] = c->end_time_ns[r] = 0;
    c->syscall_trace_id_request[r] = c->syscall_trace_id_response[r] = 0;
    c->captured_request_byte[r] = c->captured_response_byte[r] = 0;
    c->l7_protocol[r] = c->request_id[r] = c->response_status[r] = 0;
    c->response_code[r] = 0;
    c->syscall_thread_0[r] = c->syscall_thread_1[r] = 0;
    c->gpid_0[r] = c->gpid_1[r] = 0;
    c->ip4_src[r] = c->ip4_dst[r] = 0;
    c->is_v6[r] = 0;
    c->ip6_src_off[r] = c->ip6_dst_off[r] = 0;
    c->port_src[r] = c->port_dst[r] = 0;
    c->proto[r] = 0;
    c->tunnel_type[r] = 0;
    c->tunnel_id[r] = 0;
    for (int i = 0; i < 16; i++) {
        c->str_off[i][r] = 0;
        c->str_len[i][r] = 0;
    }

    while (rd.ok && rd.p < end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return false;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (wire == 0) {
            uint64_t v = rd.varint();
            if (!rd.ok) return false;
            switch (field) {
                case 1: c->flow_id[r] = v; break;
                case 3: c->l7_protocol[r] = (uint32_t)v; break;
                case 9: c->request_id[r] = (uint32_t)v; break;
                case 10: c->response_status[r] = (uint32_t)v; break;
                case 11: c->response_code[r] = (int32_t)v; break;
                case 14: c->start_time_ns[r] = v; break;
                case 15: c->end_time_ns[r] = v; break;
                case 20: c->syscall_trace_id_request[r] = v; break;
                case 21: c->syscall_trace_id_response[r] = v; break;
                case 22: c->syscall_thread_0[r] = (uint32_t)v; break;
                case 23: c->syscall_thread_1[r] = (uint32_t)v; break;
                case 24: c->captured_request_byte[r] = v; break;
                case 25: c->captured_response_byte[r] = v; break;
                case 27: c->gpid_0[r] = (uint32_t)v; break;
                case 28: c->gpid_1[r] = (uint32_t)v; break;
                default: break;  // 26 agent_id unused by the row build
            }
            continue;
        }
        if (wire == 2) {
            uint64_t n = rd.varint();
            if (!rd.ok || (uint64_t)(end - rd.p) < n) return false;
            const uint8_t* sub = rd.p;
            rd.p += n;
            if (field == 2) {  // FlowKey
                Reader kr{sub, sub + n};
                while (kr.ok && kr.p < kr.end) {
                    uint64_t ktag = kr.varint();
                    if (!kr.ok) return false;
                    uint32_t kf = (uint32_t)(ktag >> 3),
                             kw = (uint32_t)(ktag & 7);
                    if (kw == 0) {
                        uint64_t kv = kr.varint();
                        if (!kr.ok) return false;
                        switch (kf) {
                            case 3: c->port_src[r] = (uint16_t)kv; break;
                            case 4: c->port_dst[r] = (uint16_t)kv; break;
                            case 5: c->proto[r] = (uint8_t)kv; break;
                            case 7: c->tunnel_type[r] = (uint8_t)kv; break;
                            case 8: c->tunnel_id[r] = (uint32_t)kv; break;
                            default: break;  // 6 tap_port unused on l7
                        }
                    } else if (kw == 2) {
                        uint64_t kn = kr.varint();
                        if (!kr.ok || (uint64_t)(kr.end - kr.p) < kn)
                            return false;
                        const uint8_t* ks = kr.p;
                        kr.p += kn;
                        if (kf == 1 || kf == 2) {
                            if (kn == 4) {
                                uint32_t ip =
                                    (uint32_t)ks[0] << 24 |
                                    (uint32_t)ks[1] << 16 |
                                    (uint32_t)ks[2] << 8 | ks[3];
                                (kf == 1 ? c->ip4_src
                                         : c->ip4_dst)[r] = ip;
                            } else if (kn == 16) {
                                c->is_v6[r] = 1;
                                uint32_t off;
                                if (!arena_put7(c, ks, kn, &off, nullptr))
                                    return false;
                                (kf == 1 ? c->ip6_src_off
                                         : c->ip6_dst_off)[r] = off;
                            }
                        }
                    } else if (!kr.skip(kw)) {
                        return false;
                    }
                }
                if (!kr.ok) return false;
                continue;
            }
            int slot = l7_str_slot(field);
            if (slot >= 0 && n) {
                if (!arena_put7(c, sub, n, &c->str_off[slot][r],
                                &c->str_len[slot][r]))
                    return false;
            }
            continue;
        }
        if (!rd.skip(wire)) return false;
    }
    return rd.ok;
}

// Decode FlowLogBatch L7 rows columnar (top-level field 2 submessages;
// L4 submessages are skipped — the L4 pass handles those). Returns the
// number of L7 rows decoded, or -1 on malformed input / capacity
// overflow (caller falls back to the Python pb path).
int64_t df_decode_l7_cols(const uint8_t* data, uint64_t len,
                          DfL7Cols* cols) {
    Reader rd{data, data + len};
    uint32_t n = 0;
    cols->arena_used = 0;
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return -1;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (field == 2 && wire == 2) {
            uint64_t sublen = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < sublen) return -1;
            if (n >= cols->cap) return -1;
            const uint8_t* sub = rd.p;
            rd.p += sublen;
            Reader sr{sub, sub + sublen};
            if (!parse_l7(sr, sub + sublen, cols, n)) return -1;
            n++;
        } else if (!rd.skip(wire)) {
            return -1;
        }
    }
    if (!rd.ok) return -1;
    return n;
}

// Decode FlowLogBatch L4 rows columnar. Returns the number of L4 rows
// decoded, or -1 on malformed input / capacity overflow (caller falls
// back to the Python pb path). L7 submessages are NOT parsed; their
// (offset, length) pairs within `data` are written to l7_off/l7_len
// (capacity l7_cap) and counted in *n_l7 so Python can parse exactly
// those bytes without re-walking the batch.
int64_t df_decode_l4_cols(const uint8_t* data, uint64_t len,
                          DfL4Cols* cols, uint32_t* l7_off,
                          uint32_t* l7_len, uint32_t l7_cap,
                          uint32_t* n_l7) {
    Reader rd{data, data + len};
    uint32_t n = 0, l7n = 0;
    cols->arena_used = 0;
    while (rd.ok && rd.p < rd.end) {
        uint64_t tag = rd.varint();
        if (!rd.ok) return -1;
        uint32_t field = (uint32_t)(tag >> 3), wire = (uint32_t)(tag & 7);
        if (field == 1 && wire == 2) {
            uint64_t sublen = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < sublen) return -1;
            if (n >= cols->cap) return -1;
            const uint8_t* sub = rd.p;
            rd.p += sublen;
            Reader sr{sub, sub + sublen};
            if (!parse_l4(sr, sub + sublen, cols, n)) return -1;
            n++;
        } else if (field == 2 && wire == 2) {
            uint64_t sublen = rd.varint();
            if (!rd.ok || (uint64_t)(rd.end - rd.p) < sublen) return -1;
            if (l7n >= l7_cap) return -1;
            l7_off[l7n] = (uint32_t)(rd.p - data);
            l7_len[l7n] = (uint32_t)sublen;
            l7n++;
            rd.p += sublen;
        } else if (!rd.skip(wire)) {
            return -1;
        }
    }
    if (!rd.ok) return -1;
    *n_l7 = l7n;
    return n;
}

}  // extern "C"
