// LD_PRELOAD interposer: pre-encryption L7 visibility + syscall trace
// chaining for deepflow-run-launched processes.
//
// Reference analog: agent/src/ebpf/user/ssl_tracer.c (uprobes on
// SSL_read/SSL_write expose plaintext before encryption) and
// kernel/socket_trace.bpf.c:1291 (thread-scoped syscall_trace_id chains
// ingress reads to the egress writes they cause, linking request->response
// and request->downstream-call without W3C headers). Redesign: no kernel
// programs — symbol interposition in the target's own address space, with
// events shipped over an AF_UNIX datagram socket to the agent.
//
// Build: part of `make -C deepflow_tpu/native` -> libdfsslprobe.so.
// Activate: LD_PRELOAD=libdfsslprobe.so DF_SSLPROBE_SOCK=/path cmd...

#define _GNU_SOURCE 1

#include <cerrno>
#include <cstdarg>
#include <cstdint>
#include <initializer_list>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <dlfcn.h>
#include <fcntl.h>
#include <pthread.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/un.h>
#include <netinet/in.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMaxPayload = 3800;  // fits one unix dgram with header

enum : uint8_t { DIR_INGRESS = 0, DIR_EGRESS = 1 };
enum : uint8_t { SRC_PLAIN = 0, SRC_TLS = 1, SRC_FILEIO = 2 };

#pragma pack(push, 1)
struct ProbeEvent {             // must match SSL_EVENT_DTYPE (sslprobe.py)
    uint32_t pid;
    uint32_t tid;
    int32_t fd;
    uint8_t direction;          // 0 ingress (read), 1 egress (write)
    uint8_t source;             // 0 plain syscall, 1 TLS (decrypted)
    uint16_t local_port;
    uint16_t peer_port;
    uint8_t family;             // 4 or 6
    uint8_t _pad;
    uint8_t local_addr[16];
    uint8_t peer_addr[16];
    uint64_t ts_ns;
    uint64_t syscall_trace_id;  // thread-scoped chain id
    uint64_t latency_ns;        // SRC_FILEIO: operation latency
    uint64_t io_bytes;          // SRC_FILEIO: bytes read/written
    uint32_t data_len;          // bytes following this header
};
#pragma pack(pop)

using ssl_io_fn = int (*)(void*, void*, int);
using ssl_io_ex_fn = int (*)(void*, void*, size_t, size_t*);
using ssl_get_fd_fn = int (*)(const void*);
using rw_fn = ssize_t (*)(int, void*, size_t);
using send_fn = ssize_t (*)(int, const void*, size_t, int);

ssl_io_fn real_ssl_read = nullptr;
ssl_io_fn real_ssl_write = nullptr;
ssl_io_ex_fn real_ssl_read_ex = nullptr;
ssl_io_ex_fn real_ssl_write_ex = nullptr;
ssl_get_fd_fn real_ssl_get_fd = nullptr;
rw_fn real_read = nullptr;
rw_fn real_write = nullptr;
send_fn real_send = nullptr;
send_fn real_recv = nullptr;

int emit_fd = -1;
sockaddr_un emit_addr{};
bool enabled = false;
uint64_t io_threshold_ns = 0;  // DF_IOPROBE_NS: emit file IO slower than
                               // this (0 = file tracing off)
bool debug = false;            // cached: getenv is a linear environ scan,
                               // far too slow for the per-syscall hot path
uint64_t trace_epoch = 0;      // high bits of trace ids (per process)

// thread-local chain state + re-entrancy guard (our own emit writes must
// never be traced)
thread_local uint64_t tls_trace_id = 0;
thread_local uint64_t tls_counter = 0;
thread_local bool tls_in_probe = false;

uint64_t now_ns() {
    timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (uint64_t)ts.tv_sec * 1'000'000'000ULL + ts.tv_nsec;
}

void init_once() {
    static pthread_once_t once = PTHREAD_ONCE_INIT;
    pthread_once(&once, [] {
        real_read = (rw_fn)dlsym(RTLD_NEXT, "read");
        real_write = (rw_fn)dlsym(RTLD_NEXT, "write");
        real_send = (send_fn)dlsym(RTLD_NEXT, "send");
        real_recv = (send_fn)dlsym(RTLD_NEXT, "recv");
        // SSL_* are NOT resolved here: libssl is typically dlopen'd later
        // (python imports _ssl long after the first read()); they resolve
        // lazily at first SSL call
        debug = getenv("DF_SSLPROBE_DEBUG") != nullptr;
        const char* th = getenv("DF_IOPROBE_NS");
        if (th) io_threshold_ns = strtoull(th, nullptr, 10);
        const char* path = getenv("DF_SSLPROBE_SOCK");
        if (!path || !path[0]) return;
        // SEQPACKET, not DGRAM: unix dgram queues are capped by
        // net.unix.max_dgram_qlen (10 on this kernel) — a single request
        // overflows it; seqpacket keeps message boundaries with normal
        // socket buffering
        emit_fd = socket(AF_UNIX, SOCK_SEQPACKET | SOCK_CLOEXEC, 0);
        if (emit_fd < 0) return;
        emit_addr.sun_family = AF_UNIX;
        strncpy(emit_addr.sun_path, path, sizeof(emit_addr.sun_path) - 1);
        if (connect(emit_fd, (sockaddr*)&emit_addr,
                    sizeof(emit_addr)) != 0) {
            close(emit_fd);
            emit_fd = -1;
            return;
        }
        int snd = 4 << 20;
        setsockopt(emit_fd, SOL_SOCKET, SO_SNDBUF, &snd, sizeof(snd));
        enabled = true;
        trace_epoch = ((uint64_t)getpid() << 40) ^ now_ns();
    });
}

bool is_inet_socket(int fd, ProbeEvent* ev) {
    struct stat st;
    if (fstat(fd, &st) != 0 || !S_ISSOCK(st.st_mode)) return false;
    sockaddr_storage peer{}, local{};
    socklen_t plen = sizeof(peer), llen = sizeof(local);
    if (getpeername(fd, (sockaddr*)&peer, &plen) != 0) return false;
    if (peer.ss_family != AF_INET && peer.ss_family != AF_INET6)
        return false;
    getsockname(fd, (sockaddr*)&local, &llen);
    if (peer.ss_family == AF_INET) {
        auto* p = (sockaddr_in*)&peer;
        auto* l = (sockaddr_in*)&local;
        ev->family = 4;
        memcpy(ev->peer_addr, &p->sin_addr, 4);
        memcpy(ev->local_addr, &l->sin_addr, 4);
        ev->peer_port = ntohs(p->sin_port);
        ev->local_port = ntohs(l->sin_port);
    } else {
        auto* p = (sockaddr_in6*)&peer;
        auto* l = (sockaddr_in6*)&local;
        ev->family = 6;
        memcpy(ev->peer_addr, &p->sin6_addr, 16);
        memcpy(ev->local_addr, &l->sin6_addr, 16);
        ev->peer_port = ntohs(p->sin6_port);
        ev->local_port = ntohs(l->sin6_port);
    }
    return true;
}

void emit(int fd, uint8_t direction, uint8_t source, const void* data,
          size_t len) {
    if (!enabled || tls_in_probe || len == 0) {
        if (debug && source == SRC_TLS)
            fprintf(stderr, "dfsslprobe: emit early-out enabled=%d "
                            "in_probe=%d len=%zu\n", enabled, tls_in_probe,
                    len);
        return;
    }
    tls_in_probe = true;
    ProbeEvent ev{};
    if (!is_inet_socket(fd, &ev)) {
        if (debug && source == SRC_TLS)
            fprintf(stderr, "dfsslprobe: emit not-inet fd=%d\n", fd);
        tls_in_probe = false;
        return;
    }
    // thread-scoped chaining (socket_trace.bpf.c:1291 semantics): an
    // ingress starts a new chain; every egress the thread performs before
    // its next ingress inherits it
    if (direction == DIR_INGRESS) {
        tls_trace_id = trace_epoch + (++tls_counter) +
                       ((uint64_t)syscall(SYS_gettid) << 20);
    }
    ev.pid = (uint32_t)getpid();
    ev.tid = (uint32_t)syscall(SYS_gettid);
    ev.fd = fd;
    ev.direction = direction;
    ev.source = source;
    ev.ts_ns = now_ns();
    ev.syscall_trace_id = tls_trace_id;
    ev.data_len = len > kMaxPayload ? kMaxPayload : (uint32_t)len;
    char buf[sizeof(ProbeEvent) + kMaxPayload];
    memcpy(buf, &ev, sizeof(ev));
    memcpy(buf + sizeof(ev), data, ev.data_len);
    ssize_t sent = real_send(emit_fd, buf, sizeof(ev) + ev.data_len,
                             MSG_DONTWAIT | MSG_NOSIGNAL);
    if (sent < 0 && debug)
        fprintf(stderr, "dfsslprobe: emit send failed errno=%d\n", errno);
    tls_in_probe = false;
}

// Slow file IO (reference: kernel/files_rw.bpf.c — per-op latency +
// filename for reads/writes over a threshold). Only the SLOW path pays
// fstat/readlink; the hot path adds two clock reads when enabled.
void emit_file_io(int fd, uint8_t direction, uint64_t latency_ns,
                  size_t nbytes) {
    if (!enabled || tls_in_probe) return;
    struct stat st;
    if (fstat(fd, &st) != 0 || !S_ISREG(st.st_mode)) return;
    tls_in_probe = true;
    char linkpath[64];
    char path[512];
    snprintf(linkpath, sizeof(linkpath), "/proc/self/fd/%d", fd);
    ssize_t plen = readlink(linkpath, path, sizeof(path) - 1);
    if (plen <= 0) {
        tls_in_probe = false;
        return;
    }
    ProbeEvent ev{};
    ev.pid = (uint32_t)getpid();
    ev.tid = (uint32_t)syscall(SYS_gettid);
    ev.fd = fd;
    ev.direction = direction;
    ev.source = SRC_FILEIO;
    ev.ts_ns = now_ns();
    ev.syscall_trace_id = tls_trace_id;  // chains file IO to the request
    ev.latency_ns = latency_ns;
    ev.io_bytes = nbytes;
    ev.data_len = (uint32_t)plen;
    char buf[sizeof(ProbeEvent) + sizeof(path)];
    memcpy(buf, &ev, sizeof(ev));
    memcpy(buf + sizeof(ev), path, plen);
    real_send(emit_fd, buf, sizeof(ev) + plen, MSG_DONTWAIT | MSG_NOSIGNAL);
    tls_in_probe = false;
}

uint64_t mono_ns() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1'000'000'000ULL + ts.tv_nsec;
}

}  // namespace

extern "C" {

ssize_t read(int fd, void* buf, size_t count) {
    init_once();
    uint64_t t0 = io_threshold_ns ? mono_ns() : 0;
    ssize_t n = real_read(fd, buf, count);
    if (io_threshold_ns && n > 0) {
        uint64_t lat = mono_ns() - t0;
        if (lat >= io_threshold_ns)
            emit_file_io(fd, DIR_INGRESS, lat, (size_t)n);
    }
    if (n > 0) emit(fd, DIR_INGRESS, SRC_PLAIN, buf, (size_t)n);
    return n;
}

ssize_t write(int fd, const void* buf, size_t count) {
    init_once();
    uint64_t t0 = io_threshold_ns ? mono_ns() : 0;
    ssize_t n = real_write(fd, (void*)buf, count);
    if (io_threshold_ns && n > 0) {
        uint64_t lat = mono_ns() - t0;
        if (lat >= io_threshold_ns)
            emit_file_io(fd, DIR_EGRESS, lat, (size_t)n);
    }
    if (n > 0) emit(fd, DIR_EGRESS, SRC_PLAIN, buf, (size_t)n);
    return n;
}

ssize_t recv(int fd, void* buf, size_t count, int flags) {
    init_once();
    ssize_t n = real_recv(fd, buf, count, flags);
    if (n > 0 && !(flags & MSG_PEEK))
        emit(fd, DIR_INGRESS, SRC_PLAIN, buf, (size_t)n);
    return n;
}

ssize_t send(int fd, const void* buf, size_t count, int flags) {
    init_once();
    ssize_t n = real_send(fd, (void*)buf, count, flags);
    if (n > 0) emit(fd, DIR_EGRESS, SRC_PLAIN, buf, (size_t)n);
    return n;
}

// TLS: plaintext BEFORE encryption / AFTER decryption. The fd used for
// flow identity comes from SSL_get_fd, and the event is marked SRC_TLS so
// the agent drops the overlapping ciphertext syscall events for that fd.
static void resolve_ssl() {
    if (real_ssl_get_fd) return;
    // RTLD_NEXT only sees the GLOBAL scope; when libssl arrives as an
    // RTLD_LOCAL dependency of a dlopen'd extension (python's _ssl.so),
    // the interposed symbols still bind to us, but the real ones must be
    // found via a NOLOAD handle to the already-mapped libssl
    void* h = RTLD_NEXT;
    if (!dlsym(RTLD_NEXT, "SSL_get_fd")) {
        for (const char* name : {"libssl.so.3", "libssl.so.1.1",
                                 "libssl.so"}) {
            void* lh = dlopen(name, RTLD_LAZY | RTLD_NOLOAD);
            if (lh) {
                h = lh;
                break;
            }
        }
        if (h == RTLD_NEXT) return;  // libssl not loaded yet
    }
    real_ssl_read = (ssl_io_fn)dlsym(h, "SSL_read");
    real_ssl_write = (ssl_io_fn)dlsym(h, "SSL_write");
    real_ssl_read_ex = (ssl_io_ex_fn)dlsym(h, "SSL_read_ex");
    real_ssl_write_ex = (ssl_io_ex_fn)dlsym(h, "SSL_write_ex");
    real_ssl_get_fd = (ssl_get_fd_fn)dlsym(h, "SSL_get_fd");
    if (debug) {
        fprintf(stderr, "dfsslprobe: resolve h=%p read=%p read_ex=%p "
                        "get_fd=%p\n", h, (void*)real_ssl_read,
                (void*)real_ssl_read_ex, (void*)real_ssl_get_fd);
    }
}

int SSL_read(void* ssl, void* buf, int num) {
    init_once();
    resolve_ssl();
    if (!real_ssl_read) return -1;
    tls_in_probe = true;  // suppress the underlying read() of ciphertext
    int n = real_ssl_read(ssl, buf, num);
    tls_in_probe = false;
    if (n > 0 && real_ssl_get_fd)
        emit(real_ssl_get_fd(ssl), DIR_INGRESS, SRC_TLS, buf, (size_t)n);
    return n;
}

int SSL_write(void* ssl, void* buf, int num) {
    init_once();
    resolve_ssl();
    if (!real_ssl_write) return -1;
    tls_in_probe = true;  // suppress the underlying write() of ciphertext
    int n = real_ssl_write(ssl, buf, num);
    tls_in_probe = false;
    // emit AFTER, with the accepted byte count: WANT_WRITE retries and
    // partial writes must not produce phantom/duplicate plaintext events
    if (n > 0 && real_ssl_get_fd)
        emit(real_ssl_get_fd(ssl), DIR_EGRESS, SRC_TLS, buf, (size_t)n);
    return n;
}

// OpenSSL 1.1.1+ _ex API — what CPython 3.12's _ssl actually calls.
// (Intra-libssl calls don't cross the PLT, so SSL_read interposition alone
// never sees them.)
int SSL_read_ex(void* ssl, void* buf, size_t num, size_t* readbytes) {
    init_once();
    resolve_ssl();
    if (!real_ssl_read_ex) return 0;
    tls_in_probe = true;
    int ok = real_ssl_read_ex(ssl, buf, num, readbytes);
    tls_in_probe = false;
    if (debug)
        fprintf(stderr, "dfsslprobe: SSL_read_ex ok=%d n=%zu fd=%d\n", ok,
                readbytes ? *readbytes : 0,
                real_ssl_get_fd ? real_ssl_get_fd(ssl) : -1);
    if (ok > 0 && readbytes && *readbytes > 0 && real_ssl_get_fd)
        emit(real_ssl_get_fd(ssl), DIR_INGRESS, SRC_TLS, buf, *readbytes);
    return ok;
}

int SSL_write_ex(void* ssl, void* buf, size_t num, size_t* written) {
    init_once();
    resolve_ssl();
    if (!real_ssl_write_ex) return 0;
    tls_in_probe = true;
    int ok = real_ssl_write_ex(ssl, buf, num, written);
    tls_in_probe = false;
    if (ok > 0 && written && *written > 0 && real_ssl_get_fd)
        emit(real_ssl_get_fd(ssl), DIR_EGRESS, SRC_TLS, buf, *written);
    return ok;
}

}  // extern "C"
