"""Self-check CLI: report which native fast paths are live.

    python -m deepflow_tpu.native --selfcheck
    python -m deepflow_tpu.native --verify-abi

Builds (or loads) libdfnative.so the same way the server does, then
probes each fast path with a tiny synthetic input so the report shows
what will ACTUALLY run — a present-but-ABI-stale .so, a set
DF_NO_NATIVE, or a missing compiler all show up here as the fallback
they cause, instead of surfacing later as silently degraded ingest
throughput.

--verify-abi is the CI gate: exit non-zero unless the library loads at
the expected ABI version AND every ingest-hot-path probe passes — a
stale .so must fail the build loudly, not fall back silently. The only
exemption is an explicit DF_NO_NATIVE=1 (the operator asked for the
fallback).
"""

from __future__ import annotations

import os
import sys

import numpy as np


def _probe_l4(native) -> bool:
    try:
        dec = native.L4ColumnDecoder(cap=16)
        return dec.decode(b"") is not None  # empty batch: 0 rows, no error
    except Exception:
        return False


def _probe_l7(native) -> bool:
    try:
        dec = native.L7ColumnDecoder(cap=16)
        return dec.decode(b"") is not None
    except Exception:
        return False


def _probe_doc(native) -> bool:
    try:
        dec = native.DocColumnDecoder(cap=16)
        return dec.decode(b"") is not None
    except Exception:
        return False


def _probe_span(native) -> bool:
    try:
        dec = native.SpanColumnDecoder(cap=16, mem_cap=16)
        return dec.decode(b"") is not None
    except Exception:
        return False


def _probe_dict_arena() -> bool:
    try:
        from deepflow_tpu.store.dictionary import Dictionary
        d = Dictionary("selfcheck")
        arena = np.frombuffer(b"ab", dtype=np.uint8)
        ids = d.encode_arena(arena,
                             np.array([0, 0], dtype=np.uint32),
                             np.array([2, 0], dtype=np.uint32))
        return ids is not None and ids.tolist() == [1, 0]
    except Exception:
        return False


def _probe_eth(native) -> bool:
    try:
        outs, ok = native.decode_eth_batch([b"\x00" * 60])
        return outs is not None and len(ok) == 1
    except Exception:
        return False


def _ingest_paths(native, lib) -> list[tuple[str, bool, str]]:
    """(name, live, fallback) for every path --verify-abi gates on."""
    return [
        ("L4 flow-log columnar decode",
         lib is not None and _probe_l4(native),
         "per-field python protobuf parse"),
        ("L7 flow-log columnar decode",
         lib is not None and _probe_l7(native),
         "per-field python protobuf parse"),
        ("metrics doc columnar decode",
         lib is not None and _probe_doc(native),
         "per-field python protobuf parse"),
        ("tpu-span columnar decode",
         lib is not None and _probe_span(native),
         "per-field python protobuf parse"),
        ("dictionary arena encode",
         lib is not None and _probe_dict_arena(),
         "per-batch python interning"),
        ("ethernet/IPv4 batch decode",
         lib is not None and _probe_eth(native),
         "python struct unpack per header"),
    ]


def selfcheck() -> int:
    from deepflow_tpu import native

    no_native = bool(os.environ.get("DF_NO_NATIVE"))
    workers = os.environ.get("DF_INGEST_WORKERS", "1")
    lib = native.load()
    so = os.path.join(os.path.dirname(native.__file__), "libdfnative.so")

    print("deepflow-tpu native selfcheck")
    print(f"  DF_NO_NATIVE        : {'1 (kill-switch ON)' if no_native else 'unset'}")
    print(f"  DF_INGEST_WORKERS   : {workers}")
    print(f"  libdfnative.so      : "
          f"{'present' if os.path.exists(so) else 'MISSING'} ({so})")
    if lib is None:
        reason = ("kill-switch" if no_native else
                  "build/load failed or ABI mismatch")
        print(f"  library             : NOT LOADED ({reason})")
    else:
        print(f"  library             : loaded, ABI {lib.df_abi_version()}"
              f" (expected {native._ABI_VERSION})")

    paths = _ingest_paths(native, lib) + [
        ("native FlowMap", lib is not None and hasattr(lib, "df_fm_new"),
         "python FlowMap"),
        ("AF_PACKET ring capture", lib is not None and
         hasattr(lib, "df_ring_open"), "python raw socket recv"),
    ]
    live = 0
    for name, ok, fallback in paths:
        live += bool(ok)
        status = "native" if ok else f"fallback ({fallback})"
        print(f"  {name:<28}: {status}")

    for extra in ("libdfsslprobe.so", "libdfmemhook.so"):
        p = os.path.join(os.path.dirname(native.__file__), extra)
        print(f"  {extra:<28}: "
              f"{'built' if os.path.exists(p) else 'not built'}")

    print(f"  fast paths live     : {live}/{len(paths)}")
    return 0


def verify_abi() -> int:
    """CI gate: non-zero exit unless the native ingest hot path is FULLY
    live (or DF_NO_NATIVE explicitly disables it)."""
    from deepflow_tpu import native

    if os.environ.get("DF_NO_NATIVE"):
        print("verify-abi: DF_NO_NATIVE set — fallback explicitly "
              "requested, skipping")
        return 0
    lib = native.load()
    if lib is None:
        print("verify-abi: FAIL — libdfnative.so did not load "
              "(missing build or ABI mismatch; run `make -C "
              "deepflow_tpu/native` and see load warnings above)")
        return 1
    got, want = lib.df_abi_version(), native._ABI_VERSION
    if got != want:
        print(f"verify-abi: FAIL — ABI {got}, bindings expect {want}")
        return 1
    bad = [name for name, ok, _ in _ingest_paths(native, lib) if not ok]
    if bad:
        print("verify-abi: FAIL — probes failed: " + ", ".join(bad))
        return 1
    print(f"verify-abi: OK — ABI {got}, all ingest hot paths live")
    return 0


def main(argv: list[str]) -> int:
    if "--verify-abi" in argv:
        return verify_abi()
    if "--selfcheck" in argv or not argv:
        return selfcheck()
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
