"""Self-check CLI: report which native fast paths are live.

    python -m deepflow_tpu.native --selfcheck

Builds (or loads) libdfnative.so the same way the server does, then
probes each fast path with a tiny synthetic input so the report shows
what will ACTUALLY run — a present-but-ABI-stale .so, a set
DF_NO_NATIVE, or a missing compiler all show up here as the fallback
they cause, instead of surfacing later as silently degraded ingest
throughput.
"""

from __future__ import annotations

import os
import sys


def _probe_l4(native) -> bool:
    try:
        dec = native.L4ColumnDecoder(cap=16)
        return dec.decode(b"") is not None  # empty batch: 0 rows, no error
    except Exception:
        return False


def _probe_l7(native) -> bool:
    try:
        dec = native.L7ColumnDecoder(cap=16)
        return dec.decode(b"") is not None
    except Exception:
        return False


def _probe_eth(native) -> bool:
    try:
        outs, ok = native.decode_eth_batch([b"\x00" * 60])
        return outs is not None and len(ok) == 1
    except Exception:
        return False


def selfcheck() -> int:
    from deepflow_tpu import native

    no_native = bool(os.environ.get("DF_NO_NATIVE"))
    workers = os.environ.get("DF_INGEST_WORKERS", "1")
    lib = native.load()
    so = os.path.join(os.path.dirname(native.__file__), "libdfnative.so")

    print("deepflow-tpu native selfcheck")
    print(f"  DF_NO_NATIVE        : {'1 (kill-switch ON)' if no_native else 'unset'}")
    print(f"  DF_INGEST_WORKERS   : {workers}")
    print(f"  libdfnative.so      : "
          f"{'present' if os.path.exists(so) else 'MISSING'} ({so})")
    if lib is None:
        reason = ("kill-switch" if no_native else
                  "build/load failed or ABI mismatch")
        print(f"  library             : NOT LOADED ({reason})")
    else:
        print(f"  library             : loaded, ABI {lib.df_abi_version()}"
              f" (expected {native._ABI_VERSION})")

    paths = [
        ("L4 flow-log columnar decode", lib is not None and _probe_l4(native),
         "per-field python protobuf parse"),
        ("L7 flow-log columnar decode", lib is not None and _probe_l7(native),
         "per-field python protobuf parse"),
        ("ethernet/IPv4 batch decode", lib is not None and _probe_eth(native),
         "python struct unpack per header"),
        ("native FlowMap", lib is not None and hasattr(lib, "df_fm_new"),
         "python FlowMap"),
        ("AF_PACKET ring capture", lib is not None and
         hasattr(lib, "df_ring_open"), "python raw socket recv"),
    ]
    live = 0
    for name, ok, fallback in paths:
        live += bool(ok)
        status = "native" if ok else f"fallback ({fallback})"
        print(f"  {name:<28}: {status}")

    for extra in ("libdfsslprobe.so", "libdfmemhook.so"):
        p = os.path.join(os.path.dirname(native.__file__), extra)
        print(f"  {extra:<28}: "
              f"{'built' if os.path.exists(p) else 'not built'}")

    print(f"  fast paths live     : {live}/{len(paths)}")
    return 0


def main(argv: list[str]) -> int:
    if "--selfcheck" in argv or not argv:
        return selfcheck()
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
