// LD_PRELOAD malloc interposer: sampled allocation ledger -> memory
// flame graphs for processes OUTSIDE the agent.
//
// Reference analog: the EE memory profiler
// (agent/src/ebpf_dispatcher/memory_profile.rs + uprobes on allocator
// entry points, extended.h MEMORY flag) — an allocation ledger keyed by
// stack, frees credited back, periodic reports of net-live bytes.
// Redesign without eBPF: symbol interposition in the target's own
// address space (the sslprobe pattern), byte-rate SAMPLING so the hot
// path costs a thread-local counter bump in the common case, raw PCs
// shipped over AF_UNIX datagrams, symbolization done OUT of process by
// the agent (/proc/<pid>/maps + its ELF symbolizer).
//
// Build: part of `make -C deepflow_tpu/native` -> libdfmemhook.so.
// Activate: LD_PRELOAD=libdfmemhook.so DF_MEMHOOK_SOCK=/path cmd...
// Knobs: DF_MEMHOOK_SAMPLE (bytes between samples, default 1 MiB),
//        DF_MEMHOOK_INTERVAL (report seconds, default 5).

#define _GNU_SOURCE 1

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <dlfcn.h>
#include <execinfo.h>
#include <pthread.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

using malloc_t = void* (*)(size_t);
using free_t = void (*)(void*);
using calloc_t = void* (*)(size_t, size_t);
using realloc_t = void* (*)(void*, size_t);

malloc_t real_malloc;
free_t real_free;
calloc_t real_calloc;
realloc_t real_realloc;

// dlsym itself calloc()s: serve those from a static arena until the
// real symbols are resolved
char boot_arena[16384];
size_t boot_used;

bool inited;
uint64_t sample_bytes = 1 << 20;
unsigned report_interval_s = 5;
int sock_fd = -1;
uint32_t my_pid;

__thread uint64_t tl_since_sample;
__thread int tl_in_hook;  // reentrancy guard (backtrace may allocate)

constexpr int kMaxPcs = 24;
constexpr int kStackSlots = 2048;   // distinct allocation sites
constexpr int kLiveSlots = 1 << 15; // sampled live allocations

struct StackRec {
    uint64_t hash = 0;
    int n_pcs = 0;
    void* pcs[kMaxPcs];
    uint64_t alloc_w = 0;    // sampled (weighted) bytes allocated
    uint64_t free_w = 0;     // sampled bytes later freed
    uint64_t alloc_count = 0;
    bool dirty = false;
};

struct LiveRec {
    void* ptr = nullptr;     // nullptr = empty, kTombstone = deleted
    uint32_t stack_idx = 0;
    uint64_t weight = 0;
};

void* const kTombstone = (void*)(uintptr_t)1;

StackRec stacks[kStackSlots];
LiveRec live[kLiveSlots];
pthread_mutex_t ledger_mu = PTHREAD_MUTEX_INITIALIZER;
uint64_t dropped_samples;

uint64_t hash_pcs(void* const* pcs, int n) {
    uint64_t h = 0xCBF29CE484222325ULL;
    for (int i = 0; i < n; i++) {
        h ^= (uint64_t)pcs[i];
        h *= 0x100000001B3ULL;
    }
    return h ? h : 1;
}

int stack_slot(void* const* pcs, int n, uint64_t h) {
    int idx = (int)(h % kStackSlots);
    for (int probe = 0; probe < 64; probe++) {
        StackRec& s = stacks[idx];
        if (s.hash == h && s.n_pcs == n &&
            !memcmp(s.pcs, pcs, n * sizeof(void*)))
            return idx;
        if (s.hash == 0) {
            s.hash = h;
            s.n_pcs = n;
            memcpy(s.pcs, pcs, n * sizeof(void*));
            return idx;
        }
        idx = (idx + 1) % kStackSlots;
    }
    return -1;  // table full: drop
}

void record_sample(void* ptr, uint64_t weight) {
    void* pcs[kMaxPcs + 4];
    int n = backtrace(pcs, kMaxPcs + 4);
    // skip our own frames (record_sample, hook, plt)
    int skip = 2;
    if (n <= skip) return;
    void** upcs = pcs + skip;
    int un = n - skip;
    if (un > kMaxPcs) un = kMaxPcs;
    uint64_t h = hash_pcs(upcs, un);
    pthread_mutex_lock(&ledger_mu);
    int sidx = stack_slot(upcs, un, h);
    if (sidx < 0) {
        dropped_samples++;
        pthread_mutex_unlock(&ledger_mu);
        return;
    }
    StackRec& s = stacks[sidx];
    s.alloc_w += weight;
    s.alloc_count++;
    s.dirty = true;
    // track the pointer so a later free credits this stack (tombstones
    // keep probe chains intact for colliding pointers; inserts reuse
    // the first tombstone seen)
    uint64_t lh = (uint64_t)ptr * 0x9E3779B97F4A7C15ULL;
    int lidx = (int)(lh % kLiveSlots);
    int reuse = -1;
    for (int probe = 0; probe < 32; probe++) {
        LiveRec& l = live[lidx];
        if (l.ptr == ptr) {
            reuse = lidx;
            break;
        }
        if (l.ptr == kTombstone) {
            if (reuse < 0) reuse = lidx;
        } else if (l.ptr == nullptr) {
            if (reuse < 0) reuse = lidx;
            break;
        }
        lidx = (lidx + 1) % kLiveSlots;
    }
    if (reuse >= 0) {
        live[reuse].ptr = ptr;
        live[reuse].stack_idx = (uint32_t)sidx;
        live[reuse].weight = weight;
    }
    pthread_mutex_unlock(&ledger_mu);  // table full: alloc-only stats
}

// lock-free pre-check: sampled pointers are ~1 per sample_bytes of
// traffic, so the vast majority of frees must skip the ledger mutex.
// Racy reads are benign: a false hit re-checks under the lock; a miss
// during a concurrent insert loses one free credit (sampling noise).
bool maybe_sampled(void* ptr) {
    uint64_t lh = (uint64_t)ptr * 0x9E3779B97F4A7C15ULL;
    int lidx = (int)(lh % kLiveSlots);
    for (int probe = 0; probe < 32; probe++) {
        void* p = __atomic_load_n(&live[lidx].ptr, __ATOMIC_RELAXED);
        if (p == ptr) return true;
        if (p == nullptr) return false;
        lidx = (lidx + 1) % kLiveSlots;
    }
    return false;
}

void record_free(void* ptr) {
    if (!maybe_sampled(ptr)) return;
    uint64_t lh = (uint64_t)ptr * 0x9E3779B97F4A7C15ULL;
    int lidx = (int)(lh % kLiveSlots);
    pthread_mutex_lock(&ledger_mu);
    for (int probe = 0; probe < 32; probe++) {
        LiveRec& l = live[lidx];
        if (l.ptr == ptr) {
            StackRec& s = stacks[l.stack_idx];
            s.free_w += l.weight;
            s.dirty = true;
            l.ptr = kTombstone;  // chain stays walkable for collisions
            break;
        }
        if (l.ptr == nullptr) break;
        lidx = (lidx + 1) % kLiveSlots;
    }
    pthread_mutex_unlock(&ledger_mu);
}

void maybe_start_report_thread();  // defined with the report thread below

void maybe_sample(void* ptr, size_t size) {
    if (!inited || ptr == nullptr || tl_in_hook) return;
    maybe_start_report_thread();  // one relaxed load unless post-fork
    tl_since_sample += size;
    if (tl_since_sample < sample_bytes) return;
    uint64_t weight = tl_since_sample;
    tl_since_sample = 0;
    tl_in_hook = 1;
    record_sample(ptr, weight);
    tl_in_hook = 0;
}

// -- report thread -----------------------------------------------------------

#pragma pack(push, 1)
struct WireHeader {               // must match MEMHOOK dtypes (memhook.py)
    uint32_t magic;               // 0x4D454D48 "MEMH"
    uint32_t pid;
    uint32_t n_records;
    uint64_t dropped;
};
struct WireRecord {
    uint64_t alloc_w;
    uint64_t free_w;
    uint64_t alloc_count;
    uint16_t n_pcs;
    uint64_t pcs[kMaxPcs];        // first n_pcs valid
};
#pragma pack(pop)

void send_report() {
    if (sock_fd < 0) return;
    // datagrams of up to ~15 records each
    constexpr int kPerDgram = 15;
    static char buf[sizeof(WireHeader) + kPerDgram * sizeof(WireRecord)];
    WireRecord recs[kPerDgram];
    int n = 0;
    pthread_mutex_lock(&ledger_mu);
    for (int i = 0; i < kStackSlots; i++) {
        StackRec& s = stacks[i];
        if (!s.hash || !s.dirty) continue;
        WireRecord& r = recs[n];
        r.alloc_w = s.alloc_w;
        r.free_w = s.free_w;
        r.alloc_count = s.alloc_count;
        r.n_pcs = (uint16_t)s.n_pcs;
        for (int p = 0; p < s.n_pcs; p++)
            r.pcs[p] = (uint64_t)s.pcs[p];
        s.dirty = false;
        if (++n == kPerDgram) {
            pthread_mutex_unlock(&ledger_mu);
            WireHeader h{0x4D454D48, my_pid, (uint32_t)n, dropped_samples};
            memcpy(buf, &h, sizeof(h));
            memcpy(buf + sizeof(h), recs, n * sizeof(WireRecord));
            send(sock_fd, buf,
                 sizeof(h) + n * sizeof(WireRecord), MSG_DONTWAIT);
            n = 0;
            pthread_mutex_lock(&ledger_mu);
        }
    }
    pthread_mutex_unlock(&ledger_mu);
    if (n) {
        WireHeader h{0x4D454D48, my_pid, (uint32_t)n, dropped_samples};
        memcpy(buf, &h, sizeof(h));
        memcpy(buf + sizeof(h), recs, n * sizeof(WireRecord));
        send(sock_fd, buf, sizeof(h) + n * sizeof(WireRecord),
             MSG_DONTWAIT);
    }
}

void* report_main(void*) {
    for (;;) {
        sleep(report_interval_s);
        tl_in_hook = 1;  // reporter's own allocations are not samples
        send_report();
        tl_in_hook = 0;
    }
    return nullptr;
}

void start_report_thread() {
    if (sock_fd < 0) return;
    pthread_t t;
    pthread_create(&t, nullptr, report_main, nullptr);
    pthread_detach(t);
}

// set by atfork_child, consumed by the first post-fork malloc hook:
// pthread_create is not async-signal-safe, so it must never run inside
// the fork handler itself (POSIX only guarantees async-signal-safe
// calls between fork and exec). A child that execs never trips the
// flag; a child that mallocs is already past the restricted window.
int need_report_thread = 0;

void maybe_start_report_thread() {
    if (!__atomic_load_n(&need_report_thread, __ATOMIC_RELAXED)) return;
    if (!__atomic_exchange_n(&need_report_thread, 0, __ATOMIC_ACQ_REL))
        return;  // another thread won the race
    tl_in_hook = 1;  // pthread_create allocates; not a sample
    start_report_thread();
    tl_in_hook = 0;
}

// fork safety: the ledger mutex must be consistently held across fork
// (a child forked while another thread holds it would deadlock on its
// first sampled malloc), and the child needs its own pid + report
// thread (threads do not survive fork) — the thread is deferred to the
// first post-fork malloc hook, see need_report_thread above
void atfork_prepare() { pthread_mutex_lock(&ledger_mu); }
void atfork_parent() { pthread_mutex_unlock(&ledger_mu); }
void atfork_child() {
    pthread_mutex_unlock(&ledger_mu);
    my_pid = (uint32_t)getpid();
    __atomic_store_n(&need_report_thread, 1, __ATOMIC_RELEASE);
}

__attribute__((constructor)) void memhook_init() {
    real_malloc = (malloc_t)dlsym(RTLD_NEXT, "malloc");
    real_free = (free_t)dlsym(RTLD_NEXT, "free");
    real_calloc = (calloc_t)dlsym(RTLD_NEXT, "calloc");
    real_realloc = (realloc_t)dlsym(RTLD_NEXT, "realloc");
    my_pid = (uint32_t)getpid();
    const char* s = getenv("DF_MEMHOOK_SAMPLE");
    if (s && atoll(s) > 0) sample_bytes = (uint64_t)atoll(s);
    const char* iv = getenv("DF_MEMHOOK_INTERVAL");
    if (iv && atoi(iv) > 0) report_interval_s = (unsigned)atoi(iv);
    // prime backtrace: its first call dlopens libgcc (allocates)
    tl_in_hook = 1;
    void* prime[4];
    backtrace(prime, 4);
    tl_in_hook = 0;
    const char* path = getenv("DF_MEMHOOK_SOCK");
    if (path && *path) {
        sock_fd = socket(AF_UNIX, SOCK_DGRAM, 0);
        if (sock_fd >= 0) {
            sockaddr_un addr{};
            addr.sun_family = AF_UNIX;
            strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
            if (connect(sock_fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
                close(sock_fd);
                sock_fd = -1;
            }
        }
    }
    start_report_thread();
    pthread_atfork(atfork_prepare, atfork_parent, atfork_child);
    inited = true;
    if (getenv("DF_MEMHOOK_DEBUG"))
        fprintf(stderr, "memhook: init pid=%u sock=%d sample=%llu\n",
                my_pid, sock_fd, (unsigned long long)sample_bytes);
}

}  // namespace

extern "C" {

void* malloc(size_t size) {
    if (!real_malloc) {  // pre-init (dlsym bootstrap)
        void* p = boot_arena + boot_used;
        boot_used += (size + 15) & ~(size_t)15;
        return boot_used <= sizeof(boot_arena) ? p : nullptr;
    }
    void* p = real_malloc(size);
    maybe_sample(p, size);
    return p;
}

void* calloc(size_t n, size_t size) {
    if (!real_calloc) {
        size_t total = n * size;
        void* p = boot_arena + boot_used;
        boot_used += (total + 15) & ~(size_t)15;
        if (boot_used > sizeof(boot_arena)) return nullptr;
        memset(p, 0, total);
        return p;
    }
    void* p = real_calloc(n, size);
    maybe_sample(p, n * size);
    return p;
}

void* realloc(void* old, size_t size) {
    bool old_in_arena =
        old >= (void*)boot_arena &&
        old < (void*)(boot_arena + sizeof(boot_arena));
    if (!real_realloc) {
        // pre-init: behave like malloc from the bootstrap arena (old is
        // either NULL or itself an arena block; arena blocks never move)
        void* p = boot_arena + boot_used;
        boot_used += (size + 15) & ~(size_t)15;
        if (boot_used > sizeof(boot_arena)) return nullptr;
        if (old_in_arena) {
            size_t avail =
                (size_t)((char*)boot_arena + sizeof(boot_arena) -
                         (char*)old);
            memcpy(p, old, size < avail ? size : avail);
        }
        return p;
    }
    if (old_in_arena) {
        // a bootstrap block must never reach the real allocator: copy it
        // into a real allocation (size of the old block is unknown, but
        // the whole arena is readable — copy up to the requested size)
        void* p = real_malloc(size);
        if (p) {
            size_t avail =
                (size_t)((char*)boot_arena + sizeof(boot_arena) -
                         (char*)old);
            memcpy(p, old, size < avail ? size : avail);
        }
        maybe_sample(p, size);
        return p;
    }
    if (inited && old && !tl_in_hook) record_free(old);
    void* p = real_realloc(old, size);
    maybe_sample(p, size);
    return p;
}

void free(void* p) {
    if (p >= (void*)boot_arena &&
        p < (void*)(boot_arena + sizeof(boot_arena)))
        return;  // bootstrap arena is never freed
    if (!real_free) return;
    if (inited && p && !tl_in_hook) record_free(p);
    real_free(p);
}

}  // extern "C"
