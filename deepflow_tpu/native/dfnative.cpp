// dfnative: C++ hot paths for the deepflow-tpu pipeline.
//
// Reference analog: the reference keeps its hot loops native (Rust agent,
// C eBPF user-space, VPP-style bihash in agent/src/ebpf/user/bihash*.c).
// Components:
//   - SmartEncoding dictionary (string -> id interning). Measured honestly:
//     CPython's dict wins for this path through ctypes marshalling, so the
//     store keeps the Python dictionary; this backend exists for the future
//     all-native decode pipeline where strings never become PyObjects.
//   - ethernet/IPv4 packet header batch decode (3x per-frame vs Python;
//     end-to-end gain currently capped by MetaPacket materialization — the
//     full native FlowMap is the next milestone).
//
// Exposed as a C ABI consumed via ctypes (no pybind11 on this image).

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

extern "C" {

// ABI contract between this library and the ctypes bindings in
// native/__init__.py. Bump on ANY change to exported signatures or packed
// struct layouts (L7Event, DfPacketOut, flow records); load() refuses a
// library whose version differs instead of silently corrupting memory.
int32_t df_abi_version() { return 8; }

// ---------------------------------------------------------------------------
// Dictionary: string <-> uint32 id, id 0 reserved for ""
// ---------------------------------------------------------------------------

struct DfDict {
    std::unordered_map<std::string, uint32_t> map;
    std::vector<std::string> strings;
    DfDict() {
        strings.emplace_back("");
        map.emplace("", 0);
    }
};

DfDict* df_dict_new() { return new DfDict(); }

void df_dict_free(DfDict* d) { delete d; }

uint64_t df_dict_len(DfDict* d) { return d->strings.size(); }

// Encode n strings packed into `data` with `offsets` (n+1 entries,
// offsets[i]..offsets[i+1] is string i). Writes ids into out (n entries).
void df_dict_encode_batch(DfDict* d, const char* data,
                          const uint32_t* offsets, uint32_t n,
                          uint32_t* out) {
    for (uint32_t i = 0; i < n; i++) {
        std::string s(data + offsets[i], offsets[i + 1] - offsets[i]);
        auto it = d->map.find(s);
        if (it != d->map.end()) {
            out[i] = it->second;
        } else {
            uint32_t id = (uint32_t)d->strings.size();
            d->strings.push_back(s);
            d->map.emplace(std::move(s), id);
            out[i] = id;
        }
    }
}

// Batch-encode n string cells given as (off,len) pairs into a shared
// arena — the shape the native columnar decoders (pbcols.cpp,
// ingest.cpp) produce, so interning never materializes Python strings.
// Writes ids into out (n entries) and returns the dictionary length
// AFTER the batch; the caller diffs against the length BEFORE to learn
// which ids are new and fetch them back via df_dict_get. NOT
// thread-safe: the caller (store/dictionary.py) holds the Python-side
// dictionary lock across the call — one lock acquisition per batch.
uint64_t df_dict_encode_arena(DfDict* d, const uint8_t* arena,
                              const uint32_t* offs, const uint32_t* lens,
                              uint32_t n, uint32_t* out) {
    for (uint32_t i = 0; i < n; i++) {
        if (lens[i] == 0) {
            out[i] = 0;  // id 0 is always ""
            continue;
        }
        std::string s((const char*)arena + offs[i], lens[i]);
        auto it = d->map.find(s);
        if (it != d->map.end()) {
            out[i] = it->second;
        } else {
            uint32_t id = (uint32_t)d->strings.size();
            d->strings.push_back(s);
            d->map.emplace(std::move(s), id);
            out[i] = id;
        }
    }
    return d->strings.size();
}

// Lookup without insert; returns UINT32_MAX when absent.
uint32_t df_dict_lookup(DfDict* d, const char* s, uint32_t len) {
    auto it = d->map.find(std::string(s, len));
    return it == d->map.end() ? UINT32_MAX : it->second;
}

// Copy string `id` into buf (cap bytes); returns its length, or -1.
int32_t df_dict_get(DfDict* d, uint32_t id, char* buf, uint32_t cap) {
    if (id >= d->strings.size()) return -1;
    const std::string& s = d->strings[id];
    uint32_t n = (uint32_t)s.size() < cap ? (uint32_t)s.size() : cap;
    memcpy(buf, s.data(), n);
    return (int32_t)s.size();
}

// Bulk-load entries (restore from persistence). Ids assigned in order.
void df_dict_load(DfDict* d, const char* data, const uint32_t* offsets,
                  uint32_t n) {
    for (uint32_t i = 0; i < n; i++) {
        std::string s(data + offsets[i], offsets[i + 1] - offsets[i]);
        if (d->map.find(s) == d->map.end()) {
            uint32_t id = (uint32_t)d->strings.size();
            d->strings.push_back(s);
            d->map.emplace(std::move(s), id);
        }
    }
}

// ---------------------------------------------------------------------------
// Batch ethernet/IPv4/TCP/UDP header decode (pcap replay fast path).
// Output: fixed-width record per packet into parallel arrays.
// ---------------------------------------------------------------------------

#include "dfpacket.h"

static inline uint16_t rd16(const uint8_t* p) {
    return (uint16_t)((p[0] << 8) | p[1]);
}
static inline uint32_t rd32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | p[3];
}

// Tunnel decapsulation (reference: agent/src/common/decapsulate.rs).
// Attempt to strip one VXLAN/GENEVE/GRE/ERSPAN layer starting at the
// inner ethernet frame; on success the inner packet is decoded into `out`
// (offsets stay relative to the ORIGINAL buffer) and tunnel_type/id are
// stamped. Depth-capped by the caller.
static int32_t decode_frame(const uint8_t* data, uint32_t len,
                            uint32_t base, DfPacketOut* out, int depth);

static int32_t try_decap_eth(const uint8_t* data, uint32_t len,
                             uint32_t inner_off, uint8_t ttype,
                             uint32_t tid, DfPacketOut* out, int depth) {
    if (depth >= 2 || inner_off + 34 > len) return 0;
    DfPacketOut inner;
    if (!decode_frame(data, len, inner_off, &inner, depth + 1)) return 0;
    *out = inner;
    if (out->tunnel_type == 0) {  // innermost tunnel wins the stamp
        out->tunnel_type = ttype;
        out->tunnel_id = tid;
    }
    return 1;
}

static int32_t decode_frame(const uint8_t* data, uint32_t len,
                            uint32_t base, DfPacketOut* out, int depth) {
    memset(out, 0, sizeof(*out));
    if (len < base + 34) return 0;
    uint16_t eth_type = rd16(data + base + 12);
    uint32_t off = base + 14;
    if (eth_type == 0x8100) {
        if (len < base + 38) return 0;
        eth_type = rd16(data + base + 16);
        off = base + 18;
    }
    if (eth_type != 0x0800) return 0;  // v4 fast path only
    uint8_t ihl = (data[off] & 0x0F) * 4;
    if (len < off + ihl) return 0;
    uint16_t total = rd16(data + off + 2);
    uint8_t proto = data[off + 9];
    out->ip_src = rd32(data + off + 12);
    out->ip_dst = rd32(data + off + 16);
    uint32_t l4 = off + ihl;
    uint32_t end = off + total;
    if (end > len) end = len;
    if (proto == 6) {
        if (end < l4 + 20) return 0;
        out->protocol = 1;
        out->port_src = rd16(data + l4);
        out->port_dst = rd16(data + l4 + 2);
        out->seq = rd32(data + l4 + 4);
        out->ack = rd32(data + l4 + 8);
        uint8_t doff = (data[l4 + 12] >> 4) * 4;
        out->tcp_flags = data[l4 + 13];
        out->window = rd16(data + l4 + 14);
        out->payload_off = l4 + doff;
        out->payload_len = end > l4 + doff ? end - (l4 + doff) : 0;
        return 1;
    }
    if (proto == 17) {
        if (end < l4 + 8) return 0;
        uint16_t dport = rd16(data + l4 + 2);
        uint32_t pay = l4 + 8;
        // VXLAN (RFC 7348): 8-byte header, I-flag bit validates the VNI.
        // A recognized tunnel whose inner frame the fast path can't decode
        // (v6 inner, nested vlan) must go to the Python slow path — NOT be
        // reported as the outer VTEP UDP flow, which would merge every
        // tenant into one flow
        if (dport == 4789 && end >= pay + 8 && (data[pay] & 0x08)) {
            uint32_t vni = ((uint32_t)data[pay + 4] << 16) |
                           ((uint32_t)data[pay + 5] << 8) | data[pay + 6];
            return try_decap_eth(data, end, pay + 8, 1, vni, out, depth);
        }
        // GENEVE (RFC 8926): variable options, inner proto must be
        // Transparent Ethernet Bridging
        if (dport == 6081 && end >= pay + 8) {
            uint32_t optlen = (uint32_t)(data[pay] & 0x3F) * 4;
            uint16_t inner_proto = rd16(data + pay + 2);
            uint32_t vni = ((uint32_t)data[pay + 4] << 16) |
                           ((uint32_t)data[pay + 5] << 8) | data[pay + 6];
            if (inner_proto == 0x6558)
                return try_decap_eth(data, end, pay + 8 + optlen, 2, vni,
                                     out, depth);
        }
        out->protocol = 2;
        out->port_src = rd16(data + l4);
        out->port_dst = dport;
        out->payload_off = pay;
        out->payload_len = end > pay ? end - pay : 0;
        return 1;
    }
    if (proto == 47 && end >= l4 + 4) {  // GRE / ERSPAN
        uint16_t flags = rd16(data + l4);
        uint16_t gre_proto = rd16(data + l4 + 2);
        uint32_t gh = l4 + 4;
        if (flags & 0x8000) gh += 4;  // checksum (+reserved)
        uint32_t key = 0;
        if (flags & 0x2000) {         // key present
            if (end < gh + 4) return 0;
            key = rd32(data + gh);
            gh += 4;
        }
        bool has_seq = (flags & 0x1000) != 0;
        if (has_seq) gh += 4;
        if (end >= gh) {
            if (gre_proto == 0x88BE) {  // ERSPAN: II has an 8B header
                // (flagged by the GRE sequence bit), I has none
                uint32_t inner = gh + (has_seq ? 8 : 0);
                uint32_t sess = has_seq && end >= gh + 4
                    ? (rd16(data + gh + 2) & 0x03FF) : 0;
                if (try_decap_eth(data, end, inner, 3, sess, out, depth))
                    return 1;
            } else if (gre_proto == 0x22EB) {  // ERSPAN III: 12B header
                uint32_t sess = end >= gh + 4
                    ? (rd16(data + gh + 2) & 0x03FF) : 0;
                if (try_decap_eth(data, end, gh + 12, 3, sess, out, depth))
                    return 1;
            } else if (gre_proto == 0x6558) {  // transparent eth bridging
                if (try_decap_eth(data, end, gh, 4, key, out, depth))
                    return 1;
            }
        }
        return 0;  // plain GRE payloads need the Python slow path
    }
    if (proto == 1) {
        out->protocol = 3;
        out->payload_off = l4;
        out->payload_len = end > l4 ? end - l4 : 0;
        return 1;
    }
    return 0;
}

// Decode one frame at `data` of length `len` into out (tunnels stripped,
// see decode_frame). Returns 1 on success, 0 when the frame needs the
// Python slow path (v6, vlan-in-tunnel, short).
int32_t df_decode_eth(const uint8_t* data, uint32_t len, DfPacketOut* out) {
    return decode_frame(data, len, 0, out, 0);
}

// Batch decode: n frames packed into `data` with n+1 `offsets`.
// Writes one DfPacketOut per frame; ok[i]=1 when the fast path decoded it.
void df_decode_eth_batch(const uint8_t* data, const uint32_t* offsets,
                         uint32_t n, DfPacketOut* outs, uint8_t* ok) {
    for (uint32_t i = 0; i < n; i++) {
        ok[i] = (uint8_t)df_decode_eth(data + offsets[i],
                                       offsets[i + 1] - offsets[i],
                                       &outs[i]);
    }
}

}  // extern "C"
