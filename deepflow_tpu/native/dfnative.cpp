// dfnative: C++ hot paths for the deepflow-tpu pipeline.
//
// Reference analog: the reference keeps its hot loops native (Rust agent,
// C eBPF user-space, VPP-style bihash in agent/src/ebpf/user/bihash*.c).
// Components:
//   - SmartEncoding dictionary (string -> id interning). Measured honestly:
//     CPython's dict wins for this path through ctypes marshalling, so the
//     store keeps the Python dictionary; this backend exists for the future
//     all-native decode pipeline where strings never become PyObjects.
//   - ethernet/IPv4 packet header batch decode (3x per-frame vs Python;
//     end-to-end gain currently capped by MetaPacket materialization — the
//     full native FlowMap is the next milestone).
//
// Exposed as a C ABI consumed via ctypes (no pybind11 on this image).

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Dictionary: string <-> uint32 id, id 0 reserved for ""
// ---------------------------------------------------------------------------

struct DfDict {
    std::unordered_map<std::string, uint32_t> map;
    std::vector<std::string> strings;
    DfDict() {
        strings.emplace_back("");
        map.emplace("", 0);
    }
};

DfDict* df_dict_new() { return new DfDict(); }

void df_dict_free(DfDict* d) { delete d; }

uint64_t df_dict_len(DfDict* d) { return d->strings.size(); }

// Encode n strings packed into `data` with `offsets` (n+1 entries,
// offsets[i]..offsets[i+1] is string i). Writes ids into out (n entries).
void df_dict_encode_batch(DfDict* d, const char* data,
                          const uint32_t* offsets, uint32_t n,
                          uint32_t* out) {
    for (uint32_t i = 0; i < n; i++) {
        std::string s(data + offsets[i], offsets[i + 1] - offsets[i]);
        auto it = d->map.find(s);
        if (it != d->map.end()) {
            out[i] = it->second;
        } else {
            uint32_t id = (uint32_t)d->strings.size();
            d->strings.push_back(s);
            d->map.emplace(std::move(s), id);
            out[i] = id;
        }
    }
}

// Lookup without insert; returns UINT32_MAX when absent.
uint32_t df_dict_lookup(DfDict* d, const char* s, uint32_t len) {
    auto it = d->map.find(std::string(s, len));
    return it == d->map.end() ? UINT32_MAX : it->second;
}

// Copy string `id` into buf (cap bytes); returns its length, or -1.
int32_t df_dict_get(DfDict* d, uint32_t id, char* buf, uint32_t cap) {
    if (id >= d->strings.size()) return -1;
    const std::string& s = d->strings[id];
    uint32_t n = (uint32_t)s.size() < cap ? (uint32_t)s.size() : cap;
    memcpy(buf, s.data(), n);
    return (int32_t)s.size();
}

// Bulk-load entries (restore from persistence). Ids assigned in order.
void df_dict_load(DfDict* d, const char* data, const uint32_t* offsets,
                  uint32_t n) {
    for (uint32_t i = 0; i < n; i++) {
        std::string s(data + offsets[i], offsets[i + 1] - offsets[i]);
        if (d->map.find(s) == d->map.end()) {
            uint32_t id = (uint32_t)d->strings.size();
            d->strings.push_back(s);
            d->map.emplace(std::move(s), id);
        }
    }
}

// ---------------------------------------------------------------------------
// Batch ethernet/IPv4/TCP/UDP header decode (pcap replay fast path).
// Output: fixed-width record per packet into parallel arrays.
// ---------------------------------------------------------------------------

struct DfPacketOut {
    uint32_t ip_src;     // v4 only on the fast path; v6 falls back to Python
    uint32_t ip_dst;
    uint16_t port_src;
    uint16_t port_dst;
    uint8_t  protocol;   // 1 tcp, 2 udp, 3 icmp, 0 = not decodable here
    uint8_t  tcp_flags;
    uint16_t window;
    uint32_t seq;
    uint32_t ack;
    uint32_t payload_off;
    uint32_t payload_len;
};

static inline uint16_t rd16(const uint8_t* p) {
    return (uint16_t)((p[0] << 8) | p[1]);
}
static inline uint32_t rd32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | p[3];
}

// Decode one frame at `data+off` of length `len` into out. Returns 1 on
// success, 0 when the frame needs the Python slow path (v6, vlan, short).
int32_t df_decode_eth(const uint8_t* data, uint32_t len, DfPacketOut* out) {
    memset(out, 0, sizeof(*out));
    if (len < 34) return 0;
    uint16_t eth_type = rd16(data + 12);
    uint32_t off = 14;
    if (eth_type == 0x8100) {
        if (len < 38) return 0;
        eth_type = rd16(data + 16);
        off = 18;
    }
    if (eth_type != 0x0800) return 0;  // v4 fast path only
    uint8_t ihl = (data[off] & 0x0F) * 4;
    if (len < off + ihl) return 0;
    uint16_t total = rd16(data + off + 2);
    uint8_t proto = data[off + 9];
    out->ip_src = rd32(data + off + 12);
    out->ip_dst = rd32(data + off + 16);
    uint32_t l4 = off + ihl;
    uint32_t end = off + total;
    if (end > len) end = len;
    if (proto == 6) {
        if (end < l4 + 20) return 0;
        out->protocol = 1;
        out->port_src = rd16(data + l4);
        out->port_dst = rd16(data + l4 + 2);
        out->seq = rd32(data + l4 + 4);
        out->ack = rd32(data + l4 + 8);
        uint8_t doff = (data[l4 + 12] >> 4) * 4;
        out->tcp_flags = data[l4 + 13];
        out->window = rd16(data + l4 + 14);
        out->payload_off = l4 + doff;
        out->payload_len = end > l4 + doff ? end - (l4 + doff) : 0;
        return 1;
    }
    if (proto == 17) {
        if (end < l4 + 8) return 0;
        out->protocol = 2;
        out->port_src = rd16(data + l4);
        out->port_dst = rd16(data + l4 + 2);
        out->payload_off = l4 + 8;
        out->payload_len = end > l4 + 8 ? end - (l4 + 8) : 0;
        return 1;
    }
    if (proto == 1) {
        out->protocol = 3;
        out->payload_off = l4;
        out->payload_len = end > l4 ? end - l4 : 0;
        return 1;
    }
    return 0;
}

// Batch decode: n frames packed into `data` with n+1 `offsets`.
// Writes one DfPacketOut per frame; ok[i]=1 when the fast path decoded it.
void df_decode_eth_batch(const uint8_t* data, const uint32_t* offsets,
                         uint32_t n, DfPacketOut* outs, uint8_t* ok) {
    for (uint32_t i = 0; i < n; i++) {
        ok[i] = (uint8_t)df_decode_eth(data + offsets[i],
                                       offsets[i + 1] - offsets[i],
                                       &outs[i]);
    }
}

}  // extern "C"
