"""Agent side of the malloc-interposer memory profiler: decode sampled
allocation ledgers from preloaded processes, symbolize OUT of process,
emit leak-hunting flame samples.

Reference analog: the EE memory profiler's user half
(ebpf_dispatcher/memory_profile.rs — allocation ledger -> memory flame
graphs). The wire protocol is produced by native/memhook.cpp; stacks
arrive as raw PCs and are resolved here against /proc/<pid>/maps + ELF
symbols (the extprofiler's Symbolizer), so the target pays nothing for
symbolization.

Emitted samples: event_type "mem-alloc", profiler "memhook",
value = NET LIVE GROWTH in bytes for the stack during the report window
(clamped at 0). Summing values over a query range yields net growth in
that range — churn (alloc+free) nets out, leaks accumulate.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time

from deepflow_tpu.agent.profiler import ProfileSample

log = logging.getLogger("df.memhook")

_MAGIC = 0x4D454D48
_HDR = struct.Struct("<IIIQ")
_REC_FIXED = struct.Struct("<QQQH")
_MAX_PCS = 24
_REC_SIZE = _REC_FIXED.size + _MAX_PCS * 8


class MemHookListener:
    """AF_UNIX datagram listener for libdfmemhook.so reports."""

    def __init__(self, sink, sock_path: str) -> None:
        self.sink = sink              # sink(list[ProfileSample])
        self.sock_path = sock_path
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # (pid, stack_hash) -> last seen (alloc_w, free_w) for deltas
        self._last: dict[tuple, tuple[int, int]] = {}
        # pid -> its latest per-process dropped counter; the interposer's
        # counter is cumulative per process, so summing across pids (not
        # overwriting with whichever pid reported last) is the fleet total
        self._dropped_by_pid: dict[int, int] = {}
        self._next_evict = 0.0
        self._symbolizers: dict[int, object] = {}
        self.stats = {"reports": 0, "records": 0, "samples_emitted": 0,
                      "symbolize_errors": 0, "dropped_target": 0}

    def start(self) -> "MemHookListener":
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
        s = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        s.bind(self.sock_path)
        s.settimeout(0.5)
        self._sock = s
        self._thread = threading.Thread(target=self._run,
                                        name="df-memhook", daemon=True)
        self._thread.start()
        log.info("memhook listening on %s", self.sock_path)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass

    def _symbolizer(self, pid: int):
        sym = self._symbolizers.get(pid)
        if sym is None:
            from deepflow_tpu.agent.extprofiler import Symbolizer
            sym = self._symbolizers[pid] = Symbolizer(pid)
        return sym

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                data = self._sock.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self.handle_datagram(data)
            except Exception:
                log.exception("memhook datagram failed")

    def handle_datagram(self, data: bytes) -> int:
        if len(data) < _HDR.size:
            return 0
        magic, pid, n_records, dropped = _HDR.unpack_from(data, 0)
        if magic != _MAGIC:
            return 0
        self.stats["reports"] += 1
        self._dropped_by_pid[pid] = int(dropped)
        self.stats["dropped_target"] = sum(self._dropped_by_pid.values())
        try:
            sym = self._symbolizer(pid)
            sym.refresh()  # once per datagram: maps parsing is the cost
        except Exception:
            self.stats["symbolize_errors"] += 1
            return 0
        ts = time.time_ns()
        batch: list[ProfileSample] = []
        off = _HDR.size
        for _ in range(n_records):
            if off + _REC_SIZE > len(data):
                break
            alloc_w, free_w, count, n_pcs = _REC_FIXED.unpack_from(
                data, off)
            pcs = struct.unpack_from(
                f"<{min(n_pcs, _MAX_PCS)}Q", data, off + _REC_FIXED.size)
            off += _REC_SIZE
            self.stats["records"] += 1
            key = (pid, pcs)
            last_a, last_f = self._last.get(key, (0, 0))
            self._last[key] = (alloc_w, free_w)
            live_delta = (alloc_w - free_w) - (last_a - last_f)
            if live_delta <= 0:
                continue  # churn nets out; shrinking stacks aren't leaks
            try:
                frames = [sym.resolve(int(a)) for a in reversed(pcs)]
            except Exception:
                self.stats["symbolize_errors"] += 1
                continue
            batch.append(ProfileSample(
                timestamp_ns=ts, pid=pid, tid=pid, thread_name="",
                stack=";".join(frames), count=max(1, int(count)),
                value_us=int(live_delta),
                event_type="mem-alloc", profiler="memhook"))
        if batch:
            self.stats["samples_emitted"] += len(batch)
            try:
                self.sink(batch)
            except Exception:
                pass  # a failing sink must never kill the listener
        if len(self._last) > 65536 and \
                time.monotonic() >= self._next_evict:
            # rate-limited: when every pid is alive there is nothing to
            # evict, and rescanning per datagram would burn the listener
            # thread on /proc stats
            self._next_evict = time.monotonic() + 30.0
            self._evict_dead()
        return len(batch)

    def _evict_dead(self) -> None:
        """Drop baselines and symbolizers of EXITED pids only — clearing
        live pids' baselines would re-emit their whole cumulative growth
        as a spurious leak spike on the next report. Live entries are
        bounded (the interposer tracks <= 2048 stacks per process)."""
        pids = {pid for pid, _ in self._last}
        alive = {pid for pid in pids if os.path.exists(f"/proc/{pid}")}
        if alive == pids:
            return
        self._last = {k: v for k, v in self._last.items()
                      if k[0] in alive}
        self._symbolizers = {p: s for p, s in self._symbolizers.items()
                             if p in alive}
