"""Out-of-process OnCPU profiler: perf_event_open sampling of ARBITRARY
pids, with /proc/pid/maps + ELF symbolization to folded stacks.

Reference analog: agent/src/ebpf/kernel/perf_profiler.bpf.c:688 (the eBPF
99Hz profiler works on any process) + user/profile/stringifier.c:696
(address -> folded-stack stringification). Split of labor here: the native
sampler (native/perfprof.cpp) owns perf rings and address-chain
aggregation; this module owns the cold path — symbol resolution at window
close — and emits the same ProfileSample batches as the in-process sampler,
so the whole downstream (sender, decoder, flame APIs) is shared.

DWARF unwinding: agent/ehframe.py parses each mapped binary's .eh_frame
into flat tables (reference: trace-utils/src/unwind/dwarf.rs) registered
into the native sampler, which walks them over PERF_SAMPLE_REGS_USER +
PERF_SAMPLE_STACK_USER dumps; per sample the longer of the DWARF and
frame-pointer chains wins, covering FP-omitted binaries wherever a table
exists (giant runtimes beyond the parse-cost cap fall back to FP).
"""

from __future__ import annotations

import bisect
import ctypes
import logging
import os
import queue
import struct
import threading
import time
from dataclasses import dataclass

import numpy as np

from deepflow_tpu import native
from deepflow_tpu.agent.profiler import ProfileSample, SamplerStats

log = logging.getLogger("df.extprofiler")

_PT_LOAD = 1
_SHT_SYMTAB, _SHT_DYNSYM = 2, 11
_STT_FUNC = 2


@dataclass
class _Map:
    start: int
    end: int
    offset: int
    path: str
    bias: int = 0  # runtime addr - file vaddr


_SYM_DTYPE = np.dtype([  # Elf64_Sym
    ("name", "<u4"), ("info", "u1"), ("other", "u1"), ("shndx", "<u2"),
    ("value", "<u8"), ("size", "<u8")])


class ElfSymbols:
    """Minimal ELF64 symbol table: vectorized parse (a large libpython
    symtab has 100k+ entries — per-entry struct.unpack costs ~0.5s CPU,
    which would dominate the profiler's observer budget), names decoded
    lazily on first hit."""

    def __init__(self, path: str) -> None:
        self.addrs = np.empty(0, dtype=np.uint64)
        self.sizes = np.empty(0, dtype=np.uint64)
        self._name_offs = np.empty(0, dtype=np.uint32)
        self._strtab_idx = np.empty(0, dtype=np.uint8)
        self._strtabs: list[bytes] = []
        self._names: dict[int, str] = {}
        self.load_segments: list[tuple[int, int, int]] = []  # off, vaddr, sz
        self.et_dyn = False
        try:
            self._parse(path)
        except (OSError, ValueError, struct.error):
            pass

    def _parse(self, path: str) -> None:
        import mmap as _mmap

        # mmap, don't read(): a large runtime .so (libjax_common is
        # hundreds of MB) must not be copied wholesale — only the section
        # headers and symtab pages get touched
        with open(path, "rb") as f:
            try:
                data = _mmap.mmap(f.fileno(), 0, prot=_mmap.PROT_READ)
            except (ValueError, OSError):
                data = f.read()
        if data[:4] != b"\x7fELF" or data[4] != 2:  # ELF64 only
            return
        (e_type, _, _, _, e_phoff, e_shoff, _, _, e_phentsize, e_phnum,
         e_shentsize, e_shnum, _) = struct.unpack_from("<HHIQQQIHHHHHH",
                                                       data, 16)
        self.et_dyn = e_type == 3
        for i in range(e_phnum):
            off = e_phoff + i * e_phentsize
            p_type, _, p_offset, p_vaddr = struct.unpack_from(
                "<IIQQ", data, off)
            if p_type == _PT_LOAD:
                p_filesz = struct.unpack_from("<Q", data, off + 32)[0]
                self.load_segments.append((p_offset, p_vaddr, p_filesz))
        sections = []
        for i in range(e_shnum):
            off = e_shoff + i * e_shentsize
            (_, sh_type, _, _, sh_offset, sh_size, sh_link) = \
                struct.unpack_from("<IIQQQQI", data, off)
            sections.append((sh_type, sh_offset, sh_size, sh_link))
        parts = []
        for sh_type, sh_offset, sh_size, sh_link in sections:
            if sh_type not in (_SHT_SYMTAB, _SHT_DYNSYM):
                continue
            if sh_link >= len(sections):
                continue
            _, str_off, str_size, _ = sections[sh_link]
            n = sh_size // _SYM_DTYPE.itemsize
            syms = np.frombuffer(data, dtype=_SYM_DTYPE, count=n,
                                 offset=sh_offset)
            keep = ((syms["info"] & 0xF) == _STT_FUNC) & (syms["value"] != 0)
            syms = syms[keep]
            if len(syms):
                parts.append((syms, len(self._strtabs)))
                # lazy strtab view (no copy: a big .so's strtab is tens
                # of MB; names are sliced out on first lookup hit)
                self._strtabs.append((data, str_off, str_size))
        if not parts:
            return
        values = np.concatenate([s["value"] for s, _ in parts])
        sizes = np.concatenate([s["size"] for s, _ in parts])
        name_offs = np.concatenate([s["name"] for s, _ in parts])
        tab_idx = np.concatenate([
            np.full(len(s), idx, dtype=np.uint8) for s, idx in parts])
        # dedup by value (symtab shadows dynsym), sort by address
        order = np.argsort(values, kind="stable")
        values, sizes = values[order], sizes[order]
        name_offs, tab_idx = name_offs[order], tab_idx[order]
        uniq = np.ones(len(values), dtype=bool)
        uniq[1:] = values[1:] != values[:-1]
        self.addrs = values[uniq]
        self.sizes = sizes[uniq]
        self._name_offs = name_offs[uniq]
        self._strtab_idx = tab_idx[uniq]

    def _name_at(self, i: int) -> str:
        name = self._names.get(i)
        if name is None:
            buf, base, size = self._strtabs[int(self._strtab_idx[i])]
            start = base + int(self._name_offs[i])
            end = buf.find(b"\0", start, base + size)
            if end < 0:
                end = base + size
            name = bytes(buf[start:end]).decode("utf-8", "replace")
            self._names[i] = name
        return name

    def bias_for(self, m: _Map) -> int:
        """Runtime bias for a mapped region of this file: map.start maps
        file offset map.offset, which lives at some PT_LOAD vaddr."""
        if not self.et_dyn:
            return 0
        for p_offset, p_vaddr, p_filesz in self.load_segments:
            if p_offset <= m.offset < p_offset + max(p_filesz, 1):
                return m.start - (p_vaddr + (m.offset - p_offset))
        return m.start - m.offset

    def lookup(self, vaddr: int) -> str | None:
        i = int(np.searchsorted(self.addrs, vaddr, side="right")) - 1
        if i < 0:
            return None
        v, size = int(self.addrs[i]), int(self.sizes[i])
        if size and vaddr >= v + size:
            return None
        if not size and vaddr - v > 1 << 20:  # unsized symbol sanity cap
            return None
        name = self._name_at(i)
        return name or None


class Symbolizer:
    """Address -> 'binary`function' via /proc/pid/maps + ELF symtabs."""

    def __init__(self, pid: int) -> None:
        self.pid = pid
        self.maps: list[_Map] = []
        self._starts: list[int] = []
        self._elfs: dict[str, ElfSymbols] = {}
        self._cache: dict[int, str] = {}  # addr -> resolved (hot: the same
        # interpreter/runtime frames repeat across most chains)
        self.refresh()

    def refresh(self) -> bool:
        """Re-read maps; returns True when the mappings changed."""
        maps = []
        try:
            with open(f"/proc/{self.pid}/maps") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) < 6 or "x" not in parts[1]:
                        continue
                    start_s, end_s = parts[0].split("-")
                    m = _Map(start=int(start_s, 16), end=int(end_s, 16),
                             offset=int(parts[2], 16), path=parts[5])
                    maps.append(m)
        except OSError:
            pass
        maps = sorted(maps, key=lambda m: m.start)
        if [(m.start, m.end, m.path) for m in maps] != \
                [(m.start, m.end, m.path) for m in self.maps]:
            self._cache.clear()  # mappings changed; cached addrs stale
            self.maps = maps
            self._starts = [m.start for m in self.maps]
            return True
        return False

    def _elf(self, path: str) -> ElfSymbols:
        e = self._elfs.get(path)
        if e is None:
            e = self._elfs[path] = ElfSymbols(path)
        return e

    def resolve(self, addr: int) -> str:
        hit = self._cache.get(addr)
        if hit is not None:
            return hit
        out = self._resolve_uncached(addr)
        self._cache[addr] = out
        return out

    def _resolve_uncached(self, addr: int) -> str:
        i = bisect.bisect_right(self._starts, addr) - 1
        if i < 0 or addr >= self.maps[i].end:
            return f"[{addr:#x}]"
        m = self.maps[i]
        if not m.path.startswith("/"):
            return m.path or f"[{addr:#x}]"  # [vdso], [stack], anon
        e = self._elf(m.path)
        if m.bias == 0 and e.et_dyn:
            m.bias = e.bias_for(m)
        name = e.lookup(addr - m.bias)
        base = os.path.basename(m.path)
        if name:
            return f"{base}`{name}"
        return f"{base}+{addr - m.bias:#x}"


class OffCpuProfiler:
    """Out-of-process OffCPU profiler: blocked-time flame graphs for any
    pid (reference: the OffCPU profiler of user/extended/extended.h over
    perf_profiler.bpf.c). Context-switch events sample the blocking
    callchain at switch-out; PERF_RECORD_SWITCH markers time the
    switch-in; the native side aggregates blocked nanoseconds per chain.
    FP chains only (a stack dump per context switch would swamp the
    rings). Accounting happens at WAKE time, so off-CPU time includes
    runqueue wait (the standard definition) and a thread blocked for the
    entire window contributes only once it resumes — the same tail
    behavior as BPF offcputime tools."""

    ADDR_CAP = 1 << 18
    STACK_CAP = 8192

    def __init__(self, sink, pid: int, window_s: float = 1.0,
                 min_block_us: float = 10.0, process_name: str = "",
                 app_service: str = "") -> None:
        lib = native.load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._bind(lib)
        self._lib = lib
        self.sink = sink
        self.pid = pid
        self.window_s = window_s
        self.min_block_us = min_block_us
        self.process_name = process_name or ExternalProfiler._comm(pid)
        self.app_service = app_service or self.process_name
        self.stats = SamplerStats()
        self.lost = 0
        self.switches = 0
        self.paired = 0
        self._h = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sym = Symbolizer(pid)
        self._addrs = np.zeros(self.ADDR_CAP, dtype=np.uint64)
        self._lens = np.zeros(self.STACK_CAP, dtype=np.uint16)
        self._tids = np.zeros(self.STACK_CAP, dtype=np.uint32)
        self._values = np.zeros(self.STACK_CAP, dtype=np.uint64)
        self._counts = np.zeros(self.STACK_CAP, dtype=np.uint32)

    @staticmethod
    def _bind(lib) -> None:
        if getattr(lib, "_df_offcpu_bound", False):
            return
        lib.df_offcpu_open.restype = ctypes.c_void_p
        lib.df_offcpu_open.argtypes = [
            ctypes.c_int32, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32)]
        lib.df_offcpu_close.argtypes = [ctypes.c_void_p]
        lib.df_offcpu_poll.restype = ctypes.c_uint64
        lib.df_offcpu_poll.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.df_offcpu_export.restype = ctypes.c_uint32
        lib.df_offcpu_export.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_uint32]
        lib.df_offcpu_stats.argtypes = [ctypes.c_void_p,
                                        np.ctypeslib.ndpointer(np.uint64)]
        lib._df_offcpu_bound = True

    def start(self) -> "OffCpuProfiler":
        err = ctypes.c_int32(0)
        self._h = self._lib.df_offcpu_open(
            self.pid, 64, int(self.min_block_us * 1000), ctypes.byref(err))
        if not self._h:
            raise OSError(err.value, os.strerror(err.value),
                          f"offcpu perf_event_open pid={self.pid}")
        self._thread = threading.Thread(
            target=self._run, name=f"df-offcpu-{self.pid}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3.0)
            if self._thread.is_alive():
                log.warning("offcpu worker did not exit; leaking handle "
                            "for pid %d", self.pid)
                return
        self._emit()
        if self._h:
            self._lib.df_offcpu_close(self._h)
            self._h = None

    def _run(self) -> None:
        next_emit = time.monotonic() + self.window_s
        while not self._stop.is_set():
            try:
                self._lib.df_offcpu_poll(self._h, 200)
            except Exception:
                log.exception("offcpu poll failed")
                return
            if time.monotonic() >= next_emit:
                next_emit = time.monotonic() + self.window_s
                try:
                    self._emit()
                except Exception:
                    log.exception("offcpu emit failed")

    def _emit(self) -> None:
        if not self._h:
            return
        self._lib.df_offcpu_poll(self._h, 0)
        n = self._lib.df_offcpu_export(
            self._h, self._addrs.ctypes.data_as(ctypes.c_void_p),
            self.ADDR_CAP, self._lens.ctypes.data_as(ctypes.c_void_p),
            self._tids.ctypes.data_as(ctypes.c_void_p),
            self._values.ctypes.data_as(ctypes.c_void_p),
            self._counts.ctypes.data_as(ctypes.c_void_p), self.STACK_CAP)
        if n == 0:
            return
        self._sym.refresh()
        ts = time.time_ns()
        batch = []
        off = 0
        for i in range(n):
            ln = int(self._lens[i])
            chain = self._addrs[off:off + ln]
            off += ln
            frames = [self._sym.resolve(int(a)) for a in chain[::-1]]
            count = int(self._counts[i])
            batch.append(ProfileSample(
                timestamp_ns=ts, pid=self.pid, tid=int(self._tids[i]),
                thread_name=str(int(self._tids[i])),
                stack=";".join(frames), count=count,
                value_us=int(self._values[i]) // 1000,  # blocked time
                event_type="off-cpu", profiler="perf"))
            self.stats.samples += count
        self.stats.emits += 1
        self.stats.last_emit_stacks = len(batch)
        st = np.zeros(7, dtype=np.uint64)  # df_offcpu_stats writes SEVEN
        self._lib.df_offcpu_stats(self._h, st)
        self.lost = int(st[1])
        self.switches = int(st[0])
        self.paired = int(st[5])
        try:
            self.sink(batch)
        except Exception:
            pass


_TABLE_CACHE: dict = {}  # path -> UnwindTable | None (immutable, shared)
_TABLE_MISS = object()   # sentinel: "not cached" (None means "no table")
_TABLE_LOCK = threading.Lock()


def _unwind_table_cached(path: str, should_stop=None):
    """Process-wide (then machine-wide, via the ehframe disk cache) unwind
    table lookup. Returns None for no-table binaries; raises
    ParseInterrupted when should_stop fires mid-parse (result NOT cached,
    so the next attach retries)."""
    with _TABLE_LOCK:
        if path in _TABLE_CACHE:
            return _TABLE_CACHE[path]
    from deepflow_tpu.agent import ehframe
    t0 = time.monotonic()
    try:
        table = ehframe.load_unwind_table_cached(path,
                                                 should_stop=should_stop)
    except ehframe.ParseInterrupted:
        raise
    except Exception:
        log.exception("eh_frame parse failed for %s", path)
        table = None
    if table is not None and len(table):
        log.debug("unwind table %s: %d rows / %d FDEs in %.2fs", path,
                  len(table), table.n_fdes, time.monotonic() - t0)
    with _TABLE_LOCK:
        _TABLE_CACHE[path] = table
    return table


class ExternalProfiler:
    """Continuous out-of-process OnCPU profiler for one target pid."""

    ADDR_CAP = 1 << 18
    STACK_CAP = 8192

    def __init__(self, sink, pid: int, hz: float = 99.0,
                 window_s: float = 1.0, process_name: str = "",
                 app_service: str = "", dwarf: bool = True,
                 stack_dump: int = 8192, python_stacks: bool = True) -> None:
        lib = native.load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._bind(lib)
        self._lib = lib
        self.sink = sink
        self.pid = pid
        self.hz = hz
        self.window_s = window_s
        self.dwarf = dwarf
        self.stack_dump = stack_dump
        self.process_name = process_name or self._comm(pid)
        self.app_service = app_service or self.process_name
        self.stats = SamplerStats()
        self.lost = 0
        self.export_dropped = 0
        self.dwarf_samples = 0
        self.fp_samples = 0
        self.unwind_tables = 0
        # remote interpreter stacks (py-spy style, pystacks.py): spliced
        # over the _PyEval_EvalFrameDefault runs so a JAX host's profile
        # shows Python function names, not interpreter-loop soup
        self._py_enabled = python_stacks
        self._py: "object | None" = None       # RemotePython once attached
        self._py_attempts = 0
        self.py_threads = 0
        self.py_spliced = 0
        self._h = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._builder: threading.Thread | None = None
        self._sym = Symbolizer(pid)
        self._requested: set = set()   # (path, map_start) sent to builder
        self._build_q: "queue.Queue" = queue.Queue()   # (gen, map) to parse
        self._ready_q: "queue.Queue" = queue.Queue()   # (gen, ...) tables
        self._gen = 0          # bumped on clear: drops in-flight stale work
        self._pending = 0      # queued-but-unregistered table builds
        self._pending_lock = threading.Lock()
        self._addrs = np.zeros(self.ADDR_CAP, dtype=np.uint64)
        self._lens = np.zeros(self.STACK_CAP, dtype=np.uint16)
        self._tids = np.zeros(self.STACK_CAP, dtype=np.uint32)
        self._counts = np.zeros(self.STACK_CAP, dtype=np.uint32)

    @staticmethod
    def _bind(lib) -> None:
        if getattr(lib, "_df_prof_bound", False):
            return
        lib.df_prof_open.restype = ctypes.c_void_p
        lib.df_prof_open.argtypes = [ctypes.c_int32, ctypes.c_uint32,
                                     ctypes.c_uint32,
                                     ctypes.POINTER(ctypes.c_int32)]
        lib.df_prof_open_ex.restype = ctypes.c_void_p
        lib.df_prof_open_ex.argtypes = [
            ctypes.c_int32, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_int32, ctypes.c_uint32, ctypes.POINTER(ctypes.c_int32)]
        lib.df_prof_close.argtypes = [ctypes.c_void_p]
        lib.df_prof_poll.restype = ctypes.c_uint64
        lib.df_prof_poll.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.df_prof_export.restype = ctypes.c_uint32
        lib.df_prof_export.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_uint32]
        lib.df_prof_stats.argtypes = [ctypes.c_void_p,
                                      np.ctypeslib.ndpointer(np.uint64)]
        lib.df_prof_stats2.argtypes = lib.df_prof_stats.argtypes
        lib.df_prof_add_table.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, np.ctypeslib.ndpointer(np.uint64),
            np.ctypeslib.ndpointer(np.uint8),
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int32), ctypes.c_uint32]
        lib.df_prof_clear_tables.argtypes = [ctypes.c_void_p]
        lib._df_prof_bound = True

    @staticmethod
    def _comm(pid: int) -> str:
        try:
            with open(f"/proc/{pid}/comm") as f:
                return f.read().strip()
        except OSError:
            return str(pid)

    def start(self) -> "ExternalProfiler":
        err = ctypes.c_int32(0)
        self._h = self._lib.df_prof_open_ex(
            self.pid, int(self.hz), 64, 1 if self.dwarf else 0,
            self.stack_dump, ctypes.byref(err))
        if not self._h:
            raise OSError(err.value, os.strerror(err.value),
                          f"perf_event_open pid={self.pid}")
        if self.dwarf:
            # table builds are EXPENSIVE (a big runtime .so parses for
            # seconds): a background builder parses and queues; the worker
            # thread registers finished tables between polls (df_prof_add_
            # table must not race df_prof_poll). Until a table lands, its
            # samples use the FP chain — same degradation as the reference
            # while its shard cache warms.
            self._request_tables()
            self._builder = threading.Thread(
                target=self._build_tables,
                name=f"df-unwind-build-{self.pid}", daemon=True)
            self._builder.start()
        self._thread = threading.Thread(
            target=self._run, name=f"df-extprof-{self.pid}", daemon=True)
        self._thread.start()
        return self

    def _request_tables(self) -> None:
        """Register/queue every executable file-backed mapping. Paths whose
        table already sits in the process-wide memory cache register
        IMMEDIATELY (this runs on the thread that owns the native handle),
        so a maps-change rebuild costs a few add_table copies, not a trip
        through the builder — attach-time dlopen churn was re-parsing the
        whole map set per change and burning ~half a core for seconds
        (BENCH_r03's extprof_observer_pct: 50)."""
        for m in self._sym.maps:
            key = (m.path, m.start)
            if key in self._requested or not m.path.startswith("/"):
                continue
            self._requested.add(key)
            with _TABLE_LOCK:
                cached = _TABLE_CACHE.get(m.path, _TABLE_MISS)
            if cached is not _TABLE_MISS:
                if cached is not None and len(cached):
                    self._register_table(m, cached)
                continue
            with self._pending_lock:
                self._pending += 1
            self._build_q.put((self._gen, m))

    def _bias_for(self, m: _Map) -> int:
        try:
            e = self._sym._elf(m.path)
            return e.bias_for(m) if e.et_dyn else 0
        except Exception:
            return 0

    def _add_table(self, start: int, end: int, bias: int, table) -> None:
        """Single registration point (must run on the thread owning the
        native handle — see df_prof_add_table's thread contract)."""
        self._lib.df_prof_add_table(
            self._h, start, end, bias, table.pc, table.cfa_reg,
            table.cfa_off, table.rbp_off, table.ra_off, len(table))
        self.unwind_tables += 1

    def _register_table(self, m: _Map, table) -> None:
        self._add_table(m.start, m.end, self._bias_for(m), table)

    def _done_one(self) -> None:
        with self._pending_lock:
            self._pending -= 1

    def _build_tables(self) -> None:
        """Builder thread: parse .eh_frame (pure Python + disk cache; no
        native calls — registration stays on the poll thread)."""
        from deepflow_tpu.agent import ehframe
        while not self._stop.is_set():
            try:
                gen, m = self._build_q.get(timeout=0.5)
            except queue.Empty:
                continue
            if gen != self._gen:
                # stale generation: a maps change already re-requested this
                # work; parsing it anyway doubles the churn cost
                self._done_one()
                continue
            try:
                table = _unwind_table_cached(
                    m.path, should_stop=self._stop.is_set)
            except ehframe.ParseInterrupted:
                self._done_one()
                return
            except Exception:
                log.exception("unwind table build failed for %s", m.path)
                self._done_one()
                continue
            if table is None or not len(table):
                self._done_one()
                continue
            self._ready_q.put((gen, m.start, m.end, self._bias_for(m),
                               table))

    def builder_busy(self) -> bool:
        """True while unwind tables are still being parsed/registered
        (benchmarks should wait this out before timing steady state).
        Counter-based: queue emptiness alone has a false-idle window
        between dequeue and parse."""
        with self._pending_lock:
            return self._pending > 0

    def _drain_ready_tables(self) -> None:
        """Register finished tables (worker thread only: add_table must
        not race the poll loop). Items from a previous generation (built
        before a maps-change cleared the tables) are dropped — a stale
        table re-registered at a reused range would shadow the fresh one."""
        while True:
            try:
                gen, start, end, bias, table = self._ready_q.get_nowait()
            except queue.Empty:
                return
            self._done_one()
            if gen != self._gen:
                continue
            self._add_table(start, end, bias, table)

    def stop(self) -> None:
        self._stop.set()
        if self._builder:
            self._builder.join(timeout=3.0)
        if self._thread:
            self._thread.join(timeout=3.0)
            if self._thread.is_alive():
                # never touch/free native state under a live worker
                # (use-after-free); leaking the handle is the safe mode
                log.warning("extprofiler worker did not exit; leaking "
                            "perf handle for pid %d", self.pid)
                return
        self._emit()  # final window
        if self._h:
            self._lib.df_prof_close(self._h)
            self._h = None

    def _run(self) -> None:
        next_emit = time.monotonic() + self.window_s
        while not self._stop.is_set():
            try:
                self._lib.df_prof_poll(self._h, 200)
            except Exception:
                log.exception("perf poll failed")
                return
            if self.dwarf:
                # register tables the builder finished (this thread owns
                # the native handle, so add_table can't race the poll)
                try:
                    self._drain_ready_tables()
                except Exception:
                    log.exception("table registration failed")
            if time.monotonic() >= next_emit:
                next_emit = time.monotonic() + self.window_s
                try:
                    self._emit()
                except Exception:
                    log.exception("extprofiler emit failed")

    def _sample_python_stacks(self) -> dict:
        """One interpreter-state read per window (py-spy cadence). The
        target must share this build's CPython (pystacks validates); a
        non-Python target disables itself after a few attach attempts."""
        if not self._py_enabled:
            return {}
        if self._py is None:
            self._py_attempts += 1
            try:
                from deepflow_tpu.agent.pystacks import RemotePython
                self._py = RemotePython(self.pid)
            except Exception as e:
                # early startup can race the maps scan: retry a few
                # windows before concluding the target isn't Python
                if self._py_attempts >= 5:
                    self._py_enabled = False
                    log.info("remote python stacks unavailable for pid "
                             "%d: %s", self.pid, e)
                return {}
        try:
            stacks = self._py.sample()
            self.py_threads = len(stacks)
            return stacks
        except Exception:
            log.exception("python stack sample failed")
            return {}

    @staticmethod
    def _is_python_image(frame: str) -> bool:
        mod = frame.split("`", 1)[0].split("+", 1)[0]
        return mod.startswith("libpython") or mod.startswith("python")

    def _splice_python(self, frames: list[str],
                       py: list[str] | None) -> list[str]:
        """Replace the first contiguous run of python-image frames (the
        interpreter: Py_RunMain .. _PyEval_EvalFrameDefault and its
        stripped .cold chunks, which symbolize as libpython+0x…) with the
        thread's sampled Python frames, root-first. The native prefix
        (ld/libc startup) and any non-libpython suffix (a C-extension
        leaf) survive. Window-close sampling means the Python stack is an
        approximation of each individual sample's — the standard async
        mixed-mode tradeoff."""
        if not py:
            return frames
        first = next((i for i, f in enumerate(frames)
                      if self._is_python_image(f)), -1)
        if first < 0:
            return frames
        last = first
        while last + 1 < len(frames) and \
                self._is_python_image(frames[last + 1]):
            last += 1
        self.py_spliced += 1
        return frames[:first] + py + frames[last + 1:]

    def _emit(self) -> None:
        if not self._h:
            return
        self._lib.df_prof_poll(self._h, 0)
        n = self._lib.df_prof_export(
            self._h, self._addrs.ctypes.data_as(ctypes.c_void_p),
            self.ADDR_CAP, self._lens.ctypes.data_as(ctypes.c_void_p),
            self._tids.ctypes.data_as(ctypes.c_void_p),
            self._counts.ctypes.data_as(ctypes.c_void_p), self.STACK_CAP)
        if n == 0:
            return
        changed = self._sym.refresh()  # mappings change (dlopen etc.)
        if self.dwarf:
            try:
                if changed:
                    # a dlclose/dlopen can land a new binary at a stale
                    # module's range, and the stale table would shadow it:
                    # drop everything and re-register (cheap — tables are
                    # memory-cached per path)
                    self._lib.df_prof_clear_tables(self._h)
                    self.unwind_tables = 0
                    self._requested.clear()
                    self._gen += 1
                # new mappings feed the builder; finished tables register
                self._request_tables()
                self._drain_ready_tables()
            except Exception:
                log.exception("unwind table registration failed")
        py_stacks = self._sample_python_stacks()
        ts = time.time_ns()
        period_us = int(1e6 / self.hz)
        batch = []
        off = 0
        for i in range(n):
            ln = int(self._lens[i])
            chain = self._addrs[off:off + ln]
            off += ln
            # chains arrive leaf-first; folded stacks are root-first
            frames = [self._sym.resolve(int(a)) for a in chain[::-1]]
            if py_stacks:
                frames = self._splice_python(frames,
                                             py_stacks.get(
                                                 int(self._tids[i])))
            count = int(self._counts[i])
            batch.append(ProfileSample(
                timestamp_ns=ts, pid=self.pid, tid=int(self._tids[i]),
                thread_name=str(int(self._tids[i])),
                stack=";".join(frames), count=count,
                value_us=count * period_us,
                event_type="on-cpu", profiler="perf"))
            self.stats.samples += count
        self.stats.emits += 1
        self.stats.last_emit_stacks = len(batch)
        st = np.zeros(7, dtype=np.uint64)
        self._lib.df_prof_stats2(self._h, st)
        self.lost = int(st[1])
        self.export_dropped = int(st[3])
        self.dwarf_samples = int(st[4])
        self.fp_samples = int(st[5])
        try:
            self.sink(batch)
        except Exception:
            pass  # a failing sink must never kill the profiler
