"""Agent runtime: wires profilers + probes + stats into the uniform sender.

Reference analog: agent/src/trident.rs (Components wiring) — scaled to the
round-1 component set: OnCPU sampler, TPU probe, self-stats. Runs standalone
(no controller, reference `--standalone` mode) or controller-managed once the
sync plane lands.
"""

from __future__ import annotations

import functools
import logging
import os
import sys
import threading
import time

from deepflow_tpu.agent.config import AgentConfig
from deepflow_tpu.agent.profiler import OnCpuSampler, ProfileSample
from deepflow_tpu.agent.sender import UniformSender
from deepflow_tpu.codec import MessageType
from deepflow_tpu.proto import pb

log = logging.getLogger("df.agent")


class Agent:
    def __init__(self, config: AgentConfig | None = None) -> None:
        self.config = config or AgentConfig()
        self.process_name = os.path.basename(sys.argv[0]) or "python"
        self.app_service = self.config.app_service or self.process_name
        # self-telemetry spine: hop ledger + heartbeats + deadman
        # (deepflow_tpu/telemetry.py); one registry per Agent instance
        from deepflow_tpu.telemetry import DeadmanDetector, Telemetry
        sm = self.config.selfmon
        # config False forces off; config True still honors DF_NO_SELFMON
        self.telemetry = Telemetry(
            "agent", enabled=None if sm.enabled else False)
        self.deadman = DeadmanDetector(
            self.telemetry, window_s=sm.deadman_window_s,
            check_interval_s=sm.check_interval_s or None,
            on_wedge=self._on_wedge)
        if self.config.sender.replication > 1:
            from deepflow_tpu.agent.sender import ReplicatedSender
            self.sender = ReplicatedSender(
                self.config.sender.servers,
                replication=self.config.sender.replication,
                agent_id=self.config.agent_id,
                queue_size=self.config.sender.queue_size,
                telemetry=self.telemetry,
                durable=self.config.sender.durable,
                ack_window=self.config.sender.ack_window,
                spool_factory=self._build_spool_factory(),
                chaos=self._build_chaos())
        else:
            self.sender = UniformSender(
                self.config.sender.servers, agent_id=self.config.agent_id,
                queue_size=self.config.sender.queue_size,
                telemetry=self.telemetry,
                durable=self.config.sender.durable,
                ack_window=self.config.sender.ack_window,
                spool=self._build_spool(),
                chaos=self._build_chaos())
        self.sampler: OnCpuSampler | None = None
        self.memprofiler = None
        self.extprofilers: list = []
        self.tpuprobe = None
        self.synchronizer = None
        self.socket_scanner = None
        self.guard = None
        self.integration_proxy = None
        self.dispatcher = None
        self.live_capture = None
        self.sslprobe = None
        self.memhook = None
        from deepflow_tpu.agent.labeler import AclRule, Labeler
        self.labeler = Labeler()
        self.labeler.load_acls([
            AclRule(cidr=a.get("cidr", ""), port=int(a.get("port", 0)),
                    protocol=int(a.get("protocol", 0)),
                    action=a.get("action", "trace"))
            for a in getattr(self.config, "acls", [])])
        self._stats_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._components: list[str] = []
        # serializes sampler/tpuprobe lifecycle across guard, synchronizer
        # and stats threads
        self._profiler_lock = threading.RLock()
        # server-directed backpressure (qos/): last pressure level
        # applied from a SyncResponse.qos directive (0 = nominal)
        self.pressure_level = 0

    def _build_spool(self):
        sc = self.config.sender.spool
        if not sc.enabled:
            return None
        import tempfile
        from deepflow_tpu.agent.spool import Spool
        directory = sc.dir or os.path.join(
            tempfile.gettempdir(),
            f"deepflow-spool-{self.config.agent_id}")
        return Spool(directory, max_bytes=sc.max_mb << 20,
                     segment_bytes=sc.segment_mb << 20,
                     max_age_s=sc.max_age_s)

    def _build_spool_factory(self):
        """Replicated transport: one spool SUBDIRECTORY per destination
        (each destination has its own seq space; sharing a spool would
        interleave them and break trim/replay watermarks)."""
        sc = self.config.sender.spool
        if not sc.enabled:
            return None
        import tempfile
        from deepflow_tpu.agent.spool import Spool
        base = sc.dir or os.path.join(
            tempfile.gettempdir(),
            f"deepflow-spool-{self.config.agent_id}")

        def factory(dest_key: str):
            return Spool(os.path.join(base, dest_key),
                         max_bytes=sc.max_mb << 20,
                         segment_bytes=sc.segment_mb << 20,
                         max_age_s=sc.max_age_s)

        return factory

    def _build_chaos(self):
        # DF_CHAOS (env) wins over the config block; the sender also
        # falls back to the env knob itself when this returns None, so
        # returning None here means "no config-driven injector"
        from deepflow_tpu.chaos import ChaosConfig, ChaosInjector, \
            chaos_from_env
        env = chaos_from_env()
        if env is not None:
            return env
        cc = self.config.chaos
        if not cc.enabled:
            return None
        return ChaosInjector(ChaosConfig(
            enabled=True, seed=cc.seed, conn_refuse=cc.conn_refuse,
            conn_reset=cc.conn_reset, partial_write=cc.partial_write,
            latency_ms=cc.latency_ms, disk_full=cc.disk_full))

    # -- lifecycle -----------------------------------------------------------

    def start_sampler(self) -> None:
        with self._profiler_lock:
            if self.sampler is not None:
                return
            if self.guard is not None and self.guard.degraded:
                return  # guard has profiling paused; resume handles restart
            self.sampler = OnCpuSampler(
                self._profile_sink,
                hz=self.config.profiler.sample_hz,
                emit_interval_s=self.config.profiler.emit_interval_s,
                process_name=self.process_name,
                app_service=self.app_service).start()

    def start_tpuprobe(self) -> None:
        with self._profiler_lock:
            if self.tpuprobe is not None:
                return
            if self.guard is not None and self.guard.degraded:
                return
            try:
                from deepflow_tpu.tpuprobe.probe import TpuProbe
            except ImportError:
                log.debug("tpuprobe unavailable")
                return
            self.tpuprobe = TpuProbe(self).start()

    def start_memprofiler(self) -> None:
        with self._profiler_lock:
            if self.memprofiler is not None:
                return
            if self.guard is not None and self.guard.degraded:
                return
            from deepflow_tpu.agent.memprofiler import MemProfiler
            self.memprofiler = MemProfiler(
                self._profile_sink,
                interval_s=self.config.profiler.memory_interval_s).start()

    def start_extprofilers(self) -> None:
        with self._profiler_lock:
            if self.extprofilers:
                return
            if self.guard is not None and self.guard.degraded:
                return
            for pid in self.config.profiler.external_pids:
                try:
                    from deepflow_tpu.agent.extprofiler import \
                        ExternalProfiler
                    ep = ExternalProfiler(
                        None, pid=int(pid),
                        hz=self.config.profiler.sample_hz,
                        window_s=self.config.profiler.emit_interval_s)
                    # samples carry the TARGET's identity, captured at
                    # attach time (the target may exit before the last emit)
                    ep.sink = functools.partial(
                        self._profile_sink, process_name=ep.process_name,
                        app_service=ep.app_service)
                    ep.start()
                    self.extprofilers.append(ep)
                    if f"extprof-{pid}" not in self._components:
                        self._components.append(f"extprof-{pid}")
                    if self.config.profiler.external_offcpu:
                        from deepflow_tpu.agent.extprofiler import \
                            OffCpuProfiler
                        op = OffCpuProfiler(
                            None, pid=int(pid),
                            window_s=self.config.profiler.emit_interval_s)
                        op.sink = functools.partial(
                            self._profile_sink,
                            process_name=op.process_name,
                            app_service=op.app_service)
                        op.start()
                        self.extprofilers.append(op)
                        if f"offcpu-{pid}" not in self._components:
                            self._components.append(f"offcpu-{pid}")
                except (OSError, RuntimeError, ImportError,
                        AttributeError) as e:
                    # AttributeError: stale libdfnative.so without the
                    # df_prof_* symbols — degrade, don't abort startup
                    log.warning("external profiler for pid %s unavailable:"
                                " %s", pid, e)

    def pause_profilers(self) -> None:
        with self._profiler_lock:
            if self.sampler is not None:
                self.sampler.stop()
                self.sampler = None
            if self.memprofiler is not None:  # tracemalloc costs real CPU
                self.memprofiler.stop()
                self.memprofiler = None
            for ep in self.extprofilers:  # drain+symbolize burns agent CPU
                ep.stop()
            self.extprofilers = []
            if self.tpuprobe is not None:
                self.tpuprobe.stop()
                self.tpuprobe = None

    def resume_profilers(self) -> None:
        with self._profiler_lock:
            if self.config.profiler.enabled:
                self.start_sampler()
            if self.config.profiler.memory:
                self.start_memprofiler()
            if self.config.tpuprobe.enabled:
                self.start_tpuprobe()
        self.start_extprofilers()

    def apply_backpressure(self, level: int) -> None:
        """Degrade gracefully under server-reported ingest pressure
        (SyncResponse.qos): sampler hz shrinks, profile emit windows
        widen (fewer, larger frames), HLO top-K narrows, trace captures
        thin out — per-level factors from config.qos. Idempotent per
        level; scales apply to the CONFIGURED values (never compounded),
        so level 0 restores the baselines exactly."""
        cfg = self.config
        if not cfg.qos.enabled:
            return
        level = max(0, min(3, int(level)))
        if level == self.pressure_level:
            return
        prev, self.pressure_level = self.pressure_level, level
        trace_scale = cfg.qos.trace_scale[level]
        with self._profiler_lock:
            sampler = self.sampler
            if sampler is not None:
                hz = max(1.0, cfg.profiler.sample_hz
                         * cfg.qos.hz_scale[level])
                sampler.period_s = 1.0 / hz
                sampler.period_us = int(1_000_000 / hz)
                sampler.emit_interval_s = (cfg.profiler.emit_interval_s
                                           * cfg.qos.emit_scale[level])
            probe = self.tpuprobe
            if probe is not None:
                if probe.stepagg is not None:
                    base = getattr(cfg.tpuprobe, "step_topk", 5)
                    probe.stepagg.topk = max(
                        1, int(base * cfg.qos.topk_scale[level]))
                for src in probe.sources:
                    if hasattr(src, "interval_s"):
                        src.interval_s = (cfg.tpuprobe.trace_interval_s
                                          * trace_scale)
                    if hasattr(src, "steps_per_capture"):
                        src.steps_per_capture = max(1, int(
                            cfg.tpuprobe.steps_per_capture * trace_scale))
        log.info("backpressure level %d -> %d", prev, level)

    def start(self) -> "Agent":
        plugins = getattr(self.config, "plugins", [])
        if plugins:
            from deepflow_tpu.agent.ops import load_plugins
            load_plugins(plugins)
        self.deadman.start()
        self.sender.start()
        self._components.append("sender")
        if self.config.profiler.enabled:
            self.start_sampler()
            self._components.append("oncpu-sampler")
        if self.config.profiler.memory:
            self.start_memprofiler()
            self._components.append("mem-profiler")
        self.start_extprofilers()
        if self.config.tpuprobe.enabled:
            self.start_tpuprobe()
            if self.tpuprobe is not None:
                self._components.append("tpuprobe")
        has_pkt_acls = any(a.get("action") in ("pcap", "npb")
                           for a in getattr(self.config, "acls", []))
        if self.config.flow.enabled or self.config.sslprobe_sock or \
                has_pkt_acls:
            from deepflow_tpu.agent.dispatcher import Dispatcher
            self.dispatcher = Dispatcher(
                sender=self.sender,
                agent_id=self.config.agent_id,
                labeler=self.labeler,
                telemetry=self.telemetry).start()
            from deepflow_tpu.agent.packet_actions import PacketActions
            self.dispatcher.packet_actions = PacketActions(
                self.labeler, sender=self.sender,
                agent_id=self.config.agent_id,
                npb_target=self.config.npb_target,
                npb_vni=self.config.npb_vni)
        if self.config.sslprobe_sock:
            from deepflow_tpu.agent.sslprobe import SslProbeListener
            self.sslprobe = SslProbeListener(
                self.dispatcher, self.config.sslprobe_sock).start()
            self._components.append("ssl-probe")
        if self.config.memhook_sock:
            from deepflow_tpu.agent.memhook import MemHookListener

            def _mem_sink(batch):
                pid = batch[0].pid if batch else 0
                try:
                    with open(f"/proc/{pid}/comm") as f:
                        name = f.read().strip()
                except OSError:
                    name = str(pid)
                self._profile_sink(batch, process_name=name,
                                   app_service=name)
            self.memhook = MemHookListener(
                _mem_sink, self.config.memhook_sock).start()
            self._components.append("memhook")
        if self.config.flow.enabled:
            from deepflow_tpu.agent.live_capture import LiveCapture
            # the agent's own telemetry must never be captured (feedback
            # amplification): union the REAL sender ports into the exclusions
            exclude = set(self.config.flow.exclude_ports)
            exclude.update(p for _, p in self.sender.servers)
            try:
                self.live_capture = LiveCapture(
                    self.dispatcher,
                    interface=self.config.flow.interface,
                    exclude_ports=tuple(exclude),
                    capture_mode=self.config.flow.capture_mode,
                ).start()
                self._components.append("live-capture")
            except (OSError, AttributeError) as e:
                # PermissionError (no CAP_NET_RAW), ENODEV (bad iface),
                # AttributeError (no AF_PACKET on this OS): degrade
                log.warning("live capture unavailable (%s); replay and "
                            "synthetic sources still work", e)
        if self.config.integration.enabled:
            from deepflow_tpu.agent.integration_proxy import IntegrationProxy
            ic = self.config.integration
            self.integration_proxy = IntegrationProxy(
                ic.server_http, host=ic.host, port=ic.port).start()
            self._components.append("integration-proxy")
        if self.config.guard.enabled:
            from deepflow_tpu.agent.guard import Guard
            g = self.config.guard
            self.guard = Guard(
                self, max_cpu_pct=g.max_cpu_pct, max_mem_mb=g.max_mem_mb,
                check_interval_s=g.check_interval_s).start()
            self._components.append("guard")
        if self.config.controller:
            from deepflow_tpu.agent.synchronizer import Synchronizer
            self.synchronizer = Synchronizer(
                self, self.config.controller,
                interval_s=self.config.sync_interval_s).start()
            self._components.append("synchronizer")
            if getattr(self.config, "socket_scan_interval_s", 0) > 0:
                from deepflow_tpu.agent.socket_scan import SocketScanner
                self.socket_scanner = SocketScanner(
                    self.synchronizer, agent_id=self.config.agent_id,
                    interval_s=self.config.socket_scan_interval_s).start()
                self._components.append("socket-scan")
        self._stats_thread = threading.Thread(
            target=self._stats_loop, name="df-agent-stats", daemon=True)
        self._stats_thread.start()
        self._components.append("stats")
        log.info("agent started: %s", ", ".join(self._components))
        return self

    def stop(self) -> None:
        self._stop.set()
        self.deadman.stop()
        if self.guard:
            self.guard.stop()
        if getattr(self, "socket_scanner", None):
            self.socket_scanner.stop()
        if self.synchronizer:
            self.synchronizer.stop()
        if self.sampler:
            self.sampler.stop()
        if self.memprofiler:
            self.memprofiler.stop()
        for ep in self.extprofilers:
            ep.stop()
        self.extprofilers = []
        if self.tpuprobe:
            self.tpuprobe.stop()
        if self.integration_proxy:
            self.integration_proxy.stop()
        if self.sslprobe:
            self.sslprobe.stop()
        if self.memhook:
            self.memhook.stop()
        if self.live_capture:
            self.live_capture.stop()
        if self.dispatcher:
            if self.dispatcher.packet_actions is not None:
                self.dispatcher.packet_actions.stop()
            self.dispatcher.stop()
        self._emit_stats()  # final stats flush
        self.sender.flush_and_stop()

    def ensure_packet_actions(self, cfg=None) -> None:
        """Controller-pushed pcap/npb ACLs need a dispatcher + executor
        even when the agent booted without one (hot-apply path)."""
        cfg = cfg or self.config
        if self.dispatcher is None:
            from deepflow_tpu.agent.dispatcher import Dispatcher
            self.dispatcher = Dispatcher(
                sender=self.sender, agent_id=self.config.agent_id,
                labeler=self.labeler, telemetry=self.telemetry).start()
            self._components.append("dispatcher")
        if self.dispatcher.packet_actions is None:
            from deepflow_tpu.agent.packet_actions import PacketActions
            self.dispatcher.packet_actions = PacketActions(
                self.labeler, sender=self.sender,
                agent_id=self.config.agent_id,
                npb_target=getattr(cfg, "npb_target", ""),
                npb_vni=getattr(cfg, "npb_vni", 1))

    # -- sinks ---------------------------------------------------------------

    def _profile_sink(self, batch: list[ProfileSample],
                      process_name: str | None = None,
                      app_service: str | None = None) -> None:
        out = pb.ProfileBatch()
        for s in batch:
            p = out.profiles.add()
            p.process_name = process_name or self.process_name
            p.app_service = app_service or self.app_service
            p.pid = s.pid
            p.tid = s.tid & 0xFFFFFFFF
            p.thread_name = s.thread_name
            p.event_type = _EVENT_TYPES.get(s.event_type, pb.ON_CPU)
            p.timestamp_ns = s.timestamp_ns
            p.stack = s.stack.encode()
            p.value = s.value_us
            p.count = s.count
            p.profiler = s.profiler
        self.sender.send(MessageType.PROFILE, out.SerializeToString())

    def send_tpu_spans(self, spans_pb: "pb.TpuSpanBatch") -> None:
        self.sender.send(MessageType.TPU_SPAN, spans_pb.SerializeToString())

    def send_step_metrics(self, payload: bytes) -> bool:
        """Per-step rollup records (pre-encoded STEP_METRICS payload —
        JSON, not protobuf; see tpuprobe/stepmetrics.py)."""
        return self.sender.send(MessageType.STEP_METRICS, payload)

    # -- self-telemetry (reference: agent/src/utils/stats.rs -> dfstats) -----

    def _on_wedge(self, verdict: dict) -> None:
        """Deadman verdict: ship it IMMEDIATELY (the stats loop may be
        minutes away — a wedge report must not wait on a schedule)."""
        try:
            self._emit_stats()
        except Exception:
            log.exception("wedge stats emit failed")

    def _stats_loop(self) -> None:
        hb = self.telemetry.heartbeat(
            "stats", interval_hint_s=self.config.stats_interval_s)
        hb.beat()
        while not self._stop.wait(self.config.stats_interval_s):
            hb.beat()
            try:
                self._emit_stats()
            except Exception:
                log.exception("stats emit failed")  # never kill the loop

    def _emit_stats(self) -> None:
        batch = pb.StatsBatch()
        ts = time.time_ns()

        def metric(name: str, values: dict,
                   extra_tags: dict | None = None) -> None:
            m = batch.metrics.add()
            m.name = name
            m.timestamp_ns = ts
            m.tags["process"] = self.process_name
            if extra_tags:
                for k, v in extra_tags.items():
                    m.tags[k] = str(v)
            for k, v in values.items():
                m.values[k] = float(v)

        metric("agent.sender", self.sender.stats)
        sampler, tpuprobe = self.sampler, self.tpuprobe  # racy nulling-safe
        if sampler is not None:
            st = sampler.stats
            metric("agent.oncpu_sampler", {
                "samples": st.samples, "emits": st.emits,
                "overruns": st.overruns})
        if tpuprobe is not None:
            metric("agent.tpuprobe", tpuprobe.stats)
        if self.integration_proxy is not None:
            metric("agent.integration_proxy", self.integration_proxy.stats)
        if self.live_capture is not None:
            metric("agent.live_capture", self.live_capture.stats)
        if self.dispatcher is not None:
            metric("agent.flow_map", self.dispatcher.flow_map.stats)
        if self.guard is not None:
            metric("agent.guard", {
                "cpu_pct": self.guard.cpu_pct,
                "rss_mb": self.guard.rss_mb,
                "degraded": int(self.guard.degraded),
                **self.guard.stats})
        if self.pressure_level:
            metric("agent.qos", {"pressure_level": self.pressure_level})
        sync = getattr(self, "synchronizer", None)
        if sync is not None and sync.stats.get("ntp_syncs"):
            metric("agent.clock", {
                "offset_ms": sync.clock_offset_ns / 1e6,
                "ntp_rtt_ms": sync.ntp_rtt_ns / 1e6})
        # the self-telemetry spine: hop ledger, stage heartbeats, wedge
        # verdicts — all ride the same DFSTATS batch into deepflow_system
        for name, tags, values in self.telemetry.stats_metrics():
            metric(name, values, extra_tags=tags)
        self.sender.send(MessageType.DFSTATS, batch.SerializeToString())


_EVENT_TYPES = {
    "on-cpu": pb.ON_CPU,
    "off-cpu": pb.OFF_CPU,
    "mem-alloc": pb.MEM_ALLOC,
    "tpu-device": pb.TPU_DEVICE,
    "tpu-host": pb.TPU_HOST,
}

_GLOBAL_AGENT: Agent | None = None
_ATEXIT_REGISTERED = False


def attach(app_service: str = "", servers: list | None = None,
           **overrides) -> Agent:
    """In-process zero-code attach: start an agent inside the current
    process (used by `deepflow-run` and direct instrumentation)."""
    global _GLOBAL_AGENT
    if _GLOBAL_AGENT is not None:
        return _GLOBAL_AGENT
    cfg = AgentConfig()
    if app_service:
        cfg.app_service = app_service
    if servers:
        cfg.sender.servers = servers
    for k, v in overrides.items():
        setattr(cfg, k, v)
    _GLOBAL_AGENT = Agent(cfg).start()
    # interpreter teardown with a live xplane capture aborts the process
    # (daemon thread inside jax.profiler during shutdown): detach cleanly.
    # Registered once per process; detach() is idempotent.
    global _ATEXIT_REGISTERED
    if not _ATEXIT_REGISTERED:
        import atexit
        atexit.register(detach)
        _ATEXIT_REGISTERED = True
    return _GLOBAL_AGENT


def detach() -> None:
    global _GLOBAL_AGENT
    if _GLOBAL_AGENT is not None:
        _GLOBAL_AGENT.stop()
        _GLOBAL_AGENT = None


def main() -> None:
    import argparse
    parser = argparse.ArgumentParser(description="deepflow-tpu agent")
    parser.add_argument("-f", "--config", default=None)
    parser.add_argument("--standalone", action="store_true")
    parser.add_argument("--server", default=None,
                        help="host:port (overrides config when given)")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    cfg = AgentConfig.load(args.config)
    if args.standalone:
        cfg.standalone = True
        cfg.controller = ""
    if args.server is not None:
        from deepflow_tpu.agent.config import _parse_addr
        cfg.sender.servers = [_parse_addr(args.server)]
    agent = Agent(cfg).start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        agent.stop()


if __name__ == "__main__":
    main()
