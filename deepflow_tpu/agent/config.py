"""Agent configuration.

Reference analog: agent/src/config (static UserConfig + controller-pushed
RuntimeConfig, hot-applied by ConfigHandler callbacks). Round-1 surface: a
typed dataclass loadable from YAML, controller push lands with the control
plane.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field


@dataclass
class ProfilerConfig:
    enabled: bool = True
    sample_hz: float = 99.0
    emit_interval_s: float = 1.0
    memory: bool = False            # tracemalloc allocation flame graphs
    memory_interval_s: float = 10.0
    # out-of-process perf_event_open targets (ANY pid, not just Python);
    # needs CAP_PERFMON or perf_event_paranoid <= 2 with same-user targets
    external_pids: list = field(default_factory=list)
    # additionally profile external_pids OFF-CPU (blocked + runqueue wait
    # flame graphs from context-switch events; needs kernel-context perf,
    # perf_event_paranoid <= 1 or CAP_PERFMON)
    external_offcpu: bool = False


@dataclass
class TpuProbeConfig:
    enabled: bool = True
    source: str = "auto"          # auto | xplane | hooks | sim
    trace_interval_s: float = 10.0  # fallback cadence before steps observed
    trace_duration_ms: int = 1000
    # step-adaptive duty cycle: windows sized to whole steps, gaps sized so
    # this fraction of ALL steps is captured
    target_coverage: float = 0.5
    steps_per_capture: int = 20
    # per-device HBM usage sampling cadence (allocator statistics; ~free).
    # 0 disables.
    memory_poll_s: float = 5.0
    # continuous per-step rollups (STEP_METRICS records: latency, skew,
    # collective wait, top-K HLO self-times per (run_id, step))
    step_metrics: bool = True
    step_topk: int = 5


@dataclass
class FlowConfig:
    enabled: bool = False           # needs CAP_NET_RAW
    interface: str = ""             # "" = all interfaces
    # local: this host's own traffic (self-ports excluded to break the
    # telemetry feedback loop); mirror: a SPAN/mirror port carrying OTHER
    # hosts' traffic (promiscuous, no self-port exclusion; tunnels are
    # decapsulated either way)
    capture_mode: str = "local"     # local | mirror | analyzer
    exclude_ports: list = field(
        default_factory=lambda: [20033, 20035, 20416])


@dataclass
class IntegrationConfig:
    enabled: bool = False
    host: str = "0.0.0.0"           # pods reach it via the node IP
    port: int = 38086
    server_http: str = "127.0.0.1:20416"


@dataclass
class GuardConfig:
    enabled: bool = True
    max_cpu_pct: float = 50.0
    max_mem_mb: float = 2048.0
    check_interval_s: float = 10.0


@dataclass
class SpoolConfig:
    """On-disk overflow/replay spool for the durable sender
    (deepflow_tpu/agent/spool.py): frames that would be dropped land in
    CRC-framed segment files and replay on reconnect."""
    enabled: bool = False
    dir: str = ""                 # "" = <tmpdir>/deepflow-spool-<agent_id>
    max_mb: int = 64              # oldest-segment eviction past this
    segment_mb: int = 4
    # age retention: closed segments older than this are evicted (0 =
    # size-only). Bounds how stale a replayed backlog can be after a
    # long server outage; evictions ledger as dropped(spool_age_evict).
    max_age_s: float = 0.0


@dataclass
class SenderConfig:
    servers: list = field(default_factory=lambda: [("127.0.0.1", 20033)])
    queue_size: int = 8192
    # durable transport: per-frame seq + server ACKs + retransmit window
    # (at-least-once; the server dedups). False = legacy fire-and-forget
    # v1 wire for pre-ACK servers.
    durable: bool = True
    # sent-but-unacked frames kept for retransmit after a reconnect
    ack_window: int = 1024
    # replication factor R: ship every HIGH/MID frame to the first R
    # servers (independent seq/ack/spool per destination) so a dead
    # primary's frames land durably on a replica. 1 = single-copy
    # (plain UniformSender, pre-replication behavior). Normally pushed
    # down from the controller's ring via analyzer_addrs.
    replication: int = 1
    spool: SpoolConfig = field(default_factory=SpoolConfig)


@dataclass
class ChaosConfig:
    """Deterministic transport fault injection (deepflow_tpu/chaos.py).
    The DF_CHAOS env knob overrides this block; both use per-call
    probabilities in [0,1]. Never enable in production — this exists so
    the chaos harness can prove the loss bounds hold."""
    enabled: bool = False
    seed: int = 0
    conn_refuse: float = 0.0
    conn_reset: float = 0.0
    partial_write: float = 0.0
    latency_ms: float = 0.0
    disk_full: float = 0.0


@dataclass
class BackpressureConfig:
    """Server-directed degradation (deepflow_tpu/qos): each Sync/Push
    response carries the ingest tier's pressure level for this agent's
    org (0 nominal .. 3 critical); the agent scales its own emission
    down by the level-indexed factors below. Level 0 restores the
    configured baselines exactly."""
    enabled: bool = True
    # one factor per pressure level 0..3, applied to the CONFIGURED
    # value (never compounded)
    hz_scale: list = field(
        default_factory=lambda: [1.0, 0.5, 0.25, 0.1])
    emit_scale: list = field(
        default_factory=lambda: [1.0, 1.0, 2.0, 4.0])
    topk_scale: list = field(
        default_factory=lambda: [1.0, 1.0, 0.5, 0.2])
    trace_scale: list = field(
        default_factory=lambda: [1.0, 1.0, 2.0, 4.0])


@dataclass
class SelfmonConfig:
    """Self-telemetry spine: frame ledger + heartbeats + deadman
    (deepflow_tpu/telemetry.py). Also disabled globally by
    DF_NO_SELFMON=1."""
    enabled: bool = True
    # a stage with no heartbeat for this long is flagged wedged (its
    # stack is snapshotted and shipped via dfstats)
    deadman_window_s: float = 15.0
    check_interval_s: float = 0.0   # 0 = deadman_window_s / 4


@dataclass
class AgentConfig:
    agent_id: int = 0
    app_service: str = ""
    # AF_UNIX path for the LD_PRELOAD ssl/syscall probe (pre-encryption L7
    # visibility); "" = disabled
    sslprobe_sock: str = ""
    # AF_UNIX path for the LD_PRELOAD malloc interposer (out-of-process
    # allocation flame graphs, libdfmemhook.so); "" = disabled
    memhook_sock: str = ""
    # agent-side ACLs (reference: policy first_path rules): list of dicts
    # {cidr, port, protocol, action: trace|ignore|pcap|npb} — pcap and
    # npb imply trace and additionally capture/forward matched PACKETS
    # (frame-visible paths: replay + socket capture mode)
    acls: list = field(default_factory=list)
    # NPB packet broker target for action=npb ACLs (reference:
    # plugins/npb_sender): matched frames are VXLAN-encapsulated to
    # host:port; "" disables forwarding
    npb_target: str = ""
    npb_vni: int = 1
    # parser plugin modules (reference: wasm plugin hooks): each exports
    # PARSERS = [L7Parser subclasses], registered ahead of builtins
    plugins: list = field(default_factory=list)
    group: str = "default"        # agent-group for config routing
    controller: str = ""          # host:port; empty = standalone mode
    standalone: bool = True
    # /proc socket-inode scan feeding GpidSync: flow logs get process
    # identity (gpid + comm) for ANY local process, no preload required.
    # 0 disables. Needs a controller (entries ride the sync plane).
    socket_scan_interval_s: float = 30.0
    profiler: ProfilerConfig = field(default_factory=ProfilerConfig)
    tpuprobe: TpuProbeConfig = field(default_factory=TpuProbeConfig)
    guard: GuardConfig = field(default_factory=GuardConfig)
    flow: FlowConfig = field(default_factory=FlowConfig)
    integration: IntegrationConfig = field(
        default_factory=IntegrationConfig)
    sender: SenderConfig = field(default_factory=SenderConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    selfmon: SelfmonConfig = field(default_factory=SelfmonConfig)
    qos: BackpressureConfig = field(default_factory=BackpressureConfig)
    stats_interval_s: float = 10.0
    sync_interval_s: float = 10.0

    @classmethod
    def from_dict(cls, d: dict) -> "AgentConfig":
        cfg = cls()
        if isinstance(d.get("profiler"), dict):
            cfg.profiler = ProfilerConfig(**d["profiler"])
        if isinstance(d.get("tpuprobe"), dict):
            cfg.tpuprobe = TpuProbeConfig(**d["tpuprobe"])
        if isinstance(d.get("guard"), dict):
            cfg.guard = GuardConfig(**d["guard"])
        if isinstance(d.get("integration"), dict):
            cfg.integration = IntegrationConfig(**d["integration"])
        if isinstance(d.get("flow"), dict):
            cfg.flow = FlowConfig(**d["flow"])
        if isinstance(d.get("sender"), dict):
            sd = dict(d["sender"])
            if "servers" in sd:
                sd["servers"] = [
                    tuple(x) if isinstance(x, (list, tuple))
                    else _parse_addr(x) for x in sd["servers"]]
            if isinstance(sd.get("spool"), dict):
                sd["spool"] = SpoolConfig(**sd["spool"])
            cfg.sender = SenderConfig(**sd)
        if isinstance(d.get("chaos"), dict):
            cfg.chaos = ChaosConfig(**d["chaos"])
        if isinstance(d.get("selfmon"), dict):
            cfg.selfmon = SelfmonConfig(**d["selfmon"])
        if isinstance(d.get("qos"), dict):
            cfg.qos = BackpressureConfig(**d["qos"])
        for f in dataclasses.fields(cls):
            if f.name in ("profiler", "tpuprobe", "guard", "integration",
                          "flow", "sender", "chaos", "selfmon", "qos"):
                continue
            if f.name in d:
                setattr(cfg, f.name, d[f.name])
        return cfg

    def validate(self) -> "AgentConfig":
        """Type/range checks (reference: template.yaml-driven validation)."""
        def num(v, name, lo=None, hi=None):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"{name} must be a number, got {v!r}")
            if lo is not None and v < lo:
                raise ValueError(f"{name} must be >= {lo}, got {v}")
            if hi is not None and v > hi:
                raise ValueError(f"{name} must be <= {hi}, got {v}")

        num(self.profiler.sample_hz, "profiler.sample_hz", 0.1, 10_000)
        num(self.profiler.emit_interval_s, "profiler.emit_interval_s", 0.01)
        num(self.profiler.memory_interval_s, "profiler.memory_interval_s", 1)
        num(self.tpuprobe.trace_interval_s, "tpuprobe.trace_interval_s", 0.1)
        num(self.tpuprobe.trace_duration_ms, "tpuprobe.trace_duration_ms", 1)
        num(self.tpuprobe.target_coverage, "tpuprobe.target_coverage",
            0.01, 0.95)
        num(self.tpuprobe.steps_per_capture, "tpuprobe.steps_per_capture",
            1, 10_000)
        num(self.tpuprobe.step_topk, "tpuprobe.step_topk", 1, 100)
        num(self.stats_interval_s, "stats_interval_s", 0.1)
        num(self.sync_interval_s, "sync_interval_s", 0.1)
        num(self.selfmon.deadman_window_s, "selfmon.deadman_window_s", 0.1)
        num(self.selfmon.check_interval_s, "selfmon.check_interval_s", 0)
        num(self.sender.queue_size, "sender.queue_size", 1)
        num(self.sender.ack_window, "sender.ack_window", 1)
        num(self.sender.replication, "sender.replication", 1, 8)
        num(self.sender.spool.max_mb, "sender.spool.max_mb", 1)
        num(self.sender.spool.segment_mb, "sender.spool.segment_mb", 1)
        if self.sender.spool.segment_mb > self.sender.spool.max_mb:
            raise ValueError(
                "sender.spool.segment_mb must be <= sender.spool.max_mb "
                "(the cap must hold at least one segment)")
        if not isinstance(self.qos.enabled, bool):
            raise ValueError(
                f"qos.enabled must be a bool, got {self.qos.enabled!r}")
        for sname in ("hz_scale", "emit_scale", "topk_scale",
                      "trace_scale"):
            scales = getattr(self.qos, sname)
            if not isinstance(scales, (list, tuple)) or len(scales) != 4:
                raise ValueError(
                    f"qos.{sname} must be 4 factors (levels 0..3), "
                    f"got {scales!r}")
            for i, v in enumerate(scales):
                num(v, f"qos.{sname}[{i}]", 0.001, 1000)
        for p in ("conn_refuse", "conn_reset", "partial_write", "disk_full"):
            num(getattr(self.chaos, p), f"chaos.{p}", 0.0, 1.0)
        num(self.chaos.latency_ms, "chaos.latency_ms", 0)
        num(self.guard.max_cpu_pct, "guard.max_cpu_pct", 1)
        num(self.guard.max_mem_mb, "guard.max_mem_mb", 16)
        num(self.guard.check_interval_s, "guard.check_interval_s", 0.1)
        import ipaddress as _ipaddr
        for i, a in enumerate(self.acls):
            if not isinstance(a, dict):
                raise ValueError(f"acls[{i}] must be a mapping, got {a!r}")
            if a.get("action", "trace") not in ("trace", "ignore",
                                                "pcap", "npb"):
                raise ValueError(
                    f"acls[{i}].action must be trace|ignore|pcap|npb")
            if a.get("cidr"):
                try:
                    _ipaddr.ip_network(a["cidr"], strict=False)
                except ValueError as e:
                    raise ValueError(f"acls[{i}].cidr invalid: {e}") from None
            num(a.get("port", 0), f"acls[{i}].port", 0, 65535)
            num(a.get("protocol", 0), f"acls[{i}].protocol", 0, 3)
        if self.tpuprobe.source not in ("auto", "xplane", "hooks", "sim"):
            raise ValueError(
                f"tpuprobe.source must be auto|xplane|hooks|sim, "
                f"got {self.tpuprobe.source!r}")
        if self.flow.capture_mode not in ("local", "mirror", "analyzer"):
            raise ValueError(
                f"flow.capture_mode must be local|mirror|analyzer, "
                f"got {self.flow.capture_mode!r}")
        if self.flow.capture_mode in ("mirror", "analyzer") and \
                not self.flow.interface:
            raise ValueError(
                f"flow.capture_mode={self.flow.capture_mode} needs "
                "flow.interface: promiscuous mode is per-NIC, and an "
                "analyzer NIC must be named (capturing 'all' would "
                "include this host's own telemetry with exclusions off)")
        for b, name in ((self.profiler.enabled, "profiler.enabled"),
                        (self.tpuprobe.enabled, "tpuprobe.enabled"),
                        (self.tpuprobe.step_metrics,
                         "tpuprobe.step_metrics"),
                        (self.sender.durable, "sender.durable"),
                        (self.sender.spool.enabled, "sender.spool.enabled"),
                        (self.chaos.enabled, "chaos.enabled"),
                        (self.selfmon.enabled, "selfmon.enabled"),
                        (self.standalone, "standalone")):
            if not isinstance(b, bool):
                raise ValueError(f"{name} must be a bool, got {b!r}")
        return self

    @classmethod
    def load(cls, path: str | None = None) -> "AgentConfig":
        if path is None or not os.path.exists(path):
            return cls()
        import yaml
        with open(path) as f:
            data = yaml.safe_load(f) or {}
        return cls.from_dict(data)


DEFAULT_INGEST_PORT = 20033


_TEMPLATE_DOCS = {
    "agent_id": "0 = controller-assigned",
    "app_service": "logical service name (defaults to process name)",
    "group": "agent-group for config routing",
    "controller": "host:port; empty = standalone mode",
    "sslprobe_sock": "AF_UNIX path for the LD_PRELOAD ssl probe; empty=off",
    "acls": "policy rules: [{cidr, port, protocol, action: "
            "trace|ignore|pcap|npb}]; pcap/npb also capture/forward "
            "matched packets",
    "plugins": "parser plugin modules exporting PARSERS",
    "profiler.sample_hz": "OnCPU sampling rate",
    "profiler.external_pids": "out-of-process perf targets (any pid)",
    "tpuprobe.source": "auto | xplane | hooks | sim",
    "tpuprobe.target_coverage": "fraction of steps captured (0.01-0.95)",
    "tpuprobe.steps_per_capture": "whole steps per capture window",
    "tpuprobe.step_metrics": "emit per-(run_id, step) STEP_METRICS rollups",
    "tpuprobe.step_topk": "HLO self-times kept per step record",
    "flow.interface": "capture interface; empty = all",
    "flow.exclude_ports": "never capture these ports (feedback guard)",
    "sender.servers": "ingest endpoints, failover order",
    "sender.durable": "per-frame seq + server ACK + retransmit "
                      "(at-least-once); false = legacy v1 fire-and-forget",
    "sender.ack_window": "sent-but-unacked frames kept for retransmit",
    "sender.replication": "ship HIGH/MID frames to the first R servers "
                          "(per-destination seq/ack/spool); 1 = "
                          "single-copy",
    "sender.spool.enabled": "spill overflow/unsent frames to disk and "
                            "replay them on reconnect",
    "sender.spool.dir": "segment directory; empty = tmpdir",
    "sender.spool.max_mb": "spool cap; oldest segment evicted (and "
                           "ledgered as dropped) past this",
    "sender.spool.segment_mb": "rotate segment files at this size",
    "sender.spool.max_age_s": "evict closed segments older than this "
                              "(dropped(spool_age_evict)); 0 = "
                              "size-only retention",
    "chaos.enabled": "transport fault injection (tests only); the "
                     "DF_CHAOS env spec overrides this block",
    "chaos.seed": "PRNG seed — same seed, same fault schedule",
    "selfmon.deadman_window_s": "flag a stage wedged after this many "
                                "seconds without a heartbeat",
    "selfmon.check_interval_s": "deadman scan cadence; 0 = window/4",
    "qos.enabled": "honor server backpressure directives "
                   "(SyncResponse.qos pressure level 0..3)",
    "qos.hz_scale": "profiler sample_hz factor per pressure level 0..3",
    "qos.emit_scale": "profile emit-interval factor per level (bigger "
                      "window = fewer, larger frames)",
    "qos.topk_scale": "step-metrics HLO top-K factor per level",
    "qos.trace_scale": "tpuprobe trace interval / steps-per-capture "
                       "factor per level",
}


def render_template() -> str:
    """Documented YAML template generated FROM the dataclasses (reference:
    the 6535-line template.yaml that validates agent-group configs —
    here the dataclass is the single source of truth, so template and
    validation can't drift)."""
    import dataclasses
    lines = ["# deepflow-tpu agent configuration template",
             "# generated from AgentConfig (single source of truth);",
             "# every value shows its default — uncomment to override.",
             ""]

    def emit(obj, prefix: str, indent: str) -> None:
        for f in dataclasses.fields(obj):
            val = getattr(obj, f.name)
            key = f"{prefix}{f.name}" if prefix else f.name
            doc = _TEMPLATE_DOCS.get(key)
            if dataclasses.is_dataclass(val):
                lines.append(f"{indent}{f.name}:")
                emit(val, f"{key}.", indent + "  ")
                continue
            if doc:
                lines.append(f"{indent}# {doc}")
            if isinstance(val, (list, tuple)):
                import json as _j
                shown = _j.dumps([list(v) if isinstance(v, tuple) else v
                                  for v in val])
            elif isinstance(val, bool):
                shown = "true" if val else "false"
            else:
                shown = repr(val) if isinstance(val, str) else str(val)
            lines.append(f"{indent}{f.name}: {shown}")
        lines.append("")

    emit(AgentConfig(), "", "")
    return "\n".join(lines)


def _parse_addr(s: str) -> tuple[str, int]:
    host, sep, port = s.rpartition(":")
    if not sep:
        return (s or "127.0.0.1", DEFAULT_INGEST_PORT)
    try:
        return (host or "127.0.0.1", int(port))
    except ValueError:
        raise ValueError(f"bad server address {s!r}: expected host[:port]"
                         ) from None
