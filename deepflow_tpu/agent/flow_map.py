"""FlowMap: MetaPacket stream -> flows, perf stats, L7 session logs.

Reference analog: agent/src/flow_generator/flow_map.rs (FlowMap::new :255,
inject_meta_packet :716, flush :2015), flow_state.rs (TCP FSM), perf/tcp.rs
(RTT/ART), protocol_logs/parser.rs:368 (SessionQueue request/response
matching).
"""

from __future__ import annotations

import heapq
import ipaddress
import time
from collections import deque
from dataclasses import dataclass, field
from enum import IntEnum

from deepflow_tpu.agent.packet import MetaPacket, TcpFlags
from deepflow_tpu.agent.protocol_logs.base import (
    MSG_REQUEST, MSG_RESPONSE, L7ParseResult, get_parser, infer_and_parse)
from deepflow_tpu.proto import pb


class FlowState(IntEnum):
    INIT = 0
    SYN_SENT = 1
    SYN_ACK = 2
    ESTABLISHED = 3
    FIN_1 = 4
    CLOSED = 5
    RST = 6


CLOSE_TYPE = {FlowState.CLOSED: "fin", FlowState.RST: "rst"}

# common service ports for the no-SYN direction heuristic
KNOWN_SERVER_PORTS = frozenset({
    22, 25, 53, 80, 88, 110, 143, 389, 443, 465, 587, 993, 995, 1433, 1521,
    2379, 3000, 3306, 4222, 5000, 5432, 5672, 6379, 8000, 8080, 8443, 8888,
    9000, 9090, 9092, 9200, 11211, 27017, 50051})


@dataclass
class DirectionStats:
    packets: int = 0
    bytes: int = 0
    tcp_flags_bits: int = 0
    retrans: int = 0
    zero_window: int = 0
    max_seq: int = 0
    max_payload_seq: int | None = None  # None = no payload seen; 0 is a
                                        # legitimate post-wrap value


@dataclass
class PendingRequest:
    timestamp_ns: int
    record: L7ParseResult
    syscall_trace_id: int = 0   # thread chain id of the carrying packet
    tid: int = 0


@dataclass
class FlowNode:
    flow_id: int
    ip_src: bytes              # client side (flow initiator)
    ip_dst: bytes
    port_src: int
    port_dst: int
    protocol: int
    start_ns: int
    tap_port: int = 0
    tunnel_type: int = 0       # stripped outer tunnel (0 = none)
    tunnel_id: int = 0
    end_ns: int = 0
    state: FlowState = FlowState.INIT
    tx: DirectionStats = field(default_factory=DirectionStats)  # client->srv
    rx: DirectionStats = field(default_factory=DirectionStats)
    syn_count: int = 0
    synack_count: int = 0
    syn_ns: int = 0
    synack_ns: int = 0
    rtt_us: int = 0
    art_sum_us: int = 0
    art_count: int = 0
    l7_protocol: int = pb.L7_UNKNOWN
    l7_inferred: bool = False
    l7_infer_attempts: int = 0
    l7_request: int = 0
    l7_response: int = 0
    pending: deque = field(default_factory=deque)   # PendingRequest FIFO
    pending_by_id: dict = field(default_factory=dict)
    close_type: str = "unknown"
    new_flow_reported: bool = False

    def ip_src_str(self) -> str:
        return str(ipaddress.ip_address(self.ip_src))

    def ip_dst_str(self) -> str:
        return str(ipaddress.ip_address(self.ip_dst))


@dataclass
class L7Record:
    """A matched (or lone) request/response pair ready to become a row."""
    flow: FlowNode
    request: L7ParseResult | None
    response: L7ParseResult | None
    start_ns: int
    end_ns: int
    # uprobe-source chaining (sslprobe): links this record to others the
    # same thread produced, without W3C headers
    syscall_trace_id_request: int = 0
    syscall_trace_id_response: int = 0
    syscall_thread_0: int = 0   # request-side tid
    syscall_thread_1: int = 0   # response-side tid


class FlowMap:
    """Single-threaded flow table (shard it per dispatcher, like the
    reference's per-queue FlowMaps)."""

    FLOW_TIMEOUT_NS = {
        FlowState.INIT: 5_000_000_000,
        FlowState.SYN_SENT: 5_000_000_000,
        FlowState.SYN_ACK: 5_000_000_000,
        FlowState.ESTABLISHED: 300_000_000_000,
        FlowState.FIN_1: 30_000_000_000,
    }
    MAX_PENDING = 128
    # L7 inference budget (reference: per-endpoint inference verdict table
    # with inference_max_retries, server/agent_config/template.yaml:4276 —
    # redesigned as a per-flow attempt budget plus a negative per-endpoint
    # cache so fleets of unparseable flows to one service stop paying the
    # full parser sweep)
    INFER_MAX_ATTEMPTS = 5
    INFER_ENDPOINT_FAILS = 16     # flow give-ups before the endpoint caches
    INFER_RETRY_EVERY = 64        # periodic re-probe of a cached endpoint
    INFER_CACHE_CAP = 65536

    def __init__(self, on_l4_log=None, on_l7_log=None, on_flow_update=None,
                 agent_id: int = 0, max_flows: int = 1 << 16) -> None:
        self.flows: dict[tuple, FlowNode] = {}
        # (ip_dst, port_dst, protocol) -> consecutive inference failures
        self._infer_fails: dict[tuple, int] = {}
        self.on_l4_log = on_l4_log or (lambda f: None)
        self.on_l7_log = on_l7_log or (lambda r: None)
        self.on_flow_update = on_flow_update or (lambda f, closed: None)
        self.agent_id = agent_id
        self.max_flows = max_flows
        self._next_flow_id = 1
        # lazy-deletion min-heap of (end_ns, tiebreak, key) for O(log n)
        # eviction under churn (reference uses time-wheel expiry)
        self._evict_heap: list[tuple[int, int, tuple]] = []
        self._heap_seq = 0
        self.stats = {"packets": 0, "flows_created": 0, "flows_closed": 0,
                      "l7_records": 0, "evicted": 0}

    # -- ingest ---------------------------------------------------------------

    def inject(self, p: MetaPacket) -> None:
        self.stats["packets"] += 1
        node, is_tx = self._lookup_or_create(p)
        if node is None:
            return
        node.end_ns = p.timestamp_ns
        d = node.tx if is_tx else node.rx
        d.packets += 1
        d.bytes += p.packet_len
        if p.protocol == 1:
            self._tcp_update(node, p, d, is_tx)
        if p.payload:
            self._l7_update(node, p, is_tx)

    def _lookup_or_create(self, p: MetaPacket):
        node = self.flows.get(p.key)
        if node is not None:
            return node, True
        node = self.flows.get(p.reverse_key)
        if node is not None:
            return node, False
        if len(self.flows) >= self.max_flows:
            self._evict_oldest()
        # direction heuristic when no SYN is seen (mid-stream pickup):
        # a well-known/privileged source port marks the SERVER side
        if p.protocol == 1 and not (p.tcp_flags & TcpFlags.SYN):
            src_is_server = (p.port_src in KNOWN_SERVER_PORTS
                             or p.port_src < 1024) and not (
                p.port_dst in KNOWN_SERVER_PORTS or p.port_dst < 1024)
            if src_is_server:
                node = self._new_node(p, flipped=True)
                self.flows[p.reverse_key] = node
                self._heap_push(p.reverse_key, node)
                return node, False
        node = self._new_node(p, flipped=False)
        self.flows[p.key] = node
        self._heap_push(p.key, node)
        return node, True

    def _heap_push(self, key: tuple, node: FlowNode) -> None:
        self._heap_seq += 1
        heapq.heappush(self._evict_heap,
                       (node.end_ns or node.start_ns, self._heap_seq, key))

    def _new_node(self, p: MetaPacket, flipped: bool) -> FlowNode:
        fid = self._next_flow_id
        self._next_flow_id += 1
        self.stats["flows_created"] += 1
        if flipped:
            return FlowNode(
                flow_id=fid, ip_src=p.ip_dst, ip_dst=p.ip_src,
                port_src=p.port_dst, port_dst=p.port_src,
                protocol=p.protocol, start_ns=p.timestamp_ns,
                tap_port=p.tap_port, tunnel_type=p.tunnel_type,
                tunnel_id=p.tunnel_id)
        return FlowNode(
            flow_id=fid, ip_src=p.ip_src, ip_dst=p.ip_dst,
            port_src=p.port_src, port_dst=p.port_dst,
            protocol=p.protocol, start_ns=p.timestamp_ns,
            tap_port=p.tap_port, tunnel_type=p.tunnel_type,
            tunnel_id=p.tunnel_id)

    def _evict_oldest(self) -> None:
        # pop stale heap entries until one matches a live, un-refreshed flow
        while self._evict_heap:
            end_ns, _, key = heapq.heappop(self._evict_heap)
            node = self.flows.get(key)
            if node is None:
                continue  # flow already closed; stale entry
            if node.end_ns > end_ns:
                self._heap_push(key, node)  # saw traffic since; re-file
                continue
            del self.flows[key]
            node.close_type = "forced"
            self._close(node)
            self.stats["evicted"] += 1
            return
        # heap exhausted (shouldn't happen) — fall back to linear scan
        if self.flows:
            oldest_key = min(self.flows, key=lambda k: self.flows[k].end_ns)
            node = self.flows.pop(oldest_key)
            node.close_type = "forced"
            self._close(node)
            self.stats["evicted"] += 1

    # -- TCP state machine + perf ---------------------------------------------

    def _tcp_update(self, node: FlowNode, p: MetaPacket,
                    d: DirectionStats, is_tx: bool) -> None:
        flags = p.tcp_flags
        d.tcp_flags_bits |= flags
        if p.window == 0 and not (flags & TcpFlags.RST):
            d.zero_window += 1
        # retransmission: payload strictly behind the high-water mark, using
        # 32-bit serial-number arithmetic so 2^32 seq wraps (~4 GB) don't
        # produce false-retrans bursts (reference: flow_generator/perf/tcp.rs
        # seq-window logic)
        if p.payload:
            end_seq = (p.seq + len(p.payload)) & 0xFFFFFFFF
            if d.max_payload_seq is not None:
                behind = (d.max_payload_seq - p.seq) & 0xFFFFFFFF
                if 0 < behind < 0x80000000:
                    d.retrans += 1  # segment starts before the high-water mark
                else:
                    d.max_payload_seq = end_seq
            else:
                d.max_payload_seq = end_seq
        if flags & TcpFlags.RST:
            node.state = FlowState.RST
            node.close_type = "rst"
            return
        syn = bool(flags & TcpFlags.SYN)
        ack = bool(flags & TcpFlags.ACK)
        fin = bool(flags & TcpFlags.FIN)
        if syn and not ack:
            node.syn_count += 1
            if node.state == FlowState.INIT:
                node.state = FlowState.SYN_SENT
                node.syn_ns = p.timestamp_ns
        elif syn and ack:
            node.synack_count += 1
            if node.state == FlowState.SYN_SENT:
                node.state = FlowState.SYN_ACK
                node.synack_ns = p.timestamp_ns
        elif fin:
            if node.state in (FlowState.ESTABLISHED, FlowState.SYN_ACK,
                              FlowState.INIT):
                node.state = FlowState.FIN_1
            elif node.state == FlowState.FIN_1:
                node.state = FlowState.CLOSED
                node.close_type = "fin"
        elif ack:
            if node.state == FlowState.SYN_ACK:
                node.state = FlowState.ESTABLISHED
                if node.syn_ns and node.synack_ns:
                    node.rtt_us = max(
                        0, (p.timestamp_ns - node.syn_ns) // 1000)
            elif node.state == FlowState.INIT:
                # mid-stream pickup (agent started after the handshake):
                # promote so the flow gets the ESTABLISHED idle timeout
                node.state = FlowState.ESTABLISHED

    # -- L7 -------------------------------------------------------------------

    def _l7_update(self, node: FlowNode, p: MetaPacket, is_tx: bool) -> None:
        records: list[L7ParseResult] = []
        if not node.l7_inferred:
            ep = (node.ip_dst, node.port_dst, node.protocol)
            fails = self._infer_fails.get(ep, 0)
            if fails >= self.INFER_ENDPOINT_FAILS:
                # endpoint is known-unparseable: skip the parser sweep,
                # but re-probe periodically so a service that changes
                # protocol on the same port is eventually re-detected
                self._infer_fails[ep] = fails + 1
                if (fails - self.INFER_ENDPOINT_FAILS) \
                        % self.INFER_RETRY_EVERY:
                    node.l7_inferred = True  # give up (stays unknown)
                    return
            proto, records = infer_and_parse(p.payload, node.port_dst)
            node.l7_infer_attempts += 1
            if proto != pb.L7_UNKNOWN:
                node.l7_protocol = proto
                node.l7_inferred = True
                self._infer_fails.pop(ep, None)
            elif node.l7_infer_attempts >= self.INFER_MAX_ATTEMPTS or \
                    node.tx.packets + node.rx.packets > 10:
                node.l7_inferred = True  # give up (stays unknown)
                if len(self._infer_fails) >= self.INFER_CACHE_CAP:
                    self._infer_fails.clear()
                self._infer_fails[ep] = \
                    self._infer_fails.get(ep, 0) + 1
            if not records:
                return
        else:
            parser = get_parser(node.l7_protocol)
            if parser is None:
                return
            try:
                records = parser.parse(p.payload, is_request=is_tx)
            except Exception:
                return
        for rec in records:
            self._session_match(node, rec, p.timestamp_ns,
                                getattr(p, "syscall_trace_id", 0),
                                getattr(p, "tid", 0))

    def _session_match(self, node: FlowNode, rec: L7ParseResult,
                       ts_ns: int, trace_id: int = 0,
                       tid: int = 0) -> None:
        if rec.msg_type == MSG_REQUEST:
            node.l7_request += 1
            if rec.session_less:
                # fire-and-forget message: complete record, no response due
                self._emit_l7(node, rec, None, ts_ns, ts_ns,
                              req_trace=trace_id, req_tid=tid)
                return
            pending = PendingRequest(ts_ns, rec, trace_id, tid)
            if len(node.pending) >= self.MAX_PENDING:
                old = node.pending.popleft()
                node.pending_by_id.pop(old.record.request_id, None)
                self._emit_l7(node, old.record, None, old.timestamp_ns, 0,
                              req_trace=old.syscall_trace_id,
                              req_tid=old.tid)
            node.pending.append(pending)
            if rec.request_id:
                node.pending_by_id[rec.request_id] = pending
        else:
            node.l7_response += 1
            match = None
            if rec.request_id and rec.request_id in node.pending_by_id:
                match = node.pending_by_id.pop(rec.request_id)
                try:
                    node.pending.remove(match)
                except ValueError:
                    pass
            elif node.pending:
                match = node.pending.popleft()
                node.pending_by_id.pop(match.record.request_id, None)
            if match is not None:
                art_us = max(0, (ts_ns - match.timestamp_ns) // 1000)
                node.art_sum_us += art_us
                node.art_count += 1
                self._emit_l7(node, match.record, rec, match.timestamp_ns,
                              ts_ns, req_trace=match.syscall_trace_id,
                              req_tid=match.tid, resp_trace=trace_id,
                              resp_tid=tid)
            else:
                self._emit_l7(node, None, rec, ts_ns, ts_ns,
                              resp_trace=trace_id, resp_tid=tid)

    def _emit_l7(self, node: FlowNode, req: L7ParseResult | None,
                 resp: L7ParseResult | None, start_ns: int,
                 end_ns: int, req_trace: int = 0, req_tid: int = 0,
                 resp_trace: int = 0, resp_tid: int = 0) -> None:
        self.stats["l7_records"] += 1
        self.on_l7_log(L7Record(
            flow=node, request=req, response=resp,
            start_ns=start_ns, end_ns=end_ns or start_ns,
            syscall_trace_id_request=req_trace,
            syscall_trace_id_response=resp_trace,
            syscall_thread_0=req_tid, syscall_thread_1=resp_tid))

    # -- flush / close ---------------------------------------------------------

    def tick(self, now_ns: int | None = None) -> None:
        """Expire idle/closed flows; call periodically (1s)."""
        now = now_ns if now_ns is not None else time.time_ns()
        to_close = []
        for key, node in self.flows.items():
            if node.state in (FlowState.CLOSED, FlowState.RST):
                to_close.append(key)
                continue
            timeout = self.FLOW_TIMEOUT_NS.get(node.state, 60_000_000_000)
            if now - node.end_ns > timeout:
                node.close_type = "timeout"
                to_close.append(key)
        for key in to_close:
            self._close(self.flows.pop(key))
        # bound stale heap entries left behind by tick/flush closures
        if len(self._evict_heap) > 4 * len(self.flows) + 1024:
            self._evict_heap = [
                (n.end_ns or n.start_ns, i, k)
                for i, (k, n) in enumerate(self.flows.items())]
            heapq.heapify(self._evict_heap)
            self._heap_seq = len(self._evict_heap)
        # live flow updates for metering
        for node in self.flows.values():
            self.on_flow_update(node, False)

    def flush_all(self) -> None:
        for key in list(self.flows):
            node = self.flows.pop(key)
            if node.close_type == "unknown":
                node.close_type = "forced"
            self._close(node)

    def _close(self, node: FlowNode) -> None:
        self.stats["flows_closed"] += 1
        # flush unanswered requests
        while node.pending:
            old = node.pending.popleft()
            self._emit_l7(node, old.record, None, old.timestamp_ns, 0,
                          req_trace=old.syscall_trace_id, req_tid=old.tid)
        node.pending_by_id.clear()
        self.on_flow_update(node, True)
        self.on_l4_log(node)
