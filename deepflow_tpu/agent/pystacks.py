"""Remote CPython interpreter stacks via process_vm_readv (py-spy style).

Reference analog: the EE interpreter unwinder
(agent/src/ebpf/kernel/extended/interpreter_unwind.h, hooked from
kernel/perf_profiler.bpf.c) + the thread-state helpers in
agent/crates/trace-utils/src/unwind/tsd.rs. Redesign without eBPF or
version-conditional header bindings: every struct offset is CALIBRATED
empirically against this process's own interpreter using safe
process_vm_readv self-scans (a wild pointer returns EFAULT instead of
faulting), then applied to targets running the same CPython build — the
JAX-fleet case, where observer and workload ship in one image. A target
with a different interpreter build fails closed: no Python frames, native
stacks still flow.

Why this matters here: a JAX host fleet is Python processes. Native-only
out-of-process stacks collapse into _PyEval_EvalFrameDefault and say
nothing; with this module the extprofiler splices real Python function
names over the interpreter-loop frames (VERDICT r03 item 3).
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import threading
from dataclasses import dataclass, field

log = logging.getLogger("df.pystacks")

_PTR_MIN, _PTR_MAX = 0x1000, 0x7FFF_FFFF_FFFF


class _Iovec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p), ("iov_len", ctypes.c_size_t)]


_libc = ctypes.CDLL(None, use_errno=True)
_libc.process_vm_readv.restype = ctypes.c_ssize_t
_libc.process_vm_readv.argtypes = [
    ctypes.c_int, ctypes.POINTER(_Iovec), ctypes.c_ulong,
    ctypes.POINTER(_Iovec), ctypes.c_ulong, ctypes.c_ulong]


class MemReader:
    """Bounded remote reads; wild addresses return None, never fault."""

    def __init__(self, pid: int) -> None:
        self.pid = pid

    def read(self, addr: int, n: int) -> bytes | None:
        if not (_PTR_MIN < addr < _PTR_MAX) or n <= 0:
            return None
        buf = ctypes.create_string_buffer(n)
        local = _Iovec(ctypes.cast(buf, ctypes.c_void_p), n)
        remote = _Iovec(addr, n)
        got = _libc.process_vm_readv(self.pid, ctypes.byref(local), 1,
                                     ctypes.byref(remote), 1, 0)
        if got <= 0:
            return None
        return buf.raw[:got]

    def u64(self, addr: int) -> int | None:
        b = self.read(addr, 8)
        return struct.unpack("<Q", b)[0] if b and len(b) == 8 else None


def _u64(b: bytes, off: int) -> int:
    return struct.unpack_from("<Q", b, off)[0]


@dataclass
class PyOffsets:
    """Empirically calibrated struct offsets for ONE CPython build."""
    version: tuple = ()
    frame_code: int = -1        # _PyInterpreterFrame -> PyCodeObject*
    frame_prev: int = -1        # _PyInterpreterFrame -> previous
    ts_frame: int = -1          # PyThreadState -> (cframe | current_frame)
    ts_frame_indirect: bool = True   # True: deref once (3.11/3.12 cframe)
    ts_interp: int = -1
    ts_next: int = -1           # toward OLDER threads (main is the tail)
    ts_prev: int = -1           # toward NEWER threads (head end)
    ts_native_tid: int = -1
    code_qualname: int = -1
    code_filename: int = -1
    uni_len: int = 16           # PyASCIIObject.length
    uni_data: int = 40          # compact-ascii payload
    runtime_interp_offs: tuple = ()   # _PyRuntime -> interpreters.{head,main}
    interp_head_offs: tuple = ()      # PyInterpreterState -> threads.head

    def complete(self) -> bool:
        return (self.frame_code >= 0 and self.frame_prev >= 0
                and self.ts_frame >= 0 and self.ts_interp >= 0
                and self.ts_next >= 0 and self.ts_prev >= 0
                and self.ts_native_tid >= 0
                and self.code_qualname >= 0 and self.code_filename >= 0
                and bool(self.runtime_interp_offs)
                and bool(self.interp_head_offs))


class _CalibrationError(RuntimeError):
    pass


class _QualProbe:
    """Method whose co_qualname differs from co_name, so the qualname scan
    can't alias the co_name slot."""

    def method_with_distinct_qualname(self):  # pragma: no cover - trivial
        pass


def _calibrate() -> PyOffsets:
    """Discover every offset by scanning OUR OWN interpreter state with
    ground truth from ctypes.pythonapi. All reads go through
    process_vm_readv(self), so candidate pointers that are garbage fail
    with EFAULT instead of crashing the agent."""
    import sys

    rd = MemReader(os.getpid())
    off = PyOffsets(version=tuple(sys.version_info[:3]))

    ctypes.pythonapi.PyThreadState_Get.restype = ctypes.c_void_p
    ctypes.pythonapi.PyInterpreterState_Get.restype = ctypes.c_void_p

    # -- interpreter-frame shape, via our own PyFrameObject ----------------
    frame_obj = sys._getframe()
    my_code = id(frame_obj.f_code)
    caller_code = id(sys._getframe(1).f_code) if frame_obj.f_back else 0
    fo_buf = rd.read(id(frame_obj), 128)
    if fo_buf is None:
        raise _CalibrationError("cannot read own frame object")
    iframe = -1
    for o in range(0, 120, 8):
        p = _u64(fo_buf, o)
        fb = rd.read(p, 128) if _PTR_MIN < p < _PTR_MAX else None
        if fb is None:
            continue
        for co in range(0, 120, 8):
            if _u64(fb, co) == my_code:
                iframe, off.frame_code = p, co
                break
        if iframe >= 0:
            break
    if iframe < 0:
        raise _CalibrationError("no f_frame/f_code linkage found")
    fb = rd.read(iframe, 128)
    for po in range(0, 120, 8):
        q = _u64(fb, po)
        if _PTR_MIN < q < _PTR_MAX and q != iframe:
            qb = rd.read(q, off.frame_code + 8)
            if qb and len(qb) >= off.frame_code + 8 and \
                    _u64(qb, off.frame_code) == caller_code:
                off.frame_prev = po
                break
    if off.frame_prev < 0:
        raise _CalibrationError("no frame->previous linkage found")

    def frame_chain(start: int, limit: int = 64) -> list[int]:
        out, f = [], start
        while _PTR_MIN < f < _PTR_MAX and len(out) < limit:
            out.append(f)
            nxt = rd.u64(f + off.frame_prev)
            if nxt is None:
                break
            f = nxt
        return out

    # -- thread state: frame anchor via PARKED threads ---------------------
    # Scanning a RUNNING thread's state chases its moving current_frame
    # into dead datastack memory. Helper threads park in a known call
    # chain blocked on an Event: their frame chains are frozen, and the
    # scan looks for the parked leaf's code object through the chain.
    ts = ctypes.pythonapi.PyThreadState_Get()
    interp = ctypes.pythonapi.PyInterpreterState_Get()
    known_ts: dict[int, tuple[int, int]] = {}   # ts addr -> (tid, leafcode)
    ready = threading.Semaphore(0)
    ev = threading.Event()

    def park():
        known_ts[ctypes.pythonapi.PyThreadState_Get()] = (
            threading.get_native_id(), id(sys._getframe().f_code))
        ready.release()
        ev.wait()

    threads = [threading.Thread(target=park, daemon=True) for _ in range(2)]
    for t in threads:
        t.start()
    for _ in threads:
        ready.acquire(timeout=5)
    try:
        def chain_has_code(start: int, code_id: int) -> bool:
            return any(
                (cb := rd.read(f, off.frame_code + 8)) is not None
                and len(cb) >= off.frame_code + 8
                and _u64(cb, off.frame_code) == code_id
                for f in frame_chain(start))

        some_ts, (some_tid, leaf_code) = next(iter(known_ts.items()))
        sb = rd.read(some_ts, 2048)
        if sb is None:
            raise _CalibrationError("cannot read parked thread state")
        for o in range(0, len(sb) - 8, 8):
            v = _u64(sb, o)
            if v == interp and off.ts_interp < 0:
                off.ts_interp = o
            if v == some_tid and off.ts_native_tid < 0:
                off.ts_native_tid = o
            if off.ts_frame >= 0 or not (_PTR_MIN < v < _PTR_MAX):
                continue
            # direct current_frame (3.13+) vs cframe deref (3.11/3.12)
            if chain_has_code(v, leaf_code):
                off.ts_frame, off.ts_frame_indirect = o, False
            else:
                v2 = rd.u64(v)
                if v2 is not None and chain_has_code(v2, leaf_code):
                    off.ts_frame, off.ts_frame_indirect = o, True
        if off.ts_frame < 0 or off.ts_interp < 0:
            raise _CalibrationError("no tstate frame/interp anchor found")
        all_ts = set(known_ts) | {ts}

        def walk(head: int, next_off: int, limit: int = 64) -> set[int]:
            seen: set[int] = set()
            cur = head
            while _PTR_MIN < cur < _PTR_MAX and len(seen) < limit \
                    and cur not in seen:
                seen.add(cur)
                nxt = rd.u64(cur + next_off)
                if nxt is None:
                    break
                cur = nxt
            return seen

        # next/prev disambiguation (they are adjacent pointer fields and
        # "a walk reaches other known tstates" is true of BOTH): anchor
        # on the real list HEAD from the C API — only the true `next`
        # offset walks from the head through every live tstate (the
        # head's `prev` is NULL), and only the true `prev` walks back
        # from the next-chain's tail through everything.
        ctypes.pythonapi.PyInterpreterState_ThreadHead.restype = \
            ctypes.c_void_p
        ctypes.pythonapi.PyInterpreterState_ThreadHead.argtypes = \
            [ctypes.c_void_p]
        list_head = ctypes.pythonapi.PyInterpreterState_ThreadHead(interp)
        for cand in range(0, 256, 8):
            if all_ts <= walk(list_head, cand):
                off.ts_next = cand
                break
        if off.ts_next < 0:
            raise _CalibrationError("no tstate next-link found")

        def ordered_walk(start: int, next_off: int) -> list[int]:
            out: list[int] = []
            cur = start
            while _PTR_MIN < cur < _PTR_MAX and len(out) < 256 \
                    and cur not in out:
                out.append(cur)
                nxt = rd.u64(cur + next_off)
                if nxt is None:
                    break
                cur = nxt
            return out

        tail = ordered_walk(list_head, off.ts_next)[-1]
        for cand in range(0, 256, 8):
            if cand != off.ts_next and all_ts <= walk(tail, cand):
                off.ts_prev = cand
                break
        if off.ts_prev < 0:
            raise _CalibrationError("no tstate prev-link found")

        # interp->threads.head: a slot whose walk visits ALL known tstates
        ib = rd.read(interp, 4096)
        heads = []
        for o in range(0, len(ib) - 8, 8):
            v = _u64(ib, o)
            if _PTR_MIN < v < _PTR_MAX and \
                    all_ts <= walk(v, off.ts_next):
                heads.append(o)
        if not heads:
            raise _CalibrationError("no interp threads.head found")
        off.interp_head_offs = tuple(heads)
    finally:
        ev.set()

    # -- _PyRuntime -> interpreters --------------------------------------
    runtime = ctypes.addressof(
        ctypes.c_char.in_dll(ctypes.pythonapi, "_PyRuntime"))
    rb = rd.read(runtime, 4096)
    off.runtime_interp_offs = tuple(
        o for o in range(0, len(rb) - 8, 8) if _u64(rb, o) == interp)
    if not off.runtime_interp_offs:
        raise _CalibrationError("interp not found in _PyRuntime")

    # -- code object: qualname / filename --------------------------------
    meth_code = _QualProbe.method_with_distinct_qualname.__code__
    cb = rd.read(id(meth_code), 256)
    for o in range(0, len(cb) - 8, 8):
        v = _u64(cb, o)
        if v == id(meth_code.co_qualname) and off.code_qualname < 0:
            off.code_qualname = o
        elif v == id(meth_code.co_filename) and off.code_filename < 0:
            off.code_filename = o
    if off.code_qualname < 0 or off.code_filename < 0:
        raise _CalibrationError("code qualname/filename not found")

    # -- compact-ascii unicode layout ------------------------------------
    s = "dfprobe_unique_payload"
    ub = rd.read(id(s), 96)
    data_off = ub.find(s.encode())
    if data_off < 0:
        raise _CalibrationError("ascii payload not found in unicode object")
    off.uni_data = data_off
    for o in range(0, data_off - 7, 8):
        if _u64(ub, o) == len(s):
            off.uni_len = o
            break
    if not off.complete():
        raise _CalibrationError(f"incomplete calibration: {off}")
    return off


_OFFSETS: PyOffsets | None = None
_OFFSETS_ERR: str | None = None
_OFFSETS_LOCK = threading.Lock()


def offsets() -> PyOffsets | None:
    """Process-wide calibration result (None when this interpreter defeats
    the scans — remote Python stacks then simply stay off)."""
    global _OFFSETS, _OFFSETS_ERR
    with _OFFSETS_LOCK:
        if _OFFSETS is None and _OFFSETS_ERR is None:
            try:
                _OFFSETS = _calibrate()
            except Exception as e:  # noqa: BLE001 - fail closed
                _OFFSETS_ERR = str(e)
                log.warning("pystacks calibration failed: %s", e)
        return _OFFSETS


# -- ELF data-symbol lookup (the Symbolizer keeps only STT_FUNC) -------------

_SHT_SYMTAB, _SHT_DYNSYM = 2, 11


def _elf_object_symbol(path: str, name: bytes) -> int | None:
    """File vaddr of an STT_OBJECT/any symbol `name`, or None."""
    import mmap as _mmap
    try:
        with open(path, "rb") as f:
            data = _mmap.mmap(f.fileno(), 0, prot=_mmap.PROT_READ)
    except (OSError, ValueError):
        return None
    if data[:4] != b"\x7fELF" or data[4] != 2:
        return None
    (_, _, _, _, _, e_shoff, _, _, _, _, e_shentsize, e_shnum, _) = \
        struct.unpack_from("<HHIQQQIHHHHHH", data, 16)
    sections = []
    for i in range(e_shnum):
        o = e_shoff + i * e_shentsize
        (_, sh_type, _, _, sh_offset, sh_size, sh_link) = \
            struct.unpack_from("<IIQQQQI", data, o)
        sections.append((sh_type, sh_offset, sh_size, sh_link))
    for sh_type, sh_offset, sh_size, sh_link in sections:
        if sh_type not in (_SHT_SYMTAB, _SHT_DYNSYM) or \
                sh_link >= len(sections):
            continue
        _, str_off, str_size, _ = sections[sh_link]
        for o in range(sh_offset, sh_offset + sh_size, 24):
            st_name, = struct.unpack_from("<I", data, o)
            if not st_name:
                continue
            end = data.find(b"\0", str_off + st_name,
                            str_off + str_size)
            if data[str_off + st_name:end] == name:
                value, = struct.unpack_from("<Q", data, o + 8)
                if value:
                    return value
    return None


def _python_image_of(pid: int) -> tuple[str, int, tuple] | None:
    """(access_path, load bias, identity) of a process's libpython /
    python binary — the image that defines _PyRuntime.

    identity is the (dev, inode) straight from that process's own maps
    line, so it is correct across mount namespaces (stat()ing the path
    string in OUR namespace could hit a different file for a
    containerized target); access_path goes through /proc/<pid>/root so
    ELF reads see the target's file, not a same-named host file."""
    from deepflow_tpu.agent.extprofiler import ElfSymbols, _Map
    maps: list[_Map] = []
    idents: dict[str, tuple] = {}
    try:
        with open(f"/proc/{pid}/maps") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 6 or not parts[5].startswith("/"):
                    continue
                a, b = parts[0].split("-")
                maps.append(_Map(start=int(a, 16), end=int(b, 16),
                                 offset=int(parts[2], 16),
                                 path=parts[5]))
                idents.setdefault(parts[5], (parts[3], int(parts[4])))
    except OSError:
        return None
    for m in maps:
        base = os.path.basename(m.path)
        if "libpython" in base or base.startswith("python"):
            access = f"/proc/{pid}/root{m.path}"
            if not os.path.exists(access):
                access = m.path
            if _elf_object_symbol(access, b"_PyRuntime") is None:
                continue
            # load bias is uniform across an object's segments: compute
            # it from any mapping of the file (ELF phdr walk)
            e = ElfSymbols(access)
            first = min((x for x in maps if x.path == m.path),
                        key=lambda x: x.start)
            bias = e.bias_for(first) if e.et_dyn else 0
            return access, bias, idents.get(m.path, ())
    return None


class RemotePython:
    """Reader of one target process's Python thread stacks.

    Requires the target to run the SAME CPython build as this process:
    the target's _PyRuntime-defining image must be the same file
    (st_dev, st_ino) as ours; raises RuntimeError otherwise — calibrated
    offsets from one build must never be applied to another.
    """

    MAX_THREADS = 256
    MAX_DEPTH = 128

    def __init__(self, pid: int) -> None:
        offs = offsets()
        if offs is None:
            raise RuntimeError(f"calibration unavailable: {_OFFSETS_ERR}")
        self.off = offs
        self.pid = pid
        self.rd = MemReader(pid)
        self._code_names: dict[int, str | None] = {}
        self.runtime_addr = self._find_runtime()
        self.stats = {"samples": 0, "threads": 0, "bad_frames": 0}

    def _python_image(self) -> tuple[str, int, tuple] | None:
        return _python_image_of(self.pid)

    def _find_runtime(self) -> int:
        img = self._python_image()
        if img is None:
            raise RuntimeError("target has no python image with _PyRuntime")
        path, bias, ident = img
        ours = _python_image_of(os.getpid())
        if ours is None:
            raise RuntimeError("cannot locate our own python image")
        if not ident or ident != ours[2]:
            raise RuntimeError(
                f"target python build {path} ({ident}) differs from ours "
                f"{ours[0]} ({ours[2]}); calibrated offsets do not transfer")
        vaddr = _elf_object_symbol(path, b"_PyRuntime")
        our = offsets()
        assert our is not None and vaddr is not None
        return bias + vaddr

    # -- sampling ----------------------------------------------------------

    def _read_str(self, addr: int, cap: int = 256) -> str | None:
        """Compact-ASCII PyUnicode payload (code names are ascii in
        practice; anything else fails closed)."""
        head = self.rd.read(addr, self.off.uni_data)
        if head is None or len(head) < self.off.uni_data:
            return None
        n = _u64(head, self.off.uni_len)
        if not 0 < n <= cap:
            return None
        raw = self.rd.read(addr + self.off.uni_data, int(n))
        if raw is None:
            return None
        try:
            s = raw.decode("ascii")
        except UnicodeDecodeError:
            return None
        return s if s.isprintable() else None

    def _code_name(self, code_ptr: int) -> str | None:
        if code_ptr in self._code_names:
            return self._code_names[code_ptr]
        name = None
        cb = self.rd.read(code_ptr,
                          max(self.off.code_qualname,
                              self.off.code_filename) + 8)
        if cb is not None:
            qual = self._read_str(_u64(cb, self.off.code_qualname))
            if qual:
                fn = self._read_str(_u64(cb, self.off.code_filename))
                base = os.path.basename(fn) if fn else "?"
                name = f"{base}:{qual}"
        self._code_names[code_ptr] = name
        return name

    def _thread_stack(self, ts_addr: int) -> list[str]:
        """Root-first Python frames for one thread state."""
        anchor = self.rd.u64(ts_addr + self.off.ts_frame)
        if anchor is None:
            return []
        frame = self.rd.u64(anchor) if self.off.ts_frame_indirect else anchor
        out: list[str] = []
        depth = 0
        while frame and _PTR_MIN < frame < _PTR_MAX and \
                depth < self.MAX_DEPTH:
            depth += 1
            fb = self.rd.read(frame,
                              max(self.off.frame_code,
                                  self.off.frame_prev) + 8)
            if fb is None:
                break
            name = self._code_name(_u64(fb, self.off.frame_code))
            if name is None:
                self.stats["bad_frames"] += 1
            elif "<interpreter trampoline>" not in name:  # shim noise
                out.append(name)
            frame = _u64(fb, self.off.frame_prev)
        out.reverse()
        return out

    def sample(self) -> dict[int, list[str]]:
        """{native_tid: root-first python frames}. Reads are asynchronous
        (no stop-the-world): a torn frame chain yields a truncated stack
        for that one thread, never an error."""
        off = self.off
        interp = head_off = None
        for o in off.runtime_interp_offs:
            cand = self.rd.u64(self.runtime_addr + o)
            if cand is None:
                continue
            # validate: candidate's threads.head walks to tstates whose
            # interp field points back at the candidate; the thread walk
            # below must then use the SAME head offset that validated
            for ho in off.interp_head_offs:
                head = self.rd.u64(cand + ho)
                if head and self.rd.u64(head + off.ts_interp) == cand:
                    interp, head_off = cand, ho
                    break
            if interp is not None:
                break
        if interp is None:
            return {}
        result: dict[int, list[str]] = {}
        seen: set[int] = set()

        def visit(ts: int) -> None:
            tid = self.rd.u64(ts + off.ts_native_tid)
            if tid and tid < 1 << 22:   # plausible Linux tid
                stack = self._thread_stack(ts)
                if stack:
                    result[int(tid)] = stack

        # walk both directions from the head snapshot: `next` covers the
        # whole list from the true head; `prev` additionally catches
        # threads inserted at the head between our head read and now
        head = self.rd.u64(interp + head_off)
        starts = (head,
                  self.rd.u64(head + off.ts_prev) if head else None)
        for link, ts in zip((off.ts_next, off.ts_prev), starts):
            while ts and _PTR_MIN < ts < _PTR_MAX and ts not in seen and \
                    len(seen) < self.MAX_THREADS:
                seen.add(ts)
                visit(ts)
                nxt = self.rd.u64(ts + link)
                ts = nxt if nxt else 0
        self.stats["samples"] += 1
        self.stats["threads"] = len(result)
        return result
