"""Resource guard: the agent polices its own CPU/memory footprint.

Reference analog: agent/src/utils/guard.rs (controller-set cpu/mem/log
limits; throttle or restart on breach) and the exception bitmap reported in
every Sync. Here: breach pauses the profilers (the compressible load),
recovery resumes them; state surfaces through Sync as DEGRADED.
"""

from __future__ import annotations

import logging
import os
import threading
import time

log = logging.getLogger("df.guard")

_CLK_TCK = os.sysconf("SC_CLK_TCK")
_PAGE = os.sysconf("SC_PAGE_SIZE")

EXC_CPU_LIMIT = 1 << 0
EXC_MEM_LIMIT = 1 << 1


def read_self_usage() -> tuple[float, int]:
    """(cpu_seconds_total, rss_bytes) from /proc/self."""
    with open("/proc/self/stat") as f:
        parts = f.read().rsplit(") ", 1)[1].split()
    utime, stime = int(parts[11]), int(parts[12])
    cpu_s = (utime + stime) / _CLK_TCK
    with open("/proc/self/statm") as f:
        rss_pages = int(f.read().split()[1])
    return cpu_s, rss_pages * _PAGE


class Guard:
    def __init__(self, agent, max_cpu_pct: float = 50.0,
                 max_mem_mb: float = 2048.0,
                 check_interval_s: float = 10.0,
                 recover_ratio: float = 0.8) -> None:
        self.agent = agent
        self.max_cpu_pct = max_cpu_pct
        self.max_mem_mb = max_mem_mb
        self.check_interval_s = check_interval_s
        self.recover_ratio = recover_ratio
        self.exception_bitmap = 0
        self.degraded = False
        self.cpu_pct = 0.0
        self.rss_mb = 0.0
        self.stats = {"checks": 0, "degrades": 0, "recoveries": 0}
        self._last: tuple[float, float] | None = None  # (mono, cpu_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Guard":
        self._thread = threading.Thread(
            target=self._run, name="df-guard", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.check()
            except Exception:
                log.exception("guard check failed")

    def check(self, now: float | None = None) -> None:
        self.stats["checks"] += 1
        cpu_s, rss = read_self_usage()
        mono = now if now is not None else time.monotonic()
        if self._last is not None:
            dt = mono - self._last[0]
            if dt > 0:
                self.cpu_pct = 100.0 * (cpu_s - self._last[1]) / dt
        self._last = (mono, cpu_s)
        self.rss_mb = rss / (1024 * 1024)
        self._evaluate()

    def _evaluate(self) -> None:
        over_cpu = self.cpu_pct > self.max_cpu_pct
        over_mem = self.rss_mb > self.max_mem_mb
        self.exception_bitmap = ((EXC_CPU_LIMIT if over_cpu else 0)
                                 | (EXC_MEM_LIMIT if over_mem else 0))
        if not self.degraded and (over_cpu or over_mem):
            self.degraded = True
            self.stats["degrades"] += 1
            log.warning("resource limit hit (cpu %.1f%% rss %.0fMB): "
                        "pausing profilers", self.cpu_pct, self.rss_mb)
            self.agent.pause_profilers()
            if over_mem:
                # best-effort reclaim: CPython rarely returns RSS to the OS,
                # so free what we can and judge memory recovery against the
                # hard limit, not the hysteresis bar (see below)
                import gc
                gc.collect()
        elif self.degraded and \
                self.cpu_pct < self.max_cpu_pct * self.recover_ratio and \
                self.rss_mb <= self.max_mem_mb:
            self.degraded = False
            self.stats["recoveries"] += 1
            log.info("resource usage recovered: resuming profilers")
            # degraded is already False: resume_profilers' guard check passes
            self.agent.resume_profilers()
