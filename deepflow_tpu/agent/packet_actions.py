"""Per-packet ACL actions: on-demand pcap capture and NPB forwarding.

Reference analog: the policy NPB/PCAP actions
(agent/src/policy/ NPB/PCAP ACL actions; agent/plugins/npb_sender — the
ZMQ packet broker stub, lib.rs:22) and the EE pcap policy feeding the
ingester pcap store. TPU redesign: actions run at the FRAME boundary of
the python-visible packet paths (pcap replay — both engines — and the
raw-socket capture fallback); matched packets either accumulate into
rolling captures shipped to the server's pcap store (the existing
PcapUpload plane) or are VXLAN-encapsulated and forwarded to a
third-party broker over UDP. The native TPACKET ring fast path releases
its blocks without surfacing frames, so packet actions there require
the socket capture mode — flows are still traced either way (pcap/npb
ACLs imply trace, only `ignore` suppresses telemetry).
"""

from __future__ import annotations

import gzip
import logging
import socket
import struct
import threading
import time
from collections import deque

from deepflow_tpu.codec import MessageType
from deepflow_tpu.proto import pb

log = logging.getLogger("df.pktactions")

_PCAP_GLOBAL_HDR = struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0,
                               65535, 1)


class PacketActions:
    """Frame-level ACL action executor (pcap | npb)."""

    MAX_BUFFERED = 4096          # frames per capture window
    FLUSH_INTERVAL_S = 10.0
    VXLAN_PORT = 4789

    def __init__(self, labeler, sender=None, agent_id: int = 0,
                 npb_target: str = "", npb_vni: int = 1) -> None:
        self.labeler = labeler
        self.sender = sender
        self.agent_id = agent_id
        self.npb_vni = npb_vni
        self._npb_addr = None
        self._npb_sock = None
        if npb_target:
            host, sep, port = npb_target.rpartition(":")
            if not sep or not port.isdigit():
                # colon-less target or IPv6 literal without a port
                host, port = npb_target, str(self.VXLAN_PORT)
            self._npb_addr = (host.strip("[]") or "127.0.0.1", int(port))
            self._npb_sock = socket.socket(socket.AF_INET,
                                           socket.SOCK_DGRAM)
        self._buf: deque = deque(maxlen=self.MAX_BUFFERED)
        self._lock = threading.Lock()
        self._last_flush = time.monotonic()
        self._capture_seq = 0
        self.stats = {"pcap_frames": 0, "npb_frames": 0,
                      "npb_errors": 0, "uploads": 0, "dropped": 0}

    def enabled(self) -> bool:
        """Cheap per-packet guard: the ACL scan result is cached against
        the labeler's acl_version, so hot paths pay one int compare."""
        if self.labeler is None:
            return False
        version = getattr(self.labeler, "acl_version", 0)
        cached = getattr(self, "_enabled_cache", None)
        if cached is None or cached[0] != version:
            cached = (version, any(
                r.action in ("pcap", "npb")
                for r in getattr(self.labeler, "_acls", [])))
            self._enabled_cache = cached
        return cached[1]

    def handle_frame(self, frame: bytes, ts_ns: int) -> None:
        """Run ACL packet actions for one raw frame (decoded here; the
        callers' hot paths stay untouched when no packet ACLs exist)."""
        from deepflow_tpu.agent.packet import decode_ethernet
        mp = decode_ethernet(frame, timestamp_ns=ts_ns)
        if mp is None:
            return
        self.handle_meta(mp, frame)

    def handle_meta(self, mp, frame: bytes) -> None:
        """Entry point for callers that already decoded the frame (the
        live-capture rx loop) — no second ethernet decode."""
        ts_ns = mp.timestamp_ns
        _, _, action = self.labeler.label_flow(
            mp.ip_src, mp.ip_dst, mp.port_src, mp.port_dst, mp.protocol)
        if action == "pcap":
            self.stats["pcap_frames"] += 1
            with self._lock:
                if len(self._buf) == self._buf.maxlen:
                    self.stats["dropped"] += 1
                self._buf.append((ts_ns, frame))
            self.maybe_flush()
        elif action == "npb":
            self._forward_npb(frame)

    def _forward_npb(self, frame: bytes) -> None:
        """VXLAN-encapsulate and forward to the broker (reference:
        npb_sender VXLAN/ZMQ transport — VXLAN chosen: any standard
        collector decaps it)."""
        if self._npb_sock is None:
            return
        vxlan = struct.pack(">II", 0x08 << 24, self.npb_vni << 8)
        try:
            self._npb_sock.sendto(vxlan + frame, self._npb_addr)
            self.stats["npb_frames"] += 1
        except OSError:
            self.stats["npb_errors"] += 1

    def maybe_flush(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_flush < self.FLUSH_INTERVAL_S \
                and len(self._buf) < self.MAX_BUFFERED:
            return
        self.flush()

    def flush(self) -> None:
        """Ship buffered frames as a pcap to the server's pcap store."""
        with self._lock:
            frames = list(self._buf)
            self._buf.clear()
            self._last_flush = time.monotonic()
        if not frames or self.sender is None:
            return
        out = bytearray(_PCAP_GLOBAL_HDR)
        start_ns = frames[0][0]
        for ts_ns, frame in frames:
            out += struct.pack("<IIII", ts_ns // 1_000_000_000,
                               (ts_ns % 1_000_000_000) // 1000,
                               len(frame), len(frame))
            out += frame
        self._capture_seq += 1
        up = pb.PcapUpload()
        up.name = f"acl-pcap-{self.agent_id}-{self._capture_seq}"
        up.agent_id = self.agent_id
        up.start_ns = start_ns
        up.packet_count = len(frames)
        up.pcap_gz = gzip.compress(bytes(out))
        self.sender.send(MessageType.PCAP, up.SerializeToString())
        self.stats["uploads"] += 1

    def stop(self) -> None:
        self.flush()
        if self._npb_sock is not None:
            self._npb_sock.close()
            self._npb_sock = None
