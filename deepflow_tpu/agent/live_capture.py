"""Live packet capture: AF_PACKET raw socket -> FlowMap.

Reference analog: agent/src/dispatcher/recv_engine (AF_PACKET TPACKET
capture). Plain SOCK_RAW recv loop (mmap ring is an optimization for later);
requires CAP_NET_RAW — the agent degrades to replay/synthetic sources
without it.

Feedback-loop protection: the agent's own telemetry TCP (to the ingester)
and the server's ports are excluded, otherwise capturing our own sender
traffic generates flows that generate more sender traffic.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time

from deepflow_tpu.agent.packet import decode_ethernet

log = logging.getLogger("df.live-capture")

ETH_P_ALL = 0x0003


class LiveCapture:
    def __init__(self, dispatcher, interface: str = "",
                 exclude_ports: tuple = (20033, 20035, 20416),
                 snaplen: int = 65535) -> None:
        self.dispatcher = dispatcher
        self.interface = interface  # "" = all interfaces
        self.exclude_ports = frozenset(exclude_ports)
        self.snaplen = snaplen
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.stats = {"frames": 0, "injected": 0, "excluded": 0,
                      "undecoded": 0}

    def start(self) -> "LiveCapture":
        s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                          socket.htons(ETH_P_ALL))
        if self.interface:
            s.bind((self.interface, 0))
        s.settimeout(0.5)
        self._sock = s
        self._thread = threading.Thread(
            target=self._run, name="df-live-capture", daemon=True)
        self._thread.start()
        log.info("live capture on %r (excluding ports %s)",
                 self.interface or "all", sorted(self.exclude_ports))
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        if self._sock:
            self._sock.close()
            self._sock = None

    def _run(self) -> None:
        sock = self._sock
        while not self._stop.is_set():
            try:
                frame, addr = sock.recvfrom(self.snaplen)
            except socket.timeout:
                continue
            except OSError:
                return
            # addr: (iface, proto, pkttype, hatype, hwaddr); pkttype 4 =
            # outgoing copy — keep both directions but only one copy of
            # loopback traffic (lo duplicates every frame as in+out)
            if addr[0] == "lo" and addr[2] == socket.PACKET_OUTGOING:
                continue
            self.stats["frames"] += 1
            mp = decode_ethernet(frame, timestamp_ns=time.time_ns())
            if mp is None:
                self.stats["undecoded"] += 1
                continue
            if mp.port_src in self.exclude_ports or \
                    mp.port_dst in self.exclude_ports:
                self.stats["excluded"] += 1
                continue
            self.dispatcher.inject(mp)
            self.stats["injected"] += 1
