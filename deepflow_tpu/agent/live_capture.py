"""Live packet capture: TPACKET_V3 mmap ring (native) or AF_PACKET raw
socket (fallback) -> flow map.

Reference analog: agent/src/dispatcher/recv_engine (AF_PACKET TPACKET
capture, recv_engine/mod.rs:40). Preferred path: the C++ TPACKET_V3 ring
feeds the native flow map directly — packets never become Python objects.
Fallback: SOCK_RAW recv loop into the Python FlowMap. Both require
CAP_NET_RAW — the agent degrades to replay/synthetic sources without it.

Feedback-loop protection: the agent's own telemetry TCP (to the ingester)
and the server's ports are excluded, otherwise capturing our own sender
traffic generates flows that generate more sender traffic.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time

from deepflow_tpu.agent.packet import decode_ethernet

log = logging.getLogger("df.live-capture")

ETH_P_ALL = 0x0003


class LiveCapture:
    def __init__(self, dispatcher, interface: str = "",
                 exclude_ports: tuple = (20033, 20035, 20416),
                 snaplen: int = 65535, capture_mode: str = "local") -> None:
        self.dispatcher = dispatcher
        self.interface = interface  # "" = all interfaces
        # capture modes (reference: dispatcher/recv_engine 6 modes):
        # - local: this host's own traffic; self-ports excluded to break
        #   the telemetry feedback loop.
        # - mirror: a SPAN/mirror port carrying OTHER hosts' traffic —
        #   promiscuous. Port exclusions stay: a trunk mirror can include
        #   this host's own uplink.
        # - analyzer: a DEDICATED analyzer NIC fed by remote TAPs —
        #   promiscuous, and NO port exclusions: the NIC never carries
        #   this host's own telemetry, and dropping the monitored
        #   fleet's port-20033 traffic would blind the analyzer to
        #   exactly the infrastructure it watches.
        self.capture_mode = capture_mode
        if capture_mode == "analyzer":
            if not interface:
                log.warning("analyzer mode without an interface captures "
                            "ALL NICs including this host's own; set "
                            "flow.interface to the analyzer port")
            exclude_ports = ()
        self.exclude_ports = frozenset(exclude_ports)
        self.snaplen = snaplen
        self._sock: socket.socket | None = None
        self._ring = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.mode = "none"
        self.stats = {"frames": 0, "injected": 0, "excluded": 0,
                      "undecoded": 0, "ring_drops": 0}

    def start(self) -> "LiveCapture":
        if self._try_start_ring():
            return self
        s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                          socket.htons(ETH_P_ALL))
        if self.interface:
            s.bind((self.interface, 0))
            if self.capture_mode in ("mirror", "analyzer"):
                try:  # struct packet_mreq: ifindex, PACKET_MR_PROMISC
                    import struct as _struct
                    idx = socket.if_nametoindex(self.interface)
                    mreq = _struct.pack("iHH8s", idx, 1, 0, b"")
                    s.setsockopt(263, 1, mreq)  # SOL_PACKET, ADD_MEMBERSHIP
                except OSError as e:
                    log.warning("promiscuous mode failed: %s", e)
        s.settimeout(0.5)
        self._sock = s
        self.mode = "socket"
        self._thread = threading.Thread(
            target=self._run, name="df-live-capture", daemon=True)
        self._thread.start()
        log.info("live capture (SOCK_RAW) on %r (excluding ports %s)",
                 self.interface or "all", sorted(self.exclude_ports))
        return self

    def _try_start_ring(self) -> bool:
        nfm = getattr(self.dispatcher, "native_map", None)
        if nfm is None:
            return False
        try:
            from deepflow_tpu.agent.native_flow import NativeRing
            self._ring = NativeRing(self.interface)
        except Exception as e:
            log.debug("TPACKET ring unavailable (%s); falling back", e)
            return False
        for port in self.exclude_ports:
            nfm.exclude_port(port)
        if self.capture_mode in ("mirror", "analyzer") and self.interface:
            if not self._ring.promisc(self.interface):
                log.warning("promiscuous mode failed on %r; mirror "
                            "capture sees only local traffic",
                            self.interface)
        self.mode = "ring"
        self._thread = threading.Thread(
            target=self._run_ring, name="df-live-capture", daemon=True)
        self._thread.start()
        log.info("live capture (TPACKET_V3 ring, %s mode) on %r "
                 "(excluding ports %s)", self.capture_mode,
                 self.interface or "all", sorted(self.exclude_ports))
        return True

    def _run_ring(self) -> None:
        nfm = self.dispatcher.native_map
        ring = self._ring
        # the dispatcher's flush loop ticks the same native map — every
        # map access must hold its lock (C++ side is single-threaded)
        lock = self.dispatcher._lock
        prev_excluded = nfm.stats["excluded"]
        while not self._stop.is_set():
            try:
                with lock:
                    n = nfm.ring_rx(ring, timeout_ms=0)
                if n == 0:
                    # poll OUTSIDE the lock so flush never waits on capture
                    self._stop.wait(0.05)
                    continue
                st = nfm.stats
                excluded = st["excluded"] - prev_excluded
                prev_excluded = st["excluded"]
                self.stats["frames"] += n
                self.stats["injected"] += n - excluded
                self.stats["excluded"] += excluded
                self.stats["ring_drops"] += ring.drops()
            except Exception:
                log.exception("ring rx failed")
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5.0)
        if self._ring is not None:
            if self._thread is not None and self._thread.is_alive():
                # never free the ring under a live rx thread (use-after-free);
                # leaking it is the safe failure mode
                log.warning("ring thread did not exit; leaking ring handle")
            else:
                self._ring.close()
            self._ring = None
        nfm = getattr(self.dispatcher, "native_map", None)
        if nfm is not None and self.mode == "ring":
            for port in self.exclude_ports:  # don't bleed into pcap replay
                nfm.exclude_port(port, on=False)
        if self._sock:
            self._sock.close()
            self._sock = None

    def _run(self) -> None:
        sock = self._sock
        while not self._stop.is_set():
            try:
                frame, addr = sock.recvfrom(self.snaplen)
            except socket.timeout:
                continue
            except OSError:
                return
            # addr: (iface, proto, pkttype, hatype, hwaddr); pkttype 4 =
            # outgoing copy — keep both directions but only one copy of
            # loopback traffic (lo duplicates every frame as in+out)
            if addr[0] == "lo" and addr[2] == socket.PACKET_OUTGOING:
                continue
            self.stats["frames"] += 1
            mp = decode_ethernet(frame, timestamp_ns=time.time_ns())
            if mp is None:
                self.stats["undecoded"] += 1
                continue
            if mp.port_src in self.exclude_ports or \
                    mp.port_dst in self.exclude_ports:
                self.stats["excluded"] += 1
                continue
            pa = self.dispatcher.packet_actions
            if pa is not None and pa.enabled():
                try:
                    pa.handle_meta(mp, frame)  # reuse the decode above
                except Exception:
                    log.exception("packet action failed")
            self.dispatcher.inject(mp)
            self.stats["injected"] += 1
