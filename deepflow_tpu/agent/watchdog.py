"""Watchdog: parent process that respawns the agent on crash.

Reference analog: agent/src/main.rs:80-88 + agent/src/watchdog.rs (parent
watchdog fork with respawn). Usage:

    python -m deepflow_tpu.agent.watchdog [watchdog opts] -- [agent args...]
"""

from __future__ import annotations

import logging
import signal
import subprocess
import sys
import time

log = logging.getLogger("df.watchdog")


def run(agent_args: list[str], max_restarts: int = 10,
        backoff_s: float = 1.0, backoff_max_s: float = 60.0,
        healthy_reset_s: float = 300.0) -> int:
    """Supervise the agent; restart on abnormal exit with backoff. A child
    that stays up healthy_reset_s resets the restart budget."""
    restarts = 0
    backoff = backoff_s
    child: subprocess.Popen | None = None
    stopping = False

    def on_signal(signum, frame):
        nonlocal stopping
        stopping = True
        if child is not None and child.poll() is None:
            child.terminate()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    while not stopping:
        started = time.monotonic()
        cmd = [sys.executable, "-m", "deepflow_tpu.agent.agent"] + agent_args
        log.info("watchdog: starting agent (attempt %d)", restarts + 1)
        child = subprocess.Popen(cmd)
        code = child.wait()
        uptime = time.monotonic() - started
        if stopping or code == 0:
            return 0
        if uptime >= healthy_reset_s:
            restarts = 0
            backoff = backoff_s
        restarts += 1
        if restarts > max_restarts:
            log.error("watchdog: agent crashed %d times (last code %d); "
                      "giving up", restarts, code)
            return 1
        log.warning("watchdog: agent exited %d after %.1fs; restart in %.1fs",
                    code, uptime, backoff)
        time.sleep(backoff)
        backoff = min(backoff * 2, backoff_max_s)
    return 0


def main() -> int:
    import argparse
    parser = argparse.ArgumentParser(prog="deepflow-tpu-watchdog")
    parser.add_argument("--max-restarts", type=int, default=10)
    parser.add_argument("--backoff", type=float, default=1.0)
    parser.add_argument("agent_args", nargs=argparse.REMAINDER,
                        help="arguments after -- go to the agent")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    agent_args = args.agent_args
    if agent_args and agent_args[0] == "--":
        agent_args = agent_args[1:]
    return run(agent_args, max_restarts=args.max_restarts,
               backoff_s=args.backoff)


if __name__ == "__main__":
    sys.exit(main())
