"""Memory profiler: allocation flame graphs via tracemalloc.

Reference analog: the EE memory profiler (agent/src/ebpf_dispatcher/
memory_profile.rs — an in-Rust allocation ledger feeding memory flame
graphs, extended.h MEMORY profiler flag). In-process Python flavor:
periodic tracemalloc snapshots diffed into per-stack net allocation deltas,
emitted as MEM_ALLOC profile events (value = bytes).
"""

from __future__ import annotations

import threading
import tracemalloc

from deepflow_tpu.agent.profiler import ProfileSample

import time


class MemProfiler:
    """Windowed allocation sampling. value_us carries BYTES for mem-alloc
    events (the Profile.value field is unit-polymorphic, like the
    reference's)."""

    def __init__(self, sink, interval_s: float = 10.0, top_n: int = 64,
                 n_frames: int = 16) -> None:
        self.sink = sink
        self.interval_s = interval_s
        self.top_n = top_n
        self.n_frames = n_frames
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started_tracing = False
        self._prev: tracemalloc.Snapshot | None = None
        self.stats = {"snapshots": 0, "stacks_emitted": 0}
        import os
        self.pid = os.getpid()

    def start(self) -> "MemProfiler":
        if not tracemalloc.is_tracing():
            tracemalloc.start(self.n_frames)
            self._started_tracing = True
        self._thread = threading.Thread(
            target=self._run, name="df-mem-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        if self._started_tracing and tracemalloc.is_tracing():
            tracemalloc.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                pass

    def sample_once(self) -> list[ProfileSample]:
        """Emit NET NEW bytes per stack since the previous snapshot.
        Deltas (not absolutes) keep flame sums meaningful over time: a
        steady 1GB residency contributes once, not once per window.
        The first call only establishes the baseline."""
        snap = tracemalloc.take_snapshot()
        self.stats["snapshots"] += 1
        # own frames + tracemalloc internals excluded
        snap = snap.filter_traces([
            tracemalloc.Filter(False, tracemalloc.__file__),
            tracemalloc.Filter(False, __file__),
        ])
        prev, self._prev = self._prev, snap
        if prev is None:
            return []
        diffs = snap.compare_to(prev, "traceback")
        diffs.sort(key=lambda d: d.size_diff, reverse=True)
        ts = time.time_ns()
        batch = []
        for st in diffs[:self.top_n]:
            if st.size_diff <= 0:
                continue
            frames = []
            for fr in reversed(st.traceback):  # root -> leaf
                frames.append(f"{_modname(fr.filename)}:{fr.lineno}")
            batch.append(ProfileSample(
                timestamp_ns=ts, pid=self.pid, tid=0,
                thread_name="", stack=";".join(frames),
                count=max(1, st.count_diff), value_us=st.size_diff,  # BYTES
                event_type="mem-alloc", profiler="tracemalloc"))
        self.stats["stacks_emitted"] += len(batch)
        if batch:
            self.sink(batch)
        return batch


def _modname(path: str) -> str:
    parts = path.replace("\\", "/").split("/")
    name = parts[-1]
    if name.endswith(".py"):
        name = name[:-3]
    if len(parts) >= 2:
        return f"{parts[-2]}.{name}"
    return name
