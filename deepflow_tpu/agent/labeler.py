"""Agent-side policy/labeler: packet/flow -> resource labels + ACL actions.

Reference analog: agent/src/policy/first_path.rs (trie + interval matching
building a policy from platform data and ACLs) and fast_path.rs (per-tuple
LRU so the second packet of a flow never pays the trie walk). TPU
redesign: labeling runs at FLOW granularity (the fleet's hot path is flows
and HLO spans, not per-packet NPB), sourced from the controller's cluster
resource model (K8s genesis) — which is what makes fleet-scale tag
injection cheap: every agent labels its own flows, the ingester only fills
gaps.
"""

from __future__ import annotations

import ipaddress
import threading
from collections import OrderedDict
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ResourceLabel:
    pod: str = ""
    namespace: str = ""
    workload: str = ""
    node: str = ""


@dataclass
class AclRule:
    """First-path rule. Empty fields match anything."""
    cidr: str = ""               # matches either endpoint
    port: int = 0                # matches either port
    protocol: int = 0            # 1 tcp / 2 udp / 3 icmp
    action: str = "trace"        # trace | ignore
    _net: object = field(default=None, repr=False)

    def net(self):
        if self._net is None and self.cidr:
            self._net = ipaddress.ip_network(self.cidr, strict=False)
        return self._net


class IpTrie:
    """Longest-prefix match for v4 (bit trie) + exact-host table for v6."""

    def __init__(self) -> None:
        self._root: list = [None, None, None]  # [child0, child1, value]
        self._v6: dict[bytes, object] = {}

    def insert(self, cidr: str, value) -> None:
        net = ipaddress.ip_network(cidr, strict=False)
        if net.version == 6:
            # fleet v6 is host-addressed; prefix support can follow need
            self._v6[net.network_address.packed] = value
            return
        bits = int(net.network_address)
        node = self._root
        for i in range(net.prefixlen):
            b = (bits >> (31 - i)) & 1
            if node[b] is None:
                node[b] = [None, None, None]
            node = node[b]
        node[2] = value

    def lookup(self, ip: bytes):
        """Longest-prefix value for a packed address, or None."""
        if len(ip) == 16:
            return self._v6.get(ip)
        if len(ip) != 4:
            return None
        bits = int.from_bytes(ip, "big")
        node = self._root
        best = node[2]
        for i in range(32):
            node = node[(bits >> (31 - i)) & 1]
            if node is None:
                break
            if node[2] is not None:
                best = node[2]
        return best


class Labeler:
    """first_path (trie + ACL scan) with a fast_path LRU over flow tuples."""

    FAST_PATH_CAP = 1 << 16

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._trie = IpTrie()
        self._acls: list[AclRule] = []
        self._fast: OrderedDict[tuple, tuple] = OrderedDict()
        self.version = 0
        self.epoch = 0
        self.stats = {"first_path": 0, "fast_path": 0, "resources": 0,
                      "ignored_flows": 0}

    # -- feed (platform push / config) ----------------------------------------

    def load_resources(self, entries, version: int = 0) -> None:
        """entries: iterable of (cidr, ResourceLabel). Replaces the trie."""
        trie = IpTrie()
        n = 0
        for cidr, label in entries:
            trie.insert(cidr, label)
            n += 1
        with self._lock:
            self._trie = trie
            self._fast.clear()  # labels changed: cached verdicts are stale
            self.version = version
            self.stats["resources"] = n

    def load_acls(self, rules: list[AclRule]) -> None:
        ok = []
        for r in rules:
            try:
                r.net()  # pre-parse: a bad cidr must never reach the
                # flow hot path (the agent main() path skips validate())
            except ValueError as e:
                import logging
                logging.getLogger("df.labeler").warning(
                    "dropping ACL with bad cidr %r: %s", r.cidr, e)
                continue
            ok.append(r)
        with self._lock:
            self._acls = ok
            self._fast.clear()
            self.acl_version = getattr(self, "acl_version", 0) + 1

    # -- lookup ----------------------------------------------------------------

    def label_flow(self, ip_src: bytes, ip_dst: bytes, port_src: int,
                   port_dst: int, protocol: int
                   ) -> tuple[ResourceLabel | None, ResourceLabel | None,
                              str]:
        """-> (src_label, dst_label, action)."""
        key = (ip_src, ip_dst, port_src, port_dst, protocol)
        with self._lock:
            hit = self._fast.get(key)
            if hit is not None:
                self._fast.move_to_end(key)
                self.stats["fast_path"] += 1
                return hit
            self.stats["first_path"] += 1
            src = self._trie.lookup(ip_src)
            dst = self._trie.lookup(ip_dst)
            action = self._acl_action_locked(ip_src, ip_dst, port_src,
                                             port_dst, protocol)
            verdict = (src, dst, action)
            self._fast[key] = verdict
            if len(self._fast) > self.FAST_PATH_CAP:
                self._fast.popitem(last=False)
            return verdict

    def _acl_action_locked(self, ip_src, ip_dst, port_src, port_dst,
                           protocol) -> str:
        for rule in self._acls:
            if rule.protocol and rule.protocol != protocol:
                continue
            if rule.port and rule.port not in (port_src, port_dst):
                continue
            if rule.cidr:
                net = rule.net()
                try:
                    a = ipaddress.ip_address(ip_src)
                    b = ipaddress.ip_address(ip_dst)
                except ValueError:
                    continue
                if a not in net and b not in net:
                    continue
            return rule.action
        return "trace"
