"""Agent-side controller sync loop.

Reference analog: agent/src/rpc/synchronizer.rs (run :1921 — periodic Sync,
on_response :1135 config diff + hot apply). Hand-built gRPC method calls
(no generated stubs on this image).
"""

from __future__ import annotations

import logging
import os
import socket
import statistics
import threading
import time
from collections import deque

import grpc

from deepflow_tpu.proto import pb

log = logging.getLogger("df.sync")

_SYNC = "/deepflow_tpu.Synchronizer/Sync"
_NTP = "/deepflow_tpu.Synchronizer/Ntp"
_GPID = "/deepflow_tpu.Synchronizer/GpidSync"
_PUSH = "/deepflow_tpu.Synchronizer/Push"
_PODMAP = "/deepflow_tpu.Synchronizer/PodMap"
_PKG = "/deepflow_tpu.Synchronizer/FetchPackage"


class Synchronizer:
    def __init__(self, agent, controller_addr: str,
                 interval_s: float = 10.0) -> None:
        self.agent = agent
        self.addr = controller_addr
        self.interval_s = interval_s
        self._channel: grpc.Channel | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._push_thread: threading.Thread | None = None
        self._push_call = None
        self.config_version = 0
        self.config_epoch = 0
        self.platform_version = 0
        self._platform_cache: pb.PlatformData | None = None
        self._configured_servers = list(agent.sender.servers)  # for revert
        self._pending_results: list = []
        self._results_lock = threading.Lock()  # sync loop + upgrade timer
        from deepflow_tpu.agent.ops import CommandRegistry
        self._ops = CommandRegistry(agent)
        self._apply_lock = threading.Lock()  # poll + push threads both apply
        self.stats = {"syncs": 0, "errors": 0, "config_updates": 0}
        # NTP clock sync vs the controller (reference: rpc/ntp.rs): median
        # over recent min-rtt exchanges damps outliers from GC/net jitter
        self.clock_offset_ns = 0
        self.ntp_rtt_ns = 0
        self._ntp_samples: deque[int] = deque(maxlen=5)

    def start(self) -> "Synchronizer":
        # message caps sized for OTA packages (PackageRepo.MAX_PACKAGE
        # 64MiB + headroom); grpc's 4MiB default would RESOURCE_EXHAUST
        # any real agent-tree fetch
        self._channel = grpc.insecure_channel(self.addr, options=[
            ("grpc.max_receive_message_length", 80 << 20),
            ("grpc.max_send_message_length", 80 << 20)])
        self._thread = threading.Thread(
            target=self._run, name="df-synchronizer", daemon=True)
        self._thread.start()
        self._push_thread = threading.Thread(
            target=self._push_loop, name="df-sync-push", daemon=True)
        self._push_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        call = self._push_call
        if call is not None:
            call.cancel()
        if self._thread:
            self._thread.join(timeout=2.0)
        if self._push_thread:
            self._push_thread.join(timeout=2.0)
        if self._channel:
            self._channel.close()

    def _push_loop(self) -> None:
        """Config changes arrive the moment they are saved (reference:
        trisolaris Push stream), instead of waiting for the next poll."""
        stream = self._channel.unary_stream(
            _PUSH,
            request_serializer=pb.SyncRequest.SerializeToString,
            response_deserializer=pb.SyncResponse.FromString)
        while not self._stop.is_set():
            req = pb.SyncRequest()
            req.agent_group = getattr(self.agent.config, "group",
                                      "") or "default"
            req.agent_id = self.agent.config.agent_id
            req.config_version = self.config_version  # enables catch-up
            req.config_epoch = self.config_epoch  # else every (re)connect
            # looks epoch-stale and gets a spurious full-config replay
            try:
                call = stream(req)
                self._push_call = call
                for resp in call:
                    if self._stop.is_set():
                        return
                    self.stats["pushes"] = self.stats.get("pushes", 0) + 1
                    self._on_response(resp)
            except grpc.RpcError as e:
                code = getattr(e, "code", lambda: None)()
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    if self._stop.wait(30.0):  # capacity: back off hard
                        return
            finally:
                self._push_call = None
            if self._stop.wait(2.0):
                return

    def _run(self) -> None:
        # first sync immediately, then on the interval
        while True:
            try:
                self.sync_once()
            except Exception as e:
                self.stats["errors"] += 1
                log.debug("sync failed: %s", e)
            if self._stop.wait(self.interval_s):
                return

    def ntp_sync(self, exchanges: int = 3) -> int:
        """One NTP round: several 4-timestamp exchanges, keep the offset
        from the minimum-RTT one (least queueing noise), fold into the
        recent-sample median. Returns the current smoothed offset (ns)."""
        best_rtt = None
        best_off = 0
        call = self._channel.unary_unary(
            _NTP,
            request_serializer=pb.NtpRequest.SerializeToString,
            response_deserializer=pb.NtpResponse.FromString)
        for _ in range(exchanges):
            t1 = time.time_ns()
            resp = call(pb.NtpRequest(t1_ns=t1), timeout=5.0)
            t4 = time.time_ns()
            if resp.t1_ns != t1:
                continue  # not our exchange
            rtt = (t4 - t1) - (resp.t3_ns - resp.t2_ns)
            off = ((resp.t2_ns - t1) + (resp.t3_ns - t4)) // 2
            if rtt >= 0 and (best_rtt is None or rtt < best_rtt):
                best_rtt, best_off = rtt, off
        if best_rtt is not None:
            self._ntp_samples.append(best_off)
            self.ntp_rtt_ns = best_rtt
            self.clock_offset_ns = int(
                statistics.median(self._ntp_samples))
            self.stats["ntp_syncs"] = self.stats.get("ntp_syncs", 0) + 1
        return self.clock_offset_ns

    def sync_once(self) -> pb.SyncResponse:
        try:
            self.ntp_sync()
        except Exception as e:
            # clock sync is best-effort; a failed exchange must not block
            # config/platform sync
            log.debug("ntp sync failed: %s", e)
        req = pb.SyncRequest()
        req.ctrl_ip = _local_ip()
        req.hostname = socket.gethostname()
        req.agent_id = self.agent.config.agent_id
        req.config_version = self.config_version
        req.config_epoch = self.config_epoch
        req.platform_version = self.platform_version
        guard = self.agent.guard
        if guard is not None and guard.degraded:
            req.state = pb.DEGRADED
            req.exception_bitmap = guard.exception_bitmap
        else:
            req.state = pb.RUNNING
        if guard is not None:
            req.cpu_usage = guard.cpu_pct
            req.mem_bytes = int(guard.rss_mb * 1024 * 1024)
        req.version = "0.1.0"
        req.agent_group = getattr(self.agent.config, "group", "") or "default"
        # clock_offset_ns = controller_clock - agent_clock: the amount the
        # server ADDS to this agent's absolute timestamps at ingest.
        # Presence contract (messages.proto:392): only set once measured —
        # a restarted agent must not clear the controller's stored skew
        # with an unmeasured 0 before its first NTP exchange completes.
        if self._ntp_samples:
            req.clock_offset_ns = self.clock_offset_ns
        with self._results_lock:
            sent_results = list(self._pending_results)
        for r in sent_results:
            req.command_results.append(r)
        # collect topology once, but RE-SEND every sync: a restarted
        # controller must be able to rebuild its platform/gpid state from
        # long-lived agents (the request is tiny)
        if self._platform_cache is None:
            from deepflow_tpu.tpuprobe.topology import collect_platform_data
            self._platform_cache = collect_platform_data()
        req.platform.CopyFrom(self._platform_cache)
        p = req.processes.add()
        p.pid = os.getpid()
        p.name = self.agent.process_name
        call = self._channel.unary_unary(
            _SYNC,
            request_serializer=pb.SyncRequest.SerializeToString,
            response_deserializer=pb.SyncResponse.FromString)
        resp = call(req, timeout=5.0)
        # results are only dropped once the controller HAS them: a failed
        # RPC keeps them queued for the next sync (identity-based removal:
        # a concurrent sync from the upgrade timer must not over-trim)
        if sent_results:
            with self._results_lock:
                self._pending_results = [
                    r for r in self._pending_results
                    if not any(r is s for s in sent_results)]
        self.stats["syncs"] += 1
        self._on_response(resp)
        try:
            self._sync_pod_map()
        except Exception as e:
            # optional feature (older controller / no genesis): a PodMap
            # failure must not poison an otherwise-successful sync
            log.debug("pod map fetch failed: %s", e)
        return resp

    def _sync_pod_map(self) -> None:
        """Labeler feed: fetch the cluster resource model when stale
        (reference: platform data push building first_path)."""
        labeler = getattr(self.agent, "labeler", None)
        if labeler is None:
            return
        req = pb.PodMapRequest()
        req.version = labeler.version
        req.epoch = labeler.epoch
        call = self._channel.unary_unary(
            _PODMAP,
            request_serializer=pb.PodMapRequest.SerializeToString,
            response_deserializer=pb.PodMapResponse.FromString)
        resp = call(req, timeout=5.0)
        if resp.version == labeler.version and resp.epoch == labeler.epoch:
            return  # an empty-but-NEWER map still applies (pods gone)
        from deepflow_tpu.agent.labeler import ResourceLabel
        labeler.load_resources(
            ((e.cidr, ResourceLabel(pod=e.pod, namespace=e.namespace,
                                    workload=e.workload, node=e.node))
             for e in resp.entries),
            version=resp.version)
        labeler.epoch = resp.epoch
        self.stats["podmap_updates"] = \
            self.stats.get("podmap_updates", 0) + 1

    def _on_response(self, resp: pb.SyncResponse) -> None:
        with self._apply_lock:  # poll + push threads: serialize, and only
            # ever move FORWARD (a stale in-flight poll response must not
            # downgrade a newer pushed config)
            if resp.agent_id and \
                    resp.agent_id != self.agent.config.agent_id:
                self.agent.config.agent_id = resp.agent_id
                self.agent.sender.agent_id = resp.agent_id
            epoch_changed = (resp.config_epoch
                             and resp.config_epoch != self.config_epoch)
            if resp.user_config_yaml and (
                    epoch_changed
                    or resp.config_version > self.config_version):
                self._apply_config(resp.user_config_yaml,
                                   resp.config_version)
                self.config_version = resp.config_version
                if resp.config_epoch:
                    self.config_epoch = resp.config_epoch
                self.stats["config_updates"] += 1
            if resp.platform_version:  # push responses leave it unset
                self.platform_version = resp.platform_version
            if resp.analyzer_assignment:
                self._apply_analyzers(list(resp.analyzer_addrs))
            if resp.HasField("qos"):
                # closed-loop backpressure: the server's per-tenant
                # pressure level rides every Sync/Push response
                try:
                    self.agent.apply_backpressure(
                        int(resp.qos.pressure_level))
                except Exception:
                    log.exception("backpressure apply failed")
                self.stats["pressure_level"] = \
                    int(resp.qos.pressure_level)
        for rc in resp.commands:
            code, out = self._ops.run(rc.cmd, list(rc.args))
            with self._results_lock:
                self._pending_results.append(pb.CommandResult(
                    id=rc.id, exit_code=code, output=out))
            self.stats["commands"] = self.stats.get("commands", 0) + 1

    def _apply_analyzers(self, addrs: list[str]) -> None:
        """Rebalance: adopt the controller's ingest-node preference order
        (the sender fails over down this list)."""
        from deepflow_tpu.agent.config import _parse_addr
        try:
            parsed = [_parse_addr(a) for a in addrs]
        except ValueError as e:
            log.warning("bad analyzer list %r: %s", addrs, e)
            return
        if not parsed:
            # assignment cleared: fall back to the configured servers
            parsed = list(self._configured_servers)
        sender = self.agent.sender
        if parsed and parsed != sender.servers:
            sender.servers = parsed
            sender.stats["rebalances"] = \
                sender.stats.get("rebalances", 0) + 1
            log.info("analyzer assignment: %s", parsed)

    def _apply_config(self, yaml_bytes: bytes, version: int) -> None:
        """Hot-apply the pushed config (reference: ConfigHandler per-module
        callbacks): sampler rate, probe cadence, AND enable/disable take
        effect live."""
        import yaml
        from deepflow_tpu.agent.config import AgentConfig
        try:
            new = AgentConfig.from_dict(
                yaml.safe_load(yaml_bytes) or {}).validate()
        except Exception as e:
            log.warning("rejecting bad pushed config: %s", e)
            return
        cfg = self.agent.config
        cfg.profiler = new.profiler
        cfg.tpuprobe = new.tpuprobe
        cfg.stats_interval_s = new.stats_interval_s
        cfg.guard = new.guard
        cfg.acls = new.acls
        cfg.qos = new.qos
        labeler = getattr(self.agent, "labeler", None)
        if labeler is not None:  # pushed ACLs take effect live
            from deepflow_tpu.agent.labeler import AclRule
            labeler.load_acls([
                AclRule(cidr=a.get("cidr", ""),
                        port=int(a.get("port", 0)),
                        protocol=int(a.get("protocol", 0)),
                        action=a.get("action", "trace"))
                for a in new.acls])
            if any(a.get("action") in ("pcap", "npb") for a in new.acls):
                # pushed packet-action ACLs must not be silently inert
                # on agents that started without a dispatcher
                self.agent.ensure_packet_actions(new)

        # guard limits retune live (the controller's knob for hot agents)
        guard = self.agent.guard
        if guard is not None:
            guard.max_cpu_pct = new.guard.max_cpu_pct
            guard.max_mem_mb = new.guard.max_mem_mb
            guard.check_interval_s = new.guard.check_interval_s

        with self.agent._profiler_lock:
            mem = self.agent.memprofiler
            if new.profiler.memory and mem is None:
                self.agent.start_memprofiler()
            elif not new.profiler.memory and mem is not None:
                mem.stop()
                self.agent.memprofiler = None
            elif mem is not None:
                mem.interval_s = new.profiler.memory_interval_s
            sampler = self.agent.sampler
            if new.profiler.enabled and sampler is None:
                # no-op while guard-degraded (start_sampler checks)
                self.agent.start_sampler()
            elif not new.profiler.enabled and sampler is not None:
                sampler.stop()
                self.agent.sampler = None
            elif sampler is not None:
                sampler.period_s = 1.0 / new.profiler.sample_hz
                sampler.period_us = int(1_000_000 / new.profiler.sample_hz)
                sampler.emit_interval_s = new.profiler.emit_interval_s

            probe = self.agent.tpuprobe
            if new.tpuprobe.enabled and probe is None:
                self.agent.start_tpuprobe()
            elif not new.tpuprobe.enabled and probe is not None:
                probe.stop()
                self.agent.tpuprobe = None
            elif probe is not None:
                for src in probe.sources:
                    if hasattr(src, "interval_s"):
                        src.interval_s = new.tpuprobe.trace_interval_s
                        src.duration_ms = new.tpuprobe.trace_duration_ms
                    if hasattr(src, "target_coverage"):
                        # the adaptive cadence's operator throttle
                        src.target_coverage = min(max(
                            new.tpuprobe.target_coverage, 0.05), 0.95)
                        src.steps_per_capture = \
                            new.tpuprobe.steps_per_capture
        log.info("applied pushed config v%d", version)

    def fetch_package(self, name: str = "agent",
                      version: str = "") -> pb.PackageResponse:
        """OTA download over the sync plane (reference: the Upgrade
        stream, message/agent.proto:9)."""
        call = self._channel.unary_unary(
            _PKG,
            request_serializer=pb.PackageRequest.SerializeToString,
            response_deserializer=pb.PackageResponse.FromString)
        return call(pb.PackageRequest(name=name, version=version),
                    timeout=60.0)

    def gpid_sync(self, entries: list[pb.GpidEntry]) -> pb.GpidSyncResponse:
        req = pb.GpidSyncRequest()
        req.agent_id = self.agent.config.agent_id
        req.entries.extend(entries)
        call = self._channel.unary_unary(
            _GPID,
            request_serializer=pb.GpidSyncRequest.SerializeToString,
            response_deserializer=pb.GpidSyncResponse.FromString)
        return call(req, timeout=5.0)


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"
