"""Agent-side receiver for the LD_PRELOAD ssl/syscall probe
(native/sslprobe.cpp): probe events -> MetaPackets -> the flow pipeline.

Reference analog: agent/src/ebpf/user/ssl_tracer.c (user-side of the
SSL uprobes) + the socket-tracer event pump. Each probed process connects
over an AF_UNIX SEQPACKET socket and streams {header, payload} messages
for every socket read/write — TLS events carry PLAINTEXT (captured before
encryption / after decryption) and supersede the ciphertext syscall events
for the same connection.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading

from deepflow_tpu.agent.packet import MetaPacket

log = logging.getLogger("df.sslprobe")

# must match #pragma pack(1) struct ProbeEvent in native/sslprobe.cpp
HDR = struct.Struct("<IIiBBHHBB16s16sQQQQI")

DIR_INGRESS, DIR_EGRESS = 0, 1
SRC_PLAIN, SRC_TLS, SRC_FILEIO = 0, 1, 2


class SslProbeListener:
    """SEQPACKET listener feeding probe events into a dispatcher."""

    def __init__(self, dispatcher, sock_path: str) -> None:
        self.dispatcher = dispatcher
        self.sock_path = sock_path
        self._lst: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # (pid, fd) -> "plain" | "tls"; and per-direction byte counters so
        # synthetic seq numbers keep the retrans detector quiet
        self._conn_mode: dict[tuple, str] = {}
        self._seq: dict[tuple, int] = {}
        self.stats = {"events": 0, "tls_events": 0, "dropped_plain": 0,
                      "connections": 0}
        # file-io events batch (a 10ms threshold on slow storage can fire
        # thousands/s; per-event frames would crowd the sender queue)
        self._io_buf: list = []
        self._io_lock = threading.Lock()
        self._io_last_flush = 0.0

    def start(self) -> "SslProbeListener":
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
        lst = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
        lst.bind(self.sock_path)
        lst.listen(16)
        lst.settimeout(0.5)
        self._lst = lst
        t = threading.Thread(target=self._accept_loop,
                             name="df-sslprobe-accept", daemon=True)
        t.start()
        self._threads.append(t)
        log.info("ssl probe listening on %s", self.sock_path)
        return self

    def stop(self) -> None:
        self._stop.set()
        self.flush_file_io()
        for t in self._threads:
            t.join(timeout=2.0)
        if self._lst is not None:
            self._lst.close()
            self._lst = None
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.stats["connections"] += 1
            t = threading.Thread(target=self._reader, args=(conn,),
                                 name="df-sslprobe-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _reader(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv(1 << 14)
                except socket.timeout:
                    self._flush_file_io_if_stale()
                    continue
                except OSError:
                    return
                if not msg:
                    return
                try:
                    self._handle(msg)
                except Exception:
                    log.exception("probe event failed")
        finally:
            conn.close()

    def _handle(self, msg: bytes) -> None:
        if len(msg) < HDR.size:
            return
        (pid, tid, fd, direction, source, lport, pport, family, _pad,
         laddr, paddr, ts_ns, trace_id, latency_ns, io_bytes,
         dlen) = HDR.unpack_from(msg)
        payload = msg[HDR.size:HDR.size + dlen]
        self.stats["events"] += 1
        if source == SRC_FILEIO:
            self._handle_file_io(pid, tid, direction, ts_ns, trace_id,
                                 latency_ns, io_bytes, payload)
            return
        conn_key = (pid, fd)
        mode = self._conn_mode.get(conn_key)
        if source == SRC_TLS:
            self.stats["tls_events"] += 1
            if mode != "tls":
                # promotion: the connection is TLS — the flow so far only
                # held ciphertext handshake records; drop that state so the
                # plaintext stream re-infers its real protocol
                self._conn_mode[conn_key] = "tls"
                self._drop_flow(family, laddr, paddr, lport, pport)
        elif mode == "tls":
            self.stats["dropped_plain"] += 1  # ciphertext for a TLS conn
            return
        alen = 4 if family == 4 else 16
        local, peer = laddr[:alen], paddr[:alen]
        if direction == DIR_EGRESS:
            src_ip, dst_ip, sport, dport = local, peer, lport, pport
        else:
            src_ip, dst_ip, sport, dport = peer, local, pport, lport
        seq_key = (pid, fd, direction)
        seq = self._seq.get(seq_key, 1)
        self._seq[seq_key] = seq + len(payload)
        mp = MetaPacket(
            timestamp_ns=ts_ns, ip_src=src_ip, ip_dst=dst_ip,
            port_src=sport, port_dst=dport, protocol=1,
            tcp_flags=0x18,  # PSH|ACK
            seq=seq & 0xFFFFFFFF, payload=payload,
            packet_len=len(payload) + 54, tap_port=63,  # uprobe tap
            syscall_trace_id=trace_id, tid=tid)
        self.dispatcher.inject(mp)

    def _handle_file_io(self, pid, tid, direction, ts_ns, trace_id,
                        latency_ns, io_bytes, path_bytes) -> None:
        """Slow file read/write -> event.event (reference: files_rw.bpf.c
        io events with latency + filename)."""
        from deepflow_tpu.codec import MessageType
        from deepflow_tpu.proto import pb
        import time as _t
        self.stats["file_io_events"] = \
            self.stats.get("file_io_events", 0) + 1
        e = pb.Event()
        e.timestamp_ns = ts_ns
        e.event_type = ("file-io-read" if direction == DIR_INGRESS
                        else "file-io-write")
        e.resource_type = "file"
        e.resource_name = path_bytes.decode("utf-8", "replace")
        e.pid = pid
        e.description = (f"latency={latency_ns}ns bytes={io_bytes} "
                         f"tid={tid}")
        e.attrs["latency_ns"] = str(latency_ns)
        e.attrs["bytes"] = str(io_bytes)
        e.attrs["syscall_trace_id"] = str(trace_id)
        with self._io_lock:
            self._io_buf.append(e)
            full = len(self._io_buf) >= 64
            stale = _t.monotonic() - self._io_last_flush > 1.0
        if full or stale:
            self.flush_file_io()

    def _flush_file_io_if_stale(self) -> None:
        import time as _t
        with self._io_lock:
            pending = bool(self._io_buf)
            stale = _t.monotonic() - self._io_last_flush > 1.0
        if pending and stale:
            self.flush_file_io()

    def flush_file_io(self) -> None:
        from deepflow_tpu.codec import MessageType
        from deepflow_tpu.proto import pb
        import time as _t
        with self._io_lock:
            if not self._io_buf:
                return
            events, self._io_buf = self._io_buf, []
            self._io_last_flush = _t.monotonic()
        batch = pb.EventBatch()
        batch.events.extend(events)
        sender = getattr(self.dispatcher, "sender", None)
        if sender is not None:
            sender.send(MessageType.EVENT, batch.SerializeToString())

    def _drop_flow(self, family, laddr, paddr, lport, pport) -> None:
        alen = 4 if family == 4 else 16
        local, peer = laddr[:alen], paddr[:alen]
        fm = self.dispatcher.flow_map
        with self.dispatcher._lock:  # flush thread iterates fm.flows
            # keys carry tunnel identity (always 0 for uprobe sources) —
            # must match MetaPacket.key's shape exactly
            for key in ((local, peer, lport, pport, 1, 0, 0),
                        (peer, local, pport, lport, 1, 0, 0)):
                node = fm.flows.pop(key, None)
                if node is not None:
                    # silently discard: it held only undecryptable records
                    node.pending.clear()
                    node.pending_by_id.clear()
