"""Agent-side receiver for the LD_PRELOAD ssl/syscall probe
(native/sslprobe.cpp): probe events -> MetaPackets -> the flow pipeline.

Reference analog: agent/src/ebpf/user/ssl_tracer.c (user-side of the
SSL uprobes) + the socket-tracer event pump. Each probed process connects
over an AF_UNIX SEQPACKET socket and streams {header, payload} messages
for every socket read/write — TLS events carry PLAINTEXT (captured before
encryption / after decryption) and supersede the ciphertext syscall events
for the same connection.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading

from deepflow_tpu.agent.packet import MetaPacket

log = logging.getLogger("df.sslprobe")

# must match #pragma pack(1) struct ProbeEvent in native/sslprobe.cpp
HDR = struct.Struct("<IIiBBHHBB16s16sQQI")

DIR_INGRESS, DIR_EGRESS = 0, 1
SRC_PLAIN, SRC_TLS = 0, 1


class SslProbeListener:
    """SEQPACKET listener feeding probe events into a dispatcher."""

    def __init__(self, dispatcher, sock_path: str) -> None:
        self.dispatcher = dispatcher
        self.sock_path = sock_path
        self._lst: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # (pid, fd) -> "plain" | "tls"; and per-direction byte counters so
        # synthetic seq numbers keep the retrans detector quiet
        self._conn_mode: dict[tuple, str] = {}
        self._seq: dict[tuple, int] = {}
        self.stats = {"events": 0, "tls_events": 0, "dropped_plain": 0,
                      "connections": 0}

    def start(self) -> "SslProbeListener":
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
        lst = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
        lst.bind(self.sock_path)
        lst.listen(16)
        lst.settimeout(0.5)
        self._lst = lst
        t = threading.Thread(target=self._accept_loop,
                             name="df-sslprobe-accept", daemon=True)
        t.start()
        self._threads.append(t)
        log.info("ssl probe listening on %s", self.sock_path)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        if self._lst is not None:
            self._lst.close()
            self._lst = None
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.stats["connections"] += 1
            t = threading.Thread(target=self._reader, args=(conn,),
                                 name="df-sslprobe-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _reader(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv(1 << 14)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not msg:
                    return
                try:
                    self._handle(msg)
                except Exception:
                    log.exception("probe event failed")
        finally:
            conn.close()

    def _handle(self, msg: bytes) -> None:
        if len(msg) < HDR.size:
            return
        (pid, tid, fd, direction, source, lport, pport, family, _pad,
         laddr, paddr, ts_ns, trace_id, dlen) = HDR.unpack_from(msg)
        payload = msg[HDR.size:HDR.size + dlen]
        self.stats["events"] += 1
        conn_key = (pid, fd)
        mode = self._conn_mode.get(conn_key)
        if source == SRC_TLS:
            self.stats["tls_events"] += 1
            if mode != "tls":
                # promotion: the connection is TLS — the flow so far only
                # held ciphertext handshake records; drop that state so the
                # plaintext stream re-infers its real protocol
                self._conn_mode[conn_key] = "tls"
                self._drop_flow(family, laddr, paddr, lport, pport)
        elif mode == "tls":
            self.stats["dropped_plain"] += 1  # ciphertext for a TLS conn
            return
        alen = 4 if family == 4 else 16
        local, peer = laddr[:alen], paddr[:alen]
        if direction == DIR_EGRESS:
            src_ip, dst_ip, sport, dport = local, peer, lport, pport
        else:
            src_ip, dst_ip, sport, dport = peer, local, pport, lport
        seq_key = (pid, fd, direction)
        seq = self._seq.get(seq_key, 1)
        self._seq[seq_key] = seq + len(payload)
        mp = MetaPacket(
            timestamp_ns=ts_ns, ip_src=src_ip, ip_dst=dst_ip,
            port_src=sport, port_dst=dport, protocol=1,
            tcp_flags=0x18,  # PSH|ACK
            seq=seq & 0xFFFFFFFF, payload=payload,
            packet_len=len(payload) + 54, tap_port=63,  # uprobe tap
            syscall_trace_id=trace_id, tid=tid)
        self.dispatcher.inject(mp)

    def _drop_flow(self, family, laddr, paddr, lport, pport) -> None:
        alen = 4 if family == 4 else 16
        local, peer = laddr[:alen], paddr[:alen]
        fm = self.dispatcher.flow_map
        with self.dispatcher._lock:  # flush thread iterates fm.flows
            for key in ((local, peer, lport, pport, 1),
                        (peer, local, pport, lport, 1)):
                node = fm.flows.pop(key, None)
                if node is not None:
                    # silently discard: it held only undecryptable records
                    node.pending.clear()
                    node.pending_by_id.clear()
