"""On-agent integration collector: local ingest endpoint for workloads.

Reference analog: agent/src/integration_collector.rs — an HTTP listener on
the node (:38086) so pods send OTLP/profiles/logs to localhost and the agent
forwards them to the server. Keeps workload config trivial (no server
address) and survives server failover via the agent's own retry.
"""

from __future__ import annotations

import logging
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("df.integration-proxy")

_FORWARD_PATHS = ("/api/v1/otlp/traces", "/api/v1/profile/ingest",
                  "/api/v1/log", "/api/v1/otlp/logs",
                  "/api/v1/write", "/api/v1/telegraf",
                  "/v0.3/traces", "/v0.4/traces", "/v3/segments")


class IntegrationProxy:
    def __init__(self, server_http: str, host: str = "0.0.0.0",
                 port: int = 38086) -> None:
        self.server_http = server_http  # host:port of the querier HTTP
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self.stats = {"forwarded": 0, "errors": 0, "rejected": 0}

    def start(self) -> "IntegrationProxy":
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path not in _FORWARD_PATHS:
                    proxy.stats["rejected"] += 1
                    self.send_response(404)
                    self.end_headers()
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                url = f"http://{proxy.server_http}{self.path}"
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": self.headers.get(
                        "Content-Type", "application/octet-stream")})
                try:
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        out = resp.read()
                        code = resp.status
                except urllib.error.HTTPError as e:
                    out = e.read()
                    code = e.code
                except urllib.error.URLError as e:
                    proxy.stats["errors"] += 1
                    self.send_response(502)
                    self.end_headers()
                    self.wfile.write(str(e.reason).encode())
                    return
                proxy.stats["forwarded"] += 1
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            do_PUT = do_POST  # dd-trace clients PUT their trace payloads

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="df-integration-proxy", daemon=True)
        t.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
