"""Socket-inode -> process attribution, no cooperation required.

Reference analog: the agent's /proc socket scan that feeds GPIDSync
(agent/src/platform/platform_synchronizer/linux_socket.rs:95 — it walks
/proc/<pid>/fd for socket inodes, joins them against /proc/net/tcp, and
uploads GpidSyncEntry 5-tuples so the controller can hand out global
process ids and the ingester can join both sides of one connection).

Redesign notes: one scanner thread per agent (not per-netns pollers);
entries carry /proc/<pid>/comm so flow logs can show a process NAME for
*any* process — already-running services, static binaries, Go servers —
with no LD_PRELOAD (VERDICT r04 missing #1 / next #6). TLS payload
visibility still needs the preload interposer; identity does not.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time

from deepflow_tpu.proto import pb

log = logging.getLogger("df.socketscan")

# /proc/net/tcp state column
_TCP_LISTEN = 0x0A


def _parse_hex_addr4(s: str) -> tuple[bytes, int]:
    """'0100007F:1F90' -> (b'\\x7f\\x00\\x00\\x01', 8080). The kernel
    prints the address as little-endian u32 hex."""
    ip_hex, port_hex = s.split(":")
    return struct.pack("<I", int(ip_hex, 16)), int(port_hex, 16)


def _parse_hex_addr6(s: str) -> tuple[bytes, int]:
    """v6 addresses print as 4 little-endian u32 words."""
    ip_hex, port_hex = s.split(":")
    words = [int(ip_hex[i:i + 8], 16) for i in range(0, 32, 8)]
    return struct.pack("<4I", *words), int(port_hex, 16)


def parse_proc_net(text: str, v6: bool = False
                   ) -> list[tuple[bytes, int, int, int]]:
    """Parse /proc/net/{tcp,tcp6,udp} content ->
    [(local_ip, local_port, state, inode)]."""
    out = []
    parse = _parse_hex_addr6 if v6 else _parse_hex_addr4
    for line in text.splitlines()[1:]:
        parts = line.split()
        if len(parts) < 10:
            continue
        try:
            ip, port = parse(parts[1])
            state = int(parts[3], 16)
            inode = int(parts[9])
        except (ValueError, IndexError):
            continue
        out.append((ip, port, state, inode))
    return out


def scan_socket_inodes(proc_root: str = "/proc") -> dict[int, int]:
    """inode -> pid for every socket fd on the host. Requires the same
    privileges the extprofiler already needs (root or same-user)."""
    out: dict[int, int] = {}
    try:
        pids = [p for p in os.listdir(proc_root) if p.isdigit()]
    except OSError:
        return out
    for p in pids:
        fd_dir = f"{proc_root}/{p}/fd"
        try:
            fds = os.listdir(fd_dir)
        except OSError:
            continue  # raced exit or not ours
        for fd in fds:
            try:
                target = os.readlink(f"{fd_dir}/{fd}")
            except OSError:
                continue
            if target.startswith("socket:["):
                try:
                    out[int(target[8:-1])] = int(p)
                except ValueError:
                    pass
    return out


def _comm(pid: int, proc_root: str = "/proc") -> str:
    try:
        with open(f"{proc_root}/{pid}/comm") as f:
            return f.read().strip()
    except OSError:
        return ""


def scan_entries(agent_id: int = 0, proc_root: str = "/proc"
                 ) -> list[pb.GpidEntry]:
    """One full scan -> GpidEntry batch.

    Role assignment: LISTEN sockets are servers; an established socket
    whose local port is also LISTENed by the same pid is the accept()ed
    server side; everything else is a client endpoint. v6 entries ride
    with their 16-byte address (the ingester keys joins by raw ip bytes).
    """
    inode_pid = scan_socket_inodes(proc_root)
    entries: list[pb.GpidEntry] = []
    seen: set[tuple] = set()
    names: dict[int, str] = {}
    _ANY4, _ANY6 = b"\x00" * 4, b"\x00" * 16

    def add(ip: bytes, port: int, proto: int, role: int, pid: int) -> None:
        key = (ip, port, proto, role, pid)
        if key in seen:
            return
        seen.add(key)
        name = names.get(pid)
        if name is None:
            name = names[pid] = _comm(pid, proc_root)
        entries.append(pb.GpidEntry(
            agent_id=agent_id, pid=pid, ip=ip, port=port,
            proto=proto, role=role, process_name=name))

    # wildcard binds (0.0.0.0/::) are expanded into the CONCRETE local
    # addresses observed on this host's sockets, so the controller join
    # stays exact-match — a server-side "wildcard matches any ip"
    # fallback would misattribute flows toward REMOTE endpoints on the
    # same port to the local listener
    local4: set[bytes] = {struct.pack("<I", 0x0100007F)}   # 127.0.0.1
    local6: set[bytes] = {b"\x00" * 15 + b"\x01"}          # ::1
    families = (("net/tcp", pb.TCP, False), ("net/tcp6", pb.TCP, True),
                ("net/udp", pb.UDP, False), ("net/udp6", pb.UDP, True))
    parsed = []
    for path, proto, v6 in families:
        try:
            with open(f"{proc_root}/{path}") as f:
                socks = parse_proc_net(f.read(), v6=v6)
        except OSError:
            socks = []
        parsed.append(socks)
        for ip, _port, _state, _inode in socks:
            if v6 and ip != _ANY6:
                local6.add(ip)
            elif not v6 and ip != _ANY4:
                local4.add(ip)

    for (path, proto, v6), socks in zip(families, parsed):
        listen_ports: dict[int, set[int]] = {}  # pid -> listening ports
        if proto == pb.TCP:
            for ip, port, state, inode in socks:
                pid = inode_pid.get(inode)
                if pid is not None and state == _TCP_LISTEN:
                    listen_ports.setdefault(pid, set()).add(port)
        for ip, port, state, inode in socks:
            pid = inode_pid.get(inode)
            if pid is None:
                continue
            if proto == pb.TCP:
                role = 1 if (state == _TCP_LISTEN
                             or port in listen_ports.get(pid, ())) else 0
            else:
                role = 1  # bound UDP sockets serve their local port
            is_any = ip == (_ANY6 if v6 else _ANY4)
            if is_any:
                for addr in (local6 if v6 else local4):
                    add(bytes(addr), port, proto, role, pid)
            else:
                add(bytes(ip), port, proto, role, pid)
    return entries


class SocketScanner:
    """Periodic scan -> GpidSync upload over the sync plane."""

    def __init__(self, synchronizer, agent_id: int = 0,
                 interval_s: float = 30.0,
                 proc_root: str = "/proc") -> None:
        self.synchronizer = synchronizer
        self.agent_id = agent_id
        self.interval_s = interval_s
        self.proc_root = proc_root
        self.stats = {"scans": 0, "entries": 0, "errors": 0}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "SocketScanner":
        self._thread = threading.Thread(
            target=self._run, name="df-socket-scan", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3.0)

    def scan_once(self) -> int:
        t0 = time.monotonic()
        entries = scan_entries(self.agent_id, self.proc_root)
        self.stats["scans"] += 1
        self.stats["entries"] = len(entries)
        if entries:
            self.synchronizer.gpid_sync(entries)
        log.debug("socket scan: %d entries in %.0fms", len(entries),
                  (time.monotonic() - t0) * 1000)
        return len(entries)

    def _run(self) -> None:
        # first scan quickly so fresh agents attribute flows within
        # seconds; then settle onto the configured cadence
        if self._stop.wait(1.0):
            return
        while not self._stop.is_set():
            try:
                self.scan_once()
            except Exception:
                self.stats["errors"] += 1
                log.exception("socket scan failed")
            if self._stop.wait(self.interval_s):
                return
