"""UniformSender: batched, framed, reconnecting TCP telemetry sender.

Reference analog: agent/src/sender/uniform_sender.rs (Header prepend
:149-210, batching, compression, server failover).
"""

from __future__ import annotations

import logging
import queue
import socket
import threading
import time

from deepflow_tpu.codec import FrameHeader, MessageType, encode_frame

log = logging.getLogger("df.sender")


class UniformSender:
    """One TCP connection shipping frames for all message types.

    Thread-safe send(): enqueue (msg_type, payload); a background thread
    frames and writes, reconnecting with exponential backoff across the
    configured server list (failover, like the reference's sender)."""

    def __init__(self, servers: list[tuple[str, int]], agent_id: int = 0,
                 org_id: int = 0, team_id: int = 0, queue_size: int = 8192,
                 connect_timeout: float = 3.0, telemetry=None) -> None:
        if not servers:
            raise ValueError("need at least one server address")
        from deepflow_tpu.agent.config import _parse_addr
        self.servers = [_parse_addr(s) if isinstance(s, str) else tuple(s)
                        for s in servers]
        self.agent_id = agent_id
        self.org_id = org_id
        self.team_id = team_id
        self.connect_timeout = connect_timeout
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sock: socket.socket | None = None
        self._server_idx = 0
        self.stats = {"sent_frames": 0, "sent_bytes": 0, "dropped": 0,
                      "reconnects": 0, "errors": 0}
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("agent", enabled=False)
        self._hop = telemetry.hop("sender")
        self._telemetry = telemetry

    def start(self) -> "UniformSender":
        self._thread = threading.Thread(
            target=self._run, name="df-uniform-sender", daemon=True)
        self._thread.start()
        return self

    def queue_depth(self) -> int:
        return self._q.qsize()

    def peek(self, n: int = 8) -> list:
        """Non-consuming sample of queued frames (debug queue tap)."""
        with self._q.mutex:
            items = list(self._q.queue)[:n]
        return [{"type": getattr(mt, "name", str(mt)), "bytes": len(p)}
                for mt, p, _enq in items]

    def send(self, msg_type: MessageType, payload: bytes) -> bool:
        self._hop.account(emitted=1)
        try:
            self._q.put_nowait((msg_type, payload, time.monotonic_ns()))
            return True
        except queue.Full:
            self.stats["dropped"] += 1
            self._hop.account(dropped=1, reason="queue_full")
            return False

    def flush_and_stop(self, timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.02)
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self._close()

    def _close(self) -> None:
        if self._sock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _connect(self) -> bool:
        """Try servers round-robin starting at the current index."""
        for i in range(len(self.servers)):
            host, port = self.servers[(self._server_idx + i)
                                      % len(self.servers)]
            try:
                s = socket.create_connection(
                    (host, port), timeout=self.connect_timeout)
                s.settimeout(10.0)
                self._sock = s
                self._server_idx = (self._server_idx + i) % len(self.servers)
                self.stats["reconnects"] += 1
                return True
            except OSError:
                continue
        return False

    def _run(self) -> None:
        backoff = 0.1
        hb = self._telemetry.heartbeat("sender")
        while not self._stop.is_set():
            hb.beat(progress=self.stats["sent_frames"])
            if self._sock is None:
                if not self._connect():
                    time.sleep(min(backoff, 5.0))
                    backoff *= 2
                    continue
                backoff = 0.1
            try:
                msg_type, payload, enq_ns = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            frame = encode_frame(
                FrameHeader(msg_type, agent_id=self.agent_id,
                            org_id=self.org_id, team_id=self.team_id),
                payload)
            try:
                self._sock.sendall(frame)
                self.stats["sent_frames"] += 1
                self.stats["sent_bytes"] += len(frame)
                self._hop.account(
                    delivered=1, wait_ns=time.monotonic_ns() - enq_ns)
            except OSError as e:
                # the frame is lost; rotate to the next server
                self.stats["errors"] += 1
                self._hop.account(dropped=1, reason="send_error")
                log.warning("send failed (%s); reconnecting", e)
                self._close()
                self._server_idx = (self._server_idx + 1) % len(self.servers)
