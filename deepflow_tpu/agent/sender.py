"""UniformSender: batched, framed, reconnecting TCP telemetry sender.

Reference analog: agent/src/sender/uniform_sender.rs (Header prepend
:149-210, batching, compression, server failover).

Durable-delivery layer (this port goes beyond the reference, which is
fire-and-forget): every frame carries a monotonically increasing
per-agent ``seq`` (codec v2).  The server periodically writes ACK
frames (highest contiguous seq) back down the same TCP connection; the
sender keeps sent-but-unacked frames in a bounded retransmit window and
replays them after a reconnect, the server's decoders dedup on
``(agent_id, seq)`` — together: at-least-once delivery, exactly-once
rows.  Frames that would previously be dropped (queue overflow with no
lower-priority victim, a failed in-flight write, a dead server) spill
into an on-disk ``Spool`` and replay on reconnect.  Under pressure the
sender sheds by message-type class — DFSTATS/self-mon first,
STEP_METRICS/flow/trace data last — with per-class ``dropped(reason)``
ledger accounting, replacing the old blind drop-newest.

Seq-space discipline (what keeps the server's per-agent state honest):

* The counter is seeded per boot from a ~1ms wall-clock epoch in the
  high bits (``epoch << 22 | counter``), max-ed with the recovered
  spool's highest seq.  A restarted agent therefore always starts
  ABOVE any watermark or dedup floor the server still holds for its
  agent_id — without this, a restart would replay seq 1.. into a
  server whose watermark/dedup floor sits at the old boot's high-water
  mark, and every frame would be silently discarded as a dup.
* A seq is allocated at a frame's FIRST wire or spool write, never at
  ``send()``: a frame shed or dropped before reaching the wire never
  owned a seq, so it cannot leave a permanent gap that stalls the
  server's contiguous watermark (and with it acks, window trim and
  spool trim).
* The few events that DO burn an allocated seq (spool eviction at the
  disk cap, a spool disk error) — and every (re)connect — make the
  sender announce a ``SEQ_BASE`` control frame: "no seq below B will
  ever be sent (again)".  The server fast-forwards its watermark to
  B-1 instead of parking the dead gap until MAX_OOS forces a jump.

Ledger discipline: ``emitted`` is accounted once per ``send()``,
``delivered`` once per frame at its FIRST successful socket write
(retransmits of unacked frames are counted in ``stats`` but not
re-accounted), and every shed/evicted/undeliverable frame is a
``dropped(reason)`` — so ``emitted == delivered + dropped + in_flight``
holds exactly, spool or no spool.
"""

from __future__ import annotations

import logging
import queue
import select
import socket
import struct
import threading
import time

from deepflow_tpu.codec import (
    SEQ_EXT_FMT, FrameDecodeError, FrameHeader, MessageType, StreamDecoder,
    encode_frame, encode_seq_base, priority_of)

log = logging.getLogger("df.sender")

_PRIO_NAMES = {0: "high", 1: "mid", 2: "low"}


class _Frame:
    """One frame's transit state. ``needs_account`` flips False at the
    first successful write so retransmits never double-count. ``seq``
    stays None until the frame first reaches the wire or the spool —
    shed/dropped frames never own one."""

    __slots__ = ("msg_type", "payload", "seq", "enq_ns", "needs_account")

    def __init__(self, msg_type: MessageType, payload: bytes,
                 seq: int | None, enq_ns: int | None,
                 needs_account: bool = True) -> None:
        self.msg_type = msg_type
        self.payload = payload
        self.seq = seq
        self.enq_ns = enq_ns
        self.needs_account = needs_account


class UniformSender:
    """One TCP connection shipping frames for all message types.

    Thread-safe send(): enqueue (msg_type, payload); a background thread
    frames and writes, reconnecting with exponential backoff across the
    configured server list (failover, like the reference's sender)."""

    def __init__(self, servers: list[tuple[str, int]], agent_id: int = 0,
                 org_id: int = 0, team_id: int = 0, queue_size: int = 8192,
                 connect_timeout: float = 3.0, telemetry=None,
                 spool=None, ack_window: int = 1024,
                 durable: bool = True, chaos=None) -> None:
        if not servers:
            raise ValueError("need at least one server address")
        from deepflow_tpu.agent.config import _parse_addr
        self.servers = [_parse_addr(s) if isinstance(s, str) else tuple(s)
                        for s in servers]
        self.agent_id = agent_id
        self.org_id = org_id
        self.team_id = team_id
        self.connect_timeout = connect_timeout
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sock: socket.socket | None = None
        self._server_idx = 0
        # durable=False reverts to the seq-less v1 wire (no ack window,
        # no retransmit) — the bench baseline arm and a compat escape
        # hatch for pre-ACK servers
        self.durable = durable
        self.spool = spool
        self.ack_window = max(1, ack_window)
        if chaos is None:
            from deepflow_tpu.chaos import chaos_from_env
            chaos = chaos_from_env()
        self._chaos = chaos
        self._seq_lock = threading.Lock()
        # per-boot epoch above a 22-bit counter (~1ms units, unmasked so
        # it can never wrap backward; ~2^41 * 2^22 still fits u64): a
        # restarted agent's seqs start above anything the server
        # remembers for this agent_id, even across a fast clean restart
        # whose trimmed-empty spool has no max_seq to recover — the
        # counter outgrowing 22 bits just bleeds into epoch space, which
        # stays monotonic because real send rates are far below the
        # 4M-frames-per-ms that region represents
        self._next_seq = ((time.time_ns() >> 20) << 22) | 1
        if spool is not None:
            self._next_seq = max(self._next_seq, spool.max_seq() + 1)
        self.seq_base = self._next_seq - 1     # seqs are seq_base+1, +2, ...
        self._acked = 0                       # highest contiguous acked
        self._unacked: dict[int, _Frame] = {}  # sent, awaiting ack
        self._pending: list[_Frame] = []       # retransmit/replay, FIFO
        self._inflight: _Frame | None = None
        self._spool_replayed_through = 0
        self._base_dirty = False  # a seq was burned: re-announce SEQ_BASE
        # delivered frames evicted from the retransmit window before
        # their ack: still possibly in a decoder queue, so SEQ_BASE must
        # never advance past them (the dedup floor would drop their rows)
        self._evicted_unacked: set[int] = set()
        self._ackdec = StreamDecoder()
        self.stats = {"sent_frames": 0, "sent_bytes": 0, "dropped": 0,
                      "reconnects": 0, "errors": 0, "retransmits": 0,
                      "spooled": 0, "replayed": 0, "acked_seq": 0,
                      "shed": 0, "unacked_evicted": 0, "seq_bases": 0}
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("agent", enabled=False)
        self._hop = telemetry.hop("sender")
        self._telemetry = telemetry
        if self.spool is not None:
            # a spool recovered from a previous process holds frames
            # that were never emitted on THIS ledger: account them in
            # so replay's delivered keeps the ledger balanced
            self.spool.on_evict = self._on_spool_evict
            recovered = self.spool.pending_records()
            if recovered:
                self._hop.account(emitted=recovered)

    def _on_spool_evict(self, n: int, reason: str) -> None:
        self.stats["dropped"] += n
        self._hop.account(dropped=n, reason=reason)
        # evicted records owned seqs that will never be sent: tell the
        # server so its contiguous watermark doesn't stall on the gap
        self._base_dirty = True

    def start(self) -> "UniformSender":
        self._thread = threading.Thread(
            target=self._run, name="df-uniform-sender", daemon=True)
        self._thread.start()
        return self

    def queue_depth(self) -> int:
        return self._q.qsize()

    def peek(self, n: int = 8) -> list:
        """Non-consuming sample of queued frames (debug queue tap)."""
        with self._q.mutex:
            items = list(self._q.queue)[:n]
        return [{"type": getattr(f.msg_type, "name", str(f.msg_type)),
                 "bytes": len(f.payload)} for f in items]

    def _alloc_seq(self) -> int:
        with self._seq_lock:
            seq = self._next_seq
            self._next_seq += 1
            return seq

    def send(self, msg_type: MessageType, payload: bytes) -> bool:
        self._hop.account(emitted=1)
        # no seq yet: a seq is allocated at the frame's first wire/spool
        # write, so a frame shed or dropped before reaching either never
        # burns one (a burned seq is a permanent gap that stalls the
        # server's contiguous watermark — and with it every ack)
        f = _Frame(msg_type, payload, None, time.monotonic_ns())
        try:
            self._q.put_nowait(f)
            return True
        except queue.Full:
            pass
        # prioritized backpressure: shed the lowest-priority queued frame
        # strictly below this one's class before giving up room
        mine = priority_of(msg_type)
        victim = self._shed_lower_than(mine)
        if victim is not None:
            self._drop(victim, "priority_shed_"
                       + _PRIO_NAMES[priority_of(victim.msg_type)])
            self.stats["shed"] += 1
            try:
                self._q.put_nowait(f)
                return True
            except queue.Full:
                pass  # raced with other senders: fall through
        if self.spool is not None and mine == 0:
            # high-priority frames survive overflow on disk
            f.seq = self._alloc_seq()
            if self.spool.append(int(msg_type), f.seq, f.payload):
                self.stats["spooled"] += 1
                return True
            self._drop(f, "spool_error")
            self._base_dirty = True  # that seq is now a permanent gap
            return False
        self._drop(f, f"queue_full_{_PRIO_NAMES[mine]}")
        return False

    def _drop(self, f: _Frame, reason: str) -> None:
        self.stats["dropped"] += 1
        self._hop.account(dropped=1, reason=reason)

    def _shed_lower_than(self, prio: int) -> _Frame | None:
        """Remove and return the oldest queued frame with a strictly
        lower priority class than ``prio`` (higher numeric = lower)."""
        with self._q.mutex:
            dq = self._q.queue
            worst_i, worst_p = -1, prio
            for i, f in enumerate(dq):
                p = priority_of(f.msg_type)
                if p > worst_p:
                    worst_i, worst_p = i, p
                    if p == 2:
                        break  # can't get lower
            if worst_i < 0:
                return None
            victim = dq[worst_i]
            del dq[worst_i]
            self._q.not_full.notify()
            return victim

    # -- shutdown ------------------------------------------------------------

    def flush_and_stop(self, timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        # wait for the queue AND the retransmit/replay backlog AND the
        # in-flight frame — _q.empty() alone used to abandon the frame
        # the worker had already dequeued
        while time.monotonic() < deadline:
            if self._q.empty() and self._inflight is None \
                    and not self._pending and not self._spool_backlog():
                break
            if self._stop.wait(0.02):
                break
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self._close()
        if self.spool is not None:
            self.spool.close()

    def drain_unsent(self) -> list[tuple[MessageType, bytes]]:
        """Stop this sender and hand back every frame NOT yet acked, in
        seq order — the replication rebalance path: when a destination
        loses ownership, its queued/unacked/spooled frames are re-shipped
        to the new owners instead of being dropped with the sender.

        Acked frames are excluded (they are durably at the old owner and
        claimed there or by its replicas); an unacked frame that in fact
        landed may be re-reported once after an ownership change —
        delivery across rebalances is at-least-once, exactly-once within
        a stable ring (docs/CLUSTER.md). Undelivered frames are closed
        out on the ledger as dropped(rebalance); re-sending them through
        a new sender re-emits them on the same hop, so the ledger stays
        balanced end to end."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
            self._thread = None
        frames: dict[int | None, _Frame] = {}
        leftovers: list[_Frame] = []
        while True:
            try:
                f = self._q.get_nowait()
            except queue.Empty:
                break
            if f.seq is None:
                leftovers.append(f)
            else:
                frames.setdefault(f.seq, f)
        self._close()  # moves _unacked into _pending
        for f in self._pending:
            if f.seq is None or f.seq > self._acked:
                if f.seq is None:
                    leftovers.append(f)
                else:
                    frames.setdefault(f.seq, f)
        self._pending = []
        if self.spool is not None:
            for mt, seq, payload in self.spool.replay(self._acked):
                if seq in frames:
                    continue
                try:
                    msg_type = MessageType(mt)
                except ValueError:
                    continue
                frames[seq] = _Frame(msg_type, payload, seq, None)
            self.spool.close()
        out = []
        for f in sorted(frames.values(), key=lambda fr: fr.seq) + leftovers:
            if f.needs_account:
                self._drop(f, "rebalance")
            out.append((f.msg_type, f.payload))
        return out

    def _spool_backlog(self) -> bool:
        """True while the spool holds records not yet handed to replay."""
        return (self.durable and self.spool is not None
                and self.spool.max_seq() > max(self._acked,
                                               self._spool_replayed_through))

    def _close(self) -> None:
        if self._sock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._ackdec = StreamDecoder()
        # sent-but-unacked frames go back on the retransmit list: the
        # server may or may not have them; dedup makes resending safe.
        # Class-major order (HIGH, then MID, then LOW; seq within a
        # class): after an outage the profiles/spans the operator is
        # debugging with arrive before bulk stats — dedup + per-frame
        # seqs make out-of-seq delivery safe
        if self.durable and self._unacked:
            backlog = list(self._unacked.values())
            self._unacked.clear()
            self._pending = sorted(
                self._pending + backlog,
                key=lambda f: (priority_of(f.msg_type), f.seq))

    def _connect(self) -> bool:
        """Try servers round-robin starting at the current index."""
        for i in range(len(self.servers)):
            host, port = self.servers[(self._server_idx + i)
                                      % len(self.servers)]
            try:
                if self._chaos is not None:
                    self._chaos.on_connect()
                s = socket.create_connection(
                    (host, port), timeout=self.connect_timeout)
                s.settimeout(10.0)
                self._sock = s
                self._server_idx = (self._server_idx + i) % len(self.servers)
                self.stats["reconnects"] += 1
                if self.durable:
                    self._load_replay()
                return True
            except OSError:
                continue
        return False

    def _load_replay(self) -> None:
        """Queue spooled frames (never yet sent) for delivery. Unacked
        retransmits were already moved to _pending by _close()."""
        if self.spool is None:
            return
        start = max(self._acked, self._spool_replayed_through)
        fresh = []
        pending_seqs = {f.seq for f in self._pending}
        for mt, seq, payload in self.spool.replay(start):
            if seq in pending_seqs:
                continue
            try:
                msg_type = MessageType(mt)
            except ValueError:
                continue
            fresh.append(_Frame(msg_type, payload, seq, None))
            self._spool_replayed_through = max(
                self._spool_replayed_through, seq)
        if fresh:
            self.stats["replayed"] += len(fresh)
            # HIGH replays before MID/LOW (see _close): an outage must
            # not make bulk stats queue ahead of profile/span frames
            self._pending = sorted(
                self._pending + fresh,
                key=lambda f: (priority_of(f.msg_type), f.seq))

    # -- seq-base announcements ----------------------------------------------

    def _outstanding_base(self) -> int:
        """Lowest seq this sender may still (re)send. Everything below
        it is either acked or permanently gone (dropped with ledger
        accounting) — safe for the server to declare dead. Conservative
        (too-low) answers are harmless: the server only moves forward."""
        with self._seq_lock:
            cands = [self._next_seq]
        f = self._inflight
        if f is not None and f.seq is not None:
            cands.append(f.seq)
        cands.extend(fr.seq for fr in self._pending if fr.seq is not None)
        if self._unacked:
            cands.append(min(self._unacked))
        if self._evicted_unacked:
            # delivered but unacked and no longer retransmittable: they
            # may still be sitting in a server decode queue, so the base
            # (and with it the dedup floor) must stay below them
            cands.append(min(self._evicted_unacked))
        if self.spool is not None:
            s = self.spool.min_pending_seq()
            if s:
                cands.append(max(s, self._acked + 1))
        return min(cands)

    def _send_base(self) -> None:
        """Announce SEQ_BASE on the live connection (worker thread only).
        Sent after every (re)connect — a restarted agent's fresh epoch
        seq space, or any seqs burned while disconnected, fast-forward
        the server's watermark — and whenever an event burns a seq
        mid-connection (spool evict / spool disk error)."""
        frame = encode_seq_base(self.agent_id, self._outstanding_base())
        try:
            if self._chaos is not None:
                self._chaos.on_send(self._sock, frame)
            else:
                self._sock.sendall(frame)
            self.stats["seq_bases"] += 1
            self.stats["sent_bytes"] += len(frame)
            self._base_dirty = False
        except OSError as e:
            log.warning("seq-base send failed (%s); reconnecting", e)
            self.stats["errors"] += 1
            self._close()
            self._server_idx = (self._server_idx + 1) % len(self.servers)

    # -- ack processing ------------------------------------------------------

    def _read_acks(self) -> None:
        """Drain any ACK frames the server wrote back (non-blocking)."""
        sock = self._sock
        if sock is None or not self.durable:
            return
        try:
            while True:
                r, _, _ = select.select([sock], [], [], 0)
                if not r:
                    return
                data = sock.recv(4096)
                if not data:
                    raise OSError("server closed connection")
                for header, payload in self._ackdec.feed(data):
                    if header.msg_type == MessageType.ACK:
                        self._on_ack(
                            struct.unpack_from(SEQ_EXT_FMT, payload)[0])
        except (OSError, FrameDecodeError, struct.error) as e:
            log.warning("ack channel failed (%s); reconnecting", e)
            self.stats["errors"] += 1
            self._close()
            self._server_idx = (self._server_idx + 1) % len(self.servers)

    def _on_ack(self, seq: int) -> None:
        if seq <= self._acked:
            return
        self._acked = seq
        self.stats["acked_seq"] = seq
        for s in [s for s in self._unacked if s <= seq]:
            del self._unacked[s]
        kept = []
        for f in self._pending:
            if f.seq > seq:
                kept.append(f)
            elif f.needs_account:
                # the server acked a frame we thought undelivered (e.g.
                # a chaos partial write that actually landed whole):
                # it IS delivered; close its ledger entry
                self._hop.account(delivered=1)
                f.needs_account = False
        self._pending = kept
        self._evicted_unacked = {s for s in self._evicted_unacked
                                 if s > seq}
        if self.spool is not None:
            self.spool.trim(seq)
        # the ack may have drained everything below a dead gap (e.g. a
        # recovered spool's old-boot records just finished): announce the
        # jump so the server's watermark doesn't stall at the gap's edge
        if self._outstanding_base() > seq + 1:
            self._base_dirty = True

    # -- send loop -----------------------------------------------------------

    def _next_frame(self) -> _Frame | None:
        if self._pending:
            return self._pending.pop(0)
        try:
            return self._q.get(timeout=0.2)
        except queue.Empty:
            return None

    def _send_frame(self, f: _Frame) -> None:
        self._inflight = f
        is_retransmit = not f.needs_account
        if self.durable and f.seq is None:
            # first wire write: the seq is born here, in write order, so
            # the watermark at the server stays gap-free for frames that
            # actually travel (spooled frames got theirs at spool time)
            f.seq = self._alloc_seq()
        frame = encode_frame(
            FrameHeader(f.msg_type, agent_id=self.agent_id,
                        org_id=self.org_id, team_id=self.team_id,
                        seq=f.seq if self.durable else None),
            f.payload)
        try:
            if self._chaos is not None:
                self._chaos.on_send(self._sock, frame)
            else:
                self._sock.sendall(frame)
            self.stats["sent_frames"] += 1
            self.stats["sent_bytes"] += len(frame)
            if is_retransmit:
                self.stats["retransmits"] += 1
            if f.needs_account:
                if f.enq_ns is not None:
                    self._hop.account(
                        delivered=1,
                        wait_ns=time.monotonic_ns() - f.enq_ns)
                else:
                    self._hop.account(delivered=1)
                f.needs_account = False
            if self.durable:
                self._unacked[f.seq] = f
                self._cap_unacked()
        except OSError as e:
            # the frame is NOT lost: keep it at the head of the
            # retransmit list (or spool it) before rotating servers
            self.stats["errors"] += 1
            log.warning("send failed (%s); reconnecting", e)
            if f.seq is None:  # non-durable: spool still keys on seq
                f.seq = self._alloc_seq()
            if self.spool is not None and f.needs_account \
                    and f.seq > self._spool_replayed_through:
                if self.spool.append(int(f.msg_type), f.seq, f.payload):
                    self.stats["spooled"] += 1
                else:
                    self._pending.insert(0, f)
            else:
                self._pending.insert(0, f)
            self._close()
            self._server_idx = (self._server_idx + 1) % len(self.servers)
        finally:
            self._inflight = None

    def _cap_unacked(self) -> None:
        """Bound retransmit-window memory. Evicted frames were DELIVERED
        (ledger-wise nothing is lost) — we only give up the ability to
        retransmit them, so delivery degrades to at-most-once beyond the
        window. Sized so a well-acking server never hits it."""
        while len(self._unacked) > self.ack_window:
            oldest = min(self._unacked)
            del self._unacked[oldest]
            self._evicted_unacked.add(oldest)
            self.stats["unacked_evicted"] += 1
        # bound the evicted-seq floor set too; beyond it delivery was
        # already at-most-once, so forgetting the oldest loses nothing
        while len(self._evicted_unacked) > 4 * self.ack_window:
            self._evicted_unacked.discard(min(self._evicted_unacked))

    def _run(self) -> None:
        backoff = 0.1
        hb = self._telemetry.heartbeat("sender")
        while not self._stop.is_set():
            hb.beat(progress=self.stats["sent_frames"])
            if self._sock is None:
                if not self._connect():
                    # interruptible backoff: flush_and_stop used to eat
                    # up to 5s of unkillable time.sleep() here
                    if self._stop.wait(min(backoff, 5.0)):
                        return
                    backoff = min(backoff * 2, 5.0)
                    continue
                backoff = 0.1
                if self.durable:
                    # adopt this boot's seq space / skip dead gaps
                    self._send_base()
                    if self._sock is None:
                        continue
            self._read_acks()
            if self._sock is None:
                continue  # ack channel died; reconnect first
            if self.durable and self._base_dirty:
                self._send_base()
                if self._sock is None:
                    continue
            f = self._next_frame()
            if f is None:
                # idle: frames that overflowed into the spool while the
                # connection was busy drain now, without a reconnect
                if self.durable:
                    self._load_replay()
                continue
            self._send_frame(f)


class ReplicatedSender:
    """Replicated shipping: one independent UniformSender per owner
    destination, HIGH/MID frames fanned to all of them, LOW frames to
    the primary only (sheddable data doesn't earn R copies).

    Each destination gets its OWN seq space, ack window, and spool
    subdirectory — per-server watermarks are already independent on the
    server side, so the existing seq/ack/spool machinery applies per
    destination unchanged: a dead primary's frames sit durably in its
    replica senders' windows/spools and the replicas' copies are what
    the query-time claim filter promotes when the primary dies.

    ``set_destinations`` (driven by the synchronizer's analyzer_addrs
    path on a ring-epoch bump) rebalances without dropping frames: a
    removed destination's un-acked/spooled frames are harvested via
    ``drain_unsent`` and re-shipped to the newly added owners (never to
    retained ones, which already hold their own copies).

    Duck-types the UniformSender surface the agent's components use:
    send / start / flush_and_stop / servers / agent_id / stats /
    queue_depth / peek.
    """

    def __init__(self, servers: list, replication: int = 2,
                 agent_id: int = 0, org_id: int = 0, team_id: int = 0,
                 queue_size: int = 8192, connect_timeout: float = 3.0,
                 telemetry=None, spool_factory=None, ack_window: int = 1024,
                 durable: bool = True, chaos=None) -> None:
        if not servers:
            raise ValueError("need at least one server address")
        from deepflow_tpu.agent.config import _parse_addr
        parsed = [_parse_addr(s) if isinstance(s, str) else tuple(s)
                  for s in servers]
        self.replication = max(1, int(replication))
        self._agent_id = agent_id
        self.org_id = org_id
        self.team_id = team_id
        self._kw = dict(queue_size=queue_size,
                        connect_timeout=connect_timeout,
                        telemetry=telemetry, ack_window=ack_window,
                        durable=durable, chaos=chaos)
        # spool_factory(dest_key) -> Spool | None: one spool dir per
        # destination (their seq spaces are unrelated; sharing a spool
        # would interleave them and break trim/replay watermarks)
        self._spool_factory = spool_factory or (lambda key: None)
        self._lock = threading.Lock()
        self._senders: dict[tuple, UniformSender] = {}
        self._order: list[tuple] = []
        self._started = False
        self.stats = {"rebalances": 0, "reshipped": 0}
        for dest in parsed[:self.replication]:
            self._add_dest(dest)

    @staticmethod
    def _dest_key(dest: tuple) -> str:
        return f"{dest[0]}_{dest[1]}".replace(":", "_")

    def _add_dest(self, dest: tuple) -> None:
        s = UniformSender([dest], agent_id=self._agent_id,
                          org_id=self.org_id, team_id=self.team_id,
                          spool=self._spool_factory(self._dest_key(dest)),
                          **self._kw)
        self._senders[dest] = s
        self._order.append(dest)
        if self._started:
            s.start()

    # -- UniformSender surface ----------------------------------------------

    @property
    def agent_id(self) -> int:
        return self._agent_id

    @agent_id.setter
    def agent_id(self, v: int) -> None:
        self._agent_id = v
        with self._lock:
            for s in self._senders.values():
                s.agent_id = v

    @property
    def servers(self) -> list:
        with self._lock:
            return list(self._order)

    @servers.setter
    def servers(self, addrs: list) -> None:
        self.set_destinations(addrs)

    def start(self) -> "ReplicatedSender":
        with self._lock:
            self._started = True
            for s in self._senders.values():
                s.start()
        return self

    def send(self, msg_type: MessageType, payload: bytes) -> bool:
        with self._lock:
            if not self._order:
                return False
            if priority_of(msg_type) >= 2:   # LOW: primary only
                targets = [self._senders[self._order[0]]]
            else:
                targets = [self._senders[d] for d in self._order]
        ok = False
        for s in targets:
            ok = s.send(msg_type, payload) or ok
        return ok

    def queue_depth(self) -> int:
        with self._lock:
            return max((s.queue_depth()
                        for s in self._senders.values()), default=0)

    def peek(self, n: int = 8) -> list:
        with self._lock:
            primary = self._senders.get(self._order[0]) \
                if self._order else None
        return primary.peek(n) if primary is not None else []

    def flush_and_stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            senders = list(self._senders.values())
        threads = [threading.Thread(
            target=s.flush_and_stop, kwargs={"timeout": timeout},
            daemon=True) for s in senders]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 2.0)

    # -- rebalance -----------------------------------------------------------

    def set_destinations(self, addrs: list) -> None:
        """Adopt a new owner list (ring order, primary first). Senders
        for retained destinations keep running untouched — their
        windows, spools and seq spaces survive the rebalance — so no
        spooled or un-acked frame is dropped on an epoch bump."""
        from deepflow_tpu.agent.config import _parse_addr
        parsed = [_parse_addr(a) if isinstance(a, str) else tuple(a)
                  for a in addrs][:self.replication]
        with self._lock:
            if parsed == self._order or not parsed:
                return
            removed = [d for d in self._order if d not in parsed]
            added = [d for d in parsed if d not in self._senders]
            harvested: list[tuple] = []
            for dest in removed:
                s = self._senders.pop(dest)
                harvested.extend(s.drain_unsent())
            for dest in added:
                self._add_dest(dest)
            self._order = parsed
            new_targets = [self._senders[d] for d in added]
            self.stats["rebalances"] += 1
        # re-ship a lost owner's outstanding frames to the NEW owners
        # only: retained destinations already hold their own copies, and
        # a second copy there would be a same-shard duplicate row (each
        # boot's seq space is fresh, so the server-side dedup window
        # cannot catch it)
        if harvested and new_targets:
            for mt, payload in harvested:
                for s in new_targets:
                    s.send(mt, payload)
            self.stats["reshipped"] += len(harvested)

    # -- diagnostics ---------------------------------------------------------

    def stat_totals(self) -> dict:
        """Summed per-destination UniformSender stats (diagnostics)."""
        out: dict = {}
        with self._lock:
            senders = list(self._senders.values())
        for s in senders:
            for k, v in s.stats.items():
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
        return out

    def per_destination(self) -> dict:
        with self._lock:
            return {f"{h}:{p}": dict(s.stats)
                    for (h, p), s in self._senders.items()}
