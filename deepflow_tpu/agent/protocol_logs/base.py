"""Parser interface, result record, and the inference registry."""

from __future__ import annotations

from dataclasses import dataclass, field

from deepflow_tpu.proto import pb

MSG_REQUEST = 0
MSG_RESPONSE = 1


@dataclass
class L7ParseResult:
    l7_protocol: int
    msg_type: int                    # MSG_REQUEST | MSG_RESPONSE
    version: str = ""
    request_type: str = ""           # method / command
    request_domain: str = ""         # host / db
    request_resource: str = ""       # path / table / key / topic
    endpoint: str = ""
    request_id: int = 0              # protocol-level correlation id
    response_code: int = 0
    response_status: int = 0         # schema RESPONSE_STATUS index
    response_exception: str = ""
    response_result: str = ""
    trace_id: str = ""
    span_id: str = ""
    x_request_id: str = ""
    captured_byte: int = 0
    session_less: bool = False  # fire-and-forget: no response expected
    attrs: dict = field(default_factory=dict)


class L7Parser:
    PROTOCOL: int = pb.L7_UNKNOWN
    NAME: str = "unknown"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        """Cheap magic-byte inference on a request-direction payload."""
        raise NotImplementedError

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        """Parse one captured payload into zero or more records."""
        raise NotImplementedError


def status_from_code(code: int, server_error_min: int = 500,
                     client_error_min: int = 400) -> int:
    # RESPONSE_STATUS: 0 unknown, 1 ok, 2 client_error, 3 server_error, 4 timeout
    if code >= server_error_min:
        return 3
    if code >= client_error_min:
        return 2
    return 1


REGISTRY: list[L7Parser] = []


def register(parser_cls):
    REGISTRY.append(parser_cls())
    return parser_cls


def infer_and_parse(payload: bytes, port_dst: int = 0
                    ) -> tuple[int, list[L7ParseResult]]:
    """Try parsers in registry order. Returns (protocol, records)."""
    for parser in REGISTRY:
        try:
            if parser.check(payload, port_dst):
                return parser.PROTOCOL, parser.parse(payload)
        except Exception:
            continue
    return pb.L7_UNKNOWN, []


def get_parser(protocol: int) -> L7Parser | None:
    for p in REGISTRY:
        if p.PROTOCOL == protocol:
            return p
    return None


# importing the modules populates the registry, in priority order
from deepflow_tpu.agent.protocol_logs import http  # noqa: E402,F401
# ping before dns: its port==0 gate is unambiguous (ICMP only), while the
# DNS header sanity check can collide with ICMP echo layouts
from deepflow_tpu.agent.protocol_logs import ping  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import dns  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import redis  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import sqldb  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import nosql  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import mq  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import messaging  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import rpc  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import rpc2  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import enterprise  # noqa: E402,F401
from deepflow_tpu.agent.protocol_logs import tls  # noqa: E402,F401
