"""ICMP echo (ping) parser — reference lists Ping in the CE protocol set."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)


@register
class PingParser(L7Parser):
    PROTOCOL = pb.PING
    NAME = "ping"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        # ICMP flows are the only ones with port 0 (TCP/UDP always carry a
        # dst port) — without this gate, zero-heavy TCP payloads match
        if port_dst != 0 or len(payload) < 8:
            return False
        t = payload[0]
        return t in (0, 8, 128, 129) and payload[1] == 0

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        t = payload[0]
        ident, seq = struct.unpack_from(">HH", payload, 4)
        is_req = t in (8, 128)
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_REQUEST if is_req else MSG_RESPONSE,
            request_type="echo-request" if is_req else "echo-reply",
            request_id=(ident << 16) | seq,
            endpoint=f"id={ident}",
            captured_byte=len(payload))
        if not is_req:
            res.response_status = 1
        return [res]
