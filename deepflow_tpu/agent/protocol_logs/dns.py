"""DNS parser (reference analog: protocol_logs/dns.rs)."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_QTYPES = {1: "A", 2: "NS", 5: "CNAME", 6: "SOA", 12: "PTR", 15: "MX",
           16: "TXT", 28: "AAAA", 33: "SRV", 65: "HTTPS", 255: "ANY"}
_RCODES = {0: "NOERROR", 1: "FORMERR", 2: "SERVFAIL", 3: "NXDOMAIN",
           4: "NOTIMP", 5: "REFUSED"}


def _read_name(data: bytes, off: int, depth: int = 0) -> tuple[str, int]:
    labels = []
    while off < len(data):
        ln = data[off]
        if ln == 0:
            off += 1
            break
        if ln & 0xC0 == 0xC0:  # compression pointer
            if depth > 5 or off + 1 >= len(data):
                break
            ptr = ((ln & 0x3F) << 8) | data[off + 1]
            tail, _ = _read_name(data, ptr, depth + 1)
            labels.append(tail)
            off += 2
            return ".".join(x for x in labels if x), off
        off += 1
        labels.append(data[off:off + ln].decode("latin1", "replace"))
        off += ln
    return ".".join(x for x in labels if x), off


@register
class DnsParser(L7Parser):
    PROTOCOL = pb.DNS
    NAME = "dns"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 12:
            return False
        if port_dst == 53:
            return True
        flags = struct.unpack_from(">H", payload, 2)[0]
        qd = struct.unpack_from(">H", payload, 4)[0]
        opcode = (flags >> 11) & 0xF
        z = (flags >> 4) & 0x7
        if not (1 <= qd < 16 and opcode in (0, 1, 2) and z == 0):
            return False
        # off-port: the header heuristic alone misfires on binary protocols
        # (fastcgi, icmp) — also require a well-formed non-empty qname with
        # hostname-ish labels and a known qtype
        name, off = _read_name(payload, 12)
        if not name or off + 4 > len(payload):
            return False
        qtype = struct.unpack_from(">H", payload, off)[0]
        if qtype not in _QTYPES:
            return False
        return all(c.isalnum() or c in "-_." for c in name)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        txid, flags, qd, an, _ns, _ar = struct.unpack_from(">HHHHHH",
                                                           payload, 0)
        is_response = bool(flags & 0x8000)
        rcode = flags & 0xF
        name, off = _read_name(payload, 12)
        qtype = 0
        if off + 4 <= len(payload):
            qtype = struct.unpack_from(">H", payload, off)[0]
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_response else MSG_REQUEST,
            request_type=_QTYPES.get(qtype, str(qtype)),
            request_resource=name,
            request_domain=name,
            endpoint=name,
            request_id=txid,
            captured_byte=len(payload))
        if is_response:
            res.response_code = rcode
            res.response_status = 1 if rcode == 0 else (
                3 if rcode == 2 else 2)
            res.response_exception = "" if rcode == 0 else _RCODES.get(
                rcode, str(rcode))
            answers = []
            if an and off + 4 <= len(payload):
                a_off = off + 4
                for _ in range(min(an, 8)):
                    _nm, a_off = _read_name(payload, a_off)
                    if a_off + 10 > len(payload):
                        break
                    atype, _cls, _ttl, rdlen = struct.unpack_from(
                        ">HHIH", payload, a_off)
                    a_off += 10
                    rdata = payload[a_off:a_off + rdlen]
                    a_off += rdlen
                    if atype == 1 and rdlen == 4:
                        answers.append(".".join(str(b) for b in rdata))
                    elif atype == 28 and rdlen == 16:
                        import ipaddress
                        answers.append(str(ipaddress.ip_address(rdata)))
                    elif atype == 5:
                        cname, _ = _read_name(payload, a_off - rdlen)
                        answers.append(cname)
            res.response_result = ";".join(answers)
        return [res]
