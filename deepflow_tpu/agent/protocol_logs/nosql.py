"""MongoDB and Memcached parsers (reference analog: protocol_logs/mongo.rs,
memcached.rs)."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_MONGO_OPS = {1: "OP_REPLY", 2004: "OP_QUERY", 2005: "OP_GET_MORE",
              2010: "OP_COMMAND", 2011: "OP_COMMANDREPLY", 2012: "OP_COMPRESSED",
              2013: "OP_MSG"}


@register
class MongoParser(L7Parser):
    PROTOCOL = pb.MONGODB
    NAME = "mongodb"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 16:
            return False
        msg_len, _req_id, _resp_to, opcode = struct.unpack_from(
            "<IIII", payload, 0)
        return opcode in _MONGO_OPS and 16 <= msg_len < (1 << 26) and (
            port_dst == 27017 or msg_len == len(payload))

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        _msg_len, req_id, resp_to, opcode = struct.unpack_from(
            "<IIII", payload, 0)
        is_response = opcode in (1, 2011) or resp_to != 0
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_response else MSG_REQUEST,
            request_type=_MONGO_OPS.get(opcode, str(opcode)),
            request_id=resp_to if is_response else req_id,
            captured_byte=len(payload))
        if not is_response and opcode == 2013 and len(payload) > 26:
            # OP_MSG: flag(4) + section kind(1) + BSON doc; first key is the
            # command name, its value the collection
            cmd, coll = _bson_first_pair(payload[21:])
            res.request_type = cmd or res.request_type
            res.request_resource = coll
            res.endpoint = coll
        if not is_response and opcode == 2004:
            # OP_QUERY: flags(4) + fullCollectionName cstring
            name_end = payload.find(b"\x00", 20)
            if name_end > 0:
                res.request_resource = payload[20:name_end].decode(
                    "latin1", "replace")
                res.endpoint = res.request_resource
        if is_response:
            res.response_status = 1
        return [res]


def _bson_first_pair(doc: bytes) -> tuple[str, str]:
    if len(doc) < 5:
        return "", ""
    etype = doc[4]
    key_end = doc.find(b"\x00", 5)
    if key_end < 0:
        return "", ""
    key = doc[5:key_end].decode("latin1", "replace")
    value = ""
    if etype == 2 and key_end + 5 <= len(doc):  # string
        slen = struct.unpack_from("<I", doc, key_end + 1)[0]
        value = doc[key_end + 5:key_end + 4 + slen].decode(
            "latin1", "replace")
    return key, value


_MC_REQ = (b"get ", b"gets ", b"set ", b"add ", b"replace ", b"delete ",
           b"incr ", b"decr ", b"append ", b"prepend ", b"cas ", b"touch ",
           b"stats", b"flush_all", b"version")
_MC_RESP = (b"VALUE ", b"STORED", b"NOT_STORED", b"END", b"DELETED",
            b"NOT_FOUND", b"ERROR", b"CLIENT_ERROR", b"SERVER_ERROR",
            b"TOUCHED", b"VERSION ")


@register
class MemcachedParser(L7Parser):
    PROTOCOL = pb.MEMCACHED
    NAME = "memcached"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if payload.startswith(_MC_REQ):
            return b"\r\n" in payload
        return port_dst == 11211 and payload.startswith(_MC_RESP)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        first = payload.split(b"\r\n", 1)[0]
        if payload.startswith(_MC_RESP):
            err = payload.startswith((b"ERROR", b"CLIENT_ERROR",
                                      b"SERVER_ERROR"))
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                response_status=3 if payload.startswith(b"SERVER_ERROR")
                else (2 if err else 1),
                response_exception=first.decode("latin1", "replace")
                if err else "",
                response_result="" if err else first[:64].decode(
                    "latin1", "replace"),
                captured_byte=len(payload))]
        parts = first.split(b" ")
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
            request_type=parts[0].decode("latin1", "replace").upper(),
            request_resource=(parts[1].decode("latin1", "replace")
                              if len(parts) > 1 else ""),
            endpoint=parts[0].decode("latin1", "replace").upper(),
            captured_byte=len(payload))]
