"""MQTT, NATS, AMQP parsers (reference analog: protocol_logs/mqtt.rs,
plugins for NATS/AMQP in the CE list l7_protocol_log.rs:163-226)."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_MQTT_TYPES = {
    1: "CONNECT", 2: "CONNACK", 3: "PUBLISH", 4: "PUBACK", 5: "PUBREC",
    6: "PUBREL", 7: "PUBCOMP", 8: "SUBSCRIBE", 9: "SUBACK",
    10: "UNSUBSCRIBE", 11: "UNSUBACK", 12: "PINGREQ", 13: "PINGRESP",
    14: "DISCONNECT"}
_MQTT_RESPONSES = {2, 4, 5, 7, 9, 11, 13}


@register
class MqttParser(L7Parser):
    PROTOCOL = pb.MQTT
    NAME = "mqtt"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 2:
            return False
        ptype = payload[0] >> 4
        if ptype == 1:  # CONNECT carries the protocol name
            return b"MQTT" in payload[:16] or b"MQIsdp" in payload[:16]
        return port_dst == 1883 and ptype in _MQTT_TYPES

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        ptype = payload[0] >> 4
        name = _MQTT_TYPES.get(ptype, str(ptype))
        # variable-length 'remaining length'
        i, mult, _rem = 1, 1, 0
        while i < min(len(payload), 5):
            b = payload[i]
            _rem += (b & 0x7F) * mult
            mult *= 128
            i += 1
            if not b & 0x80:
                break
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=(MSG_RESPONSE if ptype in _MQTT_RESPONSES
                      else MSG_REQUEST),
            request_type=name, endpoint=name,
            captured_byte=len(payload))
        if ptype == 3:
            qos = (payload[0] >> 1) & 0x3
            res.session_less = qos == 0  # QoS0: fire-and-forget
        if ptype == 3 and i + 2 <= len(payload):  # PUBLISH: topic string
            tlen = struct.unpack_from(">H", payload, i)[0]
            topic = payload[i + 2:i + 2 + tlen]
            res.request_resource = topic.decode("latin1", "replace")
            res.endpoint = res.request_resource
        if ptype == 2 and len(payload) >= 4:  # CONNACK return code
            rc = payload[3]
            res.response_code = rc
            res.response_status = 1 if rc == 0 else 3
        elif res.msg_type == MSG_RESPONSE:
            res.response_status = 1
        return [res]


# unambiguous NATS verbs (port-free) vs reply tokens Redis/RESP also emits
_NATS_VERBS = (b"PUB ", b"SUB ", b"UNSUB ", b"HPUB ", b"HMSG ")
_NATS_AMBIGUOUS = (b"MSG ", b"CONNECT ", b"INFO ", b"PING", b"PONG",
                   b"+OK", b"-ERR")


@register
class NatsParser(L7Parser):
    PROTOCOL = pb.NATS
    NAME = "nats"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if b"\r\n" not in payload[:512]:
            return False
        if payload.startswith(_NATS_VERBS):
            return True
        # +OK/-ERR/PING/... collide with Redis RESP: require the NATS port
        return port_dst == 4222 and payload.startswith(_NATS_AMBIGUOUS)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        first = payload.split(b"\r\n", 1)[0]
        parts = first.split(b" ")
        verb = parts[0].decode("latin1", "replace")
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=(MSG_RESPONSE if verb in ("+OK", "-ERR", "PONG",
                                               "INFO", "MSG", "HMSG")
                      else MSG_REQUEST),
            request_type=verb, endpoint=verb,
            captured_byte=len(payload))
        if verb in ("PUB", "HPUB"):
            res.session_less = True
        if verb in ("PUB", "SUB", "HPUB", "MSG", "HMSG") and len(parts) > 1:
            res.request_resource = parts[1].decode("latin1", "replace")
            res.endpoint = res.request_resource
        if verb == "-ERR":
            res.response_status = 3
            res.response_exception = first[5:].decode("latin1", "replace")
        elif res.msg_type == MSG_RESPONSE:
            res.response_status = 1
        return [res]


_AMQP_CLASSES = {10: "connection", 20: "channel", 40: "exchange",
                 50: "queue", 60: "basic", 90: "tx"}
_AMQP_METHODS = {(60, 40): "basic.publish", (60, 60): "basic.deliver",
                 (60, 71): "basic.get-ok", (60, 80): "basic.ack",
                 (50, 10): "queue.declare", (50, 11): "queue.declare-ok",
                 (40, 10): "exchange.declare", (10, 10): "connection.start",
                 (10, 11): "connection.start-ok", (20, 10): "channel.open"}


@register
class AmqpParser(L7Parser):
    PROTOCOL = pb.AMQP
    NAME = "amqp"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if payload.startswith(b"AMQP\x00"):
            return True
        if len(payload) < 12 or port_dst != 5672:
            return False
        ftype = payload[0]
        size = struct.unpack_from(">I", payload, 3)[0]
        return ftype in (1, 2, 3, 8) and size < (1 << 24)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if payload.startswith(b"AMQP\x00"):
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type="protocol-header", endpoint="protocol-header",
                captured_byte=len(payload))]
        ftype = payload[0]
        if ftype != 1 or len(payload) < 12:  # only method frames parsed
            return []
        class_id, method_id = struct.unpack_from(">HH", payload, 7)
        name = _AMQP_METHODS.get(
            (class_id, method_id),
            f"{_AMQP_CLASSES.get(class_id, class_id)}.{method_id}")
        is_resp = method_id % 2 == 1 and method_id > 10 or \
            name.endswith(("-ok", "ack"))
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_resp else MSG_REQUEST,
            request_type=name, endpoint=name,
            captured_byte=len(payload))
        if is_resp:
            res.response_status = 1
        return [res]
