"""Kafka parser (reference analog: protocol_logs/mq/kafka.rs)."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_API_KEYS = {
    0: "Produce", 1: "Fetch", 2: "ListOffsets", 3: "Metadata",
    8: "OffsetCommit", 9: "OffsetFetch", 10: "FindCoordinator",
    11: "JoinGroup", 12: "Heartbeat", 13: "LeaveGroup", 14: "SyncGroup",
    15: "DescribeGroups", 16: "ListGroups", 18: "ApiVersions",
    19: "CreateTopics", 20: "DeleteTopics",
}


@register
class KafkaParser(L7Parser):
    PROTOCOL = pb.KAFKA
    NAME = "kafka"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 14:
            return False
        size = struct.unpack_from(">i", payload, 0)[0]
        api_key, api_ver = struct.unpack_from(">hh", payload, 4)
        corr = struct.unpack_from(">i", payload, 8)[0]
        client_len = struct.unpack_from(">h", payload, 12)[0]
        return (8 <= size < (1 << 24) and api_key in _API_KEYS
                and 0 <= api_ver <= 20 and corr >= 0
                and -1 <= client_len < 256
                and (port_dst == 9092 or size <= len(payload) + 4096))

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if not is_request:
            # response layout: size + correlation_id + body (no api key)
            if len(payload) < 8:
                return []
            corr = struct.unpack_from(">i", payload, 4)[0]
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                request_id=corr, response_status=1,
                captured_byte=len(payload))]
        api_key, api_ver = struct.unpack_from(">hh", payload, 4)
        corr = struct.unpack_from(">i", payload, 8)[0]
        name = _API_KEYS.get(api_key, str(api_key))
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
            version=str(api_ver),
            request_type=name,
            request_id=corr,
            endpoint=name,
            captured_byte=len(payload))
        # topic extraction for Produce/Fetch v0-ish layouts (best effort)
        client_len = struct.unpack_from(">h", payload, 12)[0]
        off = 14 + max(0, client_len)
        if api_key in (0, 1) and off + 6 < len(payload):
            probe = payload[off:off + 64]
            topic = _first_string(probe)
            if topic:
                res.request_resource = topic
        return [res]

def _first_string(buf: bytes) -> str:
    """Scan for a plausible length-prefixed string (kafka topic)."""
    for i in range(0, max(0, len(buf) - 2)):
        ln = struct.unpack_from(">h", buf, i)[0]
        if 1 <= ln <= 64 and i + 2 + ln <= len(buf):
            s = buf[i + 2:i + 2 + ln]
            if all(32 <= c < 127 for c in s) and (
                    s.replace(b"-", b"").replace(b"_", b"")
                    .replace(b".", b"").isalnum()):
                return s.decode()
    return ""


# ---------------------------------------------------------------------------
# Pulsar (reference analog: protocol_logs/mq/pulsar.rs + PulsarApi.proto)
#
# Wire format: [total_size u32 BE][command_size u32 BE][BaseCommand protobuf]
# then, for SEND/MESSAGE frames, optional broker entry metadata (magic
# 0x0e02) and message metadata (magic 0x0e01 + crc32c + size + pb) + payload.
# BaseCommand field 1 is the command type enum; the per-type sub-message
# lives at the field number EQUAL to the enum value (PulsarApi.proto:963).
# Decoded generically with the in-repo protobuf wire reader — no generated
# stubs for the 1100-line PulsarApi.proto needed for the fields we surface.
# ---------------------------------------------------------------------------

from deepflow_tpu.tpuprobe import pbwire as _pbw

_P_REQ, _P_RESP, _P_SESS = 0, 1, 2

# type -> (name, kind, request_id field in sub-msg, topic field,
#          (error_code_field, error_msg_field) | None).
# request_id -1 = Send family packing: (producer_id & 0xFFFF) << 16 |
# (sequence_id & 0xFFFF), mirroring the reference's get_msg_req.
_PULSAR_CMDS = {
    2: ("Connect", _P_REQ, 0, 0, None),
    3: ("Connected", _P_RESP, 0, 0, None),
    4: ("Subscribe", _P_REQ, 5, 1, None),
    5: ("Producer", _P_REQ, 3, 1, None),
    6: ("Send", _P_REQ, -1, 0, None),
    7: ("SendReceipt", _P_RESP, -1, 0, None),
    8: ("SendError", _P_RESP, -1, 0, (3, 4)),
    9: ("Message", _P_SESS, 0, 0, None),
    10: ("Ack", _P_SESS, 0, 0, None),
    11: ("Flow", _P_SESS, 0, 0, None),
    12: ("Unsubscribe", _P_REQ, 2, 0, None),
    13: ("Success", _P_RESP, 1, 0, None),
    14: ("Error", _P_RESP, 1, 0, (2, 3)),
    15: ("CloseProducer", _P_REQ, 2, 0, None),
    16: ("CloseConsumer", _P_REQ, 2, 0, None),
    17: ("ProducerSuccess", _P_RESP, 1, 0, None),
    18: ("Ping", _P_REQ, 0, 0, None),
    19: ("Pong", _P_RESP, 0, 0, None),
    20: ("RedeliverUnacknowledgedMessages", _P_SESS, 0, 0, None),
    21: ("PartitionedMetadata", _P_REQ, 2, 1, None),
    22: ("PartitionedMetadataResponse", _P_RESP, 2, 0, (4, 5)),
    23: ("Lookup", _P_REQ, 2, 1, None),
    24: ("LookupResponse", _P_RESP, 4, 0, (6, 7)),
    25: ("ConsumerStats", _P_REQ, 1, 0, None),
    26: ("ConsumerStatsResponse", _P_RESP, 1, 0, (2, 3)),
    27: ("ReachedEndOfTopic", _P_SESS, 0, 0, None),
    28: ("Seek", _P_REQ, 2, 0, None),
    29: ("GetLastMessageId", _P_REQ, 2, 0, None),
    30: ("GetLastMessageIdResponse", _P_RESP, 2, 0, None),
    31: ("ActiveConsumerChange", _P_SESS, 0, 0, None),
    32: ("GetTopicsOfNamespace", _P_REQ, 1, 0, None),
    33: ("GetTopicsOfNamespaceResponse", _P_RESP, 1, 0, None),
    34: ("GetSchema", _P_REQ, 1, 2, None),
    35: ("GetSchemaResponse", _P_RESP, 1, 0, (2, 3)),
    36: ("AuthChallenge", _P_REQ, 0, 0, None),
    37: ("AuthResponse", _P_RESP, 0, 0, None),
    38: ("AckResponse", _P_SESS, 0, 0, None),
    39: ("GetOrCreateSchema", _P_REQ, 1, 2, None),
    40: ("GetOrCreateSchemaResponse", _P_RESP, 1, 0, (2, 3)),
    # transaction family: request_id=1 across the board; response error
    # codes left to the generic Error command (txn error layouts vary)
    50: ("NewTxn", _P_REQ, 1, 0, None),
    51: ("NewTxnResponse", _P_RESP, 1, 0, (4, 5)),
    52: ("AddPartitionToTxn", _P_REQ, 1, 0, None),
    53: ("AddPartitionToTxnResponse", _P_RESP, 1, 0, (4, 5)),
    54: ("AddSubscriptionToTxn", _P_REQ, 1, 0, None),
    55: ("AddSubscriptionToTxnResponse", _P_RESP, 1, 0, (4, 5)),
    56: ("EndTxn", _P_REQ, 1, 0, None),
    57: ("EndTxnResponse", _P_RESP, 1, 0, (4, 5)),
    58: ("EndTxnOnPartition", _P_REQ, 1, 0, None),
    59: ("EndTxnOnPartitionResponse", _P_RESP, 1, 0, (2, 3)),
    60: ("EndTxnOnSubscription", _P_REQ, 1, 0, None),
    61: ("EndTxnOnSubscriptionResponse", _P_RESP, 1, 0, (2, 3)),
    62: ("TcClientConnectRequest", _P_REQ, 1, 0, None),
    63: ("TcClientConnectResponse", _P_RESP, 1, 0, (2, 3)),
    64: ("WatchTopicList", _P_SESS, 0, 0, None),
    65: ("WatchTopicListSuccess", _P_SESS, 0, 0, None),
    66: ("WatchTopicUpdate", _P_SESS, 0, 0, None),
    67: ("WatchTopicListClose", _P_SESS, 0, 0, None),
    68: ("TopicMigrated", _P_SESS, 0, 0, None),
}


def _pulsar_frame(payload: bytes, off: int):
    """Decode one framed BaseCommand at off. Returns (cmd_type, sub_fields,
    next_off) or None. sub_fields is the fields_dict of the sub-message."""
    if off + 8 > len(payload):
        return None
    total = struct.unpack_from(">I", payload, off)[0]
    csize = struct.unpack_from(">I", payload, off + 4)[0]
    if csize + 4 > total or total > (5 << 20):
        return None
    end = off + 8 + csize
    if end > len(payload):
        return None
    try:
        cmd = _pbw.fields_dict(payload[off + 8:end])
    except _pbw.WireError:
        return None
    ctype = _pbw.first(cmd, 1)
    meta = _PULSAR_CMDS.get(ctype)
    if meta is None:
        return None
    sub = _pbw.first(cmd, ctype)
    if not isinstance(sub, bytes):
        return None
    try:
        sub_fields = _pbw.fields_dict(sub)
    except _pbw.WireError:
        return None
    return ctype, sub_fields, off + 4 + total


def _short_topic(t: str) -> str:
    # persistent://tenant/namespace/topic -> topic (reference get_topic)
    return t.rsplit("/", 1)[-1] if t else t


@register
class PulsarParser(L7Parser):
    PROTOCOL = pb.PULSAR
    NAME = "pulsar"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        f = _pulsar_frame(payload, 0)
        if f is None:
            return False
        # a parseable BaseCommand with a known type and its own sub-message
        # is already a strong signal; off-port, require Connect/Connected
        # (every Pulsar connection starts with them) to avoid false matches
        return port_dst == 6650 or f[0] in (2, 3)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        out = []
        off = 0
        while off < len(payload) and len(out) < 16:
            f = _pulsar_frame(payload, off)
            if f is None:
                break
            ctype, sub, next_off = f
            name, kind, rid_field, topic_field, err = _PULSAR_CMDS[ctype]
            if kind == _P_SESS:
                msg_type = MSG_REQUEST if is_request else MSG_RESPONSE
            else:
                msg_type = MSG_REQUEST if kind == _P_REQ else MSG_RESPONSE
            r = L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=msg_type,
                request_type=name, endpoint=name,
                session_less=kind == _P_SESS,
                captured_byte=next_off - off)
            if rid_field == -1:  # Send family: producer_id + sequence_id
                pid = _pbw.first(sub, 1, 0)
                seq = _pbw.first(sub, 2, 0)
                r.request_id = ((int(pid) & 0xFFFF) << 16) | (int(seq) & 0xFFFF)
            elif rid_field:
                r.request_id = int(_pbw.first(sub, rid_field, 0)) & 0xFFFFFFFF
            if topic_field:
                topic = _pbw.as_str(_pbw.first(sub, topic_field, b""))
                r.request_resource = _short_topic(topic)
                if topic:
                    r.endpoint = f"{name} {r.request_resource}"
            if ctype == 2:  # Connect: protocol_version=4, broker url=6
                r.version = str(_pbw.first(sub, 4, 0))
                r.request_domain = _pbw.as_str(_pbw.first(sub, 6, b""))
            elif ctype == 3:  # Connected: protocol_version=2
                r.version = str(_pbw.first(sub, 2, 0))
            if msg_type == MSG_RESPONSE:
                code = _pbw.first(sub, err[0]) if err else None
                if code is not None:
                    r.response_status = 3  # server_error
                    r.response_code = int(code)
                    if err[1]:
                        r.response_exception = _pbw.as_str(
                            _pbw.first(sub, err[1], b""))
                else:
                    r.response_status = 1
            out.append(r)
            off = next_off
        return out
