"""Kafka parser (reference analog: protocol_logs/mq/kafka.rs)."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_API_KEYS = {
    0: "Produce", 1: "Fetch", 2: "ListOffsets", 3: "Metadata",
    8: "OffsetCommit", 9: "OffsetFetch", 10: "FindCoordinator",
    11: "JoinGroup", 12: "Heartbeat", 13: "LeaveGroup", 14: "SyncGroup",
    15: "DescribeGroups", 16: "ListGroups", 18: "ApiVersions",
    19: "CreateTopics", 20: "DeleteTopics",
}


@register
class KafkaParser(L7Parser):
    PROTOCOL = pb.KAFKA
    NAME = "kafka"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 14:
            return False
        size = struct.unpack_from(">i", payload, 0)[0]
        api_key, api_ver = struct.unpack_from(">hh", payload, 4)
        corr = struct.unpack_from(">i", payload, 8)[0]
        client_len = struct.unpack_from(">h", payload, 12)[0]
        return (8 <= size < (1 << 24) and api_key in _API_KEYS
                and 0 <= api_ver <= 20 and corr >= 0
                and -1 <= client_len < 256
                and (port_dst == 9092 or size <= len(payload) + 4096))

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if not is_request:
            # response layout: size + correlation_id + body (no api key)
            if len(payload) < 8:
                return []
            corr = struct.unpack_from(">i", payload, 4)[0]
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                request_id=corr, response_status=1,
                captured_byte=len(payload))]
        api_key, api_ver = struct.unpack_from(">hh", payload, 4)
        corr = struct.unpack_from(">i", payload, 8)[0]
        name = _API_KEYS.get(api_key, str(api_key))
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
            version=str(api_ver),
            request_type=name,
            request_id=corr,
            endpoint=name,
            captured_byte=len(payload))
        # topic extraction for Produce/Fetch v0-ish layouts (best effort)
        client_len = struct.unpack_from(">h", payload, 12)[0]
        off = 14 + max(0, client_len)
        if api_key in (0, 1) and off + 6 < len(payload):
            probe = payload[off:off + 64]
            topic = _first_string(probe)
            if topic:
                res.request_resource = topic
        return [res]

def _first_string(buf: bytes) -> str:
    """Scan for a plausible length-prefixed string (kafka topic)."""
    for i in range(0, max(0, len(buf) - 2)):
        ln = struct.unpack_from(">h", buf, i)[0]
        if 1 <= ln <= 64 and i + 2 + ln <= len(buf):
            s = buf[i + 2:i + 2 + ln]
            if all(32 <= c < 127 for c in s) and (
                    s.replace(b"-", b"").replace(b"_", b"")
                    .replace(b".", b"").isalnum()):
                return s.decode()
    return ""
