"""L7 protocol inference + parsing.

Reference analog: agent/src/flow_generator/protocol_logs/ (the ~30-protocol
decoder set listed at agent/src/common/l7_protocol_log.rs:163-226) plus the
in-kernel inference of agent/src/ebpf/kernel/include/protocol_inference.h.
Round-1 set: HTTP/1, HTTP/2(+gRPC detect), DNS, Redis, MySQL, PostgreSQL,
Memcached, Kafka, MongoDB. The registry order mirrors the reference's
inference priority (cheap magic checks first).
"""

from deepflow_tpu.agent.protocol_logs.base import (  # noqa: F401
    L7ParseResult, L7Parser, infer_and_parse, REGISTRY)
