"""TLS handshake parser: records ClientHello SNI + version + ALPN (the
request side) and ServerHello (the response). Reference analog: the EE TLS
decoder in the CE protocol list (l7_protocol_log.rs:163-226)."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_VERSIONS = {0x0301: "1.0", 0x0302: "1.1", 0x0303: "1.2", 0x0304: "1.3"}


@register
class TlsParser(L7Parser):
    PROTOCOL = pb.TLS
    NAME = "tls"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 9:
            return False
        # record: type 22 (handshake), version 3.x, sane length
        if payload[0] != 22 or payload[1] != 3 or payload[2] > 4:
            return False
        rec_len = struct.unpack_from(">H", payload, 3)[0]
        hs_type = payload[5]
        return rec_len >= 4 and hs_type in (1, 2)  # ClientHello/ServerHello

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        # called on every payload of an inferred flow: application-data
        # records (type 0x17) and continuations must produce nothing
        if not self.check(payload):
            return []
        hs_type = payload[5]
        if hs_type == 1:
            sni, alpn, version = _parse_client_hello(payload)
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                version=version,
                request_type="client-hello",
                request_domain=sni,
                request_resource=sni,
                endpoint=sni or "client-hello",
                attrs={"alpn": alpn} if alpn else {},
                captured_byte=len(payload))]
        version = _VERSIONS.get(
            struct.unpack_from(">H", payload, 9)[0]
            if len(payload) >= 11 else 0, "")
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
            version=version,
            response_status=1,
            response_result="server-hello",
            captured_byte=len(payload))]


def _parse_client_hello(payload: bytes) -> tuple[str, str, str]:
    """-> (sni, alpn, version)."""
    sni = alpn = ""
    try:
        i = 9  # record(5) + hs type(1) + hs len(3)
        legacy_ver = struct.unpack_from(">H", payload, i)[0]
        version = _VERSIONS.get(legacy_ver, "")
        i += 2 + 32          # version + random
        sid_len = payload[i]
        i += 1 + sid_len
        cs_len = struct.unpack_from(">H", payload, i)[0]
        i += 2 + cs_len
        comp_len = payload[i]
        i += 1 + comp_len
        if i + 2 > len(payload):
            return sni, alpn, version
        ext_len = struct.unpack_from(">H", payload, i)[0]
        i += 2
        end = min(len(payload), i + ext_len)
        while i + 4 <= end:
            etype, elen = struct.unpack_from(">HH", payload, i)
            i += 4
            body = payload[i:i + elen]
            i += elen
            if etype == 0 and len(body) >= 5:  # server_name
                name_len = struct.unpack_from(">H", body, 3)[0]
                sni = body[5:5 + name_len].decode("latin1", "replace")
            elif etype == 16 and len(body) >= 3:  # ALPN
                j = 2
                protos = []
                while j < len(body):
                    ln = body[j]
                    protos.append(body[j + 1:j + 1 + ln].decode(
                        "latin1", "replace"))
                    j += 1 + ln
                alpn = ",".join(protos)
            elif etype == 43 and len(body) >= 3:  # supported_versions
                sv = struct.unpack_from(">H", body, 1)[0]
                version = _VERSIONS.get(sv, version)
    except (struct.error, IndexError):
        pass
    return sni, alpn, version
