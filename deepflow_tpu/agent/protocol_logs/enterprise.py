"""Enterprise-protocol tail: Oracle TNS, IBM/WebSphere MQ, ISO8583,
SOME/IP, Dameng, NetSign.

Reference analogs: protocol_logs/sql/oracle.rs, mq/web_sphere_mq.rs,
rpc/iso8583.rs, rpc/some_ip.rs, sql/dameng.rs, rpc/net_sign.rs. Note the
reference DELEGATES dameng/netsign framing to closed enterprise crates
(dameng.rs:210, net_sign.rs:375); here those two are honest minimal
port+framing parsers built from public knowledge, while Oracle TNS, MQ
TSH, ISO8583 and SOME/IP follow their public wire specs.
"""

from __future__ import annotations

import re
import struct

from deepflow_tpu.agent.protocol_logs.base import (
    MSG_REQUEST, MSG_RESPONSE, L7ParseResult, L7Parser, register)
from deepflow_tpu.proto import pb

# ---------------------------------------------------------------------------
# Oracle TNS (sql/oracle.rs)
# ---------------------------------------------------------------------------

_TNS_TYPES = {1: "CONNECT", 2: "ACCEPT", 4: "REFUSE", 5: "REDIRECT",
              6: "DATA", 11: "RESEND", 12: "MARKER", 14: "CONTROL"}
_SQL_VERB = re.compile(
    rb"\b(SELECT|INSERT|UPDATE|DELETE|MERGE|BEGIN|CALL|CREATE|ALTER|DROP|"
    rb"COMMIT|ROLLBACK)\b", re.IGNORECASE)
_SERVICE_RE = re.compile(rb"SERVICE_NAME=([^)]+)")


@register
class OracleParser(L7Parser):
    PROTOCOL = pb.ORACLE
    NAME = "oracle"
    PORTS = (1521, 1522, 1525)

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 8:
            return False
        length = struct.unpack_from(">H", payload)[0]
        ptype = payload[4]
        if ptype not in _TNS_TYPES or payload[2:4] != b"\x00\x00":
            return False
        if ptype == 1:  # CONNECT carries the descriptor text
            return b"(DESCRIPTION=" in payload or b"(CONNECT_DATA=" in payload
        # other types only on the known ports (8-byte header is weak alone)
        return port_dst in self.PORTS and 8 <= length <= 65535

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if len(payload) < 8:
            return []
        ptype = payload[4]
        tname = _TNS_TYPES.get(ptype, "")
        if not tname:
            return []
        if ptype == 1:  # CONNECT
            m = _SERVICE_RE.search(payload)
            svc = m.group(1).decode("ascii", "replace") if m else ""
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type="CONNECT", request_domain=svc,
                captured_byte=len(payload))]
        if ptype == 2:  # ACCEPT
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                response_status=1, captured_byte=len(payload))]
        if ptype == 4:  # REFUSE
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                response_status=3,
                response_exception="connection refused",
                captured_byte=len(payload))]
        if ptype == 6 and is_request:  # DATA: surface embedded SQL
            m = _SQL_VERB.search(payload)
            if m:
                verb = m.group(1).decode().upper()
                sql = payload[m.start():m.start() + 256].split(b"\x00")[0]
                return [L7ParseResult(
                    l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                    request_type=verb,
                    attrs={"sql": sql.decode("utf-8", "replace")},
                    captured_byte=len(payload))]
            return []
        if ptype == 6:
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                response_status=1, captured_byte=len(payload))]
        return []


# ---------------------------------------------------------------------------
# IBM / WebSphere MQ (mq/web_sphere_mq.rs): TSH segment headers
# ---------------------------------------------------------------------------

_TSH_SEGMENTS = {
    0x01: "INITIAL_DATA", 0x02: "RESYNC_DATA", 0x03: "RESET_DATA",
    0x04: "MESSAGE_DATA", 0x05: "STATUS_DATA", 0x06: "SECURITY_DATA",
    0x07: "USERID_DATA", 0x08: "HEARTBEAT",
    0x81: "MQCONN", 0x82: "MQDISC", 0x83: "MQOPEN", 0x84: "MQCLOSE",
    0x85: "MQGET", 0x86: "MQPUT", 0x87: "MQPUT1", 0x88: "MQSET",
    0x89: "MQINQ", 0x8A: "MQCMIT", 0x8B: "MQBACK", 0x8C: "SPI",
    0x91: "MQCONN_REPLY", 0x92: "MQDISC_REPLY", 0x93: "MQOPEN_REPLY",
    0x94: "MQCLOSE_REPLY", 0x95: "MQGET_REPLY", 0x96: "MQPUT_REPLY",
    0x97: "MQPUT1_REPLY",
}


@register
class WebSphereMqParser(L7Parser):
    PROTOCOL = pb.WEBSPHEREMQ
    NAME = "websphere_mq"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        return len(payload) >= 28 and payload[:3] == b"TSH" and \
            payload[3:4] in (b" ", b"M", b"C")

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if len(payload) < 28 or payload[:3] != b"TSH":
            return []
        # TSHM carries conversation+request ids before the common fields
        off = 12 if payload[3:4] == b"M" else 4
        seg_len = struct.unpack_from(">I", payload, 4)[0]
        seg_type = payload[off + 5] if off + 5 < len(payload) else 0
        name = _TSH_SEGMENTS.get(seg_type, f"SEGMENT_{seg_type:#x}")
        is_reply = name.endswith("_REPLY") or seg_type == 0x05
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_reply else MSG_REQUEST,
            request_type="" if is_reply else name,
            response_status=1 if is_reply else 0,
            session_less=name in ("HEARTBEAT",),
            attrs={"segment_length": seg_len},
            captured_byte=len(payload))]


# ---------------------------------------------------------------------------
# ISO8583 financial messages (rpc/iso8583.rs)
# ---------------------------------------------------------------------------

_MTI_RE = re.compile(rb"^\d{4}$")


@register
class Iso8583Parser(L7Parser):
    PROTOCOL = pb.ISO8583
    NAME = "iso8583"
    # no IANA port; gate on the conventional deployment ports so 4 leading
    # ASCII digits on arbitrary text protocols can't pin a flow as ISO8583
    PORTS = (8583, 1080, 5105)

    @staticmethod
    def _mti_at(payload: bytes):
        """MTI possibly behind a 2-byte big-endian length prefix."""
        for off in (0, 2):
            mti = payload[off:off + 4]
            if len(mti) == 4 and _MTI_RE.match(mti):
                if off == 2:
                    ln = struct.unpack_from(">H", payload)[0]
                    if ln != len(payload) - 2:
                        continue
                # a primary bitmap must follow the MTI
                if len(payload) >= off + 12:
                    return off, mti.decode()
        return None, None

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if port_dst not in self.PORTS:
            return False
        off, mti = self._mti_at(payload)
        if mti is None:
            return False
        # version digit 0-2 (1987/1993/2003), class digit 1-8
        return mti[0] in "012" and mti[1] in "12345678"

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        off, mti = self._mti_at(payload)
        if mti is None:
            return []
        # function digit: even = request, odd = response (0200 -> 0210)
        is_resp = int(mti[2]) % 2 == 1
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_resp else MSG_REQUEST,
            request_type="" if is_resp else mti,
            response_status=1 if is_resp else 0,
            attrs={"mti": mti},
            captured_byte=len(payload))]


# ---------------------------------------------------------------------------
# SOME/IP automotive RPC (rpc/some_ip.rs)
# ---------------------------------------------------------------------------

_SOMEIP_REQ = {0x00: "REQUEST", 0x01: "REQUEST_NO_RETURN",
               0x02: "NOTIFICATION"}
_SOMEIP_RESP = {0x80: "RESPONSE", 0x81: "ERROR"}
_SOMEIP_CLIENT_ERRS = {2, 3, 7, 8, 10}  # unknown svc/method, wrong
# proto/interface version, wrong message type (some_ip.rs set_status)


@register
class SomeIpParser(L7Parser):
    PROTOCOL = pb.SOMEIP
    NAME = "someip"

    @staticmethod
    def _header_ok(payload: bytes, off: int) -> int:
        """Validate one message header at off; returns its total size
        (possibly beyond the capture for a truncated tail) or 0."""
        if off + 16 > len(payload):
            return 0
        length = struct.unpack_from(">I", payload, off + 4)[0]
        proto_ver, _iface, mtype, _rc = payload[off + 12:off + 16]
        if proto_ver != 1 or not (8 <= length <= (1 << 24)):
            return 0
        if mtype not in _SOMEIP_REQ and mtype not in _SOMEIP_RESP:
            return 0
        return 8 + length

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        size = self._header_ok(payload, 0)
        if not size:
            return False
        # exactly one message, a batch (next header must also be sane), or
        # a truncated capture of one larger message
        if size >= len(payload):
            return True
        return self._header_ok(payload, size) > 0

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        # TCP segments batch messages back to back (notification bursts):
        # emit them all
        out: list[L7ParseResult] = []
        off = 0
        while True:
            size = self._header_ok(payload, off)
            if not size:
                break
            out.extend(self._parse_one(payload, off))
            off += size
        return out

    def _parse_one(self, payload: bytes, off: int) -> list[L7ParseResult]:
        service_id, method_id = struct.unpack_from(">HH", payload, off)
        client_id, session_id = struct.unpack_from(">HH", payload, off + 8)
        _, _, mtype, return_code = payload[off + 12:off + 16]
        endpoint = f"{service_id:#06x}/{method_id:#06x}"
        if mtype in _SOMEIP_RESP:
            status = (1 if return_code == 0 else
                      2 if return_code in _SOMEIP_CLIENT_ERRS else 3)
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                endpoint=endpoint, request_id=session_id,
                response_code=return_code, response_status=status,
                attrs={"message_type": _SOMEIP_RESP[mtype],
                       "client_id": client_id},
                captured_byte=len(payload))]
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
            request_type=_SOMEIP_REQ[mtype], endpoint=endpoint,
            request_id=session_id,
            session_less=mtype in (0x01, 0x02),
            attrs={"message_type": _SOMEIP_REQ[mtype],
                   "client_id": client_id},
            captured_byte=len(payload))]


# ---------------------------------------------------------------------------
# Dameng DM8 (sql/dameng.rs — reference delegates to a closed crate;
# minimal port-gated framing here)
# ---------------------------------------------------------------------------

@register
class DamengParser(L7Parser):
    PROTOCOL = pb.DAMENG
    NAME = "dameng"
    PORTS = (5236, 5237)

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if port_dst not in self.PORTS or len(payload) < 64:
            return False
        # DM messages carry a 64-byte header; length (LE u32) at offset 8
        # must be plausible for the captured segment
        length = struct.unpack_from("<I", payload, 8)[0]
        return length <= (1 << 24)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if len(payload) < 64:
            return []
        cmd = payload[4]
        m = _SQL_VERB.search(payload)
        if is_request:
            verb = m.group(1).decode().upper() if m else f"CMD_{cmd}"
            attrs = {}
            if m:
                sql = payload[m.start():m.start() + 256].split(b"\x00")[0]
                attrs["sql"] = sql.decode("utf-8", "replace")
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type=verb, attrs=attrs,
                captured_byte=len(payload))]
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
            response_status=1, captured_byte=len(payload))]


# ---------------------------------------------------------------------------
# NetSign crypto-service (rpc/net_sign.rs — reference delegates to a closed
# crate; minimal TLV parser here)
# ---------------------------------------------------------------------------

_NETSIGN_OPS = {b"sign": "sign", b"verify": "verify",
                b"encrypt": "encrypt", b"decrypt": "decrypt",
                b"digest": "digest"}


@register
class NetSignParser(L7Parser):
    PROTOCOL = pb.NETSIGN
    NAME = "netsign"
    PORTS = (9989, 10014)

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 12 or port_dst not in self.PORTS:
            return False
        length = struct.unpack_from(">I", payload)[0]
        return 4 <= length <= (1 << 20)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if len(payload) < 12:
            return []
        low = payload[:512].lower()
        op = next((name for key, name in _NETSIGN_OPS.items()
                   if key in low), "")
        if is_request:
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type=op or "request",
                captured_byte=len(payload))]
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
            response_status=1, captured_byte=len(payload))]
