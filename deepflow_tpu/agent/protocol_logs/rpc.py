"""Dubbo, FastCGI, RocketMQ parsers (reference analog: protocol_logs/rpc/
dubbo.rs, fastcgi.rs, mq/rocketmq.rs)."""

from __future__ import annotations

import json
import re
import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_DUBBO_MAGIC = 0xDABB
# dubbo hessian strings are length-prefixed-ish; method/service appear as
# readable tokens — extract printable runs
_PRINTABLE_RE = re.compile(rb"[\x20-\x7e]{3,}")


@register
class DubboParser(L7Parser):
    PROTOCOL = pb.DUBBO
    NAME = "dubbo"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        return len(payload) >= 16 and \
            struct.unpack_from(">H", payload, 0)[0] == _DUBBO_MAGIC

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if not self.check(payload):
            return []  # continuation segment of a multi-packet body
        flags = payload[2]
        status = payload[3]
        req_id = struct.unpack_from(">Q", payload, 4)[0]
        is_req = bool(flags & 0x80)
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_REQUEST if is_req else MSG_RESPONSE,
            request_id=req_id & 0xFFFFFFFF,
            captured_byte=len(payload))
        if is_req:
            # body: dubbo-version, service path, version, method (hessian)
            tokens = [t.decode("latin1") for t in
                      _PRINTABLE_RE.findall(payload[16:16 + 256])]
            # heuristic: service looks like a.b.C, method is the next token
            service = next((t for t in tokens if "." in t and
                            not t[0].isdigit()), "")
            try:
                method = tokens[tokens.index(service) + 2] if service else ""
            except (ValueError, IndexError):
                method = ""
            res.request_domain = service
            res.request_type = method
            res.endpoint = f"{service}/{method}".strip("/")
        else:
            # 20 OK; 30/31/40... errors
            res.response_code = status
            res.response_status = 1 if status == 20 else (
                2 if status in (30, 31) else 3)
        return [res]


_FCGI_TYPES = {1: "BEGIN_REQUEST", 4: "PARAMS", 5: "STDIN", 6: "STDOUT",
               7: "STDERR", 3: "END_REQUEST"}


@register
class FastcgiParser(L7Parser):
    PROTOCOL = pb.FASTCGI
    NAME = "fastcgi"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 8 or payload[0] != 1:  # version 1
            return False
        rtype = payload[1]
        length = struct.unpack_from(">H", payload, 4)[0]
        return rtype in _FCGI_TYPES and 8 + length <= len(payload) and (
            port_dst == 9000 or rtype == 1)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        out = []
        off = 0
        params: dict[str, str] = {}
        request_id = 0
        saw_request = saw_response = False
        end_status = None
        while off + 8 <= len(payload):
            rtype = payload[off + 1]
            request_id = struct.unpack_from(">H", payload, off + 2)[0]
            length = struct.unpack_from(">H", payload, off + 4)[0]
            pad = payload[off + 6]
            body = payload[off + 8:off + 8 + length]
            off += 8 + length + pad
            if rtype == 1:
                saw_request = True
            elif rtype == 4 and body:
                params.update(_fcgi_params(body))
            elif rtype in (6, 7):
                saw_response = True
            elif rtype == 3 and len(body) >= 5:
                saw_response = True
                end_status = body[4]  # protocol status
        if saw_request or params:
            out.append(L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type=params.get("REQUEST_METHOD", ""),
                request_resource=params.get("SCRIPT_NAME",
                                            params.get("REQUEST_URI", "")),
                request_domain=params.get("SERVER_NAME", ""),
                endpoint=params.get("SCRIPT_NAME", ""),
                request_id=request_id,
                captured_byte=len(payload)))
        if saw_response:
            out.append(L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                request_id=request_id,
                response_status=1 if not end_status else 3,
                captured_byte=len(payload)))
        return out


def _fcgi_params(body: bytes) -> dict[str, str]:
    params = {}
    i = 0
    while i < len(body):
        lens = []
        for _ in range(2):
            if i >= len(body):
                return params
            n = body[i]
            if n & 0x80:
                if i + 4 > len(body):
                    return params
                n = struct.unpack_from(">I", body, i)[0] & 0x7FFFFFFF
                i += 4
            else:
                i += 1
            lens.append(n)
        k = body[i:i + lens[0]]
        i += lens[0]
        v = body[i:i + lens[1]]
        i += lens[1]
        params[k.decode("latin1", "replace")] = v.decode("latin1", "replace")
    return params


_ROCKETMQ_CODES = {
    10: "SEND_MESSAGE", 11: "PULL_MESSAGE", 12: "QUERY_MESSAGE",
    14: "QUERY_CONSUMER_OFFSET", 15: "UPDATE_CONSUMER_OFFSET",
    34: "HEART_BEAT", 35: "UNREGISTER_CLIENT", 36: "CONSUMER_SEND_MSG_BACK",
    105: "GET_ROUTEINFO_BY_TOPIC", 310: "SEND_MESSAGE_V2",
    320: "SEND_BATCH_MESSAGE"}


@register
class RocketmqParser(L7Parser):
    """RocketMQ remoting: 4B total len + 4B header-len/serialize-type +
    JSON header {"code":..,"flag":..,"opaque":..}."""

    PROTOCOL = pb.ROCKETMQ
    NAME = "rocketmq"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 12:
            return False
        total = struct.unpack_from(">I", payload, 0)[0]
        mix = struct.unpack_from(">I", payload, 4)[0]
        ser, hlen = mix >> 24, mix & 0xFFFFFF
        if ser != 0 or hlen == 0 or hlen + 8 > total + 4 or \
                hlen > len(payload):
            return False
        return payload[8:9] == b"{" and b'"code"' in payload[8:8 + hlen]

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        mix = struct.unpack_from(">I", payload, 4)[0]
        hlen = mix & 0xFFFFFF
        try:
            hdr = json.loads(payload[8:8 + hlen].decode("utf-8", "replace"))
        except ValueError:
            return []
        code = int(hdr.get("code", 0))
        flag = int(hdr.get("flag", 0))
        opaque = int(hdr.get("opaque", 0))
        is_resp = bool(flag & 0x1)
        ext = hdr.get("extFields", {}) or {}
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_resp else MSG_REQUEST,
            request_type=("" if is_resp
                          else _ROCKETMQ_CODES.get(code, str(code))),
            request_resource=str(ext.get("topic", "")),
            endpoint=str(ext.get("topic", "")) or _ROCKETMQ_CODES.get(
                code, str(code)),
            request_id=opaque & 0xFFFFFFFF,
            captured_byte=len(payload))
        if is_resp:
            res.response_code = code
            res.response_status = 1 if code == 0 else 3
            res.response_exception = str(hdr.get("remark", ""))[:128]
        return [res]
