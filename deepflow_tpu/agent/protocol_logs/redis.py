"""Redis RESP parser (reference analog: protocol_logs/redis.rs)."""

from __future__ import annotations

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_READ_CMDS = {"GET", "MGET", "EXISTS", "TTL", "SCAN", "HGET", "HGETALL",
              "LRANGE", "SMEMBERS", "ZRANGE", "KEYS", "PING", "INFO"}


@register
class RedisParser(L7Parser):
    PROTOCOL = pb.REDIS
    NAME = "redis"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if not payload or b"\r\n" not in payload[:64]:
            return False
        c = payload[0:1]
        if c == b"*":  # request array (or RESP array reply)
            return payload[1:2].isdigit()
        if port_dst == 6379 and c in b"+-$:":
            return True
        return False

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        c = payload[0:1]
        if c == b"*":
            args = self._parse_array(payload)
            if args:
                cmd = args[0].upper()
                key = args[1] if len(args) > 1 else ""
                return [L7ParseResult(
                    l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                    request_type=cmd,
                    request_resource=key,
                    endpoint=cmd,
                    captured_byte=len(payload))]
            return []
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
            captured_byte=len(payload))
        first_line = payload.split(b"\r\n", 1)[0]
        if c == b"-":
            res.response_status = 3
            res.response_exception = first_line[1:].decode("latin1",
                                                           "replace")
        else:
            res.response_status = 1
            res.response_result = first_line[:128].decode("latin1", "replace")
        return [res]

    @staticmethod
    def _parse_array(payload: bytes, max_args: int = 8) -> list[str]:
        lines = payload.split(b"\r\n")
        try:
            n = int(lines[0][1:])
        except ValueError:
            return []
        args = []
        i = 1
        while i + 1 < len(lines) and len(args) < min(n, max_args):
            if lines[i].startswith(b"$"):
                args.append(lines[i + 1].decode("latin1", "replace"))
                i += 2
            else:
                i += 1
        return args
