"""HTTP/1.x and HTTP/2 (+gRPC detection) parsers.

Reference analog: protocol_logs/http.rs (HTTP1/2 log parsing, trace-id
header propagation l7_flow_log glue).
"""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.hpack_huffman import huffman_decode
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register,
    status_from_code)

_METHODS = (b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ", b"OPTIONS ",
            b"PATCH ", b"TRACE ", b"CONNECT ")
# trace headers we lift into l7_flow_log (reference: trace_types config)
_TRACE_HEADERS = (b"traceparent", b"x-b3-traceid", b"sw8", b"uber-trace-id")
_SPAN_HEADERS = (b"x-b3-spanid",)


def _parse_headers(block: bytes) -> dict[bytes, bytes]:
    headers = {}
    for line in block.split(b"\r\n"):
        if b":" in line:
            k, _, v = line.partition(b":")
            headers[k.strip().lower()] = v.strip()
    return headers


def _trace_ids(headers: dict[bytes, bytes]) -> tuple[str, str, str]:
    trace_id = span_id = x_request_id = ""
    for h in _TRACE_HEADERS:
        v = headers.get(h)
        if v:
            s = v.decode("latin1")
            if h == b"traceparent":  # 00-<trace>-<span>-<flags>
                parts = s.split("-")
                if len(parts) >= 4:
                    trace_id, span_id = parts[1], parts[2]
            elif h == b"uber-trace-id":
                parts = s.split(":")
                trace_id = parts[0]
                if len(parts) > 1:
                    span_id = parts[1]
            else:
                trace_id = s
            break
    for h in _SPAN_HEADERS:
        v = headers.get(h)
        if v and not span_id:
            span_id = v.decode("latin1")
    xr = headers.get(b"x-request-id")
    if xr:
        x_request_id = xr.decode("latin1")
    return trace_id, span_id, x_request_id


@register
class Http1Parser(L7Parser):
    PROTOCOL = pb.HTTP1
    NAME = "http1"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        return (payload.startswith(_METHODS)
                or payload.startswith(b"HTTP/1."))

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        head, _, _body = payload.partition(b"\r\n\r\n")
        lines = head.split(b"\r\n", 1)
        first = lines[0]
        headers = _parse_headers(lines[1] if len(lines) > 1 else b"")
        trace_id, span_id, x_request_id = _trace_ids(headers)
        if first.startswith(b"HTTP/1."):
            parts = first.split(b" ", 2)
            code = int(parts[1]) if len(parts) > 1 and parts[1].isdigit() else 0
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                version=parts[0].decode("latin1").replace("HTTP/", ""),
                response_code=code,
                response_status=status_from_code(code),
                response_result=(parts[2].decode("latin1")
                                 if len(parts) > 2 else ""),
                trace_id=trace_id, span_id=span_id,
                x_request_id=x_request_id,
                captured_byte=len(payload))]
        method, _, rest = first.partition(b" ")
        path, _, version = rest.rpartition(b" ")
        host = headers.get(b"host", b"").decode("latin1")
        path_s = path.decode("latin1")
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
            version=version.decode("latin1").replace("HTTP/", ""),
            request_type=method.decode("latin1"),
            request_domain=host,
            request_resource=path_s,
            endpoint=path_s.split("?")[0],
            trace_id=trace_id, span_id=span_id, x_request_id=x_request_id,
            captured_byte=len(payload))]


H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
_H2_FRAME_TYPES = set(range(10))
# minimal HPACK static table entries we care about
_HPACK_STATIC = {
    2: (":method", "GET"), 3: (":method", "POST"),
    4: (":path", "/"), 5: (":path", "/index.html"),
    6: (":scheme", "http"), 7: (":scheme", "https"),
    8: (":status", "200"), 9: (":status", "204"), 10: (":status", "206"),
    11: (":status", "304"), 12: (":status", "400"), 13: (":status", "404"),
    14: (":status", "500"),
    31: ("content-type", ""),
    38: ("host", ""),
}


@register
class Http2Parser(L7Parser):
    """HTTP/2 frames; HPACK headers decoded including Huffman strings
    (RFC 7541 Appendix B) — covers gRPC's :path and typical stacks."""

    PROTOCOL = pb.HTTP2
    NAME = "http2"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if payload.startswith(H2_PREFACE):
            return True
        if len(payload) < 9:
            return False
        length = int.from_bytes(payload[0:3], "big")
        ftype = payload[3]
        stream_id = int.from_bytes(payload[5:9], "big") & 0x7FFFFFFF
        # frame must be sane: known type, length plausible, settings on s0
        if ftype not in _H2_FRAME_TYPES or length > (1 << 20):
            return False
        if ftype == 4:  # SETTINGS
            return stream_id == 0 and length % 6 == 0
        # DATA/HEADERS: the frame must fit in the captured payload — random
        # bytes rarely satisfy this (cuts false positives on garbage)
        return (ftype in (0, 1) and stream_id != 0
                and 9 + length <= len(payload))

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        if payload.startswith(H2_PREFACE):
            payload = payload[len(H2_PREFACE):]
        out = []
        off = 0
        while off + 9 <= len(payload):
            length = int.from_bytes(payload[off:off + 3], "big")
            ftype = payload[off + 3]
            stream_id = int.from_bytes(payload[off + 5:off + 9],
                                       "big") & 0x7FFFFFFF
            frame = payload[off + 9:off + 9 + length]
            off += 9 + length
            if ftype != 1:  # HEADERS
                continue
            headers = _hpack_literal_headers(frame)
            grpc = headers.get("content-type", "").startswith(
                "application/grpc")
            path = headers.get(":path", "")
            status = headers.get(":status", "")
            if status:
                code = int(status) if status.isdigit() else 0
                out.append(L7ParseResult(
                    l7_protocol=pb.GRPC if grpc else self.PROTOCOL,
                    msg_type=MSG_RESPONSE, version="2",
                    request_id=stream_id,
                    response_code=code,
                    response_status=status_from_code(code),
                    captured_byte=len(payload)))
            else:
                out.append(L7ParseResult(
                    l7_protocol=pb.GRPC if grpc else self.PROTOCOL,
                    msg_type=MSG_REQUEST, version="2",
                    request_type=headers.get(":method", ""),
                    request_domain=headers.get(":authority", ""),
                    request_resource=path,
                    endpoint=path,
                    request_id=stream_id,
                    captured_byte=len(payload)))
        return out


def _hpack_literal_headers(frame: bytes) -> dict[str, str]:
    """Best-effort HPACK: static-index entries + literals, Huffman included."""
    headers: dict[str, str] = {}
    i = 0
    n = len(frame)
    while i < n:
        b = frame[i]
        if b & 0x80:  # indexed field
            idx, i = _hpack_int(frame, i, 7)
            if idx in _HPACK_STATIC:
                k, v = _HPACK_STATIC[idx]
                if v:
                    headers[k] = v
            continue
        # literal with/without indexing
        if b & 0x40:
            prefix_bits = 6
        elif b & 0x20:  # dynamic table size update
            _, i = _hpack_int(frame, i, 5)
            continue
        else:
            prefix_bits = 4
        idx, i = _hpack_int(frame, i, prefix_bits)
        if idx is None:
            return headers
        if idx:
            name = _HPACK_STATIC.get(idx, (str(idx), ""))[0]
        else:
            name, i = _hpack_string(frame, i)
            if name is None:
                return headers
        value, i = _hpack_string(frame, i)
        if value is None:
            return headers
        headers[name] = value
    return headers


def _hpack_int(frame: bytes, i: int, prefix_bits: int):
    """HPACK prefix integer (RFC 7541 §5.1) -> (value, next_index)."""
    mask = (1 << prefix_bits) - 1
    v = frame[i] & mask
    i += 1
    if v < mask:
        return v, i
    shift = 0
    while i < len(frame):
        b = frame[i]
        i += 1
        v += (b & 0x7F) << shift
        shift += 7
        if not (b & 0x80):
            return v, i
    return None, i


def _hpack_string(frame: bytes, i: int):
    if i >= len(frame):
        return None, i
    huffman = bool(frame[i] & 0x80)
    ln, i = _hpack_int(frame, i, 7)
    if ln is None or i + ln > len(frame):
        return None, i
    raw = frame[i:i + ln]
    i += ln
    if huffman:
        decoded = huffman_decode(raw)
        if decoded is None:
            return None, i
        return decoded.decode("latin1", "replace"), i
    return raw.decode("latin1", "replace"), i
