"""SofaRPC (bolt), bRPC, Tars, ZMTP, OpenWire parsers.

Reference analog: the CE protocol list (l7_protocol_log.rs:163-226 — SofaRPC,
bRPC, Tars, ZMTP, OpenWire entries)."""

from __future__ import annotations

import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)


@register
class SofaRpcParser(L7Parser):
    """Bolt protocol v1: u8 proto=1, u8 type (0 resp, 1 req, 2 oneway),
    u16 cmdcode (0 heartbeat, 1 request, 2 response), u8 ver2,
    u32 request_id, u8 codec, ... classname scan for service identity."""

    PROTOCOL = pb.SOFARPC
    NAME = "sofarpc"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 10 or payload[0] != 1:
            return False
        ptype = payload[1]
        cmdcode = struct.unpack_from(">H", payload, 2)[0]
        return ptype in (0, 1, 2) and cmdcode in (0, 1, 2)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        ptype = payload[1]
        cmdcode = struct.unpack_from(">H", payload, 2)[0]
        request_id = struct.unpack_from(">I", payload, 5)[0]
        is_req = ptype in (1, 2)
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_REQUEST if is_req else MSG_RESPONSE,
            request_id=request_id,
            captured_byte=len(payload))
        if cmdcode == 0:
            res.request_type = "heartbeat"
            res.endpoint = "heartbeat"
            res.session_less = True
            return [res]
        if is_req:
            # service identity: a dotted printable class/interface name,
            # anywhere after the fixed header (header length varies with
            # bolt version, so scan instead of assuming an offset)
            import re
            m = re.search(
                rb"[A-Za-z_$][A-Za-z0-9_$]*(?:\.[A-Za-z0-9_$]+){2,}"
                rb"(?::[0-9.]+)?", payload[10:])
            if m:
                svc = m.group().decode("latin1", "replace")
                res.request_domain = svc
                res.endpoint = svc
            res.request_type = "oneway" if ptype == 2 else "call"
            res.session_less = ptype == 2
        else:
            status = struct.unpack_from(">H", payload, 10)[0] \
                if len(payload) >= 12 else 0
            res.response_code = status
            res.response_status = 1 if status == 0 else 3
        return [res]


@register
class BrpcParser(L7Parser):
    """baidu-rpc standard protocol: 'PRPC' + u32 body_size + u32 meta_size,
    then RpcMeta protobuf (request.service/method, correlation_id)."""

    PROTOCOL = pb.BRPC
    NAME = "brpc"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        return payload.startswith(b"PRPC") and len(payload) >= 12

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        body_size, meta_size = struct.unpack_from(">II", payload, 4)
        meta = payload[12:12 + meta_size]
        from deepflow_tpu.tpuprobe import pbwire as w
        service = method = err_text = ""
        corr = 0
        err_code = 0
        saw_request = saw_response = False
        try:
            for f, _, v in w.iter_fields(meta):
                if f == 1 and isinstance(v, bytes):       # request meta
                    saw_request = True
                    d = w.fields_dict(v)
                    service = w.as_str(w.first(d, 1))
                    method = w.as_str(w.first(d, 2))
                elif f == 2 and isinstance(v, bytes):     # response meta
                    saw_response = True
                    d = w.fields_dict(v)
                    err_code = int(w.first(d, 1, 0) or 0)
                    err_text = w.as_str(w.first(d, 2))
                elif f == 4 and not isinstance(v, bytes):  # correlation_id
                    corr = int(v)
        except w.WireError:
            return []
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_REQUEST if saw_request or not saw_response
            else MSG_RESPONSE,
            request_domain=service,
            request_type=method,
            endpoint=f"{service}/{method}".strip("/"),
            request_id=corr & 0xFFFFFFFF,
            captured_byte=len(payload))
        if saw_response:
            res.response_code = err_code
            res.response_status = 1 if err_code == 0 else 3
            res.response_exception = err_text[:128]
        return [res]


def _tars_read(buf: bytes, i: int):
    """One TARS field -> (tag, value, next_i). Supports the header types."""
    if i >= len(buf):
        raise ValueError("eof")
    head = buf[i]
    tag, ttype = head >> 4, head & 0xF
    i += 1
    if tag == 15:
        tag = buf[i]
        i += 1
    if ttype == 0:      # int8
        return tag, buf[i], i + 1
    if ttype == 1:      # int16
        return tag, struct.unpack_from(">h", buf, i)[0], i + 2
    if ttype == 2:      # int32
        return tag, struct.unpack_from(">i", buf, i)[0], i + 4
    if ttype == 3:      # int64
        return tag, struct.unpack_from(">q", buf, i)[0], i + 8
    if ttype == 6:      # string1
        ln = buf[i]
        return tag, buf[i + 1:i + 1 + ln].decode("latin1", "replace"), \
            i + 1 + ln
    if ttype == 7:      # string4
        ln = struct.unpack_from(">I", buf, i)[0]
        return tag, buf[i + 4:i + 4 + ln].decode("latin1", "replace"), \
            i + 4 + ln
    if ttype == 12:     # zero
        return tag, 0, i
    raise ValueError(f"tars type {ttype}")


@register
class TarsParser(L7Parser):
    """Tars RequestPacket: u32 total len + tars-encoded struct
    (1 iVersion, 2 cPacketType, 3 iMessageType, 4 iRequestId,
    5 sServantName, 6 sFuncName | response: 5 iRet)."""

    PROTOCOL = pb.TARS
    NAME = "tars"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 8:
            return False
        total = struct.unpack_from(">I", payload, 0)[0]
        if not (8 <= total <= len(payload) + 4096):
            return False
        try:
            tag, version, _ = _tars_read(payload, 4)
        except (ValueError, struct.error, IndexError):
            return False
        return tag == 1 and version in (1, 2, 3)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        fields: dict[int, object] = {}
        i = 4
        try:
            while i < len(payload) and len(fields) < 8:
                tag, value, i = _tars_read(payload, i)
                fields[tag] = value
        except (ValueError, struct.error, IndexError):
            pass
        servant = str(fields.get(5, "")) if isinstance(
            fields.get(5), str) else ""
        func = str(fields.get(6, "")) if isinstance(
            fields.get(6), str) else ""
        is_resp = not servant and 5 in fields  # response: tag5 = iRet int
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_resp else MSG_REQUEST,
            request_domain=servant,
            request_type=func,
            endpoint=f"{servant}/{func}".strip("/"),
            request_id=int(fields.get(4, 0) or 0) & 0xFFFFFFFF,
            captured_byte=len(payload))
        if is_resp:
            ret = int(fields.get(5, 0) or 0)
            res.response_code = ret
            res.response_status = 1 if ret == 0 else 3
        return [res]


@register
class ZmtpParser(L7Parser):
    """ZeroMQ transport protocol v3: greeting (\\xff...\\x7f + version +
    mechanism) and command/message frames."""

    PROTOCOL = pb.ZMTP
    NAME = "zmtp"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        return (len(payload) >= 11 and payload[0] == 0xFF
                and payload[9] == 0x7F and payload[10] in (1, 2, 3))

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        version = f"{payload[10]}.{payload[11]}" if len(payload) > 11 else ""
        mechanism = ""
        if len(payload) >= 32:
            mechanism = payload[12:32].rstrip(b"\x00").decode(
                "latin1", "replace")
        return [L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_REQUEST if is_request else MSG_RESPONSE,
            version=version,
            request_type="greeting",
            request_resource=mechanism,
            endpoint="greeting",
            session_less=True,
            captured_byte=len(payload))]


@register
class OpenwireParser(L7Parser):
    """ActiveMQ OpenWire: u32 size + u8 datatype; WIREFORMAT_INFO(1)
    carries the 'ActiveMQ' magic."""

    PROTOCOL = pb.OPENWIRE
    NAME = "openwire"

    _TYPES = {1: "WireFormatInfo", 2: "BrokerInfo", 3: "ConnectionInfo",
              4: "SessionInfo", 5: "ConsumerInfo", 6: "ProducerInfo",
              10: "KeepAlive", 11: "ShutdownInfo", 15: "Response",
              21: "MessageAck", 26: "ActiveMQMessage", 27: "ActiveMQBytesMessage",
              28: "ActiveMQMapMessage", 31: "ActiveMQTextMessage"}

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 5:
            return False
        if payload[4] == 1 and b"ActiveMQ" in payload[:20]:
            return True
        size = struct.unpack_from(">I", payload, 0)[0]
        return (port_dst == 61616 and payload[4] in self._TYPES
                and 1 <= size <= len(payload) + 4096)

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        dtype = payload[4]
        name = self._TYPES.get(dtype, str(dtype))
        is_resp = dtype == 15
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL,
            msg_type=MSG_RESPONSE if is_resp else MSG_REQUEST,
            request_type=name,
            endpoint=name,
            session_less=dtype in (1, 2, 10, 26, 27, 28, 31),
            captured_byte=len(payload))
        if is_resp:
            res.response_status = 1
        return [res]
