"""MySQL and PostgreSQL parsers (reference analog: protocol_logs/sql/)."""

from __future__ import annotations

import re
import struct

from deepflow_tpu.proto import pb
from deepflow_tpu.agent.protocol_logs.base import (
    L7Parser, L7ParseResult, MSG_REQUEST, MSG_RESPONSE, register)

_SQL_VERB_RE = re.compile(
    rb"^\s*(SELECT|INSERT|UPDATE|DELETE|CREATE|DROP|ALTER|BEGIN|COMMIT|"
    rb"ROLLBACK|SET|SHOW|USE|EXPLAIN|TRUNCATE|WITH)\b", re.IGNORECASE)
_TABLE_RE = re.compile(
    rb"\b(?:FROM|INTO|UPDATE|TABLE)\s+[`\"]?([A-Za-z0-9_.$]+)",
    re.IGNORECASE)

_MYSQL_COMMANDS = {
    1: "COM_QUIT", 2: "COM_INIT_DB", 3: "COM_QUERY", 4: "COM_FIELD_LIST",
    14: "COM_PING", 22: "COM_STMT_PREPARE", 23: "COM_STMT_EXECUTE",
    25: "COM_STMT_CLOSE",
}


def _sql_fields(sql: bytes) -> tuple[str, str]:
    verb = ""
    m = _SQL_VERB_RE.match(sql)
    if m:
        verb = m.group(1).decode().upper()
    table = ""
    tm = _TABLE_RE.search(sql)
    if tm:
        table = tm.group(1).decode("latin1", "replace")
    return verb, table


@register
class MysqlParser(L7Parser):
    PROTOCOL = pb.MYSQL
    NAME = "mysql"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 5:
            return False
        ln = int.from_bytes(payload[0:3], "little")
        seq = payload[3]
        if ln == 0 or ln + 4 > len(payload) + 1024:
            return False
        if seq == 0:
            cmd = payload[4]
            if cmd in _MYSQL_COMMANDS and (
                    cmd != 3 or _SQL_VERB_RE.match(payload[5:5 + ln - 1])):
                return cmd == 3 or port_dst == 3306
        return False

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        ln = int.from_bytes(payload[0:3], "little")
        seq = payload[3]
        if seq == 0:
            cmd = payload[4]
            name = _MYSQL_COMMANDS.get(cmd, f"COM_{cmd}")
            res = L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type=name, captured_byte=len(payload))
            if cmd == 3:  # COM_QUERY
                sql = payload[5:4 + ln]
                verb, table = _sql_fields(sql)
                res.request_type = verb or name
                res.request_resource = table
                res.endpoint = table
                res.attrs["sql"] = sql[:256].decode("latin1", "replace")
            return [res]
        # response: header byte after the packet header
        marker = payload[4]
        res = L7ParseResult(
            l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
            captured_byte=len(payload))
        if marker == 0xFF:
            code = struct.unpack_from("<H", payload, 5)[0]
            res.response_code = code
            res.response_status = 2 if code < 2000 else 3
            res.response_exception = payload[13:13 + 64].decode(
                "latin1", "replace")
        else:
            res.response_status = 1
        return [res]


@register
class PostgresParser(L7Parser):
    PROTOCOL = pb.POSTGRESQL
    NAME = "postgresql"

    # typed messages: Q query, P parse, E execute/error, C close/complete...
    _REQ_TYPES = b"QPBEDFCHSX"
    _RESP_TYPES = b"TDCEZRSNK1234"

    def check(self, payload: bytes, port_dst: int = 0) -> bool:
        if len(payload) < 5:
            return False
        t = payload[0:1]
        ln = struct.unpack_from(">I", payload, 1)[0]
        if t == b"Q" and 4 <= ln <= len(payload) + 16:
            return bool(_SQL_VERB_RE.match(payload[5:]))
        if port_dst == 5432 and t in self._REQ_TYPES and 4 <= ln < (1 << 24):
            return True
        return False

    def parse(self, payload: bytes,
              is_request: bool = True) -> list[L7ParseResult]:
        out = []
        off = 0
        while off + 5 <= len(payload) and len(out) < 16:
            t = payload[off:off + 1]
            ln = struct.unpack_from(">I", payload, off + 1)[0]
            body = payload[off + 5:off + 1 + ln]
            off += 1 + ln
            if t == b"Q":
                sql = body.rstrip(b"\x00")
                verb, table = _sql_fields(sql)
                out.append(L7ParseResult(
                    l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                    request_type=verb or "QUERY",
                    request_resource=table, endpoint=table,
                    attrs={"sql": sql[:256].decode("latin1", "replace")},
                    captured_byte=len(payload)))
            elif t == b"E":
                fields = body.split(b"\x00")
                sev = code = msg = ""
                for f in fields:
                    if f.startswith(b"S"):
                        sev = f[1:].decode("latin1", "replace")
                    elif f.startswith(b"C"):
                        code = f[1:].decode("latin1", "replace")
                    elif f.startswith(b"M"):
                        msg = f[1:].decode("latin1", "replace")
                out.append(L7ParseResult(
                    l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                    response_status=3 if sev in ("ERROR", "FATAL",
                                                 "PANIC") else 2,
                    response_exception=f"{code} {msg}".strip(),
                    captured_byte=len(payload)))
            elif t == b"C":  # CommandComplete
                out.append(L7ParseResult(
                    l7_protocol=self.PROTOCOL, msg_type=MSG_RESPONSE,
                    response_status=1,
                    response_result=body.rstrip(b"\x00").decode(
                        "latin1", "replace"),
                    captured_byte=len(payload)))
        return out
