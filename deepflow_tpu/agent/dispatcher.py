"""Dispatcher: packet sources -> FlowMap -> flow logs + metric documents.

Reference analog: agent/src/dispatcher (capture loop) + the sender
conversion in flow_generator. Sources: pcap replay and synthetic injection
(live AF_PACKET capture needs CAP_NET_RAW; gated behind a flag so the same
pipeline runs everywhere — the reference's golden-test stance).
"""

from __future__ import annotations

import json
import logging
import threading
import time

from deepflow_tpu.agent.collector import QuadrupleGenerator
from deepflow_tpu.agent.flow_map import FlowMap, FlowNode, L7Record
from deepflow_tpu.agent.packet import MetaPacket, read_pcap
from deepflow_tpu.codec import MessageType
from deepflow_tpu.proto import pb

log = logging.getLogger("df.dispatcher")


def flow_to_l4_pb(node: FlowNode) -> pb.L4FlowLog:
    f = pb.L4FlowLog()
    f.flow_id = node.flow_id
    f.key.ip_src = node.ip_src
    f.key.ip_dst = node.ip_dst
    f.key.port_src = node.port_src
    f.key.port_dst = node.port_dst
    f.key.proto = node.protocol
    f.key.tap_port = node.tap_port
    f.key.tunnel_type = node.tunnel_type
    f.key.tunnel_id = node.tunnel_id
    f.start_time_ns = node.start_ns
    f.end_time_ns = node.end_ns
    f.packet_tx = node.tx.packets
    f.packet_rx = node.rx.packets
    f.byte_tx = node.tx.bytes
    f.byte_rx = node.rx.bytes
    f.l7_request = node.l7_request
    f.l7_response = node.l7_response
    f.rtt_us = node.rtt_us
    if node.art_count:
        f.art_us = node.art_sum_us // node.art_count
    f.retrans_tx = node.tx.retrans
    f.retrans_rx = node.rx.retrans
    f.zero_win_tx = node.tx.zero_window
    f.zero_win_rx = node.rx.zero_window
    f.close_type = node.close_type
    f.tcp_flags_bit_tx = node.tx.tcp_flags_bits
    f.tcp_flags_bit_rx = node.rx.tcp_flags_bits
    f.syn_count = node.syn_count
    f.synack_count = node.synack_count
    return f


def record_to_l7_pb(r: L7Record) -> pb.L7FlowLog:
    node = r.flow
    f = pb.L7FlowLog()
    f.flow_id = node.flow_id
    f.key.ip_src = node.ip_src
    f.key.ip_dst = node.ip_dst
    f.key.port_src = node.port_src
    f.key.port_dst = node.port_dst
    f.key.proto = node.protocol
    f.key.tunnel_type = node.tunnel_type
    f.key.tunnel_id = node.tunnel_id
    f.l7_protocol = node.l7_protocol
    f.start_time_ns = r.start_ns
    f.end_time_ns = r.end_ns
    req, resp = r.request, r.response
    if req is not None:
        if req.attrs:
            f.attrs_json = json.dumps(req.attrs, sort_keys=True,
                                      default=str)
        f.version = req.version
        f.request_type = req.request_type
        f.request_domain = req.request_domain
        f.request_resource = req.request_resource
        f.endpoint = req.endpoint
        f.request_id = req.request_id
        f.trace_id = req.trace_id
        f.span_id = req.span_id
        f.x_request_id = req.x_request_id
        f.captured_request_byte = req.captured_byte
        if req.l7_protocol:
            f.l7_protocol = req.l7_protocol
    f.syscall_trace_id_request = r.syscall_trace_id_request
    f.syscall_trace_id_response = r.syscall_trace_id_response
    f.syscall_thread_0 = r.syscall_thread_0
    f.syscall_thread_1 = r.syscall_thread_1
    if resp is not None:
        f.response_status = resp.response_status
        f.response_code = resp.response_code
        f.response_exception = resp.response_exception
        f.response_result = resp.response_result[:256]
        f.captured_response_byte = resp.captured_byte
        if not resp.trace_id == "" and not f.trace_id:
            f.trace_id = resp.trace_id
    elif req is not None and not req.session_less:
        f.response_status = 4  # unanswered request -> timeout
    return f


class Dispatcher:
    """Owns one FlowMap shard and converts outputs to wire batches."""

    def __init__(self, sender=None, agent_id: int = 0,
                 flush_interval_s: float = 1.0,
                 batch_size: int = 256, engine: str = "auto",
                 labeler=None, telemetry=None) -> None:
        self.sender = sender
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("agent", enabled=False)
        self._telemetry = telemetry
        # ledger hops: flow_map counts records surfaced by the flow engine,
        # collector counts metric documents, dispatcher counts wire batches
        # handed to the sender (the only hop here that can drop)
        self._fm_hop = telemetry.hop("flow_map")
        self._co_hop = telemetry.hop("collector")
        self._hop = telemetry.hop("dispatcher")
        self.labeler = labeler  # agent-side policy/labeler (optional)
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self._l4_buf: list[pb.L4FlowLog] = []
        self._l7_buf: list[pb.L7FlowLog] = []
        self.quadruple = QuadrupleGenerator(self._emit_docs)
        self.flow_map = FlowMap(
            on_l4_log=self._on_l4, on_l7_log=self._on_l7,
            on_flow_update=self._on_flow_update, agent_id=agent_id)
        # native engine for raw-frame sources (ring capture, raw pcap
        # replay); MetaPacket injection keeps the Python map — disjoint key
        # spaces, shared output callbacks
        self.native_map = None
        if engine in ("auto", "native"):
            try:
                from deepflow_tpu.agent.native_flow import NativeFlowMap
                self.native_map = NativeFlowMap(
                    on_l4_log=self._on_l4, on_l7_log=self._on_l7,
                    on_flow_update=self._on_flow_update,
                    agent_id=agent_id)
            except Exception as e:
                if engine == "native":
                    raise
                log.debug("native flow engine unavailable: %s", e)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # per-packet ACL actions (pcap capture / NPB forward) run on the
        # frame-visible paths; None until an agent wires one in
        self.packet_actions = None

    def _run_packet_actions(self, frames) -> None:
        """frames: iterable of (frame, ts_ns)."""
        pa = self.packet_actions
        if pa is None or not pa.enabled():
            return
        for frame, ts_ns in frames:
            try:
                pa.handle_frame(frame, ts_ns)
            except Exception:
                # one malformed frame must not lose the rest of the batch
                log.exception("packet action failed")

    # -- pipeline callbacks ----------------------------------------------------

    def _label(self, node: FlowNode):
        """-> (src_label, dst_label, action) or (None, None, 'trace')."""
        if self.labeler is None:
            return None, None, "trace"
        return self.labeler.label_flow(node.ip_src, node.ip_dst,
                                       node.port_src, node.port_dst,
                                       node.protocol)

    def _on_flow_update(self, node: FlowNode, closed: bool) -> None:
        # ACL-ignored traffic is invisible EVERYWHERE: logs AND metrics
        if self._label(node)[2] == "ignore":
            return
        self.quadruple.add_flow(node, closed)

    def _on_l4(self, node: FlowNode) -> None:
        src, dst, action = self._label(node)
        if action == "ignore":
            self.labeler.stats["ignored_flows"] += 1
            self._fm_hop.account(emitted=1, dropped=1, reason="acl_ignore")
            return
        self._fm_hop.account(emitted=1, delivered=1)
        f = flow_to_l4_pb(node)
        if src is not None:
            f.pod_0 = src.pod
        if dst is not None:
            f.pod_1 = dst.pod
        self._l4_buf.append(f)
        if len(self._l4_buf) >= self.batch_size:
            self._flush_l4()

    def _on_l7(self, record: L7Record) -> None:
        src, dst, action = self._label(record.flow)
        if action == "ignore":
            self.labeler.stats["ignored_flows"] += 1
            self._fm_hop.account(emitted=1, dropped=1, reason="acl_ignore")
            return
        self._fm_hop.account(emitted=1, delivered=1)
        self.quadruple.add_l7(record)
        f = record_to_l7_pb(record)
        if src is not None:
            f.pod_0 = src.pod
        if dst is not None:
            f.pod_1 = dst.pod
        self._l7_buf.append(f)
        if len(self._l7_buf) >= self.batch_size:
            self._flush_l7()

    def _flush_l4(self) -> None:
        if not self._l4_buf or self.sender is None:
            if self._l4_buf:
                self._hop.account(emitted=1, dropped=1, reason="no_sender")
            self._l4_buf = []
            return
        batch = pb.FlowLogBatch()
        batch.l4.extend(self._l4_buf)
        self._l4_buf = []
        self._hop.account(emitted=1, delivered=1)
        self.sender.send(MessageType.L4_LOG, batch.SerializeToString())

    def _flush_l7(self) -> None:
        if not self._l7_buf or self.sender is None:
            if self._l7_buf:
                self._hop.account(emitted=1, dropped=1, reason="no_sender")
            self._l7_buf = []
            return
        batch = pb.FlowLogBatch()
        batch.l7.extend(self._l7_buf)
        self._l7_buf = []
        self._hop.account(emitted=1, delivered=1)
        self.sender.send(MessageType.L7_LOG, batch.SerializeToString())

    def _emit_docs(self, docs: list) -> None:
        self._co_hop.account(emitted=len(docs))
        if self.sender is None:
            self._co_hop.account(dropped=len(docs), reason="no_sender")
            return
        self._co_hop.account(delivered=len(docs))
        batch = pb.DocumentBatch()
        batch.docs.extend(docs)
        self._hop.account(emitted=1, delivered=1)
        self.sender.send(MessageType.METRICS, batch.SerializeToString())

    @property
    def stats(self) -> dict:
        """Merged pipeline stats across the Python and native engines."""
        s = dict(self.flow_map.stats)
        if self.native_map is not None:
            for k, v in self.native_map.stats.items():
                s[k] = s.get(k, 0) + v
        return s

    # -- feeding ----------------------------------------------------------------

    def inject(self, packet: MetaPacket) -> None:
        with self._lock:
            self.flow_map.inject(packet)

    def replay_pcap(self, path: str, tick: bool = True) -> int:
        """Replay a pcap through the pipeline (golden tests / dfctl replay).

        With the native engine, frames go straight to the C++ flow map as
        one packed batch; otherwise each frame decodes to a MetaPacket.
        """
        if self.native_map is not None:
            from deepflow_tpu.agent.packet import read_pcap_records
            raw = read_pcap_records(path)
            self._run_packet_actions(
                (frame, ts_ns) for frame, ts_ns, _ in raw)
            with self._lock:
                self.native_map.inject_frames(
                    [(frame, ts_ns) for frame, ts_ns, _ in raw])
            if tick:
                self.flush(force=True)
            return len(raw)
        if self.packet_actions is not None and \
                self.packet_actions.enabled():
            # only pay the second parse when a pcap/npb ACL exists
            from deepflow_tpu.agent.packet import read_pcap_records
            self._run_packet_actions(
                (frame, ts_ns)
                for frame, ts_ns, _ in read_pcap_records(path))
        packets = read_pcap(path)
        for p in packets:
            self.inject(p)
        if tick:
            self.flush(force=True)
        return len(packets)

    def flush(self, force: bool = False, now_ns: int | None = None) -> None:
        with self._lock:
            if force:
                self.flow_map.flush_all()
                if self.native_map is not None:
                    self.native_map.flush_all()
            else:
                self.flow_map.tick(now_ns)
                if self.native_map is not None:
                    self.native_map.tick(now_ns)
            self.quadruple.flush(
                None if now_ns is None else now_ns // 1_000_000_000)
            self._flush_l4()
            self._flush_l7()

    # -- background loop ---------------------------------------------------------

    def start(self) -> "Dispatcher":
        self._thread = threading.Thread(
            target=self._run, name="df-dispatcher", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self.flush(force=True)

    def _run(self) -> None:
        hb = self._telemetry.heartbeat(
            "dispatcher", interval_hint_s=self.flush_interval_s)
        flushes = 0
        hb.beat()
        while not self._stop.wait(self.flush_interval_s):
            flushes += 1
            hb.beat(progress=flushes)
            try:
                self.flush()
            except Exception:
                log.exception("dispatcher flush failed")
