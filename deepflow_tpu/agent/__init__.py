"""deepflow-tpu agent: per-host telemetry collection.

Reference analog: agent/src (Rust userspace) + agent/src/ebpf (C). The TPU
build keeps the same shape — profilers, dispatch/flow pipeline, senders,
config, sync — with TPU-native probes (tpuprobe/) in place of CUDA uprobes.
"""
