"""Segmented on-disk spool: the sender's overflow + replay buffer.

Reference analog: the reference agent bounds loss with large in-memory
queues and backpressure; this port goes further — frames that would be
dropped (queue overflow, dead server, failed in-flight write) land in
an append-only disk spool and replay on reconnect, so an ingest outage
shorter than the spool's capacity loses nothing.

Layout: ``<dir>/spool-<first_seq>.seg`` segment files, each a run of
CRC-framed records::

    u32 payload_len | u32 crc32(payload) | u8 msg_type | u64 seq | payload

Records are immutable once written; the spool rotates to a new segment
at ``segment_bytes`` and enforces ``max_bytes`` by deleting the OLDEST
segment (evicted records are reported to ``on_evict`` so the sender can
ledger them as ``dropped(spool_evict)`` — bounded loss is still loss,
and it must be visible).  ``trim(acked)`` deletes segments the server
has fully acknowledged.  On construction an existing directory is
recovered: every segment is scanned, torn tail records (a crash mid
append) are discarded, and the surviving records become replayable —
that is what makes an agent restart lossless for spooled frames.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib

log = logging.getLogger("df.spool")

_REC_FMT = ">IIBQ"
_REC_SIZE = struct.calcsize(_REC_FMT)  # 17
_SEG_PREFIX = "spool-"
_SEG_SUFFIX = ".seg"
# refuse obviously-insane records when recovering a damaged file
_MAX_RECORD = 64 << 20


class _Segment:
    """first_seq/last_seq are the MIN/MAX seq in the segment — appends
    are not guaranteed in seq order (the sender's OSError respool path
    can write an older in-flight seq after newer overflow spills), so
    trim/replay decisions must use the true range, not arrival order."""

    __slots__ = ("path", "first_seq", "last_seq", "records", "bytes",
                 "mtime")

    def __init__(self, path: str, first_seq: int) -> None:
        self.path = path
        self.first_seq = first_seq
        self.last_seq = first_seq
        self.records = 0
        self.bytes = 0
        self.mtime = time.time()  # wall clock of the last append

    def note(self, seq: int) -> None:
        if self.records == 0:
            self.first_seq = self.last_seq = seq
        else:
            self.first_seq = min(self.first_seq, seq)
            self.last_seq = max(self.last_seq, seq)
        self.records += 1


class Spool:
    """Thread-safe (send() callers and the sender thread both touch it)."""

    def __init__(self, directory: str, max_bytes: int = 64 << 20,
                 segment_bytes: int = 4 << 20, on_evict=None,
                 chaos=None, max_age_s: float = 0) -> None:
        self.dir = directory
        self.max_bytes = max_bytes
        # age-based retention (0 = size-only): whole CLOSED segments
        # older than this are evicted — stale spooled frames describe a
        # past the operator may no longer want replayed after a long
        # outage. Checked on append and trim; visible as spool_age_evict.
        self.max_age_s = max(0.0, float(max_age_s))
        # a segment must be well under the cap or eviction (whole
        # oldest segments, never the open writer) could not enforce it
        self.segment_bytes = max(4096, min(segment_bytes, max_bytes // 2))
        self.on_evict = on_evict  # callback(n_records, reason)
        self._chaos = chaos
        self._lock = threading.Lock()
        self._segments: list[_Segment] = []
        self._fh = None  # open handle on the newest segment
        self.stats = {"appended": 0, "replayed": 0, "evicted": 0,
                      "trimmed": 0, "corrupt": 0, "disk_errors": 0,
                      "recovered": 0}
        os.makedirs(self.dir, exist_ok=True)
        self._recover()

    # -- recovery ------------------------------------------------------------

    def _recover(self) -> None:
        names = sorted(n for n in os.listdir(self.dir)
                       if n.startswith(_SEG_PREFIX)
                       and n.endswith(_SEG_SUFFIX))
        for name in names:
            path = os.path.join(self.dir, name)
            seg = _Segment(path, 0)
            good_end = 0
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                self.stats["disk_errors"] += 1
                continue
            off = 0
            while off + _REC_SIZE <= len(data):
                ln, crc, _mt, seq = struct.unpack_from(_REC_FMT, data, off)
                end = off + _REC_SIZE + ln
                if ln > _MAX_RECORD or end > len(data):
                    break  # torn tail: a crash mid-append
                if zlib.crc32(data[off + _REC_SIZE:end]) & 0xFFFFFFFF != crc:
                    self.stats["corrupt"] += 1
                    break  # no resync marker: discard the rest
                seg.note(seq)
                good_end = end
                off = end
            if good_end < len(data):
                try:  # truncate the torn tail so appends stay framed
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                except OSError:
                    self.stats["disk_errors"] += 1
            if seg.records == 0:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            seg.bytes = good_end
            try:  # restart: age continues from the file's last write
                seg.mtime = os.path.getmtime(path)
            except OSError:
                pass
            self._segments.append(seg)
            self.stats["recovered"] += seg.records
        self._segments.sort(key=lambda s: s.first_seq)

    # -- append --------------------------------------------------------------

    def append(self, msg_type: int, seq: int, payload: bytes) -> bool:
        """Append one record; False on a disk error (the caller drops and
        ledgers the frame — the spool never throws on the send path)."""
        rec = struct.pack(_REC_FMT, len(payload),
                          zlib.crc32(payload) & 0xFFFFFFFF,
                          int(msg_type), seq) + payload
        with self._lock:
            try:
                if self._chaos is not None:
                    self._chaos.on_spool_write()
                fh = self._writer(len(rec), seq)
                fh.write(rec)
                fh.flush()
            except OSError as e:
                self.stats["disk_errors"] += 1
                log.warning("spool append failed: %s", e)
                return False
            seg = self._segments[-1]
            seg.note(seq)
            seg.bytes += len(rec)
            seg.mtime = time.time()
            self.stats["appended"] += 1
            self._enforce_cap()
            return True

    def _writer(self, need: int, seq: int):
        """Open segment with room for `need` bytes, rotating as needed."""
        if (self._fh is None or not self._segments
                or self._segments[-1].bytes + need > self.segment_bytes):
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            path = os.path.join(self.dir,
                                f"{_SEG_PREFIX}{seq:020d}{_SEG_SUFFIX}")
            self._fh = open(path, "ab")
            if not self._segments or self._segments[-1].path != path:
                self._segments.append(_Segment(path, seq))
        return self._fh

    def _enforce_cap(self) -> None:
        """Oldest-segment eviction: bounded disk, bounded (visible) loss."""
        total = sum(s.bytes for s in self._segments)
        while total > self.max_bytes and len(self._segments) > 1:
            total -= self._evict_oldest("spool_evict")
        self._enforce_age()

    def _enforce_age(self) -> None:
        if not self.max_age_s:
            return
        cutoff = time.time() - self.max_age_s
        # never the open writer (last segment): its mtime still moves
        while len(self._segments) > 1 and \
                self._segments[0].mtime < cutoff:
            self._evict_oldest("spool_age_evict")

    def _evict_oldest(self, reason: str) -> int:
        victim = self._segments.pop(0)
        self.stats["evicted"] += victim.records
        try:
            os.unlink(victim.path)
        except OSError:
            self.stats["disk_errors"] += 1
        if self.on_evict is not None:
            self.on_evict(victim.records, reason)
        return victim.bytes

    # -- replay / trim -------------------------------------------------------

    def replay(self, after_seq: int) -> list[tuple[int, int, bytes]]:
        """All surviving records with seq > after_seq, oldest first, as
        (msg_type, seq, payload). Corrupt records are skipped+counted."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
            segments = [s for s in self._segments
                        if s.last_seq > after_seq]
            paths = [s.path for s in segments]
        out: list[tuple[int, int, bytes]] = []
        for path in paths:
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                self.stats["disk_errors"] += 1
                continue
            off = 0
            while off + _REC_SIZE <= len(data):
                ln, crc, mt, seq = struct.unpack_from(_REC_FMT, data, off)
                end = off + _REC_SIZE + ln
                if ln > _MAX_RECORD or end > len(data):
                    break
                payload = data[off + _REC_SIZE:end]
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    self.stats["corrupt"] += 1
                    break
                if seq > after_seq:
                    out.append((mt, seq, payload))
                off = end
        self.stats["replayed"] += len(out)
        return out

    def trim(self, acked_seq: int) -> int:
        """Delete segments fully covered by the server's ack; returns the
        number of records released."""
        released = 0
        with self._lock:
            while self._segments and \
                    self._segments[0].last_seq <= acked_seq:
                seg = self._segments[0]
                # never unlink the segment the writer holds open
                if self._fh is not None and seg is self._segments[-1]:
                    break
                self._segments.pop(0)
                released += seg.records
                try:
                    os.unlink(seg.path)
                except OSError:
                    self.stats["disk_errors"] += 1
            self.stats["trimmed"] += released
            # acks arrive while appends may have stopped (idle agent):
            # trim is the other periodic touch point for age retention
            self._enforce_age()
        return released

    # -- introspection -------------------------------------------------------

    def max_seq(self) -> int:
        """Highest seq still spooled (0 when empty) — lets the sender's
        flush path know whether unreplayed records remain. Max across
        ALL segments: out-of-order appends mean the newest segment does
        not necessarily hold the highest seq."""
        with self._lock:
            return max((s.last_seq for s in self._segments), default=0)

    def min_pending_seq(self) -> int:
        """Lowest seq still spooled (0 when empty): a safe lower bound
        for the sender's SEQ_BASE announcement."""
        with self._lock:
            return min((s.first_seq for s in self._segments), default=0)

    def pending_records(self) -> int:
        with self._lock:
            return sum(s.records for s in self._segments)

    def pending_bytes(self) -> int:
        with self._lock:
            return sum(s.bytes for s in self._segments)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
