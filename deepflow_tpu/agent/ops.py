"""Agent ops surface: remote-exec command registry, debug queue taps,
restart-based upgrade, and the L7 parser plugin loader.

Reference analogs: message/agent.proto:18 RemoteExecRequest (a REGISTRY of
predefined commands, never arbitrary shell), agent.proto:9 UpgradeRequest
(binary swap + restart; here re-exec picks up updated code from disk —
K8s rollouts replace the pod the same way), debug/debugger.rs:111 (queue
taps sampling live queues), plugin/wasm/mod.rs:17 (custom protocol hooks;
here plugins are python modules exporting PARSERS).
"""

from __future__ import annotations

import importlib
import json
import logging
import os
import sys
import threading

log = logging.getLogger("df.ops")

MAX_OUTPUT = 64 * 1024


class CommandRegistry:
    """Named introspection commands; nothing here shells out."""

    def __init__(self, agent) -> None:
        self.agent = agent
        self._commands = {
            "help": self._help,
            "status": self._status,
            "config": self._config,
            "queues": self._queues,
            "queue-tap": self._queue_tap,
            "flows": self._flows,
            "profilers": self._profilers,
            "upgrade": self._upgrade,
            "pcap-capture": self._pcap_capture,
        }

    def names(self) -> list[str]:
        return sorted(self._commands)

    def run(self, cmd: str, args: list[str]) -> tuple[int, str]:
        fn = self._commands.get(cmd)
        if fn is None:
            return 127, f"unknown command {cmd!r}; try: " + \
                ", ".join(self.names())
        try:
            out = fn(args)
        except Exception as e:
            return 1, f"{type(e).__name__}: {e}"
        if isinstance(out, (dict, list)):
            out = json.dumps(out, default=str, sort_keys=True)
        return 0, str(out)[:MAX_OUTPUT]

    # -- commands --------------------------------------------------------------

    def _help(self, args):
        return {"commands": self.names()}

    def _status(self, args):
        a = self.agent
        return {
            "components": list(a._components),
            "pid": os.getpid(),
            "degraded": bool(a.guard is not None and a.guard.degraded),
            "sender": dict(a.sender.stats),
        }

    def _config(self, args):
        from dataclasses import asdict
        return asdict(self.agent.config)

    def _queues(self, args):
        """Queue depths across the agent (debugger.rs queue list analog)."""
        a = self.agent
        out = {"sender_queue": a.sender.queue_depth()}
        if a.dispatcher is not None:
            out["l4_buffer"] = len(a.dispatcher._l4_buf)
            out["l7_buffer"] = len(a.dispatcher._l7_buf)
        return out

    def _queue_tap(self, args):
        """Sample up to N live entries from a queue without consuming them
        (debugger.rs:111 queue tap)."""
        n = int(args[0]) if args else 8
        which = args[1] if len(args) > 1 else "sender"
        a = self.agent
        if which == "sender":
            return {"queue": "sender",
                    "entries": a.sender.peek(n)}
        if which == "l7" and a.dispatcher is not None:
            return {"queue": "l7",
                    "entries": [str(x)[:200]
                                for x in a.dispatcher._l7_buf[:n]]}
        return {"error": f"no such queue {which!r}"}

    def _flows(self, args):
        a = self.agent
        if a.dispatcher is None:
            return {"error": "flow pipeline not running"}
        return dict(a.dispatcher.stats)

    def _profilers(self, args):
        a = self.agent
        out = {}
        if a.sampler is not None:
            st = a.sampler.stats
            out["oncpu"] = {"samples": st.samples, "emits": st.emits}
        if a.tpuprobe is not None:
            out["tpuprobe"] = dict(a.tpuprobe.stats)
        for ep in a.extprofilers:
            out[f"extprof-{ep.pid}"] = {"samples": ep.stats.samples,
                                        "lost": ep.lost}
        return out

    def _pcap_capture(self, args):
        """On-demand raw capture shipped to the server (reference: pcap
        policy -> ingester pcap store). args: [seconds] [iface]
        [max_packets]. Runs inline on the sync thread (bounded seconds)."""
        import gzip
        import socket as _s
        import struct
        import time as _t

        seconds = min(float(args[0]) if args else 2.0, 30.0)
        iface = args[1] if len(args) > 1 else ""
        max_packets = min(int(args[2]) if len(args) > 2 else 2000,
                          100_000)  # bound agent memory
        try:
            sock = _s.socket(_s.AF_PACKET, _s.SOCK_RAW, _s.htons(0x0003))
        except (PermissionError, AttributeError, OSError) as e:
            return {"error": f"raw capture unavailable: {e}"}
        if iface:
            sock.bind((iface, 0))
        sock.settimeout(0.2)
        frames = []
        start_ns = _t.time_ns()
        deadline = _t.monotonic() + seconds
        try:
            while _t.monotonic() < deadline and len(frames) < max_packets:
                try:
                    frame, addr = sock.recvfrom(65535)
                except _s.timeout:
                    continue
                if addr[0] == "lo" and addr[2] == _s.PACKET_OUTGOING:
                    continue
                frames.append((frame, _t.time_ns()))
        finally:
            sock.close()
        buf = bytearray(struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0,
                                    65535, 1))
        for frame, ts in frames:
            buf += struct.pack("<IIII", ts // 1_000_000_000,
                               (ts % 1_000_000_000) // 1000,
                               len(frame), len(frame))
            buf += frame
        from deepflow_tpu.proto import pb as _pb
        up = _pb.PcapUpload()
        up.name = f"cap-{start_ns}"
        up.agent_id = self.agent.config.agent_id
        up.start_ns = start_ns
        up.packet_count = len(frames)
        up.pcap_gz = gzip.compress(bytes(buf))
        from deepflow_tpu.codec import MessageType
        self.agent.sender.send(MessageType.PCAP, up.SerializeToString())
        return {"name": up.name, "packets": len(frames),
                "bytes_gz": len(up.pcap_gz)}

    def _upgrade(self, args):
        """OTA upgrade (reference: agent.proto:9 Upgrade stream +
        cli/ctl/agent.go:135 repo rollout). Two modes:

        - no version arg: drain and re-exec, picking up updated code
          already on disk.
        - `version=vX` arg: DOWNLOAD that package from the controller
          repo over the sync plane, verify its sha256, unpack it into a
          versioned directory, and re-exec with the new tree FIRST on
          PYTHONPATH — binary distribution, not just restart.
        """
        if "dry-run" in args:
            return {"upgrading": False, "dry_run": True, "argv": sys.argv}
        version = ""
        for a in args:
            if a.startswith("version="):
                version = a.split("=", 1)[1]
        env_extra: dict[str, str] = {}
        staged = None
        if version:
            try:
                staged = self._stage_package(version)
            except Exception as e:  # noqa: BLE001 - report, don't die
                return {"upgrading": False, "error": str(e)}
            prior = os.environ.get("PYTHONPATH", "")
            env_extra["PYTHONPATH"] = (f"{staged}:{prior}" if prior
                                       else staged)

        def _reexec():
            log.warning("upgrade: re-exec %s (staged=%s)", sys.argv,
                        staged)
            sync = getattr(self.agent, "synchronizer", None)
            if sync is not None:
                try:
                    sync.sync_once()  # ship the upgrade's own result first
                except Exception:
                    pass
            try:
                self.agent.stop()
            except Exception:
                pass
            os.environ.update(env_extra)
            self._execv(sys.executable, [sys.executable] + sys.argv)

        threading.Timer(0.5, _reexec).start()
        return {"upgrading": True, "argv": sys.argv,
                "version": version or None, "staged": staged}

    def _stage_package(self, version: str) -> str:
        """Fetch + verify + unpack a repo package; returns the directory
        to prepend to PYTHONPATH."""
        import hashlib
        import tarfile
        import tempfile

        sync = getattr(self.agent, "synchronizer", None)
        if sync is None:
            raise RuntimeError("no controller connection for OTA fetch")
        resp = sync.fetch_package("agent", version)
        if not resp.found:
            raise RuntimeError(f"package agent@{version} not in repo")
        sha = hashlib.sha256(resp.data).hexdigest()
        if sha != resp.sha256:
            raise RuntimeError(
                f"package digest mismatch: {sha} != {resp.sha256}")
        base = os.environ.get("DF_UPGRADE_DIR") or os.path.join(
            tempfile.gettempdir(), "df-agent-versions")
        dest = os.path.join(base, resp.version)
        staging = dest + ".staging"
        import shutil
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging, exist_ok=True)
        import io
        with tarfile.open(fileobj=io.BytesIO(resp.data), mode="r:gz") as t:
            # refuse path traversal / links (an OTA package is trusted
            # code by definition, but a corrupted archive must not write
            # outside its version directory)
            for m in t.getmembers():
                p = os.path.normpath(m.name)
                if p.startswith("..") or os.path.isabs(p) or \
                        m.issym() or m.islnk():
                    raise RuntimeError(f"unsafe member {m.name!r}")
            try:  # belt-and-braces on 3.12+; manual checks above are
                # the real guard (filter= absent before 3.10.12/3.11.4)
                t.extractall(staging, filter="data")
            except TypeError:
                t.extractall(staging)
        shutil.rmtree(dest, ignore_errors=True)
        os.replace(staging, dest)
        log.warning("upgrade: staged agent@%s at %s", resp.version, dest)
        return dest

    # test seam: replaced in tests so an 'upgrade' never re-execs pytest
    _execv = staticmethod(os.execv)


def load_plugins(module_paths: list[str]) -> list[str]:
    """Import parser plugins: each module exports PARSERS (L7Parser
    subclasses), registered ahead of the builtins so plugins can override
    (reference: wasm hooks run before native parsers)."""
    from deepflow_tpu.agent.protocol_logs.base import REGISTRY
    loaded = []
    for path in module_paths:
        try:
            mod = importlib.import_module(path)
            parsers = getattr(mod, "PARSERS", [])
            for cls in parsers:
                REGISTRY.insert(0, cls())
                loaded.append(f"{path}.{cls.__name__}")
        except Exception as e:
            log.warning("plugin %s failed to load: %s", path, e)
    if loaded:
        log.info("plugins loaded: %s", ", ".join(loaded))
    return loaded
