""".eh_frame -> compact unwind tables for the out-of-process profiler.

Reference analog: agent/crates/trace-utils/src/unwind/dwarf.rs (parses
.eh_frame into shard tables the BPF unwinder walks) and
kernel/perf_profiler.bpf.c:1015 PROGPE(dwarf_unwind). Same split here:
this module is the cold path — parse once per binary, emit flat arrays
sorted by pc — and the native sampler (native/perfprof.cpp) walks them per
sample against PERF_SAMPLE_REGS_USER + PERF_SAMPLE_STACK_USER.

x86-64 only. Tracked register rules: CFA (must be rsp/rbp + offset), RBP,
and RA(16). Rows whose CFA comes from a DWARF expression are marked
invalid — the walker stops there and falls back to the frame-pointer
chain, the same degradation the reference accepts for odd frames.

Row encoding (one row covers [pc, next row's pc)):
  pc      u64   file vaddr
  cfa_reg u8    0 = rsp, 1 = rbp, 2 = invalid (expression/unsupported)
  cfa_off i32   CFA = reg + cfa_off
  rbp_off i32   saved rbp at CFA + rbp_off; INT32_MIN = no rule (keep)
  ra_off  i32   return address at CFA + ra_off; INT32_MIN = invalid
"""

from __future__ import annotations

import logging
import struct
from dataclasses import dataclass

import numpy as np

log = logging.getLogger("df.ehframe")

RSP, RBP, RA = 7, 6, 16
CFA_RSP, CFA_RBP, CFA_BAD = 0, 1, 2
NO_RULE = -(1 << 31)  # INT32_MIN sentinel

# DW_EH_PE pointer encodings
_PE_omit = 0xFF
_PE_FMT = 0x0F
_PE_APP = 0x70
_PE_pcrel = 0x10
_PE_datarel = 0x30
_PE_indirect = 0x80

_DEFAULT_EHFRAME_CAP = 16 << 20  # parse cost guard for giant runtimes


class EhFrameError(Exception):
    pass


def _uleb(data: bytes, p: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = data[p]
        p += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, p
        shift += 7


def _sleb(data: bytes, p: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = data[p]
        p += 1
        out |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            if b & 0x40:
                out -= 1 << shift
            return out, p


def _read_encoded(data: bytes, p: int, enc: int, sec_vaddr: int) -> \
        tuple[int, int]:
    """Decode a DW_EH_PE-encoded pointer at section offset p -> (value,
    new_p). pcrel values resolve against the section vaddr (file-relative;
    the runtime bias is applied at registration time)."""
    if enc == _PE_omit:
        return 0, p
    base = 0
    if enc & _PE_APP == _PE_pcrel:
        base = sec_vaddr + p
    fmt = enc & _PE_FMT
    if fmt == 0x00:  # absptr
        v = struct.unpack_from("<Q", data, p)[0]
        p += 8
    elif fmt == 0x01:  # uleb128
        v, p = _uleb(data, p)
    elif fmt == 0x02:  # udata2
        v = struct.unpack_from("<H", data, p)[0]
        p += 2
    elif fmt == 0x03:  # udata4
        v = struct.unpack_from("<I", data, p)[0]
        p += 4
    elif fmt == 0x04:  # udata8
        v = struct.unpack_from("<Q", data, p)[0]
        p += 8
    elif fmt == 0x09:  # sleb128
        v, p = _sleb(data, p)
    elif fmt == 0x0A:  # sdata2
        v = struct.unpack_from("<h", data, p)[0]
        p += 2
    elif fmt == 0x0B:  # sdata4
        v = struct.unpack_from("<i", data, p)[0]
        p += 4
    elif fmt == 0x0C:  # sdata8
        v = struct.unpack_from("<q", data, p)[0]
        p += 8
    else:
        raise EhFrameError(f"unsupported pointer encoding {enc:#x}")
    return (base + v) & 0xFFFFFFFFFFFFFFFF, p


def _skip_encoded(data: bytes, p: int, enc: int) -> int:
    if enc == _PE_omit:
        return p
    fmt = enc & _PE_FMT
    if fmt in (0x01, 0x09):
        _, p = _uleb(data, p)
        return p
    return p + {0x00: 8, 0x02: 2, 0x03: 4, 0x04: 8,
                0x0A: 2, 0x0B: 4, 0x0C: 8}[fmt]


@dataclass
class _Cie:
    code_align: int = 1
    data_align: int = -8
    ra_reg: int = RA
    fde_enc: int = 0x1B  # pcrel | sdata4, the common default
    aug_has_z: bool = False
    # initial state after CIE instructions: (cfa_reg_dw, cfa_off, rbp, ra)
    # where rbp/ra are CFA-relative offsets or NO_RULE
    initial: tuple = (-1, 0, NO_RULE, NO_RULE)


class _Rows:
    """Row accumulator -> flat arrays. Consecutive identical states are
    deduped (emit is the parse hot path: a big runtime emits 500k+ rows)."""

    def __init__(self) -> None:
        self.pc: list[int] = []
        self.cfa_reg: list[int] = []
        self.cfa_off: list[int] = []
        self.rbp_off: list[int] = []
        self.ra_off: list[int] = []
        self._last = None

    def emit(self, loc: int, cfa_reg_dw: int, cfa_off: int, rbp: int,
             ra: int) -> None:
        if cfa_reg_dw == RSP:
            creg = CFA_RSP
        elif cfa_reg_dw == RBP:
            creg = CFA_RBP
        else:
            creg = CFA_BAD
        if not -1073741824 < cfa_off < 1073741824:
            creg = CFA_BAD
        if not -1073741824 < rbp < 1073741824:
            rbp = NO_RULE
        if not -1073741824 < ra < 1073741824:
            ra = NO_RULE
        state = (creg, cfa_off, rbp, ra)
        if state == self._last:
            return  # extends the previous row
        self._last = state
        self.pc.append(loc)
        self.cfa_reg.append(creg)
        self.cfa_off.append(cfa_off)
        self.rbp_off.append(rbp)
        self.ra_off.append(ra)

    def sentinel(self, loc: int) -> None:
        self._last = None
        self.pc.append(loc)
        self.cfa_reg.append(CFA_BAD)
        self.cfa_off.append(0)
        self.rbp_off.append(NO_RULE)
        self.ra_off.append(NO_RULE)


def _run_cfi(data: bytes, p: int, end: int, cie: _Cie, state: tuple,
             loc: int, sec_vaddr: int, rows: _Rows | None) -> tuple:
    """Execute call-frame instructions from `state` = (cfa_reg_dw,
    cfa_off, rbp, ra). With rows=None this computes the CIE's initial
    state; otherwise emits a row per location range. State is scalar
    locals, not dicts — this loop runs ~10 ops x 50k FDEs per big binary.
    Rules for registers other than rbp/ra are parsed and skipped."""
    cfa_reg, cfa_off, rbp, ra = state
    init_cfa_reg, init_cfa_off, init_rbp, init_ra = cie.initial
    code_align, data_align, ra_reg = (cie.code_align, cie.data_align,
                                      cie.ra_reg)
    stack: list[tuple] = []
    emit = rows.emit if rows is not None else None
    while p < end:
        op = data[p]
        p += 1
        high = op & 0xC0
        if high == 0x40:  # advance_loc
            if emit is not None:
                emit(loc, cfa_reg, cfa_off, rbp, ra)
            loc += (op & 0x3F) * code_align
        elif high == 0x80:  # offset reg, uleb
            reg = op & 0x3F
            off, p = _uleb(data, p)
            if reg == RBP:
                rbp = off * data_align
            elif reg == ra_reg:
                ra = off * data_align
        elif high == 0xC0:  # restore reg
            reg = op & 0x3F
            if reg == RBP:
                rbp = init_rbp
            elif reg == ra_reg:
                ra = init_ra
        elif op == 0x00:  # nop
            pass
        elif op == 0x02:  # advance_loc1
            if emit is not None:
                emit(loc, cfa_reg, cfa_off, rbp, ra)
            loc += data[p] * code_align
            p += 1
        elif op == 0x03:  # advance_loc2
            if emit is not None:
                emit(loc, cfa_reg, cfa_off, rbp, ra)
            loc += (data[p] | data[p + 1] << 8) * code_align
            p += 2
        elif op == 0x04:  # advance_loc4
            if emit is not None:
                emit(loc, cfa_reg, cfa_off, rbp, ra)
            loc += struct.unpack_from("<I", data, p)[0] * code_align
            p += 4
        elif op == 0x0C:  # def_cfa
            cfa_reg, p = _uleb(data, p)
            cfa_off, p = _uleb(data, p)
        elif op == 0x0D:  # def_cfa_register
            cfa_reg, p = _uleb(data, p)
        elif op == 0x0E:  # def_cfa_offset
            cfa_off, p = _uleb(data, p)
        elif op == 0x0A:  # remember_state
            stack.append((cfa_reg, cfa_off, rbp, ra))
        elif op == 0x0B:  # restore_state
            if stack:
                cfa_reg, cfa_off, rbp, ra = stack.pop()
        elif op == 0x01:  # set_loc
            if emit is not None:
                emit(loc, cfa_reg, cfa_off, rbp, ra)
            loc, p = _read_encoded(data, p, cie.fde_enc, sec_vaddr)
        elif op == 0x05:  # offset_extended
            reg, p = _uleb(data, p)
            off, p = _uleb(data, p)
            if reg == RBP:
                rbp = off * data_align
            elif reg == ra_reg:
                ra = off * data_align
        elif op == 0x06:  # restore_extended
            reg, p = _uleb(data, p)
            if reg == RBP:
                rbp = init_rbp
            elif reg == ra_reg:
                ra = init_ra
        elif op in (0x07, 0x08):  # undefined / same_value
            reg, p = _uleb(data, p)
            if reg == RBP:
                rbp = NO_RULE
            elif reg == ra_reg:
                ra = NO_RULE
        elif op == 0x09:  # register (reg-in-reg: not walkable from stack)
            reg, p = _uleb(data, p)
            _, p = _uleb(data, p)
            if reg == RBP:
                rbp = NO_RULE
            elif reg == ra_reg:
                ra = NO_RULE
        elif op == 0x0F:  # def_cfa_expression
            n, p = _uleb(data, p)
            p += n
            cfa_reg = -1  # expression: invalid for our walker
        elif op == 0x10 or op == 0x16:  # expression / val_expression
            reg, p = _uleb(data, p)
            n, p = _uleb(data, p)
            p += n
            if reg == RBP:
                rbp = NO_RULE
            elif reg == ra_reg:
                ra = NO_RULE
        elif op == 0x11:  # offset_extended_sf
            reg, p = _uleb(data, p)
            off, p = _sleb(data, p)
            if reg == RBP:
                rbp = off * data_align
            elif reg == ra_reg:
                ra = off * data_align
        elif op == 0x12:  # def_cfa_sf
            cfa_reg, p = _uleb(data, p)
            off, p = _sleb(data, p)
            cfa_off = off * data_align
        elif op == 0x13:  # def_cfa_offset_sf
            off, p = _sleb(data, p)
            cfa_off = off * data_align
        elif op in (0x14, 0x15):  # val_offset(_sf)
            reg, p = _uleb(data, p)
            if op == 0x14:
                _, p = _uleb(data, p)
            else:
                _, p = _sleb(data, p)
            if reg == RBP:
                rbp = NO_RULE
            elif reg == ra_reg:
                ra = NO_RULE
        elif op == 0x2E:  # DW_CFA_GNU_args_size
            _, p = _uleb(data, p)
        elif op == 0x2D or op == 0x2F:  # GNU_window_save / negative_offset_ext
            if op == 0x2F:
                _, p = _uleb(data, p)
                _, p = _uleb(data, p)
        else:
            raise EhFrameError(f"unknown CFA op {op:#x}")
    if emit is not None:
        emit(loc, cfa_reg, cfa_off, rbp, ra)
    return cfa_reg, cfa_off, rbp, ra


def _parse_cie(data: bytes, start: int, body_start: int, end: int,
               sec_vaddr: int) -> _Cie:
    cie = _Cie()
    p = body_start
    version = data[p]
    p += 1
    if version not in (1, 3, 4):
        raise EhFrameError(f"CIE version {version}")
    aug_end = data.index(b"\0", p)
    aug = data[p:aug_end].decode("ascii", "replace")
    p = aug_end + 1
    if version == 4:
        p += 2  # address_size, segment_size
    cie.code_align, p = _uleb(data, p)
    cie.data_align, p = _sleb(data, p)
    if version == 1:
        cie.ra_reg = data[p]
        p += 1
    else:
        cie.ra_reg, p = _uleb(data, p)
    if aug.startswith("z"):
        cie.aug_has_z = True
        aug_len, p = _uleb(data, p)
        aug_data_end = p + aug_len
        for ch in aug[1:]:
            if ch == "R":
                cie.fde_enc = data[p]
                p += 1
            elif ch == "L":
                p += 1
            elif ch == "P":
                enc = data[p]
                p = _skip_encoded(data, p + 1, enc)
            elif ch == "S":
                pass  # signal frame
            else:
                break  # unknown char: skip the rest via aug_len
        p = aug_data_end
    cie.initial = _run_cfi(data, p, end, cie,
                           (-1, 0, NO_RULE, NO_RULE), 0, sec_vaddr, None)
    return cie


@dataclass
class UnwindTable:
    """Flat unwind rows for one binary, sorted by file vaddr."""
    pc: np.ndarray       # u64
    cfa_reg: np.ndarray  # u8
    cfa_off: np.ndarray  # i32
    rbp_off: np.ndarray  # i32
    ra_off: np.ndarray   # i32
    n_fdes: int = 0

    def __len__(self) -> int:
        return len(self.pc)


class ParseInterrupted(Exception):
    pass


def _cache_dir() -> str:
    import os
    d = os.environ.get("DF_UNWIND_CACHE")
    if not d:
        base = os.environ.get("XDG_CACHE_HOME",
                              os.path.expanduser("~/.cache"))
        d = os.path.join(base, "deepflow-tpu", "unwind")
    return d


def _cache_key(path: str) -> str | None:
    import hashlib
    import os
    try:
        st = os.stat(path)
    except OSError:
        return None
    return hashlib.sha1(
        f"{path}:{st.st_mtime_ns}:{st.st_size}".encode()).hexdigest()


def load_unwind_table_cached(path: str,
                             max_bytes: int = _DEFAULT_EHFRAME_CAP,
                             should_stop=None) -> UnwindTable | None:
    """load_unwind_table with a disk cache (parse a given binary once per
    machine, ever — the reference persists its unwind shards the same
    way). Key: path + mtime + size. Corrupt/missing cache -> re-parse."""
    import os
    key = _cache_key(path)
    cache_path = (os.path.join(_cache_dir(), key + ".npz")
                  if key else None)
    if cache_path and os.path.exists(cache_path):
        try:
            with np.load(cache_path) as z:
                if int(z["version"]) == 1:
                    return UnwindTable(
                        pc=z["pc"], cfa_reg=z["cfa_reg"],
                        cfa_off=z["cfa_off"], rbp_off=z["rbp_off"],
                        ra_off=z["ra_off"], n_fdes=int(z["n_fdes"]))
        except Exception:
            pass  # corrupt cache: fall through to re-parse
    table = load_unwind_table(path, max_bytes, should_stop)
    if table is not None and len(table) and cache_path:
        try:
            os.makedirs(_cache_dir(), exist_ok=True)
            # name must end in .npz or np.savez appends it
            tmp = cache_path + f".{os.getpid()}.tmp.npz"
            np.savez(tmp, version=1, n_fdes=table.n_fdes, pc=table.pc,
                     cfa_reg=table.cfa_reg, cfa_off=table.cfa_off,
                     rbp_off=table.rbp_off, ra_off=table.ra_off)
            os.replace(tmp, cache_path)
        except OSError:
            pass
    return table


def parse_eh_frame(data, sec_vaddr: int, should_stop=None) -> UnwindTable:
    """Parse one .eh_frame section blob (file vaddr sec_vaddr).
    should_stop() is polled periodically; True raises ParseInterrupted
    (a profiler shutting down must not wait out a giant runtime)."""
    rows = _Rows()
    cies: dict[int, _Cie] = {}
    p = 0
    n = len(data)
    n_fdes = 0
    n_entries = 0
    while p + 4 <= n:
        n_entries += 1
        if should_stop is not None and n_entries % 1024 == 0 \
                and should_stop():
            raise ParseInterrupted()
        start = p
        length = struct.unpack_from("<I", data, p)[0]
        p += 4
        if length == 0:
            continue  # terminator; some sections pad with several
        if length == 0xFFFFFFFF:
            length = struct.unpack_from("<Q", data, p)[0]
            p += 8
        entry_end = p + length
        if entry_end > n:
            break  # truncated
        id_off = p
        cie_id = struct.unpack_from("<I", data, p)[0]
        p += 4
        try:
            if cie_id == 0:
                cies[start] = _parse_cie(data, start, p, entry_end,
                                         sec_vaddr)
            else:
                cie = cies.get(id_off - cie_id)
                if cie is None:
                    raise EhFrameError("FDE references unknown CIE")
                pc_begin, p2 = _read_encoded(data, p, cie.fde_enc,
                                             sec_vaddr)
                pc_range, p2 = _read_encoded(
                    data, p2, cie.fde_enc & _PE_FMT, sec_vaddr)
                if cie.aug_has_z:
                    aug_len, p2 = _uleb(data, p2)
                    p2 += aug_len
                _run_cfi(data, p2, entry_end, cie, cie.initial, pc_begin,
                         sec_vaddr, rows)
                rows.sentinel(pc_begin + pc_range)
                n_fdes += 1
        except (EhFrameError, IndexError, struct.error, KeyError) as e:
            log.debug("eh_frame entry at %#x skipped: %s", start, e)
        p = entry_end
    if not rows.pc:
        return UnwindTable(pc=np.empty(0, np.uint64),
                           cfa_reg=np.empty(0, np.uint8),
                           cfa_off=np.empty(0, np.int32),
                           rbp_off=np.empty(0, np.int32),
                           ra_off=np.empty(0, np.int32))
    pc = np.asarray(rows.pc, dtype=np.uint64)
    cfa_reg = np.asarray(rows.cfa_reg, dtype=np.uint8)
    cfa_off = np.asarray(rows.cfa_off, dtype=np.int32)
    rbp_off = np.asarray(rows.rbp_off, dtype=np.int32)
    ra_off = np.asarray(rows.ra_off, dtype=np.int32)
    # sort by pc; FDE-end sentinels sort BEFORE a real row at the same pc
    # (stable sort + emit order handles adjacent functions: the next FDE's
    # first row is emitted after the previous FDE's sentinel, and with
    # kind="stable" the real row wins the searchsorted right-1 lookup)
    order = np.argsort(pc, kind="stable")
    return UnwindTable(pc=pc[order], cfa_reg=cfa_reg[order],
                       cfa_off=cfa_off[order], rbp_off=rbp_off[order],
                       ra_off=ra_off[order], n_fdes=n_fdes)


def load_unwind_table(path: str,
                      max_bytes: int = _DEFAULT_EHFRAME_CAP,
                      should_stop=None) -> UnwindTable | None:
    """Parse an ELF's .eh_frame -> UnwindTable (file vaddrs). None when the
    binary has no .eh_frame, is not ELF64, or exceeds the parse-cost cap.
    Raises ParseInterrupted when should_stop() fires mid-parse."""
    import mmap as _mmap
    try:
        with open(path, "rb") as f:
            try:
                data = _mmap.mmap(f.fileno(), 0, prot=_mmap.PROT_READ)
            except (ValueError, OSError):
                data = f.read()
    except OSError:
        return None
    try:
        if data[:4] != b"\x7fELF" or data[4] != 2:
            return None
        (_, _, _, _, _, e_shoff, _, _, _, _, e_shentsize, e_shnum,
         e_shstrndx) = struct.unpack_from("<HHIQQQIHHHHHH", data, 16)
        if not e_shnum or e_shstrndx >= e_shnum:
            return None
        # section name string table
        off = e_shoff + e_shstrndx * e_shentsize
        _, _, _, _, str_off, str_size = struct.unpack_from(
            "<IIQQQQ", data, off)
        for i in range(e_shnum):
            off = e_shoff + i * e_shentsize
            sh_name, _, _, sh_addr, sh_offset, sh_size = \
                struct.unpack_from("<IIQQQQ", data, off)
            name_end = data.find(b"\0", str_off + sh_name,
                                 str_off + str_size)
            name = bytes(data[str_off + sh_name:name_end])
            if name == b".eh_frame":
                if sh_size > max_bytes:
                    log.info("%s: .eh_frame %d bytes exceeds cap %d; "
                             "frame-pointer fallback", path, sh_size,
                             max_bytes)
                    return None
                blob = bytes(data[sh_offset:sh_offset + sh_size])
                return parse_eh_frame(blob, sh_addr, should_stop)
        return None
    except (ValueError, struct.error, IndexError):
        return None
    finally:
        if isinstance(data, _mmap.mmap):
            data.close()
