"""Native flow pipeline wrapper: batch packet ingest through the C++ flow
map, with Python touched only at the L7 boundary and at flow close.

Reference analog: agent/src/flow_generator/flow_map.rs:716 +
agent/src/dispatcher/recv_engine/mod.rs:40. The split of labor:

- C++ (flowmap.cpp): decode, flow table, TCP FSM, RTT, retrans, eviction,
  close records — per-packet cost with zero Python objects.
- Python (this file): L7 protocol inference/parsing for the payload segments
  the native side surfaces, session matching, and conversion of closed-flow
  records into the same FlowNode callbacks the pure-Python FlowMap uses —
  so collectors/senders don't know which engine ran.
"""

from __future__ import annotations

import ctypes
import time

import numpy as np

from deepflow_tpu import native
from deepflow_tpu.agent.flow_map import DirectionStats, FlowMap, FlowNode, \
    FlowState
from deepflow_tpu.agent.packet import decode_ethernet
from deepflow_tpu.agent.protocol_logs.base import get_parser
from deepflow_tpu.proto import pb

_CLOSE_TYPES = {0: "unknown", 1: "fin", 2: "rst", 3: "timeout", 4: "forced"}

# l7 feedback modes for df_fm_set_l7
L7_INFER = 0
L7_MUTED = -1


class _PayloadShim:
    """Minimal stand-in for MetaPacket at the L7 boundary (FlowMap._l7_update
    only reads .payload and .timestamp_ns)."""

    __slots__ = ("payload", "timestamp_ns")

    def __init__(self, payload: bytes, ts_ns: int) -> None:
        self.payload = payload
        self.timestamp_ns = ts_ns


class NativeFlowMap:
    """Drop-in engine with the FlowMap callback contract, batch-fed.

    L7 state lives in an embedded pure-Python FlowMap whose nodes are
    created lazily per flow that actually carries payload — header-only
    flows never materialize a Python object until they close.
    """

    L7_BUF_CAP = 4 << 20
    L7_EV_CAP = 16384
    SLOW_CAP = 16384
    CLOSED_BATCH = 8192
    # inject chunk: 2048 full-MTU payloads (~3MB) fit the 4MB l7 buffer, so
    # payload-heavy batches can't overflow the event exchange
    CHUNK = 2048

    def __init__(self, on_l4_log=None, on_l7_log=None, on_flow_update=None,
                 agent_id: int = 0, max_flows: int = 1 << 16) -> None:
        lib = native.load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._lib = lib
        self._fm = lib.df_fm_new(max_flows)
        self.on_l4_log = on_l4_log or (lambda f: None)
        self.on_l7_log = on_l7_log or (lambda r: None)
        self.on_flow_update = on_flow_update or (lambda f, closed: None)
        self.agent_id = agent_id
        self.max_flows = max_flows
        # embedded FlowMap reused for BOTH the L7 session logic (nodes keyed
        # by native flow_id) and the slow path (v6/vlan frames, keyed by
        # tuple — disjoint key spaces, one table)
        self._l7fm = FlowMap(on_l4_log=self.on_l4_log,
                             on_l7_log=self.on_l7_log,
                             on_flow_update=self.on_flow_update,
                             agent_id=agent_id, max_flows=max_flows)
        # preallocated exchange buffers
        self._l7_buf = np.zeros(self.L7_BUF_CAP, dtype=np.uint8)
        self._l7_evs = np.zeros(self.L7_EV_CAP, dtype=native.L7_EVENT_DTYPE)
        self._slow_idx = np.zeros(self.SLOW_CAP, dtype=np.uint32)
        self._slow_buf = np.zeros(1 << 20, dtype=np.uint8)
        self._slow_evs = np.zeros(4096, dtype=native.SLOW_EVENT_DTYPE)
        self._closed = np.zeros(self.CLOSED_BATCH,
                                dtype=native.FLOW_RECORD_DTYPE)
        self._n_l7 = ctypes.c_uint32(0)
        self._n_slow = ctypes.c_uint32(0)

    def __del__(self):
        try:
            if getattr(self, "_fm", None):
                self._lib.df_fm_free(self._fm)
                self._fm = None
        except Exception:
            pass

    # -- stats ---------------------------------------------------------------

    @property
    def stats(self) -> dict:
        out = np.zeros(8, dtype=np.uint64)
        self._lib.df_fm_stats(self._fm, out)
        s = {"packets": int(out[0]), "flows_created": int(out[1]),
             "flows_closed": int(out[2]), "evicted": int(out[3]),
             "l7_surfaced": int(out[4]), "l7_dropped": int(out[5]),
             "slow_path": int(out[6]), "excluded": int(out[7])}
        s["l7_records"] = self._l7fm.stats["l7_records"]
        return s

    def exclude_port(self, port: int, on: bool = True) -> None:
        self._lib.df_fm_exclude_port(self._fm, port, 1 if on else 0)

    @property
    def active_flows(self) -> int:
        return self._lib.df_fm_active_count(self._fm)

    # -- ingest --------------------------------------------------------------

    def inject_frames(self, frames: list[tuple[bytes, int]]) -> int:
        """Convenience: list of (frame, ts_ns) -> packed batch inject."""
        n = len(frames)
        offsets = np.zeros(n + 1, dtype=np.uint32)
        ts = np.zeros(n, dtype=np.uint64)
        total = 0
        for i, (f, t) in enumerate(frames):
            total += len(f)
            offsets[i + 1] = total
            ts[i] = t
        data = b"".join(f for f, _ in frames)
        return self.inject_batch(data, offsets, ts)

    def inject_batch(self, data: bytes, offsets: np.ndarray,
                     ts_ns: np.ndarray) -> int:
        """Packed frames -> native map. Returns packets handled natively."""
        n = len(offsets) - 1
        handled = 0
        for lo in range(0, n, self.CHUNK):
            hi = min(n, lo + self.CHUNK)
            off = np.ascontiguousarray(offsets[lo:hi + 1])
            handled += int(self._lib.df_fm_inject_batch(
                self._fm, data, off,
                np.ascontiguousarray(ts_ns[lo:hi]), hi - lo,
                self._l7_buf.ctypes.data_as(ctypes.c_void_p),
                self.L7_BUF_CAP,
                self._l7_evs.ctypes.data_as(ctypes.c_void_p),
                self.L7_EV_CAP, ctypes.byref(self._n_l7),
                self._slow_idx, self.SLOW_CAP,
                ctypes.byref(self._n_slow)))
            if self._n_l7.value:
                self._process_l7(self._n_l7.value)
            if self._n_slow.value:
                self._process_slow(data, offsets, ts_ns, lo,
                                   self._n_slow.value)
            self._drain_closed()
        return handled

    # -- L7 boundary ---------------------------------------------------------

    def _process_l7(self, n: int) -> None:
        # columnar extraction: one .tolist() per field beats per-record
        # numpy scalar access by ~5x at these event rates (the bench's
        # packet-path hot spot, VERDICT r04 item 8)
        evs = self._l7_evs[:n]
        flow_ids = evs["flow_id"].tolist()
        ts_l = evs["ts_ns"].tolist()
        off_l = evs["payload_off"].tolist()
        len_l = evs["payload_len"].tolist()
        istx_l = evs["is_tx"].tolist()
        ipsrc_l = evs["ip_src"].tolist()
        ipdst_l = evs["ip_dst"].tolist()
        psrc_l = evs["port_src"].tolist()
        pdst_l = evs["port_dst"].tolist()
        ttype_l = evs["tunnel_type"].tolist()
        tid_l = evs["tunnel_id"].tolist()
        buf_bytes = self._l7_buf
        flows = self._l7fm.flows
        l7_update = self._l7fm._l7_update
        for i in range(n):
            fid = flow_ids[i]
            node = flows.get(fid)
            if node is None:
                node = FlowNode(
                    flow_id=fid,
                    ip_src=ipsrc_l[i].to_bytes(4, "big"),
                    ip_dst=ipdst_l[i].to_bytes(4, "big"),
                    port_src=psrc_l[i], port_dst=pdst_l[i],
                    protocol=int(evs["protocol"][i]),
                    start_ns=ts_l[i],
                    tunnel_type=ttype_l[i], tunnel_id=tid_l[i])
                flows[fid] = node
            off = off_l[i]
            payload = buf_bytes[off:off + len_l[i]].tobytes()
            shim = _PayloadShim(payload, ts_l[i])
            before = node.l7_inferred
            # count surfaced payloads on the shadow so FlowMap's inference
            # give-up budget fires for native flows too; the close record
            # overwrites these counters with native truth
            node.tx.packets += 1
            try:
                l7_update(node, shim, bool(istx_l[i]))
            except Exception:
                pass
            if node.l7_inferred and not before:
                # verdict reached: tell native to keep surfacing (proto
                # known) or go quiet (unknown after the inference budget)
                mode = (int(node.l7_protocol)
                        if node.l7_protocol != pb.L7_UNKNOWN
                        and get_parser(node.l7_protocol) is not None
                        else L7_MUTED)
                self._lib.df_fm_set_l7(
                    self._fm, ipsrc_l[i], ipdst_l[i],
                    psrc_l[i], pdst_l[i], int(evs["protocol"][i]),
                    ttype_l[i], tid_l[i], mode)

    # -- slow path (v6 / vlan-exotic frames) ----------------------------------

    def _process_slow(self, data: bytes, offsets: np.ndarray,
                      ts_ns: np.ndarray, lo: int, n: int) -> None:
        for i in self._slow_idx[:n]:
            gi = lo + int(i)
            frame = data[int(offsets[gi]):int(offsets[gi + 1])]
            mp = decode_ethernet(frame, timestamp_ns=int(ts_ns[gi]))
            if mp is not None:
                self._l7fm.inject(mp)

    # -- close / tick ---------------------------------------------------------

    def _record_to_node(self, r) -> FlowNode:
        fid = int(r["flow_id"])
        node = self._l7fm.flows.pop(fid, None)
        if node is None:
            node = FlowNode(
                flow_id=fid,
                ip_src=int(r["ip_src"]).to_bytes(4, "big"),
                ip_dst=int(r["ip_dst"]).to_bytes(4, "big"),
                port_src=int(r["port_src"]), port_dst=int(r["port_dst"]),
                protocol=int(r["protocol"]), start_ns=int(r["start_ns"]),
                tunnel_type=int(r["tunnel_type"]),
                tunnel_id=int(r["tunnel_id"]))
        else:
            # flush unanswered requests through the session logic
            while node.pending:
                old = node.pending.popleft()
                self._l7fm._emit_l7(node, old.record, None,
                                    old.timestamp_ns, 0)
            node.pending_by_id.clear()
        node.start_ns = int(r["start_ns"])
        node.tunnel_type = int(r["tunnel_type"])
        node.tunnel_id = int(r["tunnel_id"])
        node.end_ns = int(r["end_ns"])
        node.state = FlowState(int(r["state"]))
        node.close_type = _CLOSE_TYPES.get(int(r["close_type"]), "unknown")
        node.tx = DirectionStats(
            packets=int(r["tx_packets"]), bytes=int(r["tx_bytes"]),
            tcp_flags_bits=int(r["tx_flags_bits"]),
            retrans=int(r["tx_retrans"]),
            zero_window=int(r["tx_zero_window"]))
        node.rx = DirectionStats(
            packets=int(r["rx_packets"]), bytes=int(r["rx_bytes"]),
            tcp_flags_bits=int(r["rx_flags_bits"]),
            retrans=int(r["rx_retrans"]),
            zero_window=int(r["rx_zero_window"]))
        node.syn_count = int(r["syn_count"])
        node.synack_count = int(r["synack_count"])
        node.rtt_us = int(r["rtt_us"])
        return node

    def _drain_closed(self) -> None:
        lib = self._lib
        while True:
            n = lib.df_fm_poll_closed(
                self._fm, self._closed.ctypes.data_as(ctypes.c_void_p),
                self.CLOSED_BATCH)
            if n == 0:
                return
            for r in self._closed[:n]:
                node = self._record_to_node(r)
                self.on_flow_update(node, True)
                self.on_l4_log(node)

    def tick(self, now_ns: int | None = None) -> None:
        now = now_ns if now_ns is not None else time.time_ns()
        self._lib.df_fm_tick(self._fm, now)
        self._drain_closed()
        # active-flow metering snapshot (cumulative counters; the collector
        # diffs against its seen_flows cache)
        active = self.active_flows
        buf = self._closed
        if active > self.CLOSED_BATCH:
            buf = np.zeros(active + 64, dtype=native.FLOW_RECORD_DTYPE)
        n = self._lib.df_fm_export_active(
            self._fm, buf.ctypes.data_as(ctypes.c_void_p), len(buf))
        for r in buf[:n]:
            fid = int(r["flow_id"])
            shadow = self._l7fm.flows.get(fid)
            node = self._active_node(r, shadow)
            self.on_flow_update(node, False)
        # slow-path flows tick through the embedded map (flow_id-keyed L7
        # shadow nodes created inline by _process_l7 are excluded: ints
        # never time out — the native map owns their lifecycle)
        self._tick_slow_path(now)

    def _active_node(self, r, shadow) -> FlowNode:
        """Metering view of an active flow (no shadow mutation)."""
        node = FlowNode(
            flow_id=int(r["flow_id"]),
            ip_src=int(r["ip_src"]).to_bytes(4, "big"),
            ip_dst=int(r["ip_dst"]).to_bytes(4, "big"),
            port_src=int(r["port_src"]), port_dst=int(r["port_dst"]),
            protocol=int(r["protocol"]), start_ns=int(r["start_ns"]),
            tunnel_type=int(r["tunnel_type"]),
            tunnel_id=int(r["tunnel_id"]))
        node.end_ns = int(r["end_ns"])
        node.tx = DirectionStats(
            packets=int(r["tx_packets"]), bytes=int(r["tx_bytes"]),
            retrans=int(r["tx_retrans"]),
            zero_window=int(r["tx_zero_window"]))
        node.rx = DirectionStats(
            packets=int(r["rx_packets"]), bytes=int(r["rx_bytes"]),
            retrans=int(r["rx_retrans"]),
            zero_window=int(r["rx_zero_window"]))
        node.syn_count = int(r["syn_count"])
        node.synack_count = int(r["synack_count"])
        node.rtt_us = int(r["rtt_us"])
        if shadow is not None:
            node.l7_protocol = shadow.l7_protocol
            node.l7_request = shadow.l7_request
            node.l7_response = shadow.l7_response
            node.art_sum_us = shadow.art_sum_us
            node.art_count = shadow.art_count
        return node

    def _tick_slow_path(self, now_ns: int) -> None:
        """Tick only tuple-keyed (slow-path) flows in the embedded map."""
        tuple_keys = [k for k in self._l7fm.flows if isinstance(k, tuple)]
        if not tuple_keys:
            return
        # temporarily restrict the embedded map's view
        shadows = {k: v for k, v in self._l7fm.flows.items()
                   if not isinstance(k, tuple)}
        for k in shadows:
            del self._l7fm.flows[k]
        try:
            self._l7fm.tick(now_ns)
        finally:
            self._l7fm.flows.update(shadows)

    def flush_all(self) -> None:
        self._lib.df_fm_flush_all(self._fm)
        self._drain_closed()
        # remaining shadows correspond to flows already closed natively
        # (drained above); anything left is slow-path — flush it
        self._l7fm.flush_all()

    # -- TPACKET_V3 ring ------------------------------------------------------

    def ring_rx(self, ring: "NativeRing", timeout_ms: int = 100,
                max_blocks: int = 0) -> int:
        """Consume ready ring blocks straight into the native map; only L7
        payload copies, slow-path frame copies (v6/vlan), and close records
        cross into Python.

        NOT thread-safe against tick()/flush_all()/inject_batch() on the
        same map — callers sharing the map across threads must serialize
        (the Dispatcher lock does this for the agent)."""
        consumed = int(self._lib.df_ring_rx_batch(
            ring._h, self._fm, timeout_ms,
            self._l7_buf.ctypes.data_as(ctypes.c_void_p), self.L7_BUF_CAP,
            self._l7_evs.ctypes.data_as(ctypes.c_void_p), self.L7_EV_CAP,
            ctypes.byref(self._n_l7), max_blocks,
            1 if ring.skip_outgoing else 0,
            self._slow_buf.ctypes.data_as(ctypes.c_void_p),
            len(self._slow_buf),
            self._slow_evs.ctypes.data_as(ctypes.c_void_p),
            len(self._slow_evs), ctypes.byref(self._n_slow)))
        if self._n_l7.value:
            self._process_l7(self._n_l7.value)
        for ev in self._slow_evs[:self._n_slow.value]:
            off, ln = int(ev["off"]), int(ev["len"])
            mp = decode_ethernet(self._slow_buf[off:off + ln].tobytes(),
                                 timestamp_ns=int(ev["ts_ns"]))
            if mp is not None:
                self._l7fm.inject(mp)
        self._drain_closed()
        return consumed


class NativeRing:
    """TPACKET_V3 mmap RX ring (reference: recv_engine af_packet)."""

    def __init__(self, interface: str = "", block_size: int = 1 << 20,
                 block_nr: int = 64) -> None:
        lib = native.load()
        if lib is None:
            raise RuntimeError("libdfnative.so unavailable")
        self._lib = lib
        err = ctypes.c_int32(0)
        self._h = lib.df_ring_open(interface.encode(), block_size, block_nr,
                                   ctypes.byref(err))
        if not self._h:
            import os
            raise OSError(err.value, os.strerror(err.value),
                          f"ring open on {interface or 'all'!r}")
        # lo delivers every frame twice (in + out copies)
        self.skip_outgoing = interface == "lo"

    def drops(self) -> int:
        return int(self._lib.df_ring_drops(self._h))

    def promisc(self, interface: str, on: bool = True) -> bool:
        """Promiscuous mode (mirror/SPAN ports see other hosts' frames)."""
        return self._lib.df_ring_promisc(
            self._h, interface.encode(), 1 if on else 0) == 0

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.df_ring_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
