"""In-process OnCPU continuous profiler: periodic stack sampling.

Reference analog: the eBPF perf_event profiler chain
(agent/src/ebpf/kernel/perf_profiler.bpf.c:688 oncpu sampling,
user/profile/profile_common.c aggregation, stringifier.c:696 folded stacks).
This is the in-process flavor: a sampler thread walks every Python thread's
frame stack at `hz`, folds frames into "mod.func" strings, aggregates
(thread, stack) -> count over an emit window, and hands batches to a sink.
Double-buffered aggregation mirrors the profiler_output_a/b A/B-swap design.

The out-of-process native sampler (perf_event_open) is a separate component;
this one covers the primary TPU use case — profiling the JAX workload from
inside (zero-code via `deepflow-run`).
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field


@dataclass
class ProfileSample:
    timestamp_ns: int
    pid: int
    tid: int
    thread_name: str
    stack: str          # folded: root;...;leaf
    count: int
    value_us: int       # count * sample period
    event_type: str = "on-cpu"
    profiler: str = "pysampler"


@dataclass
class SamplerStats:
    samples: int = 0
    emits: int = 0
    overruns: int = 0   # sampling tick took longer than the period
    last_emit_stacks: int = 0


# leaf functions that mean the thread is parked, not running: count the
# sample as off-cpu (blocked time), the reference's OffCPU profiler analog
_BLOCKING_LEAVES = frozenset({
    "wait", "get", "put", "sleep", "select", "poll", "epoll", "kqueue",
    "accept", "recv", "recvfrom", "recv_into", "read", "readinto", "readline",
    "acquire", "join", "wait_for", "settimeout", "flush", "dowait",
    "_recv_bytes", "poll_once", "getaddrinfo", "connect", "sendall",
})


def classify_sample(stack: str) -> str:
    """on-cpu vs off-cpu by leaf frame (mod.func -> func)."""
    leaf = stack.rsplit(";", 1)[-1]
    func = leaf.rsplit(".", 1)[-1]
    return "off-cpu" if func in _BLOCKING_LEAVES else "on-cpu"


def fold_frame(frame) -> str:
    code = frame.f_code
    mod = frame.f_globals.get("__name__", "?")
    return f"{mod}.{code.co_name}"


def fold_stack(frame, max_depth: int = 128) -> str:
    """Walk frame -> outermost, emit root;...;leaf."""
    frames = []
    depth = 0
    while frame is not None and depth < max_depth:
        frames.append(fold_frame(frame))
        frame = frame.f_back
        depth += 1
    return ";".join(reversed(frames))


class OnCpuSampler:
    """99 Hz (default) Python-stack sampler with windowed aggregation."""

    def __init__(self, sink, hz: float = 99.0, emit_interval_s: float = 1.0,
                 process_name: str = "", app_service: str = "",
                 include_agent_threads: bool = False) -> None:
        self.include_agent_threads = include_agent_threads
        self.sink = sink
        self.period_s = 1.0 / hz
        self.period_us = int(1_000_000 / hz)
        self.emit_interval_s = emit_interval_s
        self.process_name = process_name
        self.app_service = app_service
        self.stats = SamplerStats()
        self._agg: dict[tuple[int, str], int] = {}
        self._thread_names: dict[int, str] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        import os
        self.pid = os.getpid()

    def start(self) -> "OnCpuSampler":
        self._thread = threading.Thread(
            target=self._run, name="df-oncpu-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self._emit()  # flush the tail window

    def _run(self) -> None:
        my_tid = threading.get_ident()
        next_tick = time.monotonic()
        next_emit = next_tick + self.emit_interval_s
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_tick:
                self._sample(my_tid)
                next_tick += self.period_s
                if now - next_tick > self.period_s:
                    # fell behind (GIL contention): skip missed ticks
                    self.stats.overruns += 1
                    next_tick = now + self.period_s
                if now >= next_emit:
                    self._emit()
                    next_emit = now + self.emit_interval_s
            time.sleep(max(0.0, min(next_tick - time.monotonic(),
                                    self.period_s)))

    def _sample(self, my_tid: int) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            if tid == my_tid:
                continue
            name = names.get(tid, str(tid))
            if not self.include_agent_threads and name.startswith("df-"):
                continue  # never profile our own plumbing by default
            stack = fold_stack(frame)
            if not stack:
                continue
            key = (tid, stack)
            self._agg[key] = self._agg.get(key, 0) + 1
            self._thread_names[tid] = name
            self.stats.samples += 1

    def _emit(self) -> None:
        if not self._agg:
            return
        agg, self._agg = self._agg, {}  # A/B swap
        ts = time.time_ns()
        batch = [
            ProfileSample(
                timestamp_ns=ts, pid=self.pid, tid=tid,
                thread_name=self._thread_names.get(tid, str(tid)),
                stack=stack, count=n, value_us=n * self.period_us,
                event_type=classify_sample(stack))
            for (tid, stack), n in agg.items()
        ]
        self.stats.emits += 1
        self.stats.last_emit_stacks = len(batch)
        try:
            self.sink(batch)
        except Exception:
            pass  # a failing sink must never kill the sampler
