"""MetaPacket: decoded packet header view + capture sources.

Reference analog: agent/src/common/meta_packet.rs (MetaPacket) and
agent/src/dispatcher/recv_engine (capture backends). Sources here:
pcap files (own reader, classic libpcap format) and synthetic builders —
the reference's own golden-test strategy (agent/resources/test/*.pcap
replayed through FlowMap, SURVEY.md §4).
"""

from __future__ import annotations

import socket
import struct
import time
from dataclasses import dataclass, field
from enum import IntFlag


class TcpFlags(IntFlag):
    FIN = 0x01
    SYN = 0x02
    RST = 0x04
    PSH = 0x08
    ACK = 0x10
    URG = 0x20


@dataclass
class MetaPacket:
    timestamp_ns: int = 0
    ip_src: bytes = b""
    ip_dst: bytes = b""
    port_src: int = 0
    port_dst: int = 0
    protocol: int = 0            # pb.L4Protocol values: 1 tcp, 2 udp, 3 icmp
    tcp_flags: int = 0
    seq: int = 0
    ack: int = 0
    window: int = 0
    payload: bytes = b""
    packet_len: int = 0          # on-wire length
    tap_port: int = 0
    # uprobe-source extras (sslprobe): thread-scoped chain id + tid
    syscall_trace_id: int = 0
    tid: int = 0
    # tunnel decapsulation (reference: common/decapsulate.rs): when an
    # outer VXLAN/GENEVE/GRE/ERSPAN layer was stripped, the 5-tuple above
    # is the INNER packet's and these record the tunnel
    tunnel_type: int = 0         # 0 none, 1 vxlan, 2 geneve, 3 erspan,
    tunnel_id: int = 0           # 4 gre-teb; VNI / session id / GRE key

    @property
    def key(self) -> tuple:
        # tunnel identity is part of flow identity: overlapping tenant IP
        # space across VNIs must not merge into one flow
        return (self.ip_src, self.ip_dst, self.port_src, self.port_dst,
                self.protocol, self.tunnel_type, self.tunnel_id)

    @property
    def reverse_key(self) -> tuple:
        return (self.ip_dst, self.ip_src, self.port_dst, self.port_src,
                self.protocol, self.tunnel_type, self.tunnel_id)


ETH_IPV4 = 0x0800
ETH_IPV6 = 0x86DD


def decode_ethernet(frame: bytes, timestamp_ns: int = 0,
                    tap_port: int = 0,
                    _depth: int = 0) -> MetaPacket | None:
    """Ethernet II -> IPv4/IPv6 -> TCP/UDP/ICMP header decode, with
    VXLAN/GENEVE/GRE/ERSPAN decapsulation (one nesting level, matching
    the native fast path)."""
    if len(frame) < 14:
        return None
    eth_type = struct.unpack_from(">H", frame, 12)[0]
    off = 14
    if eth_type == 0x8100 and len(frame) >= 18:  # 802.1Q VLAN
        eth_type = struct.unpack_from(">H", frame, 16)[0]
        off = 18
    if eth_type == ETH_IPV4:
        return _decode_ipv4(frame, off, timestamp_ns, tap_port, len(frame),
                            _depth)
    if eth_type == ETH_IPV6:
        return _decode_ipv6(frame, off, timestamp_ns, tap_port, len(frame))
    return None


def _decap(frame: bytes, inner_off: int, ttype: int, tid: int, ts: int,
           tap: int, depth: int) -> MetaPacket | None:
    if depth >= 2:
        return None
    inner = decode_ethernet(frame[inner_off:], ts, tap, _depth=depth + 1)
    if inner is None:
        return None
    if inner.tunnel_type == 0:  # innermost tunnel wins the stamp
        inner.tunnel_type = ttype
        inner.tunnel_id = tid
    # byte metrics count WIRE bytes: the outer frame's length, including
    # the overlay headers (matches the native fast path)
    inner.packet_len = len(frame)
    return inner


def _try_decap_udp(frame: bytes, pay: int, end: int, dport: int, ts: int,
                   tap: int, depth: int) -> MetaPacket | None:
    # VXLAN (RFC 7348): 8-byte header, I-flag validates the VNI
    if dport == 4789 and end >= pay + 8 and frame[pay] & 0x08:
        vni = int.from_bytes(frame[pay + 4:pay + 7], "big")
        return _decap(frame, pay + 8, 1, vni, ts, tap, depth)
    # GENEVE (RFC 8926): options + inner proto Transparent Eth Bridging
    if dport == 6081 and end >= pay + 8:
        optlen = (frame[pay] & 0x3F) * 4
        inner_proto = struct.unpack_from(">H", frame, pay + 2)[0]
        vni = int.from_bytes(frame[pay + 4:pay + 7], "big")
        if inner_proto == 0x6558:
            return _decap(frame, pay + 8 + optlen, 2, vni, ts, tap, depth)
    return None


def _try_decap_gre(frame: bytes, l4: int, end: int, ts: int, tap: int,
                   depth: int) -> MetaPacket | None:
    if end < l4 + 4:
        return None
    flags, gre_proto = struct.unpack_from(">HH", frame, l4)
    gh = l4 + 4
    if flags & 0x8000:
        gh += 4  # checksum + reserved
    key = 0
    if flags & 0x2000:
        if end < gh + 4:
            return None
        key = struct.unpack_from(">I", frame, gh)[0]
        gh += 4
    has_seq = bool(flags & 0x1000)
    if has_seq:
        gh += 4
    if gre_proto == 0x88BE:  # ERSPAN: II has an 8B header (seq bit), I none
        sess = (struct.unpack_from(">H", frame, gh + 2)[0] & 0x03FF
                if has_seq and end >= gh + 4 else 0)
        return _decap(frame, gh + (8 if has_seq else 0), 3, sess, ts, tap,
                      depth)
    if gre_proto == 0x22EB:  # ERSPAN III: 12B header
        sess = (struct.unpack_from(">H", frame, gh + 2)[0] & 0x03FF
                if end >= gh + 4 else 0)
        return _decap(frame, gh + 12, 3, sess, ts, tap, depth)
    if gre_proto == 0x6558:  # transparent ethernet bridging
        return _decap(frame, gh, 4, key, ts, tap, depth)
    return None


def _decode_ipv4(frame: bytes, off: int, ts: int, tap: int,
                 wire_len: int, depth: int = 0) -> MetaPacket | None:
    if len(frame) < off + 20:
        return None
    ver_ihl = frame[off]
    ihl = (ver_ihl & 0x0F) * 4
    total_len = struct.unpack_from(">H", frame, off + 2)[0]
    proto = frame[off + 9]
    ip_src = frame[off + 12:off + 16]
    ip_dst = frame[off + 16:off + 20]
    l4_off = off + ihl
    end = min(len(frame), off + total_len)
    if proto == 47:  # GRE / ERSPAN
        inner = _try_decap_gre(frame, l4_off, end, ts, tap, depth)
        if inner is not None:
            return inner
        return None  # plain GRE payloads are not flow material
    if proto == 17 and end >= l4_off + 8:
        dport = struct.unpack_from(">H", frame, l4_off + 2)[0]
        inner = _try_decap_udp(frame, l4_off + 8, end, dport, ts, tap,
                               depth)
        if inner is not None:
            return inner
    return _decode_l4(frame, l4_off, end, proto, ip_src, ip_dst, ts, tap,
                      wire_len)


def _decode_ipv6(frame: bytes, off: int, ts: int, tap: int,
                 wire_len: int) -> MetaPacket | None:
    if len(frame) < off + 40:
        return None
    next_header = frame[off + 6]
    payload_len = struct.unpack_from(">H", frame, off + 4)[0]
    ip_src = frame[off + 8:off + 24]
    ip_dst = frame[off + 24:off + 40]
    l4_off = off + 40
    end = min(len(frame), l4_off + payload_len)
    return _decode_l4(frame, l4_off, end, next_header, ip_src, ip_dst, ts,
                      tap, wire_len)


def _decode_l4(frame: bytes, off: int, end: int, proto: int, ip_src: bytes,
               ip_dst: bytes, ts: int, tap: int,
               wire_len: int) -> MetaPacket | None:
    p = MetaPacket(timestamp_ns=ts, ip_src=ip_src, ip_dst=ip_dst,
                   tap_port=tap, packet_len=wire_len)
    if proto == 6:  # TCP
        if end < off + 20:
            return None
        (p.port_src, p.port_dst, p.seq, p.ack) = struct.unpack_from(
            ">HHII", frame, off)
        data_off = (frame[off + 12] >> 4) * 4
        p.tcp_flags = frame[off + 13]
        p.window = struct.unpack_from(">H", frame, off + 14)[0]
        p.protocol = 1
        p.payload = frame[off + data_off:end]
        return p
    if proto == 17:  # UDP
        if end < off + 8:
            return None
        p.port_src, p.port_dst = struct.unpack_from(">HH", frame, off)
        p.protocol = 2
        p.payload = frame[off + 8:end]
        return p
    if proto in (1, 58):  # ICMP / ICMPv6
        p.protocol = 3
        p.payload = frame[off:end]
        return p
    return None


# -- pcap file source (classic format, both endiannesses) --------------------

PCAP_MAGIC_US_LE = 0xA1B2C3D4
PCAP_MAGIC_NS_LE = 0xA1B23C4D


def read_pcap_records(path: str) -> list[tuple[bytes, int, int]]:
    """Raw pcap records: (frame_bytes, ts_ns, orig_len) — no decoding."""
    raw: list[tuple[bytes, int, int]] = []
    with open(path, "rb") as f:
        hdr = f.read(24)
        if len(hdr) < 24:
            raise ValueError(f"not a pcap file (too short): {path}")
        magic = struct.unpack_from("<I", hdr, 0)[0]
        if magic == PCAP_MAGIC_US_LE:
            endian, scale = "<", 1000
        elif magic == PCAP_MAGIC_NS_LE:
            endian, scale = "<", 1
        elif struct.unpack_from(">I", hdr, 0)[0] == PCAP_MAGIC_US_LE:
            endian, scale = ">", 1000
        elif struct.unpack_from(">I", hdr, 0)[0] == PCAP_MAGIC_NS_LE:
            endian, scale = ">", 1
        else:
            raise ValueError(f"not a pcap file: {path}")
        while True:
            rec = f.read(16)
            if len(rec) < 16:
                break
            ts_sec, ts_frac, incl, orig = struct.unpack(endian + "IIII", rec)
            data = f.read(incl)
            if len(data) < incl:
                break
            ts_ns = ts_sec * 1_000_000_000 + ts_frac * scale
            raw.append((data, ts_ns, orig))
    return raw


def read_pcap(path: str, use_native: bool = True) -> list[MetaPacket]:
    """Own pcap reader — no libpcap dependency. Returns decoded packets.

    When libdfnative.so is available, IPv4 frames decode through the C++
    batch fast path; v6/vlan/other frames fall back to the Python decoder.
    """
    raw = read_pcap_records(path)
    out: list[MetaPacket] = []
    if use_native:
        try:
            from deepflow_tpu.native import decode_eth_batch
        except Exception:
            decode_eth_batch = None
        if decode_eth_batch is not None:
            # chunk the native batches so a large capture never holds a
            # second full copy of itself in the join buffer
            for lo in range(0, len(raw), 65536):
                chunk = raw[lo:lo + 65536]
                decoded = decode_eth_batch([r[0] for r in chunk])
                if decoded is None:
                    out = []  # native unavailable mid-way: full Python pass
                    break
                _decode_chunk(chunk, decoded, out)
            else:
                return out
    for data, ts_ns, orig in raw:
        mp = decode_ethernet(data, timestamp_ns=ts_ns)
        if mp is not None:
            mp.packet_len = orig
            out.append(mp)
    return out


def _decode_chunk(raw, decoded, out: list) -> None:
    """Materialize MetaPackets from one native decode batch."""
    recs, ok = decoded
    # column-wise extraction once (structured-scalar access is slow)
    cols = {name: recs[name].tolist() for name in
            ("ip_src", "ip_dst", "port_src", "port_dst", "protocol",
             "tcp_flags", "window", "seq", "ack", "payload_off",
             "payload_len", "tunnel_type", "tunnel_id")}
    ok_l = ok.tolist()
    for i, (data, ts_ns, orig) in enumerate(raw):
        if ok_l[i]:
            po = cols["payload_off"][i]
            pl = cols["payload_len"][i]
            out.append(MetaPacket(
                timestamp_ns=ts_ns,
                ip_src=cols["ip_src"][i].to_bytes(4, "big"),
                ip_dst=cols["ip_dst"][i].to_bytes(4, "big"),
                port_src=cols["port_src"][i],
                port_dst=cols["port_dst"][i],
                protocol=cols["protocol"][i],
                tcp_flags=cols["tcp_flags"][i], seq=cols["seq"][i],
                ack=cols["ack"][i], window=cols["window"][i],
                payload=data[po:po + pl], packet_len=orig,
                tunnel_type=cols["tunnel_type"][i],
                tunnel_id=cols["tunnel_id"][i]))
        else:  # v6 / vlan / odd frames: Python slow path
            mp = decode_ethernet(data, timestamp_ns=ts_ns)
            if mp is not None:
                mp.packet_len = orig
                out.append(mp)


# -- synthetic builders (tests + fake traffic) --------------------------------

def build_tcp(ip_src: str, ip_dst: str, port_src: int, port_dst: int,
              flags: int = TcpFlags.ACK, payload: bytes = b"",
              seq: int = 0, ack: int = 0, timestamp_ns: int | None = None,
              window: int = 65535) -> MetaPacket:
    return MetaPacket(
        timestamp_ns=time.time_ns() if timestamp_ns is None else timestamp_ns,
        ip_src=socket.inet_aton(ip_src), ip_dst=socket.inet_aton(ip_dst),
        port_src=port_src, port_dst=port_dst, protocol=1,
        tcp_flags=int(flags), seq=seq, ack=ack, window=window,
        payload=payload, packet_len=54 + len(payload))


def build_udp(ip_src: str, ip_dst: str, port_src: int, port_dst: int,
              payload: bytes = b"",
              timestamp_ns: int | None = None) -> MetaPacket:
    return MetaPacket(
        timestamp_ns=time.time_ns() if timestamp_ns is None else timestamp_ns,
        ip_src=socket.inet_aton(ip_src), ip_dst=socket.inet_aton(ip_dst),
        port_src=port_src, port_dst=port_dst, protocol=2,
        payload=payload, packet_len=42 + len(payload))


def encode_tcp_frame(ip_src: str, ip_dst: str, port_src: int, port_dst: int,
                     flags: int = TcpFlags.ACK, payload: bytes = b"",
                     seq: int = 0, ack: int = 0,
                     window: int = 65535) -> bytes:
    """Raw Ethernet/IPv4/TCP frame bytes (native-pipeline tests + bench)."""
    total = 20 + 20 + len(payload)
    ip = struct.pack(">BBHHHBBH4s4s", 0x45, 0, total, 0, 0x4000, 64, 6, 0,
                     socket.inet_aton(ip_src), socket.inet_aton(ip_dst))
    tcp = struct.pack(">HHIIBBHHH", port_src, port_dst, seq & 0xFFFFFFFF,
                      ack & 0xFFFFFFFF, 5 << 4, int(flags), window, 0, 0)
    return b"\x00" * 12 + b"\x08\x00" + ip + tcp + payload


def encode_udp_frame(ip_src: str, ip_dst: str, port_src: int, port_dst: int,
                     payload: bytes = b"") -> bytes:
    total = 20 + 8 + len(payload)
    ip = struct.pack(">BBHHHBBH4s4s", 0x45, 0, total, 0, 0x4000, 64, 17, 0,
                     socket.inet_aton(ip_src), socket.inet_aton(ip_dst))
    udp = struct.pack(">HHHH", port_src, port_dst, 8 + len(payload), 0)
    return b"\x00" * 12 + b"\x08\x00" + ip + udp + payload
