"""Collector: flows + L7 records -> per-second metric Documents.

Reference analog: agent/src/collector/quadruple_generator.rs (1s/1m stash)
and collector.rs (Document assembly). Aggregation keys mirror the
reference's quadruple: (ip_src, ip_dst, server_port, protocol) for network
meters, plus l7_protocol for application meters.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from deepflow_tpu.proto import pb


@dataclass
class _NetStash:
    packet_tx: int = 0
    packet_rx: int = 0
    byte_tx: int = 0
    byte_rx: int = 0
    flow_count: int = 0
    new_flow: int = 0
    closed_flow: int = 0
    rtt_sum_us: int = 0
    rtt_count: int = 0
    retrans: int = 0
    syn: int = 0
    synack: int = 0
    # deltas need previous counters per live flow
    seen_flows: dict = field(default_factory=dict)


@dataclass
class _AppStash:
    request: int = 0
    response: int = 0
    rrt_sum_us: int = 0
    rrt_count: int = 0
    rrt_max_us: int = 0
    error_client: int = 0
    error_server: int = 0
    timeout: int = 0


class QuadrupleGenerator:
    def __init__(self, emit, interval_s: int = 1) -> None:
        """emit(list[pb.Document]) is called at each flush boundary."""
        self.emit = emit
        self.interval_s = interval_s
        self._net: dict[tuple, _NetStash] = {}
        self._app: dict[tuple, _AppStash] = {}
        self._last_flush_s = 0

    # -- feed -----------------------------------------------------------------

    def add_flow(self, node, closed: bool) -> None:
        key = (node.ip_src, node.ip_dst, node.port_dst, node.protocol)
        st = self._net.setdefault(key, _NetStash())
        prev = st.seen_flows.get(node.flow_id)
        if prev is None:
            st.new_flow += 1
            prev = (0, 0, 0, 0, 0, 0, 0)
        ptx, prx, btx, brx, rt, sy, sa = prev
        st.packet_tx += node.tx.packets - ptx
        st.packet_rx += node.rx.packets - prx
        st.byte_tx += node.tx.bytes - btx
        st.byte_rx += node.rx.bytes - brx
        st.retrans += (node.tx.retrans + node.rx.retrans) - rt
        st.syn += node.syn_count - sy
        st.synack += node.synack_count - sa
        if closed:
            st.closed_flow += 1
            st.seen_flows.pop(node.flow_id, None)
            if node.rtt_us:
                st.rtt_sum_us += node.rtt_us
                st.rtt_count += 1
        else:
            st.seen_flows[node.flow_id] = (
                node.tx.packets, node.rx.packets, node.tx.bytes,
                node.rx.bytes, node.tx.retrans + node.rx.retrans,
                node.syn_count, node.synack_count)
        st.flow_count = max(st.flow_count, len(st.seen_flows) + st.closed_flow)

    def add_l7(self, record) -> None:
        node = record.flow
        key = (node.ip_src, node.ip_dst, node.port_dst, node.l7_protocol)
        st = self._app.setdefault(key, _AppStash())
        if record.request is not None:
            st.request += 1
        if record.response is not None:
            st.response += 1
            status = record.response.response_status
            if status == 2:
                st.error_client += 1
            elif status == 3:
                st.error_server += 1
            elif status == 4:
                st.timeout += 1
        if record.request is not None and record.response is not None:
            rrt = max(0, (record.end_ns - record.start_ns) // 1000)
            st.rrt_sum_us += rrt
            st.rrt_count += 1
            st.rrt_max_us = max(st.rrt_max_us, rrt)
        elif record.request is not None and record.response is None \
                and not record.request.session_less:
            st.timeout += 1  # fire-and-forget messages are not timeouts

    # -- flush ----------------------------------------------------------------

    def flush(self, now_s: int | None = None) -> list:
        now = now_s if now_s is not None else int(time.time())
        docs = []
        for (ip_src, ip_dst, port, proto), st in self._net.items():
            if not (st.packet_tx or st.packet_rx or st.new_flow
                    or st.closed_flow):
                continue
            d = pb.Document()
            d.timestamp_s = now
            d.interval_s = self.interval_s
            d.tag.ip_src = ip_src
            d.tag.ip_dst = ip_dst
            d.tag.port = port
            d.tag.proto = proto
            m = d.flow_meter
            m.packet_tx = st.packet_tx
            m.packet_rx = st.packet_rx
            m.byte_tx = st.byte_tx
            m.byte_rx = st.byte_rx
            m.flow_count = st.flow_count
            m.new_flow = st.new_flow
            m.closed_flow = st.closed_flow
            m.rtt_sum_us = st.rtt_sum_us
            m.rtt_count = st.rtt_count
            m.retrans = st.retrans
            m.syn_count = st.syn
            m.synack_count = st.synack
            docs.append(d)
        for (ip_src, ip_dst, port, l7), st in self._app.items():
            if not (st.request or st.response):
                continue
            d = pb.Document()
            d.timestamp_s = now
            d.interval_s = self.interval_s
            d.tag.ip_src = ip_src
            d.tag.ip_dst = ip_dst
            d.tag.port = port
            d.tag.l7_protocol = l7
            m = d.app_meter
            m.request = st.request
            m.response = st.response
            m.rrt_sum_us = st.rrt_sum_us
            m.rrt_count = st.rrt_count
            m.rrt_max_us = st.rrt_max_us
            m.error_client = st.error_client
            m.error_server = st.error_server
            m.timeout = st.timeout
            docs.append(d)
        # carry live-flow baselines into the next window
        kept: dict[tuple, _NetStash] = {}
        for key, st in self._net.items():
            if st.seen_flows:
                ns = _NetStash()
                ns.seen_flows = st.seen_flows
                kept[key] = ns
        self._net = kept
        self._app = {}
        if docs:
            self.emit(docs)
        return docs
