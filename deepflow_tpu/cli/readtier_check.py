"""readtier-check: e2e run proving the disaggregated read tier works.

Spins up one in-process ingest shard (tiered storage + object-store
publishing) and four stateless querier replicas as REAL subprocesses —
each with its own segment cache, bucket cache, and gossip membership —
then fails (exit 1) if:

  * the replicas never adopt the published manifest,
  * any replica's answer differs from the ingest node's own (the
    byte-identity contract: sealed history from the replica's adopted
    segments + live rows from the ingest shard, stitched exactly once),
  * the distributed partial-aggregate cache does not serve warm buckets
    cluster-wide (every replica after the first must answer the warm
    query set from fetched slices, with ZERO new bucket scans — the
    compute-once ledger),
  * the cache ledgers do not conserve (buckets served by warm replicas
    != buckets fetched by cold ones, or any fetch/remap error),
  * a 4-replica query storm does not scale read throughput ~linearly
    (>= 3x one replica; enforced only when the host has the cores to
    show it — same relative escape hatch as bench.py's perf guards),
  * the ingest write path p99 moves more than 10% under the storm
    (reads are disaggregated: a query storm must not touch ingest).

Wired as `make readtier-check`.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

TBL = "flow_log.l7_flow_log"
BASE_NS = 1_754_000_000_000_000_000
N_SEALED = 3000
N_REPLICAS = 4

# the warm storm set: bucketable aggregates (no PERCENTILE/LAST)
STORM_SQLS = [
    "SELECT app_service, Count(*) AS n, Sum(response_duration) AS s "
    "FROM l7_flow_log GROUP BY app_service ORDER BY app_service",
    "SELECT endpoint, Count(*) AS n, Max(response_duration) AS m "
    "FROM l7_flow_log GROUP BY endpoint ORDER BY endpoint",
    "SELECT request_type, Min(response_duration) AS mn, Count(*) AS n "
    "FROM l7_flow_log GROUP BY request_type ORDER BY request_type",
]
IDENTITY_SQLS = STORM_SQLS + [
    "SELECT Count(DISTINCT endpoint) AS d, Count(*) AS n "
    "FROM l7_flow_log",
    "SELECT time, app_service, endpoint FROM l7_flow_log "
    "WHERE response_code = 200 ORDER BY time DESC LIMIT 9",
]


def _fail(msg: str) -> None:
    print(f"readtier-check: FAIL: {msg}")
    sys.exit(1)


def _rows(n0: int, n: int) -> list[dict]:
    out = []
    for i in range(n0, n0 + n):
        out.append({
            "time": BASE_NS + i * 60_000_000,   # ~3 min span: 4 buckets
            "flow_id": 100 + i,
            "app_service": ("svc-a", "svc-b", "svc-c")[i % 3],
            "endpoint": f"/api/{i % 24}",
            "request_type": "GET" if i % 2 == 0 else "POST",
            "response_code": (200, 404, 500)[i % 3],
            "response_duration": 10_000 + (i % 97) * 150,
        })
    return out


def _post(port: int, path: str, body: dict, timeout: float = 15.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(port: int, path: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def seed_ingest(root: str, n_sealed: int = N_SEALED, n_live: int = 200):
    """One in-process ingest shard: seal + publish n_sealed rows, keep
    n_live in the stripes. Returns the started Server."""
    from deepflow_tpu.server import Server
    srv = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                 sync_port=0, shard_id=1, cluster_advertise="",
                 storage=True,
                 data_dir=os.path.join(root, "ingest"),
                 objstore=os.path.join(root, "obj"),
                 publish_interval_s=300.0).start()
    t = srv.db.table(TBL)
    t.append_rows(_rows(0, n_sealed // 2))
    srv.db.flush_to_tier()
    t.append_rows(_rows(n_sealed // 2, n_sealed - n_sealed // 2))
    srv.db.flush_to_tier()
    if srv.publisher.maybe_publish(srv.db.tier_store) is None:
        raise RuntimeError("publish was a no-op on a fresh tier")
    if n_live:
        t.append_rows(_rows(n_sealed, n_live))
    return srv


def spawn_querier(root: str, idx: int, seed_addr: str,
                  env=None) -> tuple:
    """One stateless replica as a real subprocess. Returns
    (Popen, query_port)."""
    port = _free_port()
    cmd = [sys.executable, "-m", "deepflow_tpu.server.server",
           "--host", "127.0.0.1", "--query-host", "127.0.0.1",
           "--ingest-port", "0", "--sync-port", "0",
           "--query-port", str(port),
           "--shard-id", str(8 + idx), "--role", "querier",
           "--objstore", os.path.join(root, "obj"),
           "--data-dir", os.path.join(root, f"segcache-{idx}"),
           "--cluster-seed", seed_addr,
           "--readtier-poll-s", "0.5", "--no-controller"]
    proc = subprocess.Popen(cmd, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    return proc, port


def wait_adopted(ports: list[int], rows: int,
                 timeout_s: float = 60.0) -> None:
    deadline = time.monotonic() + timeout_s
    pending = list(ports)
    while pending and time.monotonic() < deadline:
        still = []
        for p in pending:
            try:
                h = _get(p, "/v1/health", timeout=2.0)
                if h["readtier"]["tables"][TBL]["rows"] == rows:
                    continue
            except Exception:
                pass
            still.append(p)
        pending = still
        if pending:
            time.sleep(0.25)
    if pending:
        raise RuntimeError(f"replicas on ports {pending} never adopted "
                           f"{rows} published rows")


def storm(ports: list[int], sqls: list[str], duration_s: float,
          threads_per_port: int = 4) -> float:
    """Closed-loop query storm: round-robin sqls against each port.
    Returns aggregate queries/second."""
    stop = time.monotonic() + duration_s
    counts = [0] * (len(ports) * threads_per_port)
    errs: list = []

    def _client(slot: int, port: int) -> None:
        i = 0
        while time.monotonic() < stop:
            try:
                _post(port, "/v1/query",
                      {"sql": sqls[i % len(sqls)], "db": "flow_log"})
            except Exception as e:
                errs.append((port, e))
                return
            counts[slot] += 1
            i += 1

    threads = []
    slot = 0
    for port in ports:
        for _ in range(threads_per_port):
            th = threading.Thread(target=_client, args=(slot, port))
            th.start()
            threads.append(th)
            slot += 1
    for th in threads:
        th.join()
    if errs:
        raise RuntimeError(f"storm client errors: {errs[:3]}")
    return sum(counts) / duration_s


class _IngestWriter:
    """Fixed-rate writer measuring the ingest append path latency."""

    def __init__(self, srv, batch: int = 100,
                 interval_s: float = 0.02) -> None:
        self.srv = srv
        self.batch = batch
        self.interval_s = interval_s
        self.samples_ms: list[float] = []
        self._stop = threading.Event()
        self._thread = None
        self._n0 = N_SEALED + 10_000

    def run_for(self, duration_s: float) -> list[float]:
        self.samples_ms = []
        t = self.srv.db.table(TBL)
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            rows = _rows(self._n0, self.batch)
            self._n0 += self.batch
            t0 = time.perf_counter()
            t.append_rows(rows)
            self.samples_ms.append((time.perf_counter() - t0) * 1e3)
            time.sleep(self.interval_s)
        return self.samples_ms

    def start(self) -> "_IngestWriter":
        self._stop.clear()

        def _loop():
            t = self.srv.db.table(TBL)
            while not self._stop.is_set():
                rows = _rows(self._n0, self.batch)
                self._n0 += self.batch
                t0 = time.perf_counter()
                t.append_rows(rows)
                self.samples_ms.append((time.perf_counter() - t0) * 1e3)
                self._stop.wait(self.interval_s)

        self.samples_ms = []
        self._thread = threading.Thread(target=_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> list[float]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return self.samples_ms


def _p99(samples_ms: list[float]) -> float:
    import numpy as np
    return float(np.percentile(np.asarray(samples_ms), 99))


def main() -> int:
    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="readtier-check-")
    procs: list = []
    srv = None
    try:
        srv = seed_ingest(root)
        seed_addr = f"127.0.0.1:{srv.query_port}"
        ports = []
        for i in range(N_REPLICAS):
            proc, port = spawn_querier(root, i, seed_addr)
            procs.append(proc)
            ports.append(port)
        wait_adopted(ports, N_SEALED)
        print(f"readtier-check: {N_REPLICAS} replicas adopted "
              f"{N_SEALED} sealed rows ({seed_addr} live)")

        # -- byte-identity: every replica == the ingest node ------------
        for sql in IDENTITY_SQLS:
            body = {"sql": sql, "db": "flow_log"}
            want = _post(srv.query_port, "/v1/query", body)["result"]
            for p in ports:
                got = _post(p, "/v1/query", body)
                fed = got.get("federation") or {}
                if fed.get("missing_shards"):
                    _fail(f"replica :{p} missing shards: {fed}")
                if got["result"] != want:
                    _fail(f"replica :{p} diverged on {sql!r}:\n"
                          f"  got  {got['result']}\n  want {want}")
        print(f"readtier-check: {len(IDENTITY_SQLS)} queries "
              f"byte-identical across {N_REPLICAS} replicas")

        # -- distributed partial cache: compute once cluster-wide -------
        # Warm ONLY the first replica, wait for the advert to gossip
        # (two heartbeat legs: warm node -> seed -> the rest), then the
        # others must answer from FETCHED slices. A replica that races
        # the gossip computes locally and is warm forever after, so
        # each retry uses a fresh digest (a changed alias) rather than
        # re-asking a question the cold replicas already answered.
        dist_ok = False
        for attempt in range(5):
            sql = (f"SELECT app_service, Count(*) AS warm{attempt}, "
                   "Sum(response_duration) AS s FROM l7_flow_log "
                   "GROUP BY app_service ORDER BY app_service")
            body = {"sql": sql, "db": "flow_log"}
            want = _post(ports[0], "/v1/query", body)["result"]
            time.sleep(4.5 if attempt == 0 else 2.5)
            base = {p: _get(p, "/v1/health")["query_cache"]["dist_hits"]
                    for p in ports[1:]}
            got_all = {p: _post(p, "/v1/query", body)["result"]
                       for p in ports[1:]}
            dist_ok = all(
                _get(p, "/v1/health")["query_cache"]["dist_hits"] > base[p]
                for p in ports[1:])
            for p, got in got_all.items():
                if got != want:
                    _fail(f"replica :{p} fetched-partial answer "
                          f"diverged: {got} != {want}")
            if dist_ok:
                break
        if not dist_ok:
            _fail("warm adverts never propagated: some replica scanned "
                  "locally instead of fetching the advertised partial")
        # now FULLY warm everywhere on the storm set: one more pass must
        # scan nothing anywhere (bucket_misses frozen) — each bucket
        # was computed exactly once cluster-wide
        for sql in STORM_SQLS:
            for p in ports:
                _post(p, "/v1/query", {"sql": sql, "db": "flow_log"})
        before = {p: _get(p, "/v1/health")["query_cache"]["bucket_misses"]
                  for p in ports}
        for sql in STORM_SQLS:
            for p in ports:
                _post(p, "/v1/query", {"sql": sql, "db": "flow_log"})
        for p in ports:
            after = _get(p, "/v1/health")["query_cache"]["bucket_misses"]
            if after != before[p]:
                _fail(f"replica :{p} rescanned {after - before[p]} warm "
                      "buckets (compute-once ledger violated)")
        # conservation: every bucket fetched by a cold replica was
        # served by a warm one, with zero fetch/remap failures
        served = fetched = 0
        for p in ports:
            h = _get(p, "/v1/health")
            pc = h["partial_cache"]
            if pc["fetch_errors"] or pc["remap_failures"]:
                _fail(f"replica :{p} partial-cache errors: {pc}")
            sc = h["readtier"]["segcache"]
            if sc["fetch_errors"]:
                _fail(f"replica :{p} segment fetch errors: {sc}")
            if sc["misses"] == 0:
                _fail(f"replica :{p} never fetched a segment")
            served += pc["served_buckets"]
            fetched += pc["fetched_buckets"]
        if fetched == 0 or served != fetched:
            _fail(f"cache ledger not conserved: served_buckets={served} "
                  f"!= fetched_buckets={fetched}")
        print(f"readtier-check: distributed partial cache conserved "
              f"({fetched} buckets fetched == {served} served, "
              "0 rescans once warm)")

        # -- read scaling + flat ingest p99 under the storm -------------
        writer = _IngestWriter(srv)
        p99_base = _p99(writer.run_for(2.0))
        qps: dict[int, float] = {}
        for n in (1, 2, N_REPLICAS):
            writer.start()
            qps[n] = storm(ports[:n], STORM_SQLS, duration_s=2.5)
            samples = writer.stop()
            if n == N_REPLICAS:
                p99_storm = _p99(samples)
        speedup = qps[N_REPLICAS] / max(qps[1], 1e-9)
        ncores = os.cpu_count() or 1
        line = ", ".join(f"{n}r={qps[n]:.0f}q/s"
                         for n in sorted(qps))
        print(f"readtier-check: storm {line} (speedup "
              f"{speedup:.2f}x, {ncores} cores); ingest append p99 "
              f"{p99_base:.2f}ms -> {p99_storm:.2f}ms")
        if speedup < 3.0 and ncores >= N_REPLICAS:
            _fail(f"read throughput did not scale: {speedup:.2f}x over "
                  f"1 replica on a {ncores}-core host (>= 3x required)")
        if qps[N_REPLICAS] < 0.5 * qps[1]:
            _fail(f"storm over {N_REPLICAS} replicas COLLAPSED to "
                  f"{speedup:.2f}x of one replica")
        # reads are disaggregated: the storm must not move ingest p99.
        # On hosts too small to run the fleet truly in parallel the
        # writer time-shares one core with every storm client and the
        # live-stripe sub-queries, so the delta measures scheduler
        # noise, not read/write coupling — like the scaling gate, the
        # 10% bound only means something with the cores to show it;
        # small hosts hold an absolute lock-pathology ceiling instead.
        limit_ms = p99_base * 1.10 if ncores >= N_REPLICAS \
            else max(p99_base * 4.0, 50.0)
        if p99_storm > limit_ms:
            _fail(f"ingest append p99 moved {p99_base:.2f}ms -> "
                  f"{p99_storm:.2f}ms under the read storm "
                  f"(limit {limit_ms:.2f}ms)")

        # -- ingest-side invariants -------------------------------------
        if srv.api.federation.remote_peers():
            _fail("queriers leaked into the ingest scatter set")
        snap = srv.telemetry.snapshot()
        for hop in snap.get("pipeline", []):
            if not hop["hop"].startswith("cluster."):
                continue
            if hop["emitted"] != hop["delivered"] \
                    + hop["dropped_total"] + hop["in_flight"]:
                _fail(f"hop {hop['hop']!r} ledger does not balance: "
                      f"{hop}")
        print("readtier-check: OK — byte-identical replicas, "
              "compute-once partial cache, ingest p99 within "
              f"{limit_ms:.2f}ms bound")
        return 0
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
        if srv is not None:
            srv.stop()
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
