"""cluster-check: brief e2e run proving scatter-gather federation works.

Spins up a real 3-shard cluster in-process (one seed + two joiners) plus
a small agent fleet — one agent per shard, each pointed at its shard's
ingest port — then fails (exit 1) if:

  * membership never converges (the seed must see both joiners),
  * rows are not stamped with the receiving shard's shard_id,
  * a federated `SELECT Count(*)` does not equal the union of the
    per-shard row counts (the acceptance criterion of the federation
    contract: one querier answers for all shards, exactly),
  * any cluster.* fan-out hop's frame ledger does not balance
    (emitted != delivered + dropped once quiesced).

Wired as `make cluster-check` — cheap enough for CI, real enough to
catch a merge step that double-counts or a fan-out hop that stops
accounting.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request


def _fail(msg: str) -> None:
    print(f"cluster-check: FAIL: {msg}")
    sys.exit(1)


def _post(port: int, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def main() -> int:
    from deepflow_tpu.agent.agent import Agent
    from deepflow_tpu.agent.config import AgentConfig
    from deepflow_tpu.server import Server

    servers: list = []
    agents: list = []
    try:
        seed = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                      sync_port=0, shard_id=1,
                      cluster_advertise="").start()
        servers.append(seed)
        seed_addr = f"127.0.0.1:{seed.query_port}"
        for sid in (2, 3):
            servers.append(Server(
                host="127.0.0.1", ingest_port=0, query_port=0,
                sync_port=0, shard_id=sid,
                cluster_seed=seed_addr).start())

        # membership: seed must see both joiners before we fan anything out
        deadline = time.time() + 15.0
        while time.time() < deadline:
            if len(seed.api.federation.remote_peers()) == 2:
                break
            time.sleep(0.2)
        else:
            _fail("membership never converged: seed sees "
                  f"{len(seed.api.federation.remote_peers())} of 2 peers")

        # small fleet: one profiling agent per shard, ~1s of traffic each
        for i, srv in enumerate(servers):
            cfg = AgentConfig()
            cfg.app_service = f"cluster-check-{i + 1}"
            cfg.sender.servers = [("127.0.0.1", srv.ingest_port)]
            cfg.profiler.sample_hz = 200.0
            cfg.profiler.emit_interval_s = 0.2
            cfg.tpuprobe.enabled = False
            cfg.stats_interval_s = 0.3
            agents.append(Agent(cfg).start())

        stop = threading.Event()

        def busy() -> None:
            while not stop.is_set():
                sum(i * i for i in range(2000))

        th = threading.Thread(target=busy, name="busy")
        th.start()
        time.sleep(1.2)
        stop.set()
        th.join()
        for a in agents:
            a.stop()
        agents = []

        # quiesce: per-shard profile counts must be nonzero and stable
        # (in-flight decoder batches land after the senders disconnect)
        table = "profile.in_process_profile"
        counts = []
        deadline = time.time() + 15.0
        while time.time() < deadline:
            cur = [len(s.db.table(table)) for s in servers]
            if all(cur) and cur == counts:
                break
            counts = cur
            time.sleep(0.3)
        if not all(counts):
            _fail(f"a shard ingested no profile rows: {counts}")

        # shard identity: every row carries the RECEIVING shard's id
        for srv in servers:
            for ch in srv.db.table(table).snapshot():
                ids = set(ch["shard_id"].tolist())
                if ids != {srv.shard_id}:
                    _fail(f"shard {srv.shard_id} rows tagged {ids}")

        # the acceptance criterion: federated count == union of shards
        union = sum(counts)
        got = _post(seed.query_port, "/v1/query", {
            "sql": "SELECT Count(*) AS n FROM in_process_profile",
            "db": "profile"})
        fed = got.get("federation") or {}
        if fed.get("missing_shards"):
            _fail(f"healthy cluster reported missing shards: {fed}")
        n = got["result"]["values"][0][0]
        if int(n) != union:
            _fail(f"federated Count(*) = {n}, union of shards = {union} "
                  f"(per-shard {counts})")

        # per-shard breakdown must reproduce the same union
        got = _post(seed.query_port, "/v1/query", {
            "sql": "SELECT shard_id, Count(*) AS n FROM in_process_profile"
                   " GROUP BY shard_id ORDER BY shard_id",
            "db": "profile"})
        by_shard = {int(r[0]): int(r[1]) for r in got["result"]["values"]}
        if by_shard != {i + 1: c for i, c in enumerate(counts)}:
            _fail(f"GROUP BY shard_id {by_shard} != per-shard {counts}")

        # fan-out hop ledgers: every cluster.* hop balances, none in flight
        snap = seed.telemetry.snapshot()
        hops = [p for p in snap.get("pipeline", [])
                if p["hop"].startswith("cluster.")]
        if not hops:
            _fail("no cluster.* hops in seed telemetry "
                  "(selfmon disabled? DF_NO_SELFMON set?)")
        for p in hops:
            if p["emitted"] != p["delivered"] + p["dropped_total"] \
                    + p["in_flight"]:
                _fail(f"hop {p['hop']!r} ledger does not balance: {p}")
            if p["in_flight"] != 0:
                _fail(f"hop {p['hop']!r} never drained: {p}")
        if not any(p["emitted"] for p in hops):
            _fail("federation hops saw no traffic")

        print(f"cluster-check: OK — 3 shards, {union} rows "
              f"(per-shard {counts}), federated count exact, "
              f"{len(hops)} fan-out hops balanced")
        return 0
    finally:
        for a in agents:
            a.stop()
        for s in servers:
            s.stop()


if __name__ == "__main__":
    sys.exit(main())
