"""selfmon-check: brief e2e run proving the self-telemetry spine works.

Spins up a real server + agent in-process, pushes ~1s of profiling
traffic through the full pipeline, then fails (exit 1) if:

  * any hop's frame ledger does not balance
    (emitted != delivered + dropped once quiesced), or
  * any registered stage reports no heartbeat, or
  * anything is wedged / health is degraded.

Wired as `make selfmon-check` — cheap enough for CI, real enough to
catch a hop that stops accounting or a stage that stops beating.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request


def _fail(msg: str) -> None:
    print(f"selfmon-check: FAIL: {msg}")
    sys.exit(1)


def main() -> int:
    from deepflow_tpu.agent.agent import Agent
    from deepflow_tpu.agent.config import AgentConfig
    from deepflow_tpu.server import Server

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    selfstats_interval_s=0.5).start()
    agent = None
    try:
        cfg = AgentConfig()
        cfg.app_service = "selfmon-check"
        cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
        cfg.profiler.sample_hz = 200.0
        cfg.profiler.emit_interval_s = 0.2
        cfg.tpuprobe.enabled = False
        cfg.stats_interval_s = 0.3
        agent = Agent(cfg).start()

        stop = threading.Event()

        def busy() -> None:
            while not stop.is_set():
                sum(i * i for i in range(2000))

        th = threading.Thread(target=busy, name="busy")
        th.start()
        time.sleep(1.2)
        stop.set()
        th.join()
        agent.stop()
        agent = None

        # quiesce: poll until every server hop drains (or time out)
        deadline = time.time() + 15.0
        health: dict = {}
        while time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.query_port}/v1/health",
                    timeout=5) as resp:
                health = json.loads(resp.read())
            hops = health.get("pipeline", [])
            if hops and all(p["in_flight"] == 0 for p in hops) \
                    and health.get("agents_selfmon"):
                break
            time.sleep(0.2)

        hops = health.get("pipeline", [])
        if not hops:
            _fail("no pipeline telemetry in /v1/health "
                  "(selfmon disabled? DF_NO_SELFMON set?)")
        for p in hops:
            if p["emitted"] != p["delivered"] + p["dropped_total"] \
                    + p["in_flight"]:
                _fail(f"hop {p['hop']!r} ledger does not balance: {p}")
            if p["in_flight"] != 0:
                _fail(f"hop {p['hop']!r} never drained: {p}")
        if not any(p["emitted"] for p in hops):
            _fail("server pipeline saw no traffic")

        stages = health.get("stages", [])
        if not stages:
            _fail("no stage heartbeats in /v1/health")
        for s in stages:
            if s["beats"] < 1:
                _fail(f"stage {s['stage']!r} reports no heartbeat: {s}")
            if s.get("wedged"):
                _fail(f"stage {s['stage']!r} is wedged")
        if health.get("status") != "ok":
            _fail(f"health status {health.get('status')!r} "
                  f"(wedged: {health.get('wedged_stages')})")

        ag = health.get("agents_selfmon") or {}
        if not ag.get("pipeline") or not ag.get("heartbeats"):
            _fail("agent self-telemetry never arrived in deepflow_system")
        for hop in ag["pipeline"].values():
            emitted = hop.get("emitted", 0)
            accounted = hop.get("delivered", 0) + hop.get("dropped", 0) \
                + hop.get("in_flight", 0)
            if emitted != accounted:
                _fail(f"agent hop ledger does not balance: {hop}")

        n_hops = len(hops) + len(ag["pipeline"])
        n_stages = len(stages) + len(ag["heartbeats"])
        print(f"selfmon-check: OK — {n_hops} hops balanced, "
              f"{n_stages} stages beating, 0 wedges")
        return 0
    finally:
        if agent is not None:
            agent.stop()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
