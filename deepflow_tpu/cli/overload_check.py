"""overload-check: closed-loop QoS gate under 10x synthetic overload.

Three phases over the deepflow_tpu/qos subsystem (wired as
`make overload-check`); any violated invariant exits non-zero:

  A. END-TO-END OVERLOAD (real server + 3 durable senders, one per
     tenant): each tenant offers bulk DFSTATS at ~10x its configured
     frames-per-second quota while a HIGH-class STEP_METRICS stream
     rides along.  Fails unless:
       * zero HIGH-class loss — every STEP_METRICS row lands in the
         store exactly once (quota never sheds HIGH, and pressure
         sheds withhold the ack so the durable sender retransmits);
       * every tenant's bulk overage is shed as dropped(quota) and the
         per-tenant counters account every admitted frame (admission's
         view and the receiver's drop attribution agree);
       * no tenant is starved (every tenant lands bulk rows);
       * ingest p99 ack latency stays bounded under the overload;
       * every hop ledger (3 senders + server) balances:
         emitted == delivered + dropped(reason) + in_flight.

  B. WEIGHTED FAIRNESS (real AdmissionQueues, metered drain): tenants
     weighted 4/2/1 pre-backlog 10x what the metered drain can move in
     the window.  Fails unless each tenant's delivered share is within
     2x of its configured weight share, no tenant is starved, and
     every tenant's HIGH frames clear before its bulk (strict class
     priority inside a tenant).

  C. CLOSED LOOP (Qos facade, live pressure thread): a forced decoder
     -fill spike must raise the pressure level within one interval and
     cut the advertised head-sampling rate below 1; releasing the
     spike must decay the level back to nominal one notch per decay_s.
"""

from __future__ import annotations

import sys
import threading
import time


def _fail(msg: str) -> None:
    print(f"overload-check: FAIL: {msg}")
    sys.exit(1)


def _check_ledgers(telemetry, who: str) -> None:
    for h in telemetry.snapshot()["pipeline"]:
        if h["emitted"] != h["delivered"] + h["dropped_total"] \
                + h["in_flight"]:
            _fail(f"{who} hop {h['hop']!r} ledger does not balance: {h}")


MS = 1_000_000
WEIGHTS = {1: 4, 2: 2, 3: 1}
N_HIGH = 100        # STEP_METRICS frames per tenant
BULK_PER_HIGH = 10  # DFSTATS frames interleaved per HIGH frame
QUOTA_FPS = 40.0    # bulk quota; offered bulk rate is far above 10x this


def _step_payload(org: int, i: int) -> bytes:
    from deepflow_tpu.tpuprobe.stepmetrics import encode_step_payload
    return encode_step_payload([{
        "time": i * MS, "end_ns": i * MS + 500, "latency_ns": 500,
        "run_id": org, "step": i, "job": f"overload-{org}",
        "device_count": 4, "device_skew_ns": 0, "compute_ns": 1,
        "collective_ns": 1, "straggler_device": 0, "straggler_lag_ns": 0,
        "top_hlos": []}])


def _stats_payload() -> bytes:
    from deepflow_tpu.proto import pb
    batch = pb.StatsBatch()
    m = batch.metrics.add()
    m.name = "overload_check_bulk"
    m.timestamp_ns = time.time_ns()
    m.values["v"] = 1.0
    return batch.SerializeToString()


class _AckLatency:
    """p99 send->ack latency via the sender's contiguous watermark:
    seqs are allocated in send order, so when the watermark advances to
    frame k every frame up to k is acked."""

    def __init__(self, sender):
        self.sender = sender
        self.send_times: list[float] = []
        self.latencies: list[float] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def sent(self) -> None:
        self.send_times.append(time.monotonic())

    def _run(self) -> None:
        done = 0
        while not self._stop.is_set():
            acked = self.sender.stats["acked_seq"] - self.sender.seq_base
            now = time.monotonic()
            while done < min(acked, len(self.send_times)):
                self.latencies.append(now - self.send_times[done])
                done += 1
            time.sleep(0.005)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def _p99(xs: list[float]) -> float:
    if not xs:
        return 0.0
    return sorted(xs)[min(len(xs) - 1, int(len(xs) * 0.99))]


def _phase_a() -> None:
    from deepflow_tpu.agent.sender import UniformSender
    from deepflow_tpu.codec import MessageType
    from deepflow_tpu.qos import QosConfig, TenantQos
    from deepflow_tpu.server import Server
    from deepflow_tpu.telemetry import Telemetry

    cfg = QosConfig()
    for org, w in WEIGHTS.items():
        cfg.set_tenant(TenantQos(org_id=org, weight=w,
                                 rate_fps=QUOTA_FPS, burst=QUOTA_FPS))
    server = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    qos_config=cfg).start()
    senders, lats, tels = {}, {}, {}
    try:
        for org in WEIGHTS:
            tels[org] = Telemetry("agent", enabled=True)
            senders[org] = UniformSender(
                [("127.0.0.1", server.ingest_port)], agent_id=org,
                org_id=org, telemetry=tels[org]).start()
            lats[org] = _AckLatency(senders[org])
        t0 = time.monotonic()
        for i in range(1, N_HIGH + 1):
            for org, s in senders.items():
                s.send(MessageType.STEP_METRICS, _step_payload(org, i))
                lats[org].sent()
                for _ in range(BULK_PER_HIGH):
                    s.send(MessageType.DFSTATS, _stats_payload())
                    lats[org].sent()
            time.sleep(0.002)
        offered_s = time.monotonic() - t0
        offered_fps = N_HIGH * BULK_PER_HIGH / offered_s
        if offered_fps < 10 * QUOTA_FPS:
            _fail(f"phase A offered only {offered_fps:.0f} bulk fps per "
                  f"tenant — not a 10x overload of quota {QUOTA_FPS}")
        for s in senders.values():
            s.flush_and_stop(timeout=60.0)

        # zero HIGH-class loss, exactly once
        want = len(WEIGHTS) * N_HIGH
        if not server.wait_for_rows("profile.tpu_step_metrics", want,
                                    timeout=30.0):
            got = len(server.db.table("profile.tpu_step_metrics"))
            _fail(f"HIGH loss under overload: {got}/{want} "
                  f"STEP_METRICS rows")
        time.sleep(0.5)
        table = server.db.table("profile.tpu_step_metrics")
        table.flush()
        cols = table.column_concat(["run_id", "step"])
        keys = list(zip(cols["run_id"].tolist(), cols["step"].tolist()))
        if len(keys) != want or len(set(keys)) != want:
            _fail(f"HIGH not exactly-once: {len(keys)} rows, "
                  f"{len(set(keys))} unique of {want}")

        tenants = server.qos.admission.tenant_snapshot()
        drops = server.receiver.drop_attribution()["by_org"]
        for org in WEIGHTS:
            t = tenants.get(org)
            if t is None:
                _fail(f"tenant {org} never reached admission")
            if t["shed_quota"] <= 0:
                _fail(f"tenant {org} offered 10x quota but shed nothing: "
                      f"{t}")
            if t["delivered"] <= N_HIGH:
                _fail(f"tenant {org} starved: only {t['delivered']} "
                      f"frames delivered (HIGH alone is {N_HIGH})")
            att = drops.get(str(org), {}).get("quota", 0)
            if att != t["shed_quota"]:
                _fail(f"tenant {org} drop attribution disagrees with "
                      f"admission: {att} != {t['shed_quota']}")
            p99 = _p99(lats[org].latencies)
            if p99 > 10.0:
                _fail(f"tenant {org} ingest p99 ack latency unbounded "
                      f"under overload: {p99:.2f}s")
        for org, tel in tels.items():
            _check_ledgers(tel, f"sender-{org}")
        _check_ledgers(server.telemetry, "server")
        shed = sum(t["shed_quota"] for t in tenants.values())
        p99s = {o: round(_p99(v.latencies), 3) for o, v in lats.items()}
        print(f"overload-check: phase A OK — {want}/{want} HIGH exactly "
              f"once at ~{offered_fps:.0f} bulk fps/tenant (quota "
              f"{QUOTA_FPS:.0f}), {shed} bulk frames quota-shed and "
              f"conserved, ack p99 by tenant {p99s}")
    finally:
        for latw in lats.values():
            latw.stop()
        for s in senders.values():
            s.flush_and_stop(timeout=1.0)
        server.stop()


def _phase_b() -> None:
    from deepflow_tpu.codec import MessageType
    from deepflow_tpu.qos import AdmissionQueues, QosConfig, TenantQos

    cfg = QosConfig(queue_frames=100_000)
    for org, w in WEIGHTS.items():
        cfg.set_tenant(TenantQos(org_id=org, weight=w))
    capacity_fps = 4000.0
    window_frames = 4000
    backlog = 10 * window_frames // len(WEIGHTS)   # 10x per tenant
    delivered: dict[int, dict[str, int]] = {
        org: {"high": 0, "bulk": 0} for org in WEIGHTS}
    total = {"n": 0}
    lock = threading.Lock()

    def metered_deliver(msg_type, lane, enq_ns, group):
        # lane carries the org; sleeping here is the drain capacity cap
        with lock:
            if total["n"] >= window_frames:
                return True  # window over: swallow the rest instantly
            cls = "high" if msg_type == MessageType.STEP_METRICS \
                else "bulk"
            delivered[lane][cls] += len(group)
            total["n"] += len(group)
        time.sleep(len(group) / capacity_fps)
        return True

    aq = AdmissionQueues(cfg, metered_deliver)
    n_high = 64
    for org in WEIGHTS:
        aq.submit(org, 0, MessageType.STEP_METRICS, org,
                  [(None, b"")] * n_high, 0)
        for _ in range((backlog - n_high) // 8):
            aq.submit(org, 2, MessageType.DFSTATS, org,
                      [(None, b"")] * 8, 0)
    aq.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with lock:
            if total["n"] >= window_frames:
                break
        time.sleep(0.01)
    aq.stop()
    with lock:
        counted = {org: d["high"] + d["bulk"]
                   for org, d in delivered.items()}
        n = sum(counted.values())
    wsum = sum(WEIGHTS.values())
    for org, w in WEIGHTS.items():
        if delivered[org]["high"] != n_high:
            _fail(f"tenant {org} HIGH not fully drained inside the "
                  f"contended window: {delivered[org]}")
        share, want = counted[org] / n, w / wsum
        if not want / 2 <= share <= want * 2:
            _fail(f"tenant {org} delivered share {share:.3f} outside "
                  f"2x of weight share {want:.3f} ({counted})")
    shares = {o: round(counted[o] / n, 3) for o in WEIGHTS}
    print(f"overload-check: phase B OK — DRR shares {shares} vs "
          f"weights {WEIGHTS} over {n} contended frames, HIGH first")


def _phase_c() -> None:
    from deepflow_tpu.qos import Qos, QosConfig

    cfg = QosConfig(interval_s=0.05, decay_s=0.2)
    fill = {"v": 0.0}
    qos = Qos(cfg)
    qos.attach(lambda *a: True, decoder_fill=lambda: fill["v"])
    qos.start()
    try:
        fill["v"] = 0.95
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline \
                and qos.pressure.level(0) < 3:
            time.sleep(0.01)
        if qos.pressure.level(0) != 3:
            _fail("pressure never reached critical under a 0.95 "
                  f"decoder-fill spike: {qos.pressure.snapshot()}")
        d = qos.directive(7)
        if d["pressure_level"] != 3 or d["sample_rate"] >= 1.0:
            _fail(f"directive does not reflect the spike: {d}")
        if qos.sampler.rate_for(7) >= 1.0:
            _fail("adaptive sampler still at full rate under critical "
                  "pressure")
        fill["v"] = 0.0
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and qos.pressure.level(0) > 0:
            time.sleep(0.02)
        if qos.pressure.level(0) != 0:
            _fail(f"pressure never decayed back to nominal: "
                  f"{qos.pressure.snapshot()}")
        snap = qos.pressure.snapshot()
        print(f"overload-check: phase C OK — spike raised to critical "
              f"and decayed to nominal (raises={snap['raises']}, "
              f"decays={snap['decays']})")
    finally:
        qos.stop()


def main() -> int:
    _phase_a()
    _phase_b()
    _phase_c()
    print("overload-check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
