"""dfctl: operator CLI against the querier/controller HTTP API.

Reference analog: cli/ctl/*.go (deepflow-ctl). Subcommands:

    dfctl health
    dfctl agent list
    dfctl agent-group-config set config.yaml
    dfctl query "SELECT ..." --db profile
    dfctl flame --service my-svc [--event-type on-cpu]
    dfctl tpu-flame [--device 0]
    dfctl trace <trace_id>
    dfctl trace-search --tags "service.name=shop" --min-duration 100ms
    dfctl promql 'histogram_quantile(0.95, rate(lat_bucket[5m]))'
    dfctl alert list|set <json>|delete <name>
    dfctl exporter list|add <json>|delete <endpoint>
    dfctl watch "SELECT ..." --window 300
    dfctl events --follow
    dfctl replay capture.pcap --ingest host:20033
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request


def _api(server: str, path: str, body: dict | None = None,
         token: str | None = None) -> dict:
    url = f"http://{server}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data)
    if token:
        req.add_header("X-DF-Token", token)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        err = e.read().decode("utf-8", "replace")
        raise SystemExit(f"error {e.code}: {err}")
    except urllib.error.URLError as e:
        raise SystemExit(f"cannot reach {url}: {e.reason}")


def print_table(columns: list[str], rows: list[list]) -> None:
    if not rows:
        print("(no rows)")
        return
    widths = [max(len(str(c)), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(columns)]
    print("  ".join(str(c).ljust(w) for c, w in zip(columns, widths)))
    print("  ".join("-" * w for w in widths))
    for r in rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))


def print_flame(node: dict, depth: int = 0, total: int | None = None,
                max_depth: int = 12) -> None:
    if total is None:
        total = node["total_value"] or 1
    if depth > max_depth:
        return
    pct = 100.0 * node["total_value"] / total
    bar = "▇" * max(1, int(pct / 5)) if depth else ""
    print(f"{'  ' * depth}{node['name']}  {node['total_value']:,} "
          f"({pct:.1f}%) {bar}")
    for child in node.get("children", [])[:20]:
        print_flame(child, depth + 1, total, max_depth)


def _load_json_arg(spec: str) -> dict:
    if not spec:
        raise SystemExit("a json spec (inline or @file) is required")
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            return json.load(f)
    import os
    if os.path.exists(spec):
        with open(spec) as f:
            return json.load(f)
    try:
        return json.loads(spec)
    except json.JSONDecodeError as e:
        raise SystemExit(f"bad json spec: {e}\n{spec}")


def _subscribe_updates(server: str, sid: str, use_poll: bool = False):
    """Yield standing-query updates for one subscriber: SSE stream
    first, transparent long-poll fallback (old servers, proxies that
    buffer event streams)."""
    if not use_poll:
        try:
            req = urllib.request.Request(
                f"http://{server}/v1/subscribe?subscriber={sid}")
            resp = urllib.request.urlopen(req, timeout=30)
            while True:
                line = resp.readline()
                if not line:
                    return
                if line.startswith(b"data: "):
                    yield json.loads(line[6:])
            # unreachable
        except (urllib.error.HTTPError, urllib.error.URLError):
            pass  # fall through to long-poll
    while True:
        out = _api(server, "/v1/subscribe",
                   {"action": "poll", "subscriber": sid,
                    "timeout_s": 25})
        yield from out["updates"]
        if out.get("closed"):
            return


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="dfctl")
    parser.add_argument("--server", default="127.0.0.1:20416",
                        help="querier host:port")
    parser.add_argument("--token", default=None,
                        help="API token for gated endpoints (repo upload, "
                             "OTA exec); default $DF_API_TOKEN")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_health = sub.add_parser(
        "health", help="per-stage heartbeats, wedge verdicts, ledger "
                       "imbalance — server and agents")
    p_health.add_argument("--json", action="store_true",
                          help="raw /v1/health JSON instead of tables")

    sub.add_parser(
        "pipeline", help="hop-by-hop frame ledger waterfall "
                         "(emitted/delivered/drops/queue waits)")

    p_cluster = sub.add_parser(
        "cluster", help="federation peer table: shard id, epoch, "
                        "last-seen, per-shard row counts, probe latency")
    p_cluster.add_argument("--json", action="store_true",
                           help="raw /v1/cluster/status JSON")

    p_agent = sub.add_parser("agent")
    p_agent.add_argument("action", choices=["list"])

    p_cfg = sub.add_parser("agent-group-config")
    p_cfg.add_argument("action", choices=["set"])
    p_cfg.add_argument("file")
    p_cfg.add_argument("--group", default="default")

    p_query = sub.add_parser("query")
    p_query.add_argument("sql")
    p_query.add_argument("--db", default="")
    p_query.add_argument("--org", type=int, default=None,
                         help="scope results to this org id")

    p_ds = sub.add_parser(
        "datasources", help="tiered storage view: per-table segment "
                            "counts, on-disk bytes, time spans and "
                            "rollup completeness horizons")
    p_ds.add_argument("--json", action="store_true",
                      help="raw /v1/health storage block JSON")
    p_ds.add_argument("--zones", action="store_true",
                      help="add a ZONES column: segments carrying "
                           "zone-map footers (prunable), as "
                           "zoned/total")

    p_seg = sub.add_parser(
        "segments", help="per-segment inspector: format version, rows, "
                         "codecs, zone/bloom index presence and "
                         "sorted-run membership")
    p_seg.add_argument("table", nargs="?", default=None,
                       help="limit to one table (default: all)")
    p_seg.add_argument("--v1", action="store_true",
                       help="only segments still on format v1 "
                            "(awaiting migrate-on-compact)")
    p_seg.add_argument("--json", action="store_true",
                       help="raw /v1/segments JSON")

    p_fsck = sub.add_parser(
        "fsck", help="verify every block checksum of every sealed "
                     "segment now; corrupt segments are quarantined "
                     "and repaired from their published object-store "
                     "copy (the background scrubber's on-demand form)")
    p_fsck.add_argument("table", nargs="?", default=None,
                        help="limit to one table (default: all)")
    p_fsck.add_argument("--no-repair", action="store_true",
                        help="report only: leave corrupt segments in "
                             "service (no quarantine, no repair)")
    p_fsck.add_argument("--json", action="store_true",
                        help="raw /v1/fsck JSON")

    p_rt = sub.add_parser(
        "readtier", help="stateless querier view: adopted publish gens "
                         "per ingest shard, per-table adopted "
                         "segments/rows, segment-cache hit/evict "
                         "ledger and distributed partial-cache "
                         "counters")
    p_rt.add_argument("--json", action="store_true",
                      help="raw readtier + partial_cache health JSON")

    p_org = sub.add_parser("org", help="org/team scoping: assign agent "
                                       "groups to orgs, list assignments")
    p_org.add_argument("--assign", nargs=2, metavar=("GROUP", "ORG_ID"),
                       default=None)

    p_qos = sub.add_parser(
        "qos", help="multi-tenant overload control: per-tenant "
                    "weights/quotas/pressure levels, admission + "
                    "sampling counters; --set hot-applies a tenant "
                    "policy")
    p_qos.add_argument("--set", nargs="+", metavar="ORG_ID KEY=VAL",
                       default=None,
                       help="set tenant knobs: ORG_ID then one or more "
                            "of weight=N | rate_fps=F | burst=F")
    p_qos.add_argument("--json", action="store_true",
                       help="raw /v1/qos JSON")

    p_repo = sub.add_parser("repo", help="agent package repo for OTA "
                                         "rollout (upload/list)")
    p_repo.add_argument("action", choices=["upload", "list"])
    p_repo.add_argument("file", nargs="?", help="upload: package tar.gz")
    p_repo.add_argument("--version", default="",
                        help="upload: package version tag")
    p_repo.add_argument("--name", default="agent")

    p_flame = sub.add_parser("flame")
    p_flame.add_argument("--service", default=None)
    p_flame.add_argument("--event-type", default="on-cpu")

    p_tpu = sub.add_parser("tpu-flame")
    p_tpu.add_argument("--device", type=int, default=None)
    p_tpu.add_argument("--include-host", action="store_true",
                       help="include host compile/runtime spans")

    p_mem = sub.add_parser("tpu-memory",
                           help="per-device HBM usage, headroom, top ops "
                                "by memory traffic, OOM forensics")
    p_mem.add_argument("--device", type=int, default=None)
    p_mem.add_argument("--start", type=int, default=None)
    p_mem.add_argument("--end", type=int, default=None)
    p_mem.add_argument("--top", type=int, default=15)

    p_coll = sub.add_parser("collectives",
                            help="cross-device collective groups "
                                 "(latency/skew/bandwidth)")
    p_coll.add_argument("--start", type=int, default=None)
    p_coll.add_argument("--end", type=int, default=None)

    p_step = sub.add_parser("step-trace",
                            help="one training step stitched across devices")
    p_step.add_argument("--run-id", type=int, default=None)

    p_steps = sub.add_parser(
        "steps", help="per-step health waterfall: latency sparkline, "
                      "device skew, collective wait, regression verdict")
    p_steps.add_argument("--job", default=None)
    p_steps.add_argument("--run-id", type=int, default=None)
    p_steps.add_argument("--limit", type=int, default=50)
    p_steps.add_argument("--critical-path", type=int, default=None,
                         metavar="STEP",
                         help="attribute one step's latency against its "
                              "rolling healthy baseline")
    p_steps.add_argument("--json", action="store_true",
                         help="raw endpoint JSON instead of the waterfall")

    p_replay = sub.add_parser("replay")
    p_replay.add_argument("pcap")
    p_replay.add_argument("--ingest", default="127.0.0.1:20033")

    p_trace = sub.add_parser("trace")
    p_trace.add_argument("trace_id")

    p_explain = sub.add_parser(
        "explain", help="EXPLAIN ANALYZE a DF-SQL statement: plan "
                        "(tier, segments pruned, morsel degree, cache "
                        "layer) + observed per-stage wall/CPU time")
    p_explain.add_argument("sql")
    p_explain.add_argument("--db", default="")
    p_explain.add_argument("--no-analyze", action="store_true",
                           help="plan only, don't execute")
    p_explain.add_argument("--json", action="store_true",
                           help="raw explain JSON")

    p_qtrace = sub.add_parser(
        "query-trace", help="span waterfall for one query trace id "
                            "(from EXPLAIN ANALYZE or trace-search)")
    p_qtrace.add_argument("trace_id")
    p_qtrace.add_argument("--flame", action="store_true",
                          help="render as a flame graph (self-time "
                               "weighted) instead of a waterfall")

    p_promql = sub.add_parser(
        "promql", help="evaluate a PromQL expression (instant by default; "
                       "--start/--end for a range)")
    p_promql.add_argument("expr")
    p_promql.add_argument("--time", type=int, default=None)
    p_promql.add_argument("--start", type=int, default=None)
    p_promql.add_argument("--end", type=int, default=None)
    p_promql.add_argument("--step", type=int, default=15)
    p_promql.add_argument("--org", type=int, default=None,
                          help="scope results to this org id")

    p_ts = sub.add_parser(
        "trace-search", help="search traces by tags/duration "
                             "(tags is logfmt: service.name=x ...)")
    p_ts.add_argument("--tags", default="")
    p_ts.add_argument("--min-duration", default=None)
    p_ts.add_argument("--max-duration", default=None)
    p_ts.add_argument("--start", type=int, default=None)
    p_ts.add_argument("--end", type=int, default=None)
    p_ts.add_argument("--limit", type=int, default=20)

    p_alert = sub.add_parser("alert")
    p_alert.add_argument("action", choices=["list", "set", "delete"])
    p_alert.add_argument("spec", nargs="?",
                         help="set: json file or inline json; delete: name")

    p_exec = sub.add_parser("exec",
                            help="remote-exec a registry command on an "
                                 "agent (help|status|config|queues|"
                                 "queue-tap|flows|profilers|upgrade)")
    p_exec.add_argument("agent_id", type=int)
    p_exec.add_argument("command")
    p_exec.add_argument("cargs", nargs="*")
    p_exec.add_argument("--timeout", type=float, default=30.0)

    p_watch = sub.add_parser(
        "watch", help="register a standing query and render live "
                      "updates: the server maintains it incrementally "
                      "and pushes each new generation over SSE "
                      "(long-poll fallback)")
    p_watch.add_argument("sql")
    p_watch.add_argument("--name", default=None,
                         help="standing-query name (default: derived)")
    p_watch.add_argument("--table", default=None,
                         help="explicit table (default: FROM clause)")
    p_watch.add_argument("--window", type=float, default=0.0,
                         metavar="SECONDS",
                         help="sliding window anchored on newest data")
    p_watch.add_argument("--org", type=int, default=None)
    p_watch.add_argument("--poll", action="store_true",
                         help="force long-poll instead of SSE")
    p_watch.add_argument("--keep", action="store_true",
                         help="leave the query registered on exit")
    p_watch.add_argument("--count", type=int, default=0,
                         help="exit after N updates (0 = forever)")

    p_events = sub.add_parser(
        "events", help="event.event rows (alerts, rule errors, step "
                       "regressions); --follow tails new events over "
                       "the standing-query push API")
    p_events.add_argument("--follow", "-f", action="store_true")
    p_events.add_argument("--type", default=None,
                          help="filter by event_type")
    p_events.add_argument("--limit", type=int, default=50)
    p_events.add_argument("--poll", action="store_true",
                          help="force long-poll instead of SSE")
    p_events.add_argument("--count", type=int, default=0,
                          help="follow: exit after N new events")

    p_exp = sub.add_parser("exporter")
    p_exp.add_argument("action", choices=["list", "add", "delete"])
    p_exp.add_argument("spec", nargs="?",
                       help="add: json {type,endpoint,...}; delete: endpoint")

    args = parser.parse_args(argv)
    token = args.token or os.environ.get("DF_API_TOKEN") or None

    if args.cmd == "health":
        h = _api(args.server, "/v1/health")
        if args.json:
            print(json.dumps(h, indent=2))
            return 0
        print(f"status: {h['status']}")
        if h.get("wedged_stages"):
            print("wedged: " + ", ".join(h["wedged_stages"]))
        stages = h.get("stages", [])
        if stages:
            print("\nserver stages:")
            print_table(
                ["STAGE", "BEATS", "PROGRESS", "AGE_S", "HINT_S", "STATE"],
                [[s["stage"], s["beats"], s["progress"], s["age_s"],
                  s["interval_hint_s"],
                  "WEDGED" if s.get("wedged") else "ok"] for s in stages])
        ag = h.get("agents_selfmon", {})
        hbs = ag.get("heartbeats", {})
        if hbs:
            print("\nagent stages (via deepflow_system):")
            print_table(
                ["STAGE", "BEATS", "PROGRESS", "AGE_S", "STATE"],
                [[s["stage"], int(s.get("beats", 0)),
                  int(s.get("progress", 0)), s.get("age_s", ""),
                  "WEDGED" if s.get("wedged") else "ok"]
                 for s in sorted(hbs.values(), key=lambda x: x["stage"])])
        for w in h.get("wedges", []):
            print(f"\nserver wedge: {w['stage']} "
                  f"stalled {w.get('stalled_s', '?')}s "
                  f"(window {w.get('window_s', '?')}s)")
            if w.get("stack"):
                print(w["stack"].rstrip())
        for w in ag.get("wedges", []):
            print(f"\nagent wedge: {w['stage']} "
                  f"stalled {w.get('stalled_s', '?')}s")
            if w.get("stack"):
                print(w["stack"].rstrip())
        if "ledger_imbalance" in h:
            print(f"\nledger imbalance (in-flight): "
                  f"{h['ledger_imbalance']}")
    elif args.cmd == "pipeline":
        h = _api(args.server, "/v1/health")
        hops = h.get("pipeline", [])
        if hops:
            print("server pipeline:")
            print_table(
                ["HOP", "EMITTED", "DELIVERED", "DROPPED", "REASONS",
                 "IN_FLIGHT", "WAIT_P50_MS", "WAIT_P99_MS"],
                [[p["hop"], p["emitted"], p["delivered"],
                  p["dropped_total"],
                  ",".join(f"{k}={v}"
                           for k, v in sorted(p["dropped"].items())) or "-",
                  p["in_flight"], p["wait"]["p50_ms"], p["wait"]["p99_ms"]]
                 for p in hops])
        ag_hops = h.get("agents_selfmon", {}).get("pipeline", {})
        if ag_hops:
            print("\nagent pipeline (via deepflow_system):")
            print_table(
                ["HOP", "EMITTED", "DELIVERED", "DROPPED", "REASONS",
                 "IN_FLIGHT", "WAIT_P99_MS"],
                [[p["hop"], int(p.get("emitted", 0)),
                  int(p.get("delivered", 0)), int(p.get("dropped", 0)),
                  ",".join(f"{k}={int(v)}" for k, v in sorted(
                      p.get("dropped_by_reason", {}).items())) or "-",
                  int(p.get("in_flight", 0)), p.get("wait_p99_ms", "")]
                 for p in sorted(ag_hops.values(),
                                 key=lambda x: x["hop"])])
        if not hops and not ag_hops:
            print("(no pipeline telemetry — selfmon disabled?)")
    elif args.cmd == "cluster":
        st = _api(args.server, "/v1/cluster/status")
        if args.json:
            print(json.dumps(st, indent=2))
            return 0
        print(f"answering shard: {st['shard_id']}  "
              f"directory version: {st['version']}")
        peers = sorted(st.get("peers", []), key=lambda p: p["shard_id"])
        print_table(
            ["SHARD", "ADDR", "EPOCH", "LAST_SEEN_S", "RAW_ROWS",
             "LATENCY_MS", "STATE"],
            [[p["shard_id"],
              p["addr"] + (" *" if p["shard_id"] == st["shard_id"]
                           else ""),
              p["epoch"], p["last_seen_s"],
              # raw physical count: replicated rows appear on R shards,
              # so this column is NOT a logical row count (pre-rename
              # servers still send "rows")
              rr if (rr := p.get("raw_rows", p.get("rows"))) is not None
              else "-",
              p["latency_ms"] if p["latency_ms"] is not None else "-",
              "alive" if p["alive"]
              else ("DEAD " + p.get("error", "")).strip()]
             for p in peers])
        fan = st.get("fanout") or {}
        if fan:
            print("\nfan-out clients (this shard -> peer):")
            print_table(
                ["ADDR", "ATTEMPTS", "HEDGES", "ERRORS"],
                [[addr, s.get("attempts", 0), s.get("hedges", 0),
                  s.get("errors", 0)] for addr, s in sorted(fan.items())])
    elif args.cmd == "agent":
        out = _api(args.server, "/v1/agents")
        rows = [[a["agent_id"], a["hostname"], a["ctrl_ip"],
                 a.get("staleness_s", ""), a.get("degraded", ""),
                 a.get("exception_bitmap", 0), a.get("version", "")]
                for a in out["agents"]]
        print_table(["ID", "HOSTNAME", "CTRL_IP", "STALE_S", "DEGRADED",
                     "EXC", "VERSION"], rows)
    elif args.cmd == "exec":
        import time as _time
        out = _api(args.server, "/v1/agents/exec",
                   {"agent_id": args.agent_id, "cmd": args.command,
                    "args": args.cargs}, token=token)
        rid = out["result_id"]
        deadline = _time.time() + args.timeout
        while _time.time() < deadline:
            r = _api(args.server, "/v1/agents/exec",
                     {"result_id": rid})["result"]
            if r["state"] == "done":
                print(r.get("output", ""))
                return 0 if r.get("exit_code", 1) == 0 else 1
            _time.sleep(0.5)
        print("timed out waiting for result", rid)
        return 2
    elif args.cmd == "tpu-memory":
        body = {"top": args.top}
        if args.device is not None:
            body["device_id"] = args.device
        if args.start:
            body["time_start"] = args.start
        if args.end:
            body["time_end"] = args.end
        r = _api(args.server, "/v1/profile/TpuMemory", body)["result"]
        if not r["devices"]:
            print("(no HBM samples)")
            return 0
        gib = 1 << 30
        print_table(
            ["DEVICE", "IN_USE_GIB", "PEAK_GIB", "LIMIT_GIB", "PEAK_%",
             "FRAG_FREE_GIB"],
            [[d["device_id"],
              round(d["bytes_in_use"] / gib, 2),
              round(d["peak_bytes_in_use"] / gib, 2),
              round(d["bytes_limit"] / gib, 2),
              d["peak_pct"],
              round(d["largest_free_block"] / gib, 2)]
             for d in r["devices"]])
        if r["top_ops"]:
            print("\ntop HLO ops by HBM traffic:")
            print_table(
                ["OP", "MODULE", "GIB_ACCESSED", "GB/S", "COUNT"],
                [[o["hlo_op"], o["hlo_module"],
                  round(o["bytes_accessed"] / gib, 2), o["hbm_gbps"],
                  o["count"]] for o in r["top_ops"]])
        f = r.get("forensics")
        if f:
            print(f"\npressure peak: {f['pressure_pct']}% of HBM on "
                  f"device {f['pressure_peak']['device_id']} at "
                  f"{f['pressure_peak']['time']}")
            for o in f["ops_near_peak"]:
                print(f"  {o['hlo_op']}: {o['bytes_accessed']:,}B near peak")
    elif args.cmd == "collectives":
        body = {}
        if args.start:
            body["time_start"] = args.start
        if args.end:
            body["time_end"] = args.end
        out = _api(args.server, "/v1/profile/TpuCollectives", body)
        rows = [[g["collective"], g["hlo_op"], g["run_id"],
                 g["n_participants"], g.get("transport", "ici"),
                 len(g.get("hosts", [])) or 1, g["latency_ns"],
                 g["skew_ns"], g["algo_bw_gbyte_s"]] for g in out["result"]]
        print_table(["COLLECTIVE", "OP", "RUN", "DEVS", "TRANSPORT",
                     "HOSTS", "LATENCY_NS", "SKEW_NS", "GB/S"], rows)
    elif args.cmd == "step-trace":
        body = {}
        if args.run_id is not None:
            body["run_id"] = args.run_id
        tr = _api(args.server, "/v1/profile/TpuStepTrace", body)["result"]
        if not tr["devices"]:
            print("(no TPU span data)")
            return 0
        print(f"run {tr['run_id']}: step {tr['step_latency_ns']:,}ns, "
              f"device skew {tr['device_skew_ns']:,}ns")
        rows = [[d, v["compute_ns"], v["collective_ns"], v["n_spans"]]
                for d, v in sorted(tr["devices"].items())]
        print_table(["DEVICE", "COMPUTE_NS", "COLLECTIVE_NS", "SPANS"],
                    rows)
        for g in tr["collectives"]:
            print(f"  {g['collective']} {g['hlo_op']}: "
                  f"{g['latency_ns']:,}ns across "
                  f"{g['n_participants']} devices (skew {g['skew_ns']}ns)")
    elif args.cmd == "steps":
        body = {"limit": args.limit}
        if args.job:
            body["job"] = args.job
        if args.run_id is not None:
            body["run_id"] = args.run_id
        if args.critical_path is not None:
            body["step"] = args.critical_path
            out = _api(args.server, "/v1/tpu/steps/critical_path", body)
            if args.json:
                print(json.dumps(out, indent=2))
                return 0
            r = out["result"]
            s, att = r["step"], r["attribution"]
            print(f"{s['job'] or '(job)'} run {s['run_id']} "
                  f"step {s['step']}: {s['latency_ns']:,}ns "
                  f"(baseline {att['baseline_latency_ns']:,}ns over "
                  f"{att['baseline_steps']} healthy steps)")
            print(f"verdict: {att['verdict']}  straggler: "
                  f"{att['straggler_host'] or '?'}:"
                  f"TPU{att['straggler_device']} "
                  f"(+{att['straggler_lag_ns']:,}ns)")
            print_table(
                ["COMPONENT", "NS", "BASELINE_NS", "DELTA_NS"],
                [[k, att["components_ns"][k],
                  att["baseline_components_ns"][k],
                  att["component_deltas_ns"][k]]
                 for k in att["components_ns"]])
            if att["dominant_hlos"]:
                print("\ndominant HLOs by delta vs baseline:")
                print_table(
                    ["HLO_OP", "SELF_NS", "BASELINE_NS", "DELTA_NS"],
                    [[h["hlo_op"], h["self_ns"], h["baseline_ns"],
                      h["delta_ns"]] for h in att["dominant_hlos"]])
            return 0
        out = _api(args.server, "/v1/tpu/steps", body)
        if args.json:
            print(json.dumps(out, indent=2))
            return 0
        steps = out["result"]["steps"]
        if not steps:
            print("(no step records)")
            return 0
        # sparkline scaled to the window's max latency
        blocks = "▁▂▃▄▅▆▇█"
        peak = max(s["latency_ns"] for s in steps) or 1
        rows = []
        for s in steps:
            spark = blocks[min(len(blocks) - 1,
                               int(len(blocks) * s["latency_ns"] / peak))]
            rows.append([
                s["job"], s["run_id"], s["step"],
                f"{s['latency_ns']:,}", spark,
                f"{s['device_skew_ns']:,}", f"{s['collective_ns']:,}",
                s["device_count"], len(s.get("hosts", [])),
                s["verdict"] if s["regressed"] else "ok"])
        print_table(
            ["JOB", "RUN", "STEP", "LATENCY_NS", "", "SKEW_NS",
             "WAIT_NS", "DEVS", "HOSTS", "VERDICT"], rows)
        regressed = [s for s in steps if s["regressed"]]
        for s in regressed:
            att = s["attribution"]
            top = att["dominant_hlos"][0] if att["dominant_hlos"] else None
            print(f"\nstep {s['step']} (run {s['run_id']}) REGRESSED: "
                  f"{att['verdict']} — straggler "
                  f"{att['straggler_host'] or '?'}:"
                  f"TPU{att['straggler_device']} "
                  f"(+{att['straggler_lag_ns']:,}ns)"
                  + (f", dominant HLO {top['hlo_op']} "
                     f"(+{top['delta_ns']:,}ns)" if top else ""))
        fed = out.get("federation")
        if fed:
            print(f"\n(federated over {fed['shards']} shards"
                  + (f", MISSING {fed['missing_shards']}"
                     if fed.get("missing_shards") else "") + ")")
    elif args.cmd == "agent-group-config":
        with open(args.file) as f:
            yaml_text = f.read()
        out = _api(args.server, "/v1/agent-group-config",
                   {"group": args.group, "yaml": yaml_text})
        print(f"group {out['group']} -> version {out['version']}")
    elif args.cmd == "query":
        body = {"db": args.db, "sql": args.sql}
        if args.org is not None:
            body["org_id"] = args.org
        out = _api(args.server, "/v1/query/", body)
        r = out["result"]
        print_table(r["columns"], r["values"])
    elif args.cmd == "datasources":
        h = _api(args.server, "/v1/health")
        st = h.get("storage")
        if st is None:
            print("(storage tier disabled — start the server with "
                  "--storage)")
            return 0
        if args.json:
            print(json.dumps(st, indent=2))
            return 0
        print(f"root: {st['root']}  flush_gen: {st['flush_gen']}  "
              f"evict_gen: {st['evict_gen']}  "
              f"gate_pending: {st.get('gate_pending', 0)}")
        tables = st.get("tables", {})
        if tables:
            # tier = trailing datasource suffix; everything else is a
            # raw event table (flow logs, profiles, ...)
            tiers = ("1s", "1m", "1h", "1d")
            rows = []
            for name, v in sorted(tables.items()):
                sfx = name.rsplit(".", 1)[-1]
                row = [
                    name, sfx if sfx in tiers else "raw",
                    v["segments"], v["rows"], v["bytes"],
                    v["tmin"] if v["tmin"] is not None else "-",
                    v["tmax"] if v["tmax"] is not None else "-"]
                if args.zones:
                    # pre-zone-map segments stay readable but never
                    # prune; the ratio shows rewrite progress
                    row.append(f"{v.get('zoned_segments', 0)}"
                               f"/{v['segments']}")
                rows.append(row)
            hdr = ["TABLE", "TIER", "SEGMENTS", "ROWS", "BYTES",
                   "TMIN", "TMAX"]
            if args.zones:
                hdr.append("ZONES")
            print()
            print_table(hdr, rows)
        else:
            print("(no segments on disk yet)")
        horizons = st.get("rollup_horizons", {})
        if horizons:
            print("\nrollup completeness horizons (exclusive, epoch s):")
            print_table(["DATASOURCE", "COMPLETE_BEFORE"],
                        [[k, v] for k, v in sorted(horizons.items())])
    elif args.cmd == "segments":
        path = "/v1/segments"
        q = []
        if args.table:
            q.append(f"table={args.table}")
        if args.v1:
            q.append("v1=1")
        if q:
            path += "?" + "&".join(q)
        out = _api(args.server, path)
        if not out.get("storage"):
            print("(storage tier disabled — start the server with "
                  "--storage)")
            return 0
        if args.json:
            print(json.dumps(out, indent=2))
            return 0
        rows = []
        for name, segs in sorted(out.get("tables", {}).items()):
            for s in segs:
                codecs = s.get("codecs", {})
                # codec histogram beats per-column spam at a glance
                counts: dict[str, int] = {}
                for c in codecs.values():
                    counts[c] = counts.get(c, 0) + 1
                codec_s = ",".join(f"{k}:{v}" for k, v
                                   in sorted(counts.items()))
                idx = s.get("indexed_cols", [])
                rows.append([
                    name, s["file"], f"v{s['format']}",
                    s["rows"], s["bytes"],
                    s["run"] if s["run"] is not None else "-",
                    s.get("sorted_by") or "-",
                    s.get("zoned_cols", 0),
                    ",".join(idx) if idx else "-",
                    codec_s or "-"])
        print_table(["TABLE", "SEGMENT", "FMT", "ROWS", "BYTES", "RUN",
                     "SORTED_BY", "ZONES", "INDEXED", "CODECS"], rows)
        print(f"\ncompact_gen: {out.get('compact_gen', 0)}")
    elif args.cmd == "fsck":
        path = "/v1/fsck"
        q = []
        if args.table:
            q.append(f"table={args.table}")
        if args.no_repair:
            q.append("repair=0")
        if q:
            path += "?" + "&".join(q)
        out = _api(args.server, path)
        if not out.get("storage"):
            print("(storage tier disabled — start the server with "
                  "--storage)")
            return 0
        if args.json:
            print(json.dumps(out, indent=2))
            return 0
        rows = []
        for name, t in sorted(out.get("tables", {}).items()):
            q_info = t.get("quarantined") or {}
            rows.append([
                name, t["segments"], t["clean"], t["unverifiable"],
                len(t["corrupt"]), len(t["repaired"]),
                len(t["repair_failed"]), len(q_info),
                t["blocks_checked"], t["bytes"]])
        print_table(["TABLE", "SEGS", "CLEAN", "UNVERIF", "CORRUPT",
                     "REPAIRED", "REPAIR_FAIL", "QUARANTINED",
                     "BLOCKS", "BYTES"], rows)
        for name, t in sorted(out.get("tables", {}).items()):
            for c in t["corrupt"]:
                print(f"  corrupt: {name}/{c['file']} "
                      f"blocks={','.join(c['blocks'])}")
            for fn, info in sorted((t.get("quarantined") or {}).items()):
                print(f"  quarantined: {name}/{fn} "
                      f"reason={info.get('reason', '?')} "
                      f"rows={info.get('rows', 0)}")
        print(f"\nfsck: {'OK' if out.get('ok') else 'DEGRADED'}")
        return 0 if out.get("ok") else 1
    elif args.cmd == "readtier":
        h = _api(args.server, "/v1/health")
        rt = h.get("readtier")
        if rt is None:
            print("(no read tier — this server is not a "
                  "--role=querier replica)")
            return 0
        if args.json:
            print(json.dumps({"readtier": rt,
                              "partial_cache": h.get("partial_cache"),
                              "query_cache": h.get("query_cache")},
                             indent=2))
            return 0
        adopted = rt.get("adopted", {})
        print("adopted manifests (ingest shard -> publish gen): "
              + (", ".join(f"{s}->{g}" for s, g
                           in sorted(adopted.items())) or "(none)"))
        print_table(
            ["TABLE", "SEGMENTS", "ROWS", "BYTES", "PUB_TOKEN"],
            [[name, t["segments"], t["rows"], t["bytes"],
              (t.get("pub_token") or "-")[:12]]
             for name, t in sorted(rt.get("tables", {}).items())])
        sc = rt.get("segcache", {})
        print(f"\nsegment cache ({sc.get('segments', 0)} segments, "
              f"{sc.get('bytes', 0)}/{sc.get('max_bytes', 0)} bytes):")
        print_table(
            ["HITS", "MISSES", "FETCH_ERRS", "EVICTIONS",
             "ROWS_EVICTED", "DEFERRED_UNLINKS"],
            [[sc.get("hits", 0), sc.get("misses", 0),
              sc.get("fetch_errors", 0), sc.get("evictions", 0),
              sc.get("rows_evicted", 0),
              sc.get("deferred_unlinks", 0)]])
        pc = h.get("partial_cache") or {}
        qc = h.get("query_cache") or {}
        if pc:
            print("\ndistributed partial cache:")
            print_table(
                ["DIST_HITS", "FETCHES", "FETCHED_BKTS", "SERVED_BKTS",
                 "FETCH_ERRS", "REMAP_FAILS", "ADVERTISED"],
                [[qc.get("dist_hits", 0), pc.get("fetches", 0),
                  pc.get("fetched_buckets", 0),
                  pc.get("served_buckets", 0),
                  pc.get("fetch_errors", 0),
                  pc.get("remap_failures", 0),
                  pc.get("advertised", 0)]])
    elif args.cmd == "flame":
        body = {"event_type": args.event_type}
        if args.service:
            body["app_service"] = args.service
        out = _api(args.server, "/v1/profile/ProfileTracing", body)
        print_flame(out["result"])
    elif args.cmd == "tpu-flame":
        body = {}
        if args.device is not None:
            body["device_id"] = args.device
        if args.include_host:
            body["include_host"] = True
        out = _api(args.server, "/v1/profile/TpuFlame", body)
        print_flame(out["result"])
    elif args.cmd == "org":
        body = {"action": "list"}
        if args.assign:
            try:
                org_id = int(args.assign[1])
            except ValueError:
                raise SystemExit(
                    f"org: ORG_ID must be an integer, got "
                    f"{args.assign[1]!r}")
            body = {"action": "assign", "group": args.assign[0],
                    "org_id": org_id}
        out = _api(args.server, "/v1/orgs", body)
        rows = sorted(out["orgs"].items())
        print_table(["GROUP", "ORG_ID"],
                    [[g, o] for g, o in rows] or
                    [["(all groups)", out["default_org"]]])
    elif args.cmd == "qos":
        body = {"action": "list"}
        if args.set:
            if len(args.set) < 2:
                raise SystemExit(
                    "qos: --set takes ORG_ID then one or more KEY=VAL")
            org_raw, kvs = args.set[0], args.set[1:]
            try:
                org_id = int(org_raw)
            except ValueError:
                raise SystemExit(
                    f"qos: ORG_ID must be an integer, got {org_raw!r}")
            body = {"action": "set", "org_id": org_id}
            for kv in kvs:
                key, sep, val = kv.partition("=")
                if not sep or key not in ("weight", "rate_fps", "burst"):
                    raise SystemExit(
                        "qos: --set takes weight=N | rate_fps=F | burst=F")
                try:
                    body[key] = float(val)
                except ValueError:
                    raise SystemExit(
                        f"qos: {key} must be a number, got {val!r}")
        out = _api(args.server, "/v1/qos", body)
        if args.json:
            print(json.dumps(out, indent=2))
            return 0
        if not out.get("enabled"):
            print("(qos disabled — DF_NO_QOS set, enabled: false, or "
                  "a --role=querier replica)")
            return 0
        pressure = out.get("pressure", {})
        levels = pressure.get("levels", {})
        sampling = out.get("sampling", {})
        rows = []
        for org, t in sorted(out.get("tenants", {}).items(),
                             key=lambda kv_: int(kv_[0])):
            s = sampling.get(str(org), {})
            d = t.get("depth", {})
            rows.append([
                org, t.get("weight", 1), t.get("rate_fps", 0) or "-",
                levels.get(str(org), 0),
                f"{s.get('rate', 1.0):.2f}",
                t.get("admitted", 0), t.get("delivered", 0),
                t.get("shed_quota", 0), t.get("shed_queue_full", 0),
                f"{d.get('high', 0)}/{d.get('mid', 0)}/{d.get('low', 0)}",
            ])
        print(f"global pressure level: "
              f"{pressure.get('global_level', 0)}")
        print_table(["ORG", "WEIGHT", "RATE_FPS", "LEVEL", "SAMPLE",
                     "ADMITTED", "DELIVERED", "SHED_QUOTA",
                     "SHED_QFULL", "DEPTH H/M/L"],
                    rows or [["(no tenant traffic yet)"] + [""] * 9])
    elif args.cmd == "repo":
        if args.action == "upload":
            if not args.file or not args.version:
                raise SystemExit("repo upload needs FILE and --version")
            import base64
            with open(args.file, "rb") as f:
                data_b64 = base64.b64encode(f.read()).decode()
            out = _api(args.server, "/v1/repo",
                       {"action": "upload", "name": args.name,
                        "version": args.version, "data_b64": data_b64},
                       token=token)
            u = out["uploaded"]
            print(f"uploaded {u['name']}@{u['version']} "
                  f"({u['size']:,}B sha256={u['sha256'][:12]}...)")
        else:
            out = _api(args.server, "/v1/repo", {"action": "list"})
            rows = [[n, v["version"], v["size"], v["sha256"][:12]]
                    for n, vs in out["packages"].items() for v in vs]
            print_table(["NAME", "VERSION", "SIZE", "SHA256"], rows)
    elif args.cmd == "promql":
        from urllib.parse import quote
        import time as _time
        if (args.start is None) != (args.end is None):
            raise SystemExit(
                "promql: --start and --end must be given together "
                "(a range query needs both bounds)")
        org_q = f"&org_id={args.org}" if args.org is not None else ""
        if args.start is not None and args.end is not None:
            url = (f"/prom/api/v1/query_range?query={quote(args.expr)}"
                   f"&start={args.start}&end={args.end}&step={args.step}"
                   f"{org_q}")
            out = _api(args.server, url)
            if out.get("status") != "success":
                raise SystemExit(f"promql: {out.get('error')}")
            for s in out["data"]["result"]:
                print(json.dumps(s["metric"]))
                for t, v in s["values"]:
                    print(f"  {t}  {v}")
        else:
            t = args.time if args.time is not None else int(_time.time())
            url = (f"/prom/api/v1/query?query={quote(args.expr)}"
                   f"&time={t}{org_q}")
            out = _api(args.server, url)
            if out.get("status") != "success":
                raise SystemExit(f"promql: {out.get('error')}")
            data = out["data"]
            if data["resultType"] == "scalar":
                print(data["result"][1])
            else:
                rows = [[json.dumps(s["metric"]), s["value"][1]]
                        for s in data["result"]]
                print_table(["SERIES", "VALUE"], rows)
    elif args.cmd == "trace-search":
        from urllib.parse import urlencode
        q = {"limit": args.limit}
        if args.tags:
            q["tags"] = args.tags
        if args.min_duration:
            q["minDuration"] = args.min_duration
        if args.max_duration:
            q["maxDuration"] = args.max_duration
        if args.start is not None:
            q["start"] = args.start
        if args.end is not None:
            q["end"] = args.end
        out = _api(args.server, f"/api/search?{urlencode(q)}")
        rows = [[t["traceID"], t["rootServiceName"], t["rootTraceName"],
                 t["durationMs"], t["startTimeUnixNano"]]
                for t in out["traces"]]
        print_table(["TRACE_ID", "SERVICE", "NAME", "MS", "START_NS"], rows)
    elif args.cmd == "explain":
        sql = args.sql.strip()
        if sql[:7].upper() != "EXPLAIN":
            kw = "EXPLAIN" if args.no_analyze else "EXPLAIN ANALYZE"
            sql = f"{kw} {sql}"
        out = _api(args.server, "/v1/query/", {"db": args.db, "sql": sql})
        ex = out.get("explain")
        if ex is None:
            raise SystemExit("server returned no explain block "
                             "(old server?)")
        if args.json:
            print(json.dumps(ex, indent=2))
            return 0
        plan = ex.get("plan", {})
        print(f"trace_id: {ex.get('trace_id', '')}")
        for k in sorted(plan):
            print(f"  {k}: {plan[k]}")
        r = out["result"]
        print_table(r["columns"], r["values"])
        if ex.get("analyze"):
            print(f"total: {ex.get('total_ms', 0):.3f}ms over "
                  f"{ex.get('spans', 0)} spans")
    elif args.cmd == "query-trace":
        out = _api(args.server, "/v1/trace/Tracing",
                   {"trace_id": args.trace_id})
        tree = out["result"]
        if not tree["spans"]:
            raise SystemExit(f"no spans for trace {args.trace_id}")
        if args.flame:
            from deepflow_tpu.query.flamegraph import (build_flame_tree,
                                                       trace_flame_stacks)
            stacks, values = trace_flame_stacks(tree)
            print_flame(build_flame_tree(
                stacks, values, root_name=args.trace_id).to_dict())
            return 0
        t0 = min(int(s["start_ns"]) for s in tree["spans"])
        t1 = max(int(s["end_ns"]) for s in tree["spans"])
        total = max(1, t1 - t0)
        width = 40
        print(f"trace {tree['trace_id']}: {tree['span_count']} spans, "
              f"{total / 1e6:.2f}ms")

        def waterfall(node, depth=0):
            off = int(node["start_ns"]) - t0
            lead = min(width - 1, int(width * off / total))
            w = max(1, int(width * int(node["duration_ns"]) / total))
            bar = " " * lead + "▇" * min(w, width - lead)
            label = "  " * depth + node["name"]
            print(f"{label:<34.34} {node['duration_ns'] / 1e6:>9.3f}ms "
                  f"|{bar:<{width}}| {node['service']} {node['status']}")
            for c in node["children"]:
                waterfall(c, depth + 1)

        for root in sorted(tree["spans"], key=lambda s: s["start_ns"]):
            waterfall(root)
    elif args.cmd == "trace":
        out = _api(args.server, "/v1/trace/Tracing",
                   {"trace_id": args.trace_id})
        tree = out["result"]
        print(f"trace {tree['trace_id']}: {tree['span_count']} spans")

        def show(node, depth=0):
            dur_ms = node["duration_ns"] / 1e6
            mark = "◆" if node["kind"] == "device" else "●"
            print(f"{'  ' * depth}{mark} {node['name']}  "
                  f"[{node['service']}] {dur_ms:.2f}ms {node['status']}")
            for c in node["children"]:
                show(c, depth + 1)
        for root in tree["spans"]:
            show(root)
    elif args.cmd == "alert":
        if args.action == "list":
            out = _api(args.server, "/v1/alerts")
            rows = [[r["name"], r["severity"], r["op"], r["threshold"],
                     r["firing"], r["last_value"]] for r in out["rules"]]
            print_table(["NAME", "SEVERITY", "OP", "THRESHOLD", "FIRING",
                         "LAST"], rows)
        elif args.action == "set":
            spec = _load_json_arg(args.spec)
            out = _api(args.server, "/v1/alerts", spec)
            print(f"rule {out['rule']['name']} saved")
        else:
            if not args.spec:
                raise SystemExit("usage: dfctl alert delete <name>")
            out = _api(args.server, "/v1/alerts/delete",
                       {"name": args.spec})
            print(f"deleted: {out['deleted']}")
    elif args.cmd == "watch":
        reg = _api(args.server, "/v1/subscribe",
                   {"action": "register", "sql": args.sql,
                    "name": args.name, "table": args.table,
                    "window_s": args.window,
                    "org_id": args.org})["registered"]
        qname = reg["name"]
        sub_out = _api(args.server, "/v1/subscribe",
                       {"action": "subscribe", "queries": [qname]})
        sid = sub_out["subscriber"]
        print(f"watching {qname} on {reg['table']} "
              f"(window {reg['window_s'] or '-'}s, subscriber {sid}) "
              f"— ^C to stop")
        seen = 0
        try:
            for u in _subscribe_updates(args.server, sid,
                                        use_poll=args.poll):
                if u.get("query") != qname:
                    continue
                d = u.get("delta") or {}
                print(f"\n== gen {u['gen']}  mode={u['mode']}  "
                      f"refresh {u.get('refresh_ms', 0)}ms  "
                      f"(+{len(d.get('added', []))} "
                      f"-{len(d.get('removed', []))} rows)")
                print_table(u["columns"], u["rows"])
                seen += 1
                if args.count and seen >= args.count:
                    break
        except KeyboardInterrupt:
            pass
        finally:
            try:
                _api(args.server, "/v1/subscribe",
                     {"action": "unsubscribe", "subscriber": sid})
                if not args.keep:
                    _api(args.server, "/v1/subscribe",
                         {"action": "unregister", "name": qname})
            except SystemExit:
                pass
    elif args.cmd == "events":
        ev_sql = ("SELECT time, event_type, resource_type, "
                  "resource_name, description FROM event")
        if args.type:
            safe = args.type.replace("'", "")
            ev_sql += f" WHERE event_type = '{safe}'"
        if not args.follow:
            out = _api(args.server, "/v1/query/",
                       {"db": "event",
                        "sql": ev_sql + f" ORDER BY time DESC "
                                        f"LIMIT {args.limit}"})
            r = out["result"]
            print_table(r["columns"], r["values"])
            return 0
        reg = _api(args.server, "/v1/subscribe",
                   {"action": "register", "sql": ev_sql,
                    "table": "event.event"})["registered"]
        sub_out = _api(args.server, "/v1/subscribe",
                       {"action": "subscribe",
                        "queries": [reg["name"]]})
        sid = sub_out["subscriber"]
        print(f"following event.event (subscriber {sid}) — ^C to stop")
        first = True
        seen = 0
        try:
            for u in _subscribe_updates(args.server, sid,
                                        use_poll=args.poll):
                added = (u.get("delta") or {}).get("added", [])
                if first:
                    # baseline snapshot: show the tail, then deltas only
                    added = sorted(added)[-args.limit:]
                    first = False
                for row in added:
                    print("  ".join(str(v) for v in row))
                seen += len(added)
                if args.count and seen >= args.count:
                    break
        except KeyboardInterrupt:
            pass
        finally:
            try:
                _api(args.server, "/v1/subscribe",
                     {"action": "unsubscribe", "subscriber": sid})
            except SystemExit:
                pass
    elif args.cmd == "exporter":
        if args.action == "list":
            out = _api(args.server, "/v1/exporters")
            for name, st in out["exporters"].items():
                print(name, st)
            if not out["exporters"]:
                print("(none)")
        elif args.action == "add":
            spec = _load_json_arg(args.spec)
            out = _api(args.server, "/v1/exporters", spec)
            print(f"added {out['added']} -> {out['endpoint']}")
        else:
            if not args.spec:
                raise SystemExit("usage: dfctl exporter delete <endpoint>")
            out = _api(args.server, "/v1/exporters/delete",
                       {"endpoint": args.spec})
            print(f"removed: {out['removed']}")
    elif args.cmd == "replay":
        from deepflow_tpu.agent.dispatcher import Dispatcher
        from deepflow_tpu.agent.sender import UniformSender
        sender = UniformSender([args.ingest]).start()
        disp = Dispatcher(sender=sender)
        n = disp.replay_pcap(args.pcap)
        sender.flush_and_stop()
        print(f"replayed {n} packets: {disp.stats}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
