"""storage-check: durable-write SIGKILL e2e for the tiered store.

Proves the claim docs/STORAGE.md makes about `--storage`: an ack is a
durability receipt. Wired as `make storage-check`:

  1. a server subprocess starts with --storage on a fresh data_dir and
     a fast flush interval; a durable sender (disk spool + retransmit
     window) pumps a HIGH-priority STEP_METRICS stream into it
  2. once the ack watermark has advanced — with storage on, acks only
     release AFTER the manifest commit that makes the rows' segments
     durable — the server is SIGKILLed with frames still in flight:
     no decoder drain, no graceful persist, RAM tables gone
  3. more frames are sent into the dead port (they park in the window
     and the spool), then a server restarts on the same port+data_dir
  4. the check fails unless:
       * recovery found on-disk segments holding at least every frame
         acked before the kill (the durable prefix came from disk —
         acked frames were pruned from the retransmit window, so
         nothing else can supply them),
       * after the sender drains, EVERY frame sent landed EXACTLY once
         (pre-kill acked from segments, the rest replayed) — zero
         loss, zero dups: the persisted ack floors absorb retransmits
         of committed-but-unacked frames instead of double-ingesting,
       * a real SQL query over the recovered table returns the exact
         pre-kill data (count + step span), not a partial answer.

Contrast with chaos-check's hard-kill phase, which runs WITHOUT
--storage and asserts the opposite bound: there the acked-before-kill
prefix is exactly what dies. Same kill, same transport — the tier is
what turns the ack from a delivery receipt into a durability receipt.

A second phase then proves retention drops are observed, never silent:
everything is flushed to the tier and a janitor sweep with a 1s TTL
evicts the aged segments — the check fails unless every evicted row is
accounted in the storage hop ledger under reason ``segment_evict`` and
the tier actually shrank by the evicted rows.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

MS = 1_000_000
N_PRE = 150    # frames sent before the SIGKILL
N_POST = 80    # frames sent while the server is dead
TABLE = "profile.tpu_step_metrics"


def _fail(msg: str) -> None:
    print(f"storage-check: FAIL: {msg}")
    sys.exit(1)


def _step_payload(i: int) -> bytes:
    from deepflow_tpu.tpuprobe.stepmetrics import encode_step_payload
    return encode_step_payload([{
        "time": i * MS, "end_ns": i * MS + 500, "latency_ns": 500,
        "run_id": 3, "step": i, "job": "storage", "device_count": 4,
        "device_skew_ns": 0, "compute_ns": 1, "collective_ns": 1,
        "straggler_device": 0, "straggler_lag_ns": 0, "top_hlos": []}])


def _check_ledgers(telemetry, who: str) -> None:
    for h in telemetry.snapshot()["pipeline"]:
        if h["emitted"] != h["delivered"] + h["dropped_total"] \
                + h["in_flight"]:
            _fail(f"{who} hop {h['hop']!r} ledger does not balance: {h}")


def _eviction_phase(server) -> None:
    """Flush everything to the tier, then TTL-evict it: every dropped
    row must surface in the storage hop ledger under segment_evict."""
    from deepflow_tpu.server.janitor import Janitor

    server.flusher.flush_once(seal=True)
    snap = server.db.tier_store.snapshot()
    before = snap["tables"].get(TABLE, {}).get("rows", 0)
    if before <= 0:
        _fail(f"eviction: nothing on the tier for {TABLE} after a "
              f"forced flush (snapshot: {snap['tables']})")

    ledger0 = server.telemetry.hop("storage").snapshot()
    drop0 = ledger0["dropped"].get("segment_evict", 0)
    jan = Janitor(server.db, ttl_s={TABLE: 1},
                  telemetry=server.telemetry)
    # step timestamps sit near the epoch, so any real `now` ages every
    # segment past the 1s TTL — the sweep must evict the whole table
    evicted = jan.sweep_tier(now=time.time())
    if evicted != before:
        _fail(f"eviction: TTL sweep evicted {evicted} rows, tier held "
              f"{before} (janitor stats: {jan.stats})")
    after = server.db.tier_store.snapshot()["tables"] \
        .get(TABLE, {}).get("rows", 0)
    if after != 0:
        _fail(f"eviction: {after} rows remain on the tier after the "
              f"sweep that reported evicting all {before}")
    ledger = server.telemetry.hop("storage").snapshot()
    dropped = ledger["dropped"].get("segment_evict", 0) - drop0
    if dropped != evicted:
        _fail(f"eviction: ledger records {dropped} segment_evict drops "
              f"for {evicted} evicted rows — drops went silent "
              f"(ledger: {ledger})")
    print(f"storage-check: eviction OK — {evicted} rows TTL-evicted, "
          f"every one ledgered under segment_evict")


def main() -> int:
    from deepflow_tpu.agent.sender import UniformSender
    from deepflow_tpu.agent.spool import Spool
    from deepflow_tpu.codec import MessageType
    from deepflow_tpu.server import Server
    from deepflow_tpu.telemetry import Telemetry

    data_dir = tempfile.mkdtemp(prefix="df-storage-data-")
    spool_dir = tempfile.mkdtemp(prefix="df-storage-spool-")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    log = open(os.path.join(data_dir, "server.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "deepflow_tpu.server.server",
         "--host", "127.0.0.1", "--query-host", "127.0.0.1",
         "--ingest-port", str(port), "--query-port", "0",
         "--sync-port", "0", "--no-controller", "--data-dir", data_dir,
         "--storage", "--flush-interval-s", "0.2"],
        stdout=log, stderr=subprocess.STDOUT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    deadline = time.time() + 30.0
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.5).close()
            break
        except OSError:
            time.sleep(0.2)
    else:
        _fail("subprocess server never listened")

    telemetry = Telemetry("agent", enabled=True)
    sender = UniformSender(
        [("127.0.0.1", port)], agent_id=13, telemetry=telemetry,
        spool=Spool(spool_dir)).start()
    server = None
    try:
        for i in range(1, N_PRE + 1):
            sender.send(MessageType.STEP_METRICS, _step_payload(i))
            time.sleep(0.002)
        # wait for the ack watermark to move — with --storage that means
        # at least one flush cycle committed a manifest — but NOT for
        # the full stream to drain: the kill lands with frames in flight
        deadline = time.time() + 20.0
        while time.time() < deadline and \
                sender.stats["acked_seq"] <= sender.seq_base:
            time.sleep(0.05)
        if sender.stats["acked_seq"] <= sender.seq_base:
            _fail("ack watermark never advanced — no durable commit "
                  "happened before the kill window")

        proc.send_signal(signal.SIGKILL)   # no drain, no persist
        proc.wait(timeout=10)
        time.sleep(0.3)  # let the ack channel settle: watermark final
        acked_kill = sender.stats["acked_seq"] - sender.seq_base
        if not 0 < acked_kill <= N_PRE:
            _fail(f"acked watermark {acked_kill} outside (0, {N_PRE}] — "
                  f"scenario did not exercise the durable prefix")
        print(f"storage-check: SIGKILL at acked={acked_kill}/{N_PRE}")

        for i in range(N_PRE + 1, N_PRE + N_POST + 1):
            sender.send(MessageType.STEP_METRICS, _step_payload(i))
            time.sleep(0.002)

        # restart on the same port + data_dir: recovery must re-open
        # the committed segments and re-seed the ack floors
        server = Server(host="127.0.0.1", ingest_port=port,
                        query_port=0, data_dir=data_dir,
                        storage=True, flush_interval_s=0.2).start()
        snap = server.db.tier_store.snapshot()["tables"].get(TABLE, {})
        if snap.get("rows", 0) < acked_kill:
            _fail(f"recovery found {snap.get('rows', 0)} durable rows "
                  f"on disk, but {acked_kill} frames were acked before "
                  f"the kill — acks outran the manifest commit")
        print(f"storage-check: recovered {snap.get('rows', 0)} rows in "
              f"{snap.get('segments', 0)} segments from disk")

        sender.flush_and_stop(timeout=60.0)
        total = N_PRE + N_POST
        if not server.wait_for_rows(TABLE, total, timeout=30.0):
            got = len(server.db.table(TABLE))
            _fail(f"loss after recovery: {got}/{total} rows "
                  f"(sender stats: {sender.stats})")
        time.sleep(0.5)  # let any straggler dups land before counting
        table = server.db.table(TABLE)
        table.flush()
        cols = table.column_concat(["step"])
        steps = cols["step"].tolist() if len(table) else []
        if len(steps) != len(set(steps)):
            _fail(f"duplicate rows after SIGKILL recovery: {len(steps)} "
                  f"rows, {len(set(steps))} unique — persisted ack "
                  f"floors failed to absorb a retransmit")
        missing = set(range(1, total + 1)) - set(steps)
        if missing:
            _fail(f"missing steps after recovery: {sorted(missing)} — "
                  f"acked-durable rows or spooled replays were lost")

        # the exact query the durability claim is about: full SQL path
        # (parse → datasource selection → encoded execute) over a table
        # whose prefix now lives in mmap'd segments
        res = server.api.query({
            "sql": f"SELECT Count(step) AS n, Min(step) AS lo, "
                   f"Max(step) AS hi FROM {TABLE}"})["result"]
        if res["values"] != [[total, 1.0, float(total)]] and \
                res["values"] != [[total, 1, total]]:
            _fail(f"exact query over recovered data diverged: "
                  f"{res['values']} != [[{total}, 1, {total}]]")
        _check_ledgers(telemetry, "sender")
        print(f"storage-check: durability OK — all {total} frames "
              f"exactly once across a SIGKILL ({acked_kill} served "
              f"from disk segments, {total - acked_kill} replayed)")

        _eviction_phase(server)
        return 0
    finally:
        sender.flush_and_stop(timeout=1.0)
        if server is not None:
            server.stop()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        log.close()


if __name__ == "__main__":
    sys.exit(main())
