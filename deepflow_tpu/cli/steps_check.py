"""steps-check: brief e2e run proving the step-health pipeline works.

Builds a synthetic 4-device pod, pushes eight healthy training steps plus
one step where a single device runs its dominant fusion 2x slower, and
drives the records through the REAL pipeline: agent-side StepAggregator
-> STEP_METRICS frames over the wire -> StepMetricsDecoder ->
profile.tpu_step_metrics -> StepRegressionDetector. Fails (exit 1) if:

  * the step records don't all land in the columnar table,
  * the detector does not fire exactly one `step_regression` alert,
  * the attribution does not name the injected straggler device and its
    dominant HLO, or
  * the /v1/tpu/steps timeline disagrees with the alert.

Wired as `make steps-check` — cheap enough for CI, real enough to catch
a decoder that drops fields or a detector that fires on healthy noise.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

N_DEVICES = 4
SLOW_DEVICE = 2
HEALTHY_STEPS = 8
JOB = "jit_check_train_step"
MS = 1_000_000


def _fail(msg: str) -> None:
    print(f"steps-check: FAIL: {msg}")
    sys.exit(1)


def _step_events(run_id: int, slow: bool = False) -> list:
    """One synthetic step: every device runs fusion.1 then all-reduce.1
    in parallel; the slow variant doubles SLOW_DEVICE's fusion time."""
    from deepflow_tpu.tpuprobe.events import TpuSpanEvent
    t0 = run_id * 10 * MS
    events = []
    for dev in range(N_DEVICES):
        fuse = 2 * MS * (2 if slow and dev == SLOW_DEVICE else 1)
        events.append(TpuSpanEvent(
            start_ns=t0, duration_ns=fuse, device_id=dev,
            hlo_module=JOB, hlo_op="fusion.1",
            hlo_category="convolution fusion", run_id=run_id,
            step=run_id))
        events.append(TpuSpanEvent(
            start_ns=t0 + fuse, duration_ns=900_000, device_id=dev,
            hlo_module=JOB, hlo_op="all-reduce.1",
            hlo_category="all-reduce", run_id=run_id, step=run_id,
            collective="all-reduce"))
    return events


def main() -> int:
    from deepflow_tpu.agent.agent import Agent
    from deepflow_tpu.agent.config import AgentConfig
    from deepflow_tpu.server import Server
    from deepflow_tpu.tpuprobe.stepmetrics import (StepAggregator,
                                                   encode_step_payload)

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    agent = None
    try:
        cfg = AgentConfig()
        cfg.app_service = "steps-check"
        cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
        cfg.profiler.enabled = False
        cfg.tpuprobe.enabled = False
        agent = Agent(cfg).start()

        sent = {"n": 0}

        def ship(records: list) -> None:
            if not agent.send_step_metrics(
                    encode_step_payload(records, pid=4242,
                                        process_name="steps-check")):
                _fail("agent send queue rejected a STEP_METRICS frame")
            sent["n"] += len(records)

        agg = StepAggregator(ship)
        for rid in range(1, HEALTHY_STEPS + 1):
            agg.feed(_step_events(rid))
        agg.feed(_step_events(HEALTHY_STEPS + 1, slow=True))
        agg.flush()
        n_steps = HEALTHY_STEPS + 1
        if sent["n"] != n_steps:
            _fail(f"aggregator emitted {sent['n']} records, "
                  f"wanted {n_steps}")
        agent.stop()
        agent = None

        if not server.wait_for_rows("profile.tpu_step_metrics", n_steps,
                                    timeout=10.0):
            rows = len(server.db.table("profile.tpu_step_metrics"))
            _fail(f"only {rows}/{n_steps} step records reached the "
                  "columnar table")

        # two passes: the first records per-step counts, the second sees
        # them stable (no trailing host partials) and scores everything
        server.step_detector.poll()
        alerts = [a for a in server.step_detector.poll()
                  if a["type"] == "alert"]
        if len(alerts) != 1:
            _fail(f"wanted exactly 1 step_regression alert, got "
                  f"{len(alerts)}: {alerts}")
        att = alerts[0]["attribution"]
        if att["straggler_device"] != SLOW_DEVICE:
            _fail(f"attribution blames device "
                  f"{att['straggler_device']}, injected {SLOW_DEVICE}")
        if att["verdict"] not in ("skew", "compute"):
            _fail(f"verdict {att['verdict']!r} (slow device should read "
                  "as skew or compute)")
        dom = att["dominant_hlos"]
        if not dom or dom[0]["hlo_op"] != "fusion.1":
            _fail(f"dominant HLO should be the slowed fusion.1, got "
                  f"{dom[:1]}")

        # the timeline a human reads must agree with the alert
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.query_port}/v1/tpu/steps",
            data=json.dumps({"job": JOB}).encode())
        with urllib.request.urlopen(req, timeout=5) as resp:
            steps = json.loads(resp.read())["result"]["steps"]
        if len(steps) != n_steps:
            _fail(f"/v1/tpu/steps returned {len(steps)} steps, "
                  f"wanted {n_steps}")
        regressed = [s for s in steps if s["regressed"]]
        if [s["step"] for s in regressed] != [HEALTHY_STEPS + 1]:
            _fail(f"timeline regressions {[(s['step']) for s in regressed]}"
                  f" disagree with the alert (wanted [{HEALTHY_STEPS + 1}])")
        if regressed[0]["attribution"]["straggler_device"] != SLOW_DEVICE:
            _fail("timeline attribution disagrees with the alert")

        print(f"steps-check: OK — {n_steps} steps ingested, 1 regression "
              f"fired, straggler TPU{SLOW_DEVICE} named, dominant HLO "
              f"{dom[0]['hlo_op']} (+{dom[0]['delta_ns']:,}ns)")
        return 0
    finally:
        if agent is not None:
            agent.stop()
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
