"""deepflow-run: zero-code instrumentation launcher.

    python -m deepflow_tpu.cli.runner [--server H:P] [--service NAME] \
        script.py [args...]

Attaches the in-process agent (OnCPU sampler + TPU probe) before handing
control to the target script via runpy — the workload needs no code change.
Reference analog: the agent's zero-intrusion stance; in-process because TPU
workloads are long-lived Python processes and the xplane probe must live
inside them.
"""

from __future__ import annotations

import argparse
import runpy
import sys


def main() -> int:
    parser = argparse.ArgumentParser(prog="deepflow-run")
    parser.add_argument("--server", default="127.0.0.1:20033")
    parser.add_argument("--controller", default="")
    parser.add_argument("--service", default="")
    parser.add_argument("-m", dest="module", action="store_true",
                        help="run target as a module (python -m style)")
    parser.add_argument("--io-probe-ms", type=float, default=0.0,
                        help="with --ssl-probe: report file reads/writes "
                             "slower than this many ms as events")
    parser.add_argument("--ssl-probe", action="store_true",
                        help="pre-encryption L7 visibility: LD_PRELOAD the "
                             "ssl/syscall interposer into CHILD processes "
                             "this workload spawns (and configure the "
                             "in-process agent to receive its events)")
    parser.add_argument("--mem-profile", action="store_true",
                        help="allocation flame graphs: LD_PRELOAD the "
                             "sampling malloc interposer into CHILD "
                             "processes (reports land as mem-alloc "
                             "profile events)")
    parser.add_argument("target")
    parser.add_argument("args", nargs=argparse.REMAINDER)
    opts = parser.parse_args()

    sslprobe_sock = ""
    memhook_sock = ""
    if opts.ssl_probe or opts.mem_profile:
        import os
        import tempfile
        native_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "native")
        # private 0700 dir: a predictable /tmp name could be squatted
        sock_dir = tempfile.mkdtemp(prefix="dfprobe-")
        preloads = []
        if opts.ssl_probe:
            so = os.path.join(native_dir, "libdfsslprobe.so")
            if os.path.exists(so):
                sslprobe_sock = os.path.join(sock_dir, "probe.sock")
                preloads.append(so)
                os.environ["DF_SSLPROBE_SOCK"] = sslprobe_sock
                if opts.io_probe_ms > 0:
                    os.environ["DF_IOPROBE_NS"] = str(
                        int(opts.io_probe_ms * 1e6))
            else:
                print("deepflow-run: libdfsslprobe.so not built; "
                      "--ssl-probe disabled", file=sys.stderr)
        if opts.mem_profile:
            so = os.path.join(native_dir, "libdfmemhook.so")
            if os.path.exists(so):
                memhook_sock = os.path.join(sock_dir, "memhook.sock")
                preloads.append(so)
                os.environ["DF_MEMHOOK_SOCK"] = memhook_sock
            else:
                print("deepflow-run: libdfmemhook.so not built; "
                      "--mem-profile disabled", file=sys.stderr)
        if preloads:
            prior = os.environ.get("LD_PRELOAD", "")
            chain = ":".join(preloads)
            os.environ["LD_PRELOAD"] = (f"{chain}:{prior}" if prior
                                        else chain)

    from deepflow_tpu.agent.agent import attach, detach
    attach(app_service=opts.service or opts.target,
           servers=[opts.server], controller=opts.controller,
           sslprobe_sock=sslprobe_sock, memhook_sock=memhook_sock)

    sys.argv = [opts.target] + opts.args
    try:
        if opts.module:
            runpy.run_module(opts.target, run_name="__main__",
                             alter_sys=True)
        else:
            runpy.run_path(opts.target, run_name="__main__")
        return 0
    finally:
        detach()


if __name__ == "__main__":
    sys.exit(main())
