"""deepflow-run: zero-code instrumentation launcher.

    python -m deepflow_tpu.cli.runner [--server H:P] [--service NAME] \
        script.py [args...]

Attaches the in-process agent (OnCPU sampler + TPU probe) before handing
control to the target script via runpy — the workload needs no code change.
Reference analog: the agent's zero-intrusion stance; in-process because TPU
workloads are long-lived Python processes and the xplane probe must live
inside them.
"""

from __future__ import annotations

import argparse
import runpy
import sys


def main() -> int:
    parser = argparse.ArgumentParser(prog="deepflow-run")
    parser.add_argument("--server", default="127.0.0.1:20033")
    parser.add_argument("--controller", default="")
    parser.add_argument("--service", default="")
    parser.add_argument("-m", dest="module", action="store_true",
                        help="run target as a module (python -m style)")
    parser.add_argument("--io-probe-ms", type=float, default=0.0,
                        help="with --ssl-probe: report file reads/writes "
                             "slower than this many ms as events")
    parser.add_argument("--ssl-probe", action="store_true",
                        help="pre-encryption L7 visibility: LD_PRELOAD the "
                             "ssl/syscall interposer into CHILD processes "
                             "this workload spawns (and configure the "
                             "in-process agent to receive its events)")
    parser.add_argument("target")
    parser.add_argument("args", nargs=argparse.REMAINDER)
    opts = parser.parse_args()

    sslprobe_sock = ""
    if opts.ssl_probe:
        import os
        import tempfile
        so = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "native", "libdfsslprobe.so")
        if os.path.exists(so):
            # private 0700 dir: a predictable /tmp name could be squatted
            sslprobe_sock = os.path.join(
                tempfile.mkdtemp(prefix="dfprobe-"), "probe.sock")
            prior = os.environ.get("LD_PRELOAD", "")
            os.environ["LD_PRELOAD"] = f"{so}:{prior}" if prior else so
            os.environ["DF_SSLPROBE_SOCK"] = sslprobe_sock
            if opts.io_probe_ms > 0:
                os.environ["DF_IOPROBE_NS"] = str(
                    int(opts.io_probe_ms * 1e6))
        else:
            print("deepflow-run: libdfsslprobe.so not built; "
                  "--ssl-probe disabled", file=sys.stderr)

    from deepflow_tpu.agent.agent import attach, detach
    attach(app_service=opts.service or opts.target,
           servers=[opts.server], controller=opts.controller,
           sslprobe_sock=sslprobe_sock)

    sys.argv = [opts.target] + opts.args
    try:
        if opts.module:
            runpy.run_module(opts.target, run_name="__main__",
                             alter_sys=True)
        else:
            runpy.run_path(opts.target, run_name="__main__")
        return 0
    finally:
        detach()


if __name__ == "__main__":
    sys.exit(main())
