"""compaction-check: segment-format-v2 compaction gate.

Proves the claims docs/STORAGE.md makes about "Format v2". Wired as
`make compaction-check`:

  1. build a fragmented v1 tier: 200 small format-v1 segments
     (DF_SEG_FORMAT=1 pins the legacy writer) over several
     compaction time partitions, with high-cardinality trace_ids and
     repetitive service/body strings
  2. record golden answers (needle trace_id lookups, a GROUP BY
     aggregate, an ordered string predicate) and time the selective
     needle scans over the v1 tier
  3. chaos arms on COPIES of the v1 tier: a subprocess compaction is
     killed via DF_COMPACT_CRASH (os._exit) both after staging the new
     run files and after the manifest commit; each copy must reopen
     clean, answer the goldens byte-identically, and a re-compaction
     must converge to zero v1 segments — including in a child pinned
     to DF_SEG_FORMAT=1 (migrate-on-compact overrides the env pin)
  4. compact the main tier: every v1 segment must be replaced by
     sorted v2 runs (ledgered, counted), goldens must stay
     byte-identical, the selective scans must consult bloom filters
     (bloom_checked/bloom_pruned > 0) and run >= 3x faster, and the
     query.scan hop ledger must balance exactly (every candidate
     segment accounted scanned/pruned/bloom_pruned, none silently
     dropped)
"""

from __future__ import annotations

import logging
import os
import shutil
import subprocess
import sys
import tempfile
import time

N_SEGMENTS = 200
ROWS_PER_SEGMENT = 1000
# each trace id occurs twice: first-seen in the first half of the
# stream, repeated at a scrambled position in the second half. The
# repeat de-correlates dictionary ids from time, so the id zone maps
# cannot prune the later runs and needle lookups must consult blooms
# (spans of one trace arriving minutes apart is also just realistic).
N_UNIQUE = N_SEGMENTS * ROWS_PER_SEGMENT // 2
N_NEEDLES = 20
HOUR_NS = 3_600_000_000_000
SPEEDUP_TARGET = 3.0
TABLE = "application_log.log"
SERVICES = [f"svc-{i}" for i in range(10)]


def _fail(msg: str) -> None:
    print(f"compaction-check: FAIL: {msg}")
    sys.exit(1)


def _trace_id(i: int) -> str:
    # hash-first like a real trace id: collation zones overlap across
    # runs, so only the bloom index can prune a needle lookup
    return f"{i * 2654435761 % (1 << 32):08x}{i:08x}"


def _tid_of_row(i: int) -> str:
    if i < N_UNIQUE:
        return _trace_id(i)
    return _trace_id((i - N_UNIQUE) * 7919 % N_UNIQUE)


def _build_v1_tier(data_dir: str):
    """200 single-chunk flush commits, one v1 segment each, spread over
    6 compaction partitions (hours)."""
    from deepflow_tpu.store.db import Database
    os.environ["DF_SEG_FORMAT"] = "1"
    try:
        db = Database(data_dir=data_dir, storage=True,
                      chunk_rows=ROWS_PER_SEGMENT)
        t = db.table(TABLE)
        row_id = 0
        for s in range(N_SEGMENTS):
            rows = []
            for _ in range(ROWS_PER_SEGMENT):
                i = row_id
                row_id += 1
                rows.append({
                    "time": i * (6 * HOUR_NS
                                 // (N_SEGMENTS * ROWS_PER_SEGMENT)) + i,
                    "app_service": SERVICES[i % len(SERVICES)],
                    "app_instance": f"inst-{i % 7}",
                    "log_source": (i % 4) + 1,
                    "severity_number": (i % 24) + 1,
                    "severity_text": ("INFO", "WARN", "ERROR")[i % 3],
                    "body": f"request completed path=/api/v{i % 50}",
                    "trace_id": _tid_of_row(i),
                    "span_id": f"span-{i:06x}",
                    "attrs": "{}",
                })
            t.append_rows(rows)
            t.flush()
            db.flush_to_tier()
    finally:
        del os.environ["DF_SEG_FORMAT"]
    return db


def _goldens(db) -> list:
    """The golden query set. Returned as plain (columns, values) pairs
    so byte-identity is a straight == comparison."""
    from deepflow_tpu.query.engine import execute
    t = db.table(TABLE)
    out = []
    for k in range(N_NEEDLES):
        tid = _trace_id((k * (N_UNIQUE // N_NEEDLES) + 17) % N_UNIQUE)
        r = execute(t, "SELECT Count(*) AS c, Sum(severity_number) AS s "
                       f"FROM log WHERE trace_id = '{tid}'")
        out.append((r.columns, r.values))
    r = execute(t, "SELECT app_service, Count(*) AS c, "
                   "Sum(severity_number) AS s FROM log "
                   "GROUP BY app_service ORDER BY app_service")
    out.append((r.columns, r.values))
    r = execute(t, "SELECT Count(*) AS c FROM log "
                   "WHERE app_service >= 'svc-8' AND severity_number > 20")
    out.append((r.columns, r.values))
    r = execute(t, f"SELECT Count(*) AS c FROM log WHERE time >= "
                   f"{2 * HOUR_NS} AND time < {4 * HOUR_NS}")
    out.append((r.columns, r.values))
    return out


def _time_needles(db, rounds: int = 3) -> float:
    """Best-of-N wall time for the selective needle sweep."""
    from deepflow_tpu.query.engine import execute
    t = db.table(TABLE)
    needles = [_trace_id((j * 9973 + 41) % N_UNIQUE)
               for j in range(N_NEEDLES)]
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for tid in needles:
            execute(t, "SELECT Count(*) AS c, Max(time) AS mt "
                       f"FROM log WHERE trace_id = '{tid}'")
        best = min(best, time.perf_counter() - t0)
    return best


def _chaos_arm(src_dir: str, mode: str, golden: list,
               pin_v1: bool) -> None:
    """Kill a subprocess compaction at `mode`, then prove the copy
    reopens clean, answers exactly, and converges on re-compaction."""
    d2 = tempfile.mkdtemp(prefix=f"df-compchk-{mode}-")
    shutil.rmtree(d2)
    shutil.copytree(src_dir, d2)
    env = dict(os.environ)
    env.pop("DF_SEG_FORMAT", None)
    env["DF_COMPACT_CRASH"] = mode
    env["JAX_PLATFORMS"] = "cpu"
    if pin_v1:
        env["DF_SEG_FORMAT"] = "1"
    child = ("from deepflow_tpu.store.db import Database\n"
             f"db = Database({d2!r}, storage=True)\n"
             "db.compact_tier()\n")
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, timeout=300)
    if proc.returncode != 43:
        _fail(f"chaos {mode}: crash hook did not fire "
              f"(rc={proc.returncode}, err={proc.stderr.decode()[-500:]})")
    from deepflow_tpu.store.db import Database
    db = Database(d2, storage=True)
    got = _goldens(db)
    if got != golden:
        _fail(f"chaos {mode}: answers diverged after crash-recovery")
    res = db.compact_tier()
    left = db.tier_store.migrate_v1_remaining()
    if left != 0:
        _fail(f"chaos {mode}: re-compaction did not converge "
              f"({left} v1 segments left, res={res})")
    if _goldens(db) != golden:
        _fail(f"chaos {mode}: answers diverged after convergence")
    shutil.rmtree(d2, ignore_errors=True)
    print(f"  chaos {mode}{' (DF_SEG_FORMAT=1 pinned)' if pin_v1 else ''}"
          f": recovered exact, converged to v2")


def main() -> int:
    from deepflow_tpu.query import engine as qengine
    from deepflow_tpu.telemetry import Telemetry

    # the after_commit chaos arm legitimately leaves ~200 victims for
    # reopen to delete; one warning per file would drown the verdict
    logging.getLogger("df.tiered").setLevel(logging.ERROR)
    tel = Telemetry("compaction-check", enabled=True)
    qengine.set_scan_telemetry(tel)
    data_dir = tempfile.mkdtemp(prefix="df-compchk-")
    try:
        total_rows = N_SEGMENTS * ROWS_PER_SEGMENT
        print(f"compaction-check: building {N_SEGMENTS} v1 segments "
              f"({total_rows} rows)...")
        db = _build_v1_tier(data_dir)
        tt = db.tier_store.tier(TABLE)
        n_v1 = sum(1 for s in tt.segments() if s.fmt < 2)
        if tt.segment_count() < N_SEGMENTS or n_v1 != tt.segment_count():
            _fail(f"build: expected >= {N_SEGMENTS} v1 segments, got "
                  f"{tt.segment_count()} ({n_v1} v1)")

        golden = _goldens(db)
        t_v1 = _time_needles(db)
        print(f"  v1 tier: {tt.segment_count()} segments, "
              f"needle sweep {t_v1 * 1e3:.1f}ms")

        # chaos arms run on copies of the PRE-compaction tier
        _chaos_arm(data_dir, "after_stage", golden, pin_v1=False)
        _chaos_arm(data_dir, "after_commit", golden, pin_v1=True)

        stats0 = qengine.scan_stats()
        res = db.compact_tier()
        if res["runs_built"] < 1:
            _fail(f"compaction built no runs: {res}")
        if res["segments_replaced"] < N_SEGMENTS:
            _fail(f"compaction replaced {res['segments_replaced']} "
                  f"segments, expected >= {N_SEGMENTS}")
        left = db.tier_store.migrate_v1_remaining()
        if left != 0:
            _fail(f"{left} v1 segments remain after compaction")
        n_after = tt.segment_count()
        if n_after >= N_SEGMENTS // 4:
            _fail(f"compaction left {n_after} segments (fragmentation "
                  f"not reduced)")
        st = db.tier_store.stats
        if st["bytes_before"] <= 0 or st["bytes_after"] <= 0:
            _fail(f"compaction byte counters not ledgered: {st}")
        print(f"  compacted: {res['runs_built']} runs, "
              f"{res['segments_replaced']} segments replaced, "
              f"{st['bytes_before']}B -> {st['bytes_after']}B")

        got = _goldens(db)
        if got != golden:
            for i, (g, h) in enumerate(zip(golden, got)):
                if g != h:
                    _fail(f"golden {i} diverged after compaction:\n"
                          f"  v1: {g}\n  v2: {h}")
        t_v2 = _time_needles(db)
        stats1 = qengine.scan_stats()
        bloom_checked = stats1["bloom_checked"] - stats0["bloom_checked"]
        bloom_pruned = stats1["bloom_pruned"] - stats0["bloom_pruned"]
        if bloom_checked <= 0 or bloom_pruned <= 0:
            _fail(f"bloom indexes not consulted: checked={bloom_checked} "
                  f"pruned={bloom_pruned}")
        speedup = t_v1 / max(t_v2, 1e-9)
        print(f"  v2 tier: {n_after} segments, needle sweep "
              f"{t_v2 * 1e3:.1f}ms, speedup {speedup:.1f}x, "
              f"bloom checked={bloom_checked} pruned={bloom_pruned}")
        if speedup < SPEEDUP_TARGET:
            _fail(f"selective-scan speedup {speedup:.2f}x < "
                  f"{SPEEDUP_TARGET}x")

        for h in tel.snapshot()["pipeline"]:
            if h["emitted"] != h["delivered"] + h["dropped_total"] \
                    + h["in_flight"]:
                _fail(f"hop {h['hop']!r} ledger does not balance: {h}")
        print("compaction-check: PASS")
        return 0
    finally:
        qengine.set_scan_telemetry(None)
        shutil.rmtree(data_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
