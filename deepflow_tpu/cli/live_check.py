"""live-check: standing-query / push-subscription / streaming-alert gate.

Proves the live-observability surface end to end against real servers:

  * a 1M-row flow-log window with a registered dashboard standing query
    under sustained ingest — incremental refresh must be >= 10x faster
    than a from-scratch execute of the same windowed SQL at small
    deltas, and byte-identical to it (DF_STANDING=0 kill-switch arm
    must also be byte-identical);
  * 3 concurrent subscribers each receive every generation exactly
    once, in order, with the conserved ``query.standing`` hop ledger
    balancing after they detach;
  * a threshold alert breached by an append must fire (event.event row
    written, rule firing) within 2 seconds — push evaluation, no poll;
  * a 3-shard federated standing query stays byte-identical to a
    single node holding the union, and a delta landing on ONE shard
    recomputes only that shard (if_state machinery: the other shard
    answers "unchanged");
  * an exporter ships rows with a conserved ``exporter.<kind>`` ledger.

Wired as `make live-check` — the CI gate for PR 18's live surface.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
import urllib.request

import numpy as np

BASE_NS = 1_600_000_000_000_000_000
BUCKET_NS = 60_000_000_000
N_BUCKETS = 30
ROWS_TOTAL = 1_000_000
GROUPS = 8
SQL = ("SELECT app_service, Count(*) AS n, Sum(response_duration) AS s "
       "FROM l7_flow_log GROUP BY app_service ORDER BY app_service")


def _fail(msg: str) -> None:
    print(f"live-check: FAIL: {msg}")
    sys.exit(1)


def _post(port: int, path: str, body: dict, timeout: float = 30) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
        return json.loads(resp.read())


def _canon(values) -> str:
    return json.dumps(values, sort_keys=True, default=str)


def _seed(table, rows: int = ROWS_TOTAL) -> None:
    per_bucket = rows // N_BUCKETS
    per_group = per_bucket // GROUPS
    for b in range(N_BUCKETS):
        for g in range(GROUPS):
            i = np.arange(per_group, dtype=np.uint64)
            table.append_columns(
                {"time": BASE_NS + b * BUCKET_NS
                 + (g * per_group + i) * 1_000,
                 "app_service": f"svc-{g:03d}",
                 "response_duration": (i * 37) % 5_000},
                n=per_group)


def _drain(port: int, sid: str, sink: list, stop: threading.Event) -> None:
    """One subscriber: long-poll until stopped, recording every update."""
    while not stop.is_set():
        out = _post(port, "/v1/subscribe",
                    {"action": "poll", "subscriber": sid,
                     "timeout_s": 2})
        sink.extend(out["updates"])
        if out.get("closed"):
            return


def _local_arm() -> None:
    from deepflow_tpu.query import engine
    from deepflow_tpu.server import Server

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    sync_port=0).start()
    try:
        table = server.db.table("flow_log.l7_flow_log")
        t0 = time.perf_counter()
        _seed(table)
        print(f"live-check: seeded {len(table):,} rows in "
              f"{time.perf_counter() - t0:.1f}s")

        reg = _post(server.query_port, "/v1/subscribe",
                    {"action": "register", "sql": SQL, "name": "dash",
                     "table": "flow_log.l7_flow_log",
                     "window_s": float(N_BUCKETS * 60)})["registered"]
        if reg["gen"] != 1:
            _fail(f"register did not return gen 1: {reg}")

        # 3 concurrent subscribers, each with its own drain thread
        sids, sinks, threads = [], [], []
        stop = threading.Event()
        for _ in range(3):
            sid = _post(server.query_port, "/v1/subscribe",
                        {"action": "subscribe",
                         "queries": ["dash"]})["subscriber"]
            sids.append(sid)
            sink: list = []
            sinks.append(sink)
            th = threading.Thread(target=_drain,
                                  args=(server.query_port, sid, sink,
                                        stop), daemon=True)
            th.start()
            threads.append(th)

        # sustained ingest: 10 small deltas into the newest bucket
        deltas = 10
        hi = BASE_NS + (N_BUCKETS - 1) * BUCKET_NS
        for d in range(deltas):
            table.append_rows([
                {"time": hi + 50_000_000_000 + d * 1_000 + j,
                 "app_service": "svc-000",
                 "response_duration": 100 + j}
                for j in range(200)])
            time.sleep(0.35)   # > MIN_GAP_S: every delta becomes a gen

        # wait until every subscriber has seen the final generation
        sq = server.standing.get("dash")
        deadline = time.time() + 15
        while time.time() < deadline:
            if all(any(u["gen"] == sq.gen for u in s) for s in sinks):
                break
            time.sleep(0.1)
        stop.set()
        for th in threads:
            th.join(timeout=5)

        # exactly-once per (subscriber, generation), in order, complete
        for i, sink in enumerate(sinks):
            gens = [u["gen"] for u in sink if u["query"] == "dash"]
            if not gens:
                _fail(f"subscriber {i} saw no updates")
            if len(gens) != len(set(gens)):
                _fail(f"subscriber {i} saw a generation twice: {gens}")
            if gens != sorted(gens):
                _fail(f"subscriber {i} saw generations out of order: "
                      f"{gens}")
            if gens != list(range(gens[0], gens[0] + len(gens))):
                _fail(f"subscriber {i} has a generation gap: {gens}")
            if gens[-1] != sq.gen:
                _fail(f"subscriber {i} missed the final gen "
                      f"{sq.gen}: {gens}")
        n_gens = len([u for u in sinks[0] if u["query"] == "dash"])
        print(f"live-check: {deltas} deltas -> {n_gens} generations, "
              f"each delivered exactly once to 3 subscribers: OK")

        # incremental >= 10x from-scratch on small deltas, byte-identical
        inc_ms = [u["refresh_ms"] for u in sinks[0]
                  if u["mode"] == "incremental"]
        if len(inc_ms) < 3:
            _fail(f"too few incremental refreshes: {inc_ms}")
        _brange, wsel = server.standing._window(sq)
        full_ms = []
        for _ in range(5):
            f0 = time.perf_counter()
            ref = engine.execute(table, wsel)
            full_ms.append((time.perf_counter() - f0) * 1e3)
        if _canon(json.loads(_canon(ref.values))) != _canon(sq.rows):
            _fail("standing rows diverge from from-scratch execute")
        inc = statistics.median(inc_ms)
        full = statistics.median(full_ms)
        speedup = full / max(inc, 1e-9)
        if speedup < 10.0:
            _fail(f"incremental refresh only {speedup:.1f}x faster than "
                  f"from-scratch ({inc:.2f}ms vs {full:.2f}ms; need 10x)")
        print(f"live-check: incremental {inc:.2f}ms vs from-scratch "
              f"{full:.2f}ms ({speedup:.1f}x, >=10x floor), "
              f"byte-identical: OK")

        # kill-switch arm: DF_STANDING=0 must give the same bytes
        os.environ["DF_STANDING"] = "0"
        try:
            _post(server.query_port, "/v1/subscribe",
                  {"action": "register", "sql": SQL, "name": "dash-off",
                   "table": "flow_log.l7_flow_log",
                   "window_s": float(N_BUCKETS * 60)})
            off = server.standing.get("dash-off")
            if off.counters["full"] < 1 or off.counters["incremental"]:
                _fail(f"kill-switch arm still folded incrementally: "
                      f"{off.counters}")
            if _canon(off.rows) != _canon(sq.rows):
                _fail("DF_STANDING=0 result diverges from incremental")
        finally:
            os.environ.pop("DF_STANDING", None)
            _post(server.query_port, "/v1/subscribe",
                  {"action": "unregister", "name": "dash-off"})
        print("live-check: DF_STANDING=0 kill-switch byte-identical: OK")

        # streaming alert: breach -> event row within 2s, no polling
        _post(server.query_port, "/v1/alerts", {
            "name": "errors-high", "db": "flow_log",
            "sql": "SELECT Count(*) FROM l7_flow_log "
                   "WHERE response_code = 500",
            "op": ">", "threshold": 5, "interval_s": 999})
        rule = server.alerts.rules["errors-high"]
        if rule.standing_name != "alert:errors-high":
            _fail(f"alert rule not standing-backed: {rule.standing_name}")
        a0 = time.perf_counter()
        table.append_rows([
            {"time": hi + 55_000_000_000 + j, "app_service": "svc-000",
             "response_code": 500, "response_duration": 1}
            for j in range(10)])
        while time.perf_counter() - a0 < 5.0 and not rule.firing:
            time.sleep(0.01)
        fire_s = time.perf_counter() - a0
        if not rule.firing:
            _fail("alert never fired after breaching append")
        if fire_s > 2.0:
            _fail(f"alert fired after {fire_s:.2f}s (need < 2s)")
        ev = server.db.table("event.event")
        deadline = time.time() + 5
        while time.time() < deadline and not len(ev):
            time.sleep(0.05)
        r = engine.execute(
            ev,
            "SELECT resource_name FROM event WHERE event_type = 'alert'")
        if not r.values or r.values[0][0] != "errors-high":
            _fail(f"no alert event row: {r.values}")
        print(f"live-check: alert fired {fire_s * 1e3:.0f}ms after the "
              f"breaching append (push-evaluated, <2s gate): OK")

        # detach everyone; the hop ledger must balance
        for sid in sids:
            _post(server.query_port, "/v1/subscribe",
                  {"action": "unsubscribe", "subscriber": sid})
        led = _get(server.query_port, "/v1/health")["standing"]["ledger"]
        if led["emitted"] != led["delivered"] + led["dropped_total"] \
                + led["in_flight"]:
            _fail(f"query.standing ledger does not conserve: {led}")
        if led["in_flight"] != 0:
            _fail(f"updates stranded in flight after detach: {led}")
        print(f"live-check: query.standing ledger conserved "
              f"(emitted {led['emitted']} = delivered {led['delivered']}"
              f" + dropped {led['dropped_total']}): OK")
    finally:
        server.stop()


def _federated_arm() -> None:
    from deepflow_tpu.query import engine
    from deepflow_tpu.server import Server

    def _rows(shard_tag: int, n: int, t_off: int = 0) -> list[dict]:
        return [{"time": BASE_NS + t_off + i * 1_000_000,
                 "app_service": f"svc-{(i + shard_tag) % 5:03d}",
                 "response_duration": (i * 13) % 900}
                for i in range(n)]

    servers: list = []
    try:
        solo = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                      sync_port=0).start()
        servers.append(solo)
        seed = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                      sync_port=0, shard_id=1,
                      cluster_advertise="").start()
        servers.append(seed)
        addr = f"127.0.0.1:{seed.query_port}"
        shards = [seed]
        for sid in (2, 3):
            s = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                       sync_port=0, shard_id=sid,
                       cluster_seed=addr).start()
            servers.append(s)
            shards.append(s)
        deadline = time.time() + 15.0
        while time.time() < deadline:
            if len(seed.api.federation.remote_peers()) == 2:
                break
            time.sleep(0.2)
        else:
            _fail("federated arm: membership never converged")

        for i, s in enumerate(shards):
            rows = _rows(i, 2_000)
            s.db.table("flow_log.l7_flow_log").append_rows(rows)
            solo.db.table("flow_log.l7_flow_log").append_rows(rows)

        _post(seed.query_port, "/v1/subscribe",
              {"action": "register", "sql": SQL, "name": "fed",
               "table": "flow_log.l7_flow_log"})
        sub = _post(seed.query_port, "/v1/subscribe",
                    {"action": "subscribe", "queries": ["fed"]})
        sq = seed.standing.get("fed")
        gen0 = sq.gen
        # let a couple of warm federation ticks pass, then baseline
        time.sleep(1.5)
        refetched0 = sq.counters["fed_shards_refetched"]
        warm0 = sq.counters["fed_warm"]

        delta = _rows(7, 300, t_off=5_000_000_000)
        shards[2].db.table("flow_log.l7_flow_log").append_rows(delta)
        solo.db.table("flow_log.l7_flow_log").append_rows(delta)
        deadline = time.time() + 10
        while time.time() < deadline and sq.gen == gen0:
            time.sleep(0.05)
        if sq.gen == gen0:
            _fail("federated arm: remote delta never produced a new gen")
        time.sleep(1.0)   # settle back into warm ticks

        want = engine.execute(
            solo.db.table("flow_log.l7_flow_log"), SQL)
        if _canon(json.loads(_canon(want.values))) != _canon(sq.rows):
            _fail("federated standing rows diverge from single node")
        refetched = sq.counters["fed_shards_refetched"] - refetched0
        if not 1 <= refetched <= 2:
            _fail(f"federated arm: expected only the changed shard to "
                  f"recompute, saw {refetched} refetches")
        if sq.counters["fed_shards_unchanged"] == 0:
            _fail("federated arm: no shard ever answered 'unchanged'")
        if sq.counters["fed_warm"] <= warm0:
            _fail("federated arm: no warm (zero-work) tick observed")
        out = _post(seed.query_port, "/v1/subscribe",
                    {"action": "poll", "subscriber": sub["subscriber"],
                     "timeout_s": 5})
        gens = [u["gen"] for u in out["updates"] if u["query"] == "fed"]
        if sq.gen not in gens:
            _fail(f"federated arm: push missed gen {sq.gen}: {gens}")
        print(f"live-check: 3-shard federated standing query "
              f"byte-identical to single node; delta on one shard "
              f"refetched {refetched} shard(s), others unchanged, "
              f"warm ticks zero-work: OK")
    finally:
        for s in servers:
            s.stop()


def _exporter_arm() -> None:
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from deepflow_tpu.server import Server

    got = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            got.append(self.rfile.read(n))
            self.send_response(200)
            self.end_headers()

    sink = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=sink.serve_forever, daemon=True).start()
    server = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    sync_port=0).start()
    try:
        _post(server.query_port, "/v1/exporters", {
            "type": "json-lines",
            "endpoint": f"http://127.0.0.1:{sink.server_address[1]}/x",
            "tables": ["application_log.log"]})
        n = 40
        for i in range(n):
            _post(server.query_port, "/api/v1/log",
                  {"service": "s", "message": f"m{i}"})
        deadline = time.time() + 15
        led = None
        while time.time() < deadline:
            ex = _get(server.query_port, "/v1/health").get("exporters", {})
            led = next(iter(ex.values()), {}).get("ledger")
            if led and led["delivered"] >= n:
                break
            time.sleep(0.1)
        if not led:
            _fail("no exporter ledger in /v1/health")
        if led["emitted"] != led["delivered"] + led["dropped_total"] \
                + led["in_flight"]:
            _fail(f"exporter ledger does not conserve: {led}")
        if led["delivered"] < n:
            _fail(f"exporter delivered {led['delivered']}/{n}: {led}")
        print(f"live-check: exporter.jsonlines ledger conserved "
              f"(emitted {led['emitted']} = delivered {led['delivered']}"
              f" + dropped {led['dropped_total']}): OK")
    finally:
        server.stop()
        sink.shutdown()
        sink.server_close()


def main() -> int:
    _local_arm()
    _federated_arm()
    _exporter_arm()
    print("live-check: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
