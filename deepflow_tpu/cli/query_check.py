"""query-check: golden parity of the three query execution paths plus a
warm/cold cache latency report.

Runs a battery of DF-SQL over a seeded corpus through

  * legacy       — decoded row pipeline (DF_QUERY_ENCODED=0),
  * numpy        — encoded columns, pure-numpy kernels (DF_NO_NATIVE=1),
  * native       — encoded columns through libdfnative's qexec kernels
                   (skipped with a note when the .so is unavailable),

and fails (exit 1) on any result divergence — the encoded paths must be
byte-identical to the legacy one. Then a 3-shard in-process cluster
proves federated ORDER BY + LIMIT + HAVING parity against a single node
holding the same rows, and the query cache is timed cold vs warm, both
local (per-bucket partials) and federated (coordinator scatter cache).

Wired as `make query-check` — the CI gate for the encoded pipeline.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request


def _fail(msg: str) -> None:
    print(f"query-check: FAIL: {msg}")
    sys.exit(1)


def _post(port: int, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _canon(x):
    if isinstance(x, bool):
        return x
    if isinstance(x, (int, float)):
        return round(float(x), 6)
    if isinstance(x, list):
        return [_canon(v) for v in x]
    if isinstance(x, dict):
        return {k: _canon(v) for k, v in x.items()}
    return x


ROWS = 12_000
GROUPS = 600

BATTERY = [
    "SELECT app_service, Count(*) AS n, Sum(response_duration) AS s, "
    "Avg(response_duration) AS a FROM l7_flow_log GROUP BY app_service "
    "HAVING Count(*) > 1 ORDER BY n DESC, app_service LIMIT 50",
    "SELECT app_service, endpoint, Max(response_duration) AS mx "
    "FROM l7_flow_log GROUP BY app_service, endpoint "
    "ORDER BY mx DESC, app_service, endpoint LIMIT 25",
    "SELECT l7_protocol, Count(DISTINCT app_service) AS d, "
    "Min(response_duration) AS mn FROM l7_flow_log "
    "GROUP BY l7_protocol ORDER BY l7_protocol",
    "SELECT Count(*) AS n, Sum(response_duration) AS s "
    "FROM l7_flow_log WHERE app_service LIKE 'svc-01%'",
    "SELECT time, app_service, endpoint FROM l7_flow_log "
    "WHERE response_code = 500 ORDER BY time DESC LIMIT 10",
]


def _corpus_rows(base_ns: int) -> list[dict]:
    return [
        {"time": base_ns + i * 1_000_000,
         "app_service": f"svc-{i % GROUPS:05d}",
         "endpoint": f"/api/{i % 17}",
         "l7_protocol": 1 + (i % 3),
         "response_code": 500 if i % 97 == 0 else 200,
         "response_duration": (i * 37) % 5_000}
        for i in range(ROWS)]


def _make_table(rows: list[dict]):
    from deepflow_tpu.store.db import Database
    t = Database().table("flow_log.l7_flow_log")
    t.append_rows(rows)
    return t


def _run_mode(t, env: dict) -> dict:
    from deepflow_tpu.query import engine
    saved = {k: os.environ.get(k) for k in env}
    try:
        for k, v in env.items():
            os.environ[k] = v
        out = {}
        for sql in BATTERY:
            r = engine.execute(t, sql)
            out[sql] = _canon({"columns": r.columns, "values": r.values})
        return out
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _parity(t) -> None:
    from deepflow_tpu import native
    legacy = _run_mode(t, {"DF_QUERY_ENCODED": "0"})
    numpy_ = _run_mode(t, {"DF_QUERY_ENCODED": "1", "DF_NO_NATIVE": "1"})
    for sql in BATTERY:
        if numpy_[sql] != legacy[sql]:
            _fail(f"numpy path diverges from legacy on: {sql}")
    print(f"query-check: parity legacy==numpy over {len(BATTERY)} "
          "queries: OK")
    if native.available():
        nat = _run_mode(t, {"DF_QUERY_ENCODED": "1"})
        for sql in BATTERY:
            if nat[sql] != legacy[sql]:
                _fail(f"native path diverges from legacy on: {sql}")
        print(f"query-check: parity legacy==native over {len(BATTERY)} "
              "queries: OK")
    else:
        print("query-check: libdfnative unavailable — native arm "
              "skipped (numpy fallback already verified)")


def _cache_report(t) -> None:
    from deepflow_tpu.query.cache import QueryCache
    qc = QueryCache()
    sql = BATTERY[0]
    t0 = time.perf_counter()
    qc.execute(t, sql)
    cold_ms = (time.perf_counter() - t0) * 1e3
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        qc.execute(t, sql)
    warm_ms = (time.perf_counter() - t0) * 1e3 / reps
    snap = qc.snapshot()
    if snap["hits"] != reps:
        _fail(f"expected {reps} warm hits, counters: {snap}")
    t.append_rows(_corpus_rows(1_700_000_000_000_000_000)[:50])
    qc.execute(t, sql)
    if qc.counters["stale"] != 1 or qc.counters["bucket_hits"] == 0:
        _fail("append did not take the per-bucket refresh path: "
              f"{qc.snapshot()}")
    print(f"query-check: local cache cold {cold_ms:.2f}ms, "
          f"warm {warm_ms:.3f}ms "
          f"({cold_ms / max(warm_ms, 1e-9):.1f}x), "
          f"bucket slices reused after append: "
          f"{qc.counters['bucket_hits']}")


def _pruning() -> None:
    """Zone-map gate: a 5% time slice of a 100-segment table must prune
    >=90% of the segments, proven by the scan ledger, and still answer
    exactly."""
    import tempfile

    import numpy as np

    from deepflow_tpu.query import engine
    from deepflow_tpu.store.db import Database

    nseg, per = 100, 400
    with tempfile.TemporaryDirectory() as d:
        db = Database(data_dir=d, storage=True)
        t = db.table("flow_log.l7_flow_log")
        for k in range(nseg):
            t.append_columns(
                {"time": np.arange(per, dtype=np.uint64) + k * 1000,
                 "app_service": f"svc-{k:03d}",
                 "response_duration": np.full(per, k, dtype=np.uint64)},
                n=per)
            if db.flush_to_tier() == 0:
                _fail("pruning arm: flush wrote no rows")
        # 5 of 100 segment spans overlap [90_000, 95_000)
        sql = ("SELECT Sum(response_duration) AS s, Count(*) AS c "
               "FROM l7_flow_log WHERE time >= 90000 AND time < 95000")
        before = engine.scan_stats()
        res = engine.execute(t, sql)
        after = engine.scan_stats()
        pruned = after["pruned_segments"] - before["pruned_segments"]
        scanned = after["scanned_segments"] - before["scanned_segments"]
        if scanned + pruned != nseg:
            _fail(f"pruning arm: ledger saw {scanned + pruned} segments, "
                  f"expected {nseg}")
        if pruned < int(0.9 * nseg):
            _fail(f"pruning arm: only {pruned}/{nseg} segments pruned "
                  f"for a 5% time slice (need >=90)")
        want = [[float(sum(k * per for k in range(90, 95))),
                 float(5 * per)]]
        if _canon(res.values) != _canon(want):
            _fail(f"pruning arm: wrong answer {res.values} != {want}")
        print(f"query-check: pruning {pruned}/{nseg} segments skipped "
              f"on a 5% time slice, answer exact: OK")


def _parallel() -> None:
    """Morsel-parallel gate: byte-identity always; the >=3x speedup
    floor only where the hardware can express it (>=4 cores)."""
    import numpy as np

    from deepflow_tpu.query import engine
    from deepflow_tpu.store.db import Database

    n = 1_200_000
    t = Database().table("flow_log.l7_flow_log")
    i = np.arange(n, dtype=np.uint64)
    t.append_columns(
        {"time": 1_600_000_000_000_000_000 + i * 1_000_000,
         "l7_protocol": (i % 7).astype(np.uint8),
         "response_code": np.where(i % 97 == 0, 500, 200).astype(np.uint16),
         "response_duration": (i * 37) % 5_000}, n=n)
    sql = ("SELECT l7_protocol, Sum(response_duration) AS s, "
           "Count(*) AS c, Max(response_duration) AS mx "
           "FROM l7_flow_log GROUP BY l7_protocol ORDER BY l7_protocol")

    def _timed(env: dict) -> tuple[float, dict]:
        saved = {k: os.environ.get(k) for k in env}
        try:
            for k, v in env.items():
                os.environ[k] = v
            best, out = float("inf"), None
            for _ in range(5):
                t0 = time.perf_counter()
                r = engine.execute(t, sql)
                best = min(best, time.perf_counter() - t0)
                out = _canon({"columns": r.columns, "values": r.values})
            return best, out
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    serial_s, serial = _timed({"DF_QUERY_PARALLEL": "0",
                               "DF_QUERY_THREADS": "1"})
    threads = os.cpu_count() or 1
    par_s, par = _timed({"DF_QUERY_PARALLEL": "1",
                         "DF_QUERY_THREADS": str(threads)})
    if par != serial:
        _fail("parallel path diverges from serial (byte-identity)")
    speedup = serial_s / max(par_s, 1e-9)
    if threads >= 4:
        if speedup < 3.0:
            _fail(f"parallel speedup {speedup:.2f}x < 3x floor on "
                  f"{threads} cores (serial {serial_s * 1e3:.1f}ms, "
                  f"parallel {par_s * 1e3:.1f}ms)")
        verdict = "OK (>=3x floor)"
    else:
        verdict = f"floor skipped ({threads} cores < 4)"
    print(f"query-check: parallel byte-identity over {n} rows: OK — "
          f"serial {serial_s * 1e3:.1f}ms, parallel {par_s * 1e3:.1f}ms "
          f"({speedup:.2f}x, {verdict})")


def _federated(rows: list[dict]) -> None:
    from deepflow_tpu.server import Server
    servers: list = []
    try:
        solo = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                      sync_port=0).start()
        servers.append(solo)
        seed = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                      sync_port=0, shard_id=1,
                      cluster_advertise="").start()
        servers.append(seed)
        addr = f"127.0.0.1:{seed.query_port}"
        shards = [seed]
        for sid in (2, 3):
            s = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                       sync_port=0, shard_id=sid,
                       cluster_seed=addr).start()
            servers.append(s)
            shards.append(s)
        deadline = time.time() + 15.0
        while time.time() < deadline:
            if len(seed.api.federation.remote_peers()) == 2:
                break
            time.sleep(0.2)
        else:
            _fail("membership never converged")
        solo.db.table("flow_log.l7_flow_log").append_rows(rows)
        for i, row in enumerate(rows):
            shards[i % 3].db.table("flow_log.l7_flow_log") \
                .append_rows([row])
        lat = {}
        for sql in BATTERY[:3]:
            body = {"sql": sql, "db": "flow_log"}
            want = _post(solo.query_port, "/v1/query", body)["result"]
            t0 = time.perf_counter()
            got = _post(seed.query_port, "/v1/query", body)
            lat.setdefault("cold", []).append(
                (time.perf_counter() - t0) * 1e3)
            if got["federation"]["missing_shards"]:
                _fail(f"missing shards on: {sql}")
            if json.dumps(_canon(got["result"]), sort_keys=True) != \
                    json.dumps(_canon(want), sort_keys=True):
                _fail(f"federated result diverges from single-node: "
                      f"{sql}")
            t0 = time.perf_counter()
            again = _post(seed.query_port, "/v1/query", body)
            lat.setdefault("warm", []).append(
                (time.perf_counter() - t0) * 1e3)
            if again["federation"].get("cache") != "warm":
                _fail(f"repeat query did not validate warm: {sql}")
        cold = sum(lat["cold"]) / len(lat["cold"])
        warm = sum(lat["warm"]) / len(lat["warm"])
        print(f"query-check: federated parity over {len(BATTERY[:3])} "
              f"queries (3 shards vs 1 node): OK — scatter cold "
              f"{cold:.2f}ms, warm {warm:.2f}ms")
    finally:
        for s in servers:
            s.stop()


def main() -> int:
    rows = _corpus_rows(1_600_000_000_000_000_000)
    t = _make_table(rows)
    _parity(t)
    _cache_report(t)
    _pruning()
    _parallel()
    _federated(rows[:3_000])
    print("query-check: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
