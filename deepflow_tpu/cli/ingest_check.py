"""ingest-check: throughput gate for the native ingest hot path.

Wired as `make ingest-check`. Ships the same L4 flow-log frames through
a real Server twice — once on the native columnar path (zero-copy frame
decode -> C++ column decode -> batched C++ dictionary encode) and once
with DF_NO_NATIVE=1 forcing the per-field python protobuf fallback —
and exits non-zero unless:

  * the native arm sustains >= 2.5x the fallback's rows/s.  The gate is
    RELATIVE so a slow CI host can't fail a fast code path; on
    production-grade hardware the same path clears the absolute 1M
    rows/s target tracked by bench.py.
  * neither arm drops frames or times out waiting for rows to land
    (a throughput win that loses data would be no win).

Each arm is best-of-N to keep a one-off scheduler stall from failing a
healthy build.  The per-stage breakdown (recv/decode/dict/write) is
printed either way so a regression is attributable to a stage, not
just visible in the ratio.
"""

from __future__ import annotations

import os
import sys

# bench.py lives at the repo root, above the deepflow_tpu package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import bench  # noqa: E402

MIN_SPEEDUP = 2.5
RUNS = 2  # best-of per arm


def _fail(msg: str) -> None:
    print(f"ingest-check: FAIL — {msg}")
    sys.exit(1)


def _best(no_native: bool) -> dict:
    runs = [bench._run_ingest(bench._make_l4_frame, no_native=no_native)
            for _ in range(RUNS)]
    return max(runs, key=lambda r: r["rows_per_sec"])


def _stages(r: dict) -> str:
    return (f"recv={r['recv_ms']:.0f}ms decode={r['decode_ms']:.0f}ms "
            f"dict={r['dict_ms']:.0f}ms write={r['write_ms']:.0f}ms")


def main() -> int:
    from deepflow_tpu import native
    if native.load() is None:
        _fail("libdfnative.so not loaded — nothing to gate "
              "(run `make native`; DF_NO_NATIVE must be unset)")

    nat = _best(no_native=False)
    pb = _best(no_native=True)

    for name, r in (("native", nat), ("pb-fallback", pb)):
        print(f"ingest-check: {name:<11} {r['rows_per_sec']:>9,} rows/s  "
              f"{_stages(r)}")
        if r["timed_out"]:
            _fail(f"{name} arm timed out: {r['rows']}/{r['rows_expected']} "
                  f"rows landed")
        if r["frames_dropped"]:
            _fail(f"{name} arm dropped {r['frames_dropped']} frames")

    speedup = nat["rows_per_sec"] / max(1, pb["rows_per_sec"])
    if speedup < MIN_SPEEDUP:
        _fail(f"native speedup {speedup:.2f}x < required {MIN_SPEEDUP}x "
              f"({nat['rows_per_sec']:,} vs {pb['rows_per_sec']:,} rows/s)")
    print(f"ingest-check: OK — native {speedup:.2f}x over pb fallback "
          f"(>= {MIN_SPEEDUP}x), zero drops on both arms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
