"""chaos-check: restart-and-recover e2e proving the loss-bounded transport.

Scenario (seeded fault schedule, wired as `make chaos-check`):

  1. server A starts with a data_dir (ack watermarks + tables persist)
  2. a durable sender (disk spool + ack/retransmit window + chaos
     injector randomly resetting connections and truncating writes)
     pumps two streams through it: STEP_METRICS (HIGH priority) and
     DFSTATS (LOW priority)
  3. mid-stream server A is stopped (graceful: decoder queues drain,
     ack watermarks persist — the restart unit the exactly-once claim
     covers, see docs/ROBUSTNESS.md for the hard-kill bound); traffic
     keeps flowing, parking in the retransmit window and the on-disk
     spool; server B then restarts on the same port + data_dir and the
     sender reconnects and replays
  4. later the AGENT restarts too — same agent_id, same spool dir — so
     the check also proves a restarted agent's fresh (epoch-seeded) seq
     space is adopted by the server instead of being discarded as dups
     against the old boot's watermark
  5. after quiescence the check fails unless:
       * every HIGH frame landed in the store EXACTLY once — zero
         loss to the restarts or the injected faults, zero duplicate
         rows from the retransmits that recovered them
       * the hop ledgers of both sender boots and server B balance
         (emitted == delivered + dropped(reason): nothing vanished
         without a named reason)

A second phase then validates the HARD-kill bound documented in
docs/ROBUSTNESS.md ("Scope of the exactly-once claim"): the server
runs as a SUBPROCESS and is SIGKILLed mid-stream — no decoder drain,
no watermark persist, in-memory tables gone. Because acks only follow
decode+write, the admissible loss is EXACTLY the frames the agent saw
acked before the kill (their rows died with the process and their
acks pruned them from the retransmit window). The phase fails unless,
after a restart on the same port + data_dir:

  * every frame UNACKED at kill time landed (retransmitted from the
    window/spool — zero loss outside the documented bound),
  * every frame sent AFTER the kill landed,
  * the missing set is precisely the acked-before-kill prefix, and
  * no frame landed twice (restart floors + dedup hold under SIGKILL).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

MS = 1_000_000
N_HIGH = 300            # STEP_METRICS frames, one record each
LOW_EVERY = 3           # a DFSTATS frame every N high frames
KILL_AT = 100           # stop server A after this many high frames
RESTART_AT = 180        # start server B after this many high frames
AGENT_RESTART_AT = 240  # restart the sender (same agent_id + spool dir)


def _fail(msg: str) -> None:
    print(f"chaos-check: FAIL: {msg}")
    sys.exit(1)


def _step_payload(i: int) -> bytes:
    from deepflow_tpu.tpuprobe.stepmetrics import encode_step_payload
    return encode_step_payload([{
        "time": i * MS, "end_ns": i * MS + 500, "latency_ns": 500,
        "run_id": 7, "step": i, "job": "chaos", "device_count": 4,
        "device_skew_ns": 0, "compute_ns": 1, "collective_ns": 1,
        "straggler_device": 0, "straggler_lag_ns": 0, "top_hlos": []}])


def _stats_payload() -> bytes:
    from deepflow_tpu.proto import pb
    batch = pb.StatsBatch()
    m = batch.metrics.add()
    m.name = "chaos_check_noise"
    m.timestamp_ns = time.time_ns()
    m.values["v"] = 1.0
    return batch.SerializeToString()


def _check_ledgers(telemetry, who: str) -> None:
    for h in telemetry.snapshot()["pipeline"]:
        if h["emitted"] != h["delivered"] + h["dropped_total"] \
                + h["in_flight"]:
            _fail(f"{who} hop {h['hop']!r} ledger does not balance: {h}")


def _hard_kill_phase() -> None:
    """SIGKILL a subprocess server mid-stream; prove the documented
    hard-crash loss bound is tight: missing == acked-before-kill,
    everything else exactly once."""
    from deepflow_tpu.agent.sender import UniformSender
    from deepflow_tpu.agent.spool import Spool
    from deepflow_tpu.codec import MessageType
    from deepflow_tpu.server import Server
    from deepflow_tpu.telemetry import Telemetry

    n_pre, n_post = 120, 80
    data_dir = tempfile.mkdtemp(prefix="df-chaos-hk-data-")
    spool_dir = tempfile.mkdtemp(prefix="df-chaos-hk-spool-")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    log = open(os.path.join(data_dir, "server.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "deepflow_tpu.server.server",
         "--host", "127.0.0.1", "--query-host", "127.0.0.1",
         "--ingest-port", str(port), "--query-port", "0",
         "--sync-port", "0", "--no-controller", "--data-dir", data_dir],
        stdout=log, stderr=subprocess.STDOUT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    deadline = time.time() + 30.0
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.5).close()
            break
        except OSError:
            time.sleep(0.2)
    else:
        _fail("hard-kill: subprocess server never listened")

    telemetry = Telemetry("agent", enabled=True)
    sender = UniformSender(
        [("127.0.0.1", port)], agent_id=5, telemetry=telemetry,
        spool=Spool(spool_dir)).start()
    server = None
    try:
        # HIGH-only stream: frame i carries seq seq_base + i, so the
        # agent's contiguous ack watermark translates 1:1 to step ids
        for i in range(1, n_pre + 1):
            sender.send(MessageType.STEP_METRICS, _step_payload(i))
            time.sleep(0.002)
        deadline = time.time() + 15.0
        while time.time() < deadline and \
                sender.stats["acked_seq"] <= sender.seq_base:
            time.sleep(0.05)

        proc.send_signal(signal.SIGKILL)   # no drain, no persist
        proc.wait(timeout=10)
        time.sleep(0.3)  # let the ack channel settle: watermark final
        acked_kill = sender.stats["acked_seq"] - sender.seq_base
        if not 0 < acked_kill <= n_pre:
            _fail(f"hard-kill: acked watermark {acked_kill} outside "
                  f"(0, {n_pre}] — scenario did not exercise the bound")
        print(f"chaos-check: hard-kill at acked={acked_kill}/{n_pre}")

        for i in range(n_pre + 1, n_pre + n_post + 1):
            sender.send(MessageType.STEP_METRICS, _step_payload(i))
            time.sleep(0.002)

        # restart on the same port + data_dir (in-process: we read the
        # store directly); the agent reconnects and replays its window
        server = Server(host="127.0.0.1", ingest_port=port,
                        query_port=0, data_dir=data_dir).start()
        sender.flush_and_stop(timeout=60.0)
        want = n_pre + n_post - acked_kill
        server.wait_for_rows("profile.tpu_step_metrics", want,
                             timeout=30.0)
        time.sleep(0.5)
        table = server.db.table("profile.tpu_step_metrics")
        table.flush()
        cols = table.column_concat(["step"])
        steps = cols["step"].tolist() if len(table) else []
        if len(steps) != len(set(steps)):
            _fail(f"hard-kill: duplicate rows after SIGKILL recovery "
                  f"({len(steps)} rows, {len(set(steps))} unique)")
        missing = set(range(1, n_pre + n_post + 1)) - set(steps)
        bound = set(range(1, acked_kill + 1))
        if missing != bound:
            _fail(f"hard-kill: loss outside the documented bound — "
                  f"missing {sorted(missing)} != acked-before-kill "
                  f"prefix 1..{acked_kill} (sender stats: "
                  f"{sender.stats})")
        _check_ledgers(telemetry, "hard-kill sender")
        print(f"chaos-check: hard-kill OK — lost exactly the "
              f"{acked_kill} acked-before-kill frames, "
              f"{want}/{want} others exactly once")
    finally:
        sender.flush_and_stop(timeout=1.0)
        if server is not None:
            server.stop()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        log.close()


def main() -> int:
    from deepflow_tpu.agent.sender import UniformSender
    from deepflow_tpu.agent.spool import Spool
    from deepflow_tpu.chaos import ChaosConfig, ChaosInjector
    from deepflow_tpu.codec import MessageType
    from deepflow_tpu.server import Server
    from deepflow_tpu.telemetry import Telemetry

    data_dir = tempfile.mkdtemp(prefix="df-chaos-data-")
    spool_dir = tempfile.mkdtemp(prefix="df-chaos-spool-")

    server_a = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                      data_dir=data_dir).start()
    port = server_a.ingest_port

    chaos = ChaosInjector(ChaosConfig(
        enabled=True, seed=42, conn_reset=0.01, partial_write=0.01))
    telemetry = Telemetry("agent", enabled=True)
    sender = UniformSender(
        [("127.0.0.1", port)], agent_id=9, telemetry=telemetry,
        spool=Spool(spool_dir), chaos=chaos).start()

    server_b = None
    try:
        for i in range(1, N_HIGH + 1):
            sender.send(MessageType.STEP_METRICS, _step_payload(i))
            if i % LOW_EVERY == 0:
                sender.send(MessageType.DFSTATS, _stats_payload())
            if i == KILL_AT:
                server_a.stop()   # graceful: drains decoders, persists
                print(f"chaos-check: server stopped at frame {i}")
            if i == RESTART_AT:
                server_b = Server(host="127.0.0.1", ingest_port=port,
                                  query_port=0, data_dir=data_dir).start()
                print(f"chaos-check: server restarted at frame {i}")
            if i == AGENT_RESTART_AT:
                # agent restart with the SAME agent_id and spool dir:
                # the new boot's epoch-seeded seq space must be adopted
                # by the server (SEQ_BASE fast-forward), not discarded
                # as dups against the old boot's watermark
                sender.flush_and_stop(timeout=30.0)
                sender = UniformSender(
                    [("127.0.0.1", port)], agent_id=9, telemetry=telemetry,
                    spool=Spool(spool_dir), chaos=chaos).start()
                print(f"chaos-check: agent restarted at frame {i}")
            time.sleep(0.002)

        # drain: queue + retransmit window + spool backlog, across
        # whatever reconnect/backoff cycles the chaos schedule forces
        sender.flush_and_stop(timeout=60.0)
        if not server_b.wait_for_rows("profile.tpu_step_metrics", N_HIGH,
                                      timeout=30.0):
            got = len(server_b.db.table("profile.tpu_step_metrics"))
            _fail(f"HIGH loss: {got}/{N_HIGH} STEP_METRICS rows after "
                  f"kill-and-recover (sender stats: {sender.stats})")

        # exactly-once: at-least-once retransmit + (agent_id, seq) dedup
        # must leave each (run_id, step) as ONE row, not >=1
        time.sleep(0.5)  # let any straggler dups land before counting
        table = server_b.db.table("profile.tpu_step_metrics")
        table.flush()
        cols = table.column_concat(["run_id", "step"])
        keys = list(zip(cols["run_id"].tolist(), cols["step"].tolist()))
        if len(keys) != N_HIGH or len(set(keys)) != N_HIGH:
            _fail(f"not exactly-once: {len(keys)} rows, "
                  f"{len(set(keys))} unique of {N_HIGH} sent "
                  f"(dedup stats: {[d.stats for d in server_b.decoders]})")

        _check_ledgers(telemetry, "sender")
        _check_ledgers(server_b.telemetry, "server-b")
        faults = dict(chaos.stats)
        print(f"chaos-check: OK — {N_HIGH}/{N_HIGH} HIGH frames exactly "
              f"once across a server kill-and-recover; "
              f"retransmits={sender.stats['retransmits']} "
              f"spooled={sender.stats['spooled']} "
              f"replayed={sender.stats['replayed']} faults={faults}")
        server_b.stop()
        server_b = None
        _hard_kill_phase()
        return 0
    finally:
        sender.flush_and_stop(timeout=1.0)
        if server_b is not None:
            server_b.stop()


if __name__ == "__main__":
    sys.exit(main())
