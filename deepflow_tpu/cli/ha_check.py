"""ha-check: kill a shard under load, lose nothing.

End-to-end proof of the replicated-ingest contract (`make ha-check`):

  1. a real 3-shard cluster starts as SUBPROCESSES (one seed + two
     joiners, ``--replication 2``) — subprocesses so the fault below is
     a genuine SIGKILL, not a graceful drain
  2. once the consistent-hash ring converges (every shard reports the
     same 3-member ring), a fleet of ReplicatedSenders pumps
     STEP_METRICS (HIGH priority) at the ring owners each agent hashes
     to — every frame lands on R=2 shards
  3. healthy checkpoint: a federated ``SELECT Count(*)`` must equal the
     number of LOGICAL frames sent — not 2x — proving the query-time
     claim filter hides replica copies exactly
  4. one owner shard is SIGKILLed mid-stream and the fleet keeps
     pumping; frames aimed at the corpse park in its sender's ack
     window while the surviving replica copy keeps landing
  5. the check fails unless the final federated count is EXACT (every
     frame from both phases, zero HIGH loss), ``missing_shards`` is
     empty (the dead shard is covered, answers stay exact, not
     partial), and no surviving destination dropped a frame

This is the acceptance criterion of the replication tentpole run as a
standalone binary, cheap enough for CI like chaos-check/cluster-check.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

MS = 1_000_000
AGENTS = (101, 102, 103, 104, 105, 106)   # simulated agent_ids
N_PHASE = 40                              # HIGH frames per agent per phase
REPLICATION = 2


def _fail(msg: str) -> None:
    print(f"ha-check: FAIL: {msg}")
    sys.exit(1)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.loads(resp.read())


def _post(port: int, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=15) as resp:
        return json.loads(resp.read())


def _step_payload(agent_id: int, i: int) -> bytes:
    from deepflow_tpu.tpuprobe.stepmetrics import encode_step_payload
    return encode_step_payload([{
        "time": i * MS, "end_ns": i * MS + 500, "latency_ns": 500,
        "run_id": agent_id, "step": i, "job": "ha", "device_count": 4,
        "device_skew_ns": 0, "compute_ns": 1, "collective_ns": 1,
        "straggler_device": 0, "straggler_lag_ns": 0, "top_hlos": []}])


def _spawn_shard(sid: int, iports: dict, qports: dict, base: str,
                 seed_addr: str | None, logs: list) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "deepflow_tpu.server.server",
           "--host", "127.0.0.1", "--query-host", "127.0.0.1",
           "--ingest-port", str(iports[sid]),
           "--query-port", str(qports[sid]),
           "--sync-port", "0", "--shard-id", str(sid),
           "--advertise", f"127.0.0.1:{qports[sid]}",
           "--replication", str(REPLICATION),
           "--fanout-timeout-s", "2.0",
           "--no-controller",
           "--data-dir", os.path.join(base, f"shard{sid}")]
    if seed_addr:
        cmd += ["--cluster-seed", seed_addr]
    log = open(os.path.join(base, f"shard{sid}.log"), "wb")
    logs.append(log)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            env=env)


def _fed_count(port: int) -> tuple[int, dict]:
    got = _post(port, "/v1/query", {
        "sql": "SELECT Count(*) AS n FROM tpu_step_metrics",
        "db": "profile"})
    values = got.get("result", {}).get("values") or []
    n = int(values[0][0]) if values and values[0] else 0
    return n, (got.get("federation") or {})


def _wait_count(port: int, want: int, timeout: float) -> tuple[int, dict]:
    deadline = time.time() + timeout
    n, fed = -1, {}
    while time.time() < deadline:
        try:
            n, fed = _fed_count(port)
        except OSError:
            time.sleep(0.3)
            continue
        if n >= want:
            return n, fed
        time.sleep(0.3)
    return n, fed


def main() -> int:
    from deepflow_tpu.agent.sender import ReplicatedSender
    from deepflow_tpu.cluster.hashring import HashRing
    from deepflow_tpu.codec import MessageType

    base = tempfile.mkdtemp(prefix="df-ha-")
    shards = (1, 2, 3)
    iports = {sid: _free_port() for sid in shards}
    qports = {sid: _free_port() for sid in shards}
    procs: dict[int, subprocess.Popen] = {}
    logs: list = []
    senders: dict[int, ReplicatedSender] = {}
    try:
        seed_addr = f"127.0.0.1:{qports[1]}"
        procs[1] = _spawn_shard(1, iports, qports, base, None, logs)
        for sid in (2, 3):
            procs[sid] = _spawn_shard(sid, iports, qports, base,
                                      seed_addr, logs)

        # ring convergence: every shard must report the SAME 3-member
        # ring before we pump, so every row is tagged at the final
        # epoch and placement matches the local ring computed below
        deadline = time.time() + 30.0
        seen: dict[int, list] = {}
        while time.time() < deadline:
            seen = {}
            for sid in shards:
                try:
                    ring = _get(qports[sid],
                                "/v1/cluster/status").get("ring") or {}
                    seen[sid] = ring.get("members") or []
                except OSError:
                    seen[sid] = []
            if all(seen[sid] == [1, 2, 3] for sid in shards):
                break
            time.sleep(0.3)
        else:
            _fail(f"ring never converged: per-shard members {seen}")

        # placement is a pure function of the member shard ids, so this
        # locally built ring agrees with the servers' ring on owners
        members = {sid: {"addr": f"127.0.0.1:{qports[sid]}",
                         "ingest": f"127.0.0.1:{iports[sid]}"}
                   for sid in shards}
        ring = HashRing(members, replication=REPLICATION)
        owner_sets = {aid: ring.owners(aid) for aid in AGENTS}
        victim = next(s for s in (3, 2)
                      if any(s in o for o in owner_sets.values()))
        survivor = next(s for s in shards if s != victim)

        for aid in AGENTS:
            senders[aid] = ReplicatedSender(
                ring.ingest_addrs(aid), replication=REPLICATION,
                agent_id=aid).start()

        # phase 1: healthy cluster — every frame lands on R=2 shards
        for i in range(1, N_PHASE + 1):
            for aid in AGENTS:
                senders[aid].send(MessageType.STEP_METRICS,
                                  _step_payload(aid, i))
            time.sleep(0.002)
        want = len(AGENTS) * N_PHASE
        n, fed = _wait_count(qports[survivor], want, timeout=30.0)
        if n != want:
            _fail(f"healthy federated Count(*) = {n}, want {want} "
                  f"(logical frames, not {REPLICATION}x): replica "
                  f"dedup broken or ingest lost frames; fed={fed}")
        if fed.get("missing_shards"):
            _fail(f"healthy cluster reported missing shards: {fed}")
        print(f"ha-check: healthy checkpoint OK — {n}/{want} logical "
              f"rows via shard {survivor}, owners {owner_sets}")

        # phase 2: SIGKILL one owner shard, keep pumping. Frames aimed
        # at the corpse park in its sender's ack window; the surviving
        # replica copy is what the claim filter must promote.
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=10)
        print(f"ha-check: shard {victim} SIGKILLed mid-stream")
        for i in range(N_PHASE + 1, 2 * N_PHASE + 1):
            for aid in AGENTS:
                senders[aid].send(MessageType.STEP_METRICS,
                                  _step_payload(aid, i))
            time.sleep(0.002)

        want = len(AGENTS) * 2 * N_PHASE
        n, fed = _wait_count(qports[survivor], want, timeout=60.0)
        if n != want:
            _fail(f"federated Count(*) = {n} after killing shard "
                  f"{victim}, want {want} — HIGH frames lost; fed={fed}")
        if fed.get("missing_shards"):
            _fail(f"answer degraded to partial despite replication: "
                  f"{fed}")
        # no over-count either: a second read must still be exact
        n2, _ = _fed_count(qports[survivor])
        if n2 != want:
            _fail(f"count not stable after failover: {n2} != {want}")

        # surviving destinations must not have shed a single HIGH frame
        for aid, s in senders.items():
            for dest, st in s.per_destination().items():
                port = int(dest.rsplit(":", 1)[1])
                if port != iports[victim] and st.get("dropped"):
                    _fail(f"agent {aid} dropped {st['dropped']} frames "
                          f"to surviving dest {dest}: {st}")

        print(f"ha-check: OK — {want}/{want} HIGH frames exact after "
              f"SIGKILL of shard {victim} (covered="
              f"{fed.get('covered_shards')}, ring_epoch="
              f"{fed.get('ring_epoch')}); zero loss, zero dup")
        return 0
    finally:
        for s in senders.values():
            try:
                s.flush_and_stop(timeout=2.0)
            except Exception:
                pass
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
